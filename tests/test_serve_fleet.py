"""Serving fleet failover: health-checked router, journal handoff,
traffic-driven autoscale (tpusystem/serve/fleet.py).

Two layers of drill, the failover-test discipline one tier up:

* **Policy tests** drive the router over FAKE replicas (a scripted
  scheduler with the real surface — deterministic token emission, no
  jax) on a fake clock: placement, retry/timeout ladders, hedging,
  fleet watermarks/brownout, autoscale breathing — zero real sleeps,
  zero compiles.
* **Chaos drills** run REAL engines: 3 replicas serving a mixed
  workload, a :class:`~tpusystem.parallel.chaos.PreemptionWave`
  SIGKILL-analogue kills one (or two, the slow drill) mid-stream, and
  every journaled request completes TOKEN-EXACT against an
  uninterrupted fleet — hot handoff for seated rows onto a *different*
  engine than the one that died, cold re-submit for queued ones, no
  request silently dropped, and the router never routes to the dead
  replica after its health verdict.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.checkpoint.memstore import MemStore
from tpusystem.models import gpt2_tiny
from tpusystem.parallel.chaos import PreemptionWave
from tpusystem.serve import (AutoscalePolicy, Engine, FleetSaturated,
                             NoHealthyReplica, QueueFull, ReplicaHandle,
                             Request, RequestJournal, RoutePolicy, Router,
                             Scheduler, ServingReplica, Watermarks)
from tpusystem.serve.scheduler import Completion, Tick
from tpusystem.services.prodcon import Consumer, Producer


class FakeClock:
    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def witness(producer, *event_types):
    """Collect the given event types dispatched on ``producer``."""
    seen = []
    consumer = Consumer('probe')
    for event_type in event_types:
        consumer.register(event_type, seen.append)
    producer.register(consumer)
    return seen


# ---------------------------------------------------------------------------
# the fake replica: the real Scheduler surface, scripted decode
# ---------------------------------------------------------------------------


def scripted_token(request_id: str, position: int) -> int:
    """Deterministic emission: the fake fleet's stand-in for greedy
    decode — any replica resuming ``request_id`` at ``position`` emits
    the same token, so hot handoffs are checkable arithmetic."""
    return (sum(map(ord, request_id)) * 31 + position) % 997


def expected_tokens(request_id: str, budget: int) -> list:
    return [scripted_token(request_id, p) for p in range(budget)]


class FakeScheduler:
    """The :class:`~tpusystem.serve.Scheduler` surface with scripted
    decode: each step seats up to ``rows`` requests (emitting the
    admission token, the engine's contract) and every seated row emits
    one :func:`scripted_token` per tick. ``wedged=True`` seats rows but
    never decodes past the admission token — the straggler the
    timeout/hedge ladder must beat."""

    def __init__(self, *, clock, rows: int = 2, max_queued=None,
                 wedged: bool = False) -> None:
        self.rows = rows
        self.max_queued = max_queued
        self.wedged = wedged
        self.journal = None
        self.backpressure = False
        self._clock = clock
        self._queue = []             # (request, submitted, prefix)
        self._seated = {}            # id -> [request, submitted, tokens]
        self.results = {}
        self.steps = 0

    # ------------------------------------------------------- intake
    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def active(self):
        return len(self._seated)

    @property
    def idle(self):
        return not self._queue and not self._seated

    def submit(self, request):
        if (self.max_queued is not None
                and len(self._queue) >= self.max_queued):
            raise QueueFull(f'{request.id!r}: backlog full')
        self._queue.append((request, self._clock(), []))
        if self.journal is not None:
            self.journal.record(request, self._clock())

    def restore(self, request, *, waited=0.0, prefix=()):
        prefix = [int(t) for t in prefix]
        if len(prefix) >= request.max_new:
            raise ValueError(f'{request.id!r} already finished')
        submitted = self._clock() - waited
        self._queue.append((request, submitted, prefix))
        if self.journal is not None:
            self.journal.restored(request, submitted, prefix)

    def cancel(self, request_id):
        for entry in list(self._queue):
            if entry[0].id == request_id:
                self._queue.remove(entry)
                if self.journal is not None:
                    self.journal.finished(request_id)
                return 'queued'
        seated = self._seated.pop(request_id, None)
        if seated is not None:
            self._complete(seated[0], seated[1], seated[2], 'cancelled')
            return 'active'
        return None

    def shed_candidates(self):
        now = self._clock()
        out = []
        for request, submitted, _prefix in self._queue:
            slack = (None if request.deadline is None
                     else request.deadline - (now - submitted))
            out.append((request.id, slack, now - submitted))
        return out

    def shed(self, request_id):
        for entry in list(self._queue):
            if entry[0].id == request_id:
                self._queue.remove(entry)
                return self._complete(entry[0], entry[1], [], 'shed')
        return None

    # ------------------------------------------------------- serving
    def _complete(self, request, submitted, tokens, reason):
        completion = Completion(request, list(tokens), reason,
                                self._clock() - submitted)
        self.results[request.id] = completion
        if self.journal is not None:
            self.journal.finished(request.id)
        return completion

    def step(self):
        self.steps += 1
        admitted = []
        while self._queue and len(self._seated) < self.rows:
            request, submitted, prefix = self._queue.pop(0)
            tokens = list(prefix)
            self._seated[request.id] = [request, submitted, tokens]
            admitted.append((request, None, self._clock() - submitted))
            if not prefix:           # admission emits the first token
                tokens.append(scripted_token(request.id, 0))
                if self.journal is not None:
                    self.journal.seated(request.id, tokens[-1])
        emitted, completed = {}, []
        for request_id, entry in list(self._seated.items()):
            request, submitted, tokens = entry
            if not self.wedged and len(tokens) < request.max_new:
                token = scripted_token(request_id, len(tokens))
                tokens.append(token)
                emitted[request_id] = token
                if self.journal is not None:
                    self.journal.append(request_id, token)
            if len(tokens) >= request.max_new:
                del self._seated[request_id]
                completed.append(self._complete(request, submitted,
                                                tokens, 'length'))
        if self.journal is not None:
            self.journal.observe_tick()
        return Tick(admitted, emitted, completed, len(self._queue),
                    len(self._seated))


class FakeReplica:
    """The ServingReplica surface over a :class:`FakeScheduler`, with
    the journal wired exactly like the real one (client = supervisor-RAM
    stand-in that outlives a kill)."""

    def __init__(self, identity, *, clock, client=None, cadence=1,
                 fallbacks=(), **knobs):
        self.identity = identity
        self.client = client
        self.fallbacks = tuple(fallbacks)
        self.scheduler = FakeScheduler(clock=clock, **knobs)
        self.scheduler.journal = RequestJournal(identity, client=client,
                                                cadence=cadence, clock=clock)

    def submit(self, request):
        self.scheduler.submit(request)

    def step(self):
        return self.scheduler.step()

    @property
    def results(self):
        return self.scheduler.results

    @property
    def idle(self):
        return self.scheduler.idle


def fake_fleet(clock, n=2, *, cadence=1, router_knobs=None, **knobs):
    stores = [MemStore() for _ in range(n)]
    handles = [ReplicaHandle(FakeReplica(f'rep{i}', clock=clock,
                                         client=stores[i], cadence=cadence,
                                         **knobs))
               for i in range(n)]
    router = Router(handles, clock=clock, **(router_knobs or {}))
    return router, handles, stores


# ---------------------------------------------------------------------------
# routing and health
# ---------------------------------------------------------------------------


class TestRouting:

    def test_least_loaded_placement(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=3)
        names = [router.submit(Request(f'r{i}', [1], 4)) for i in range(3)]
        # each submission deepens a replica, so the next goes elsewhere
        assert sorted(names) == ['rep0', 'rep1', 'rep2']

    def test_prefix_affinity_steers_to_the_warm_replica(self):
        """A replica whose engine holds the prompt's prefix in its radix
        tree wins placement over emptier-but-cold replicas — and loses
        it again the moment it is backpressured (affinity is a steering
        hint, never a pressure override)."""
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=3)

        class _WarmEngine:
            @staticmethod
            def prefix_cached_len(prompt):
                return 8 if list(prompt[:2]) == [1, 2] else 0

        handles[2].scheduler.engine = _WarmEngine()
        # depth tie everywhere: the warm radix tree breaks it
        assert router.submit(Request('warm', [1, 2, 3, 4], 4)) == 'rep2'
        # ... and keeps winning even when rep2 is now DEEPER than the rest
        assert router.submit(Request('warm2', [1, 2, 3, 4], 4)) == 'rep2'
        # a cold prompt ignores affinity: least-loaded as before
        assert router.submit(Request('cold', [9, 9], 4)) in ('rep0', 'rep1')
        # backpressure outranks the warm cache
        handles[2].scheduler.backpressure = True
        assert router.submit(Request('warm3', [1, 2, 3, 4], 4)) != 'rep2'

    def test_backpressured_replica_passed_over(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=2)
        handles[0].scheduler.backpressure = True
        assert router.submit(Request('a', [1], 4)) == 'rep1'
        # ... unless every healthy replica is backpressured
        handles[1].scheduler.backpressure = True
        assert router.submit(Request('b', [1], 4)) in ('rep0', 'rep1')

    def test_queue_full_falls_to_next_replica_then_saturates(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=2, max_queued=1)
        assert router.submit(Request('r0', [1], 8)) == 'rep0'
        # rep0's backlog is full: the router retries on rep1
        assert router.submit(Request('r1', [1], 8)) == 'rep1'
        with pytest.raises(FleetSaturated):
            router.submit(Request('overflow', [1], 8))

    def test_dead_fleet_raises_no_healthy(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=1)
        handles[0].kill()
        with pytest.raises(NoHealthyReplica):
            router.submit(Request('a', [1], 4))
        assert not handles[0].healthy   # dying at submit IS the verdict

    def test_dead_on_submit_reroutes_to_survivor(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=2)
        handles[0].kill()
        assert router.submit(Request('a', [1], 4)) == 'rep1'
        assert not handles[0].healthy

    def test_completions_settle_and_drain(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=2)
        for i in range(4):
            router.submit(Request(f'r{i}', [1], 3))
        results = router.run_until_idle()
        assert set(results) == {'r0', 'r1', 'r2', 'r3'}
        for i in range(4):
            assert results[f'r{i}'].tokens == expected_tokens(f'r{i}', 3)
            assert results[f'r{i}'].reason == 'length'

    def test_fleet_cancel_reaches_the_placed_replica(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=2)
        router.submit(Request('a', [1], 8))
        assert router.cancel('a') == 'queued'
        assert router.cancel('a') is None    # idempotent: route gone
        assert router.idle

    def test_external_replica_completions_settle(self):
        """Review regression: an externally-driven replica's completions
        must settle through the router (it never sees their Ticks) —
        otherwise the route table leaks, idle never lands, and the
        retry ladder re-places finished work."""
        clock = FakeClock()
        external = ReplicaHandle(FakeReplica('ext', clock=clock),
                                 external=True)
        router = Router([external], clock=clock,
                        policy=RoutePolicy(timeout=5.0, max_retries=2),
                        heartbeat_timeout=100.0)
        assert router.submit(Request('a', [1], 3)) == 'ext'
        # the replica's own loop runs it to completion...
        external.beat()
        while not external.replica.idle:
            external.replica.step()
        clock.advance(10.0)          # ...past the retry patience
        tick = router.step()         # harvest, not a timeout reroute
        assert tick.completed == ['a']
        assert not tick.rerouted
        assert router.idle
        assert router.results['a'].tokens == expected_tokens('a', 3)

    def test_cancel_purges_the_orphan_buffer(self):
        """Review regression: a cancelled orphan must not be
        resurrected by the next adopt."""
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=1)
        router.submit(Request('a', [1], 5))
        router.step()
        handles[0].kill()
        router.step()                # 'a' parks in the orphan buffer
        assert router.cancel('a') == 'queued'
        router.adopt(ReplicaHandle(FakeReplica('rep9', clock=clock)))
        assert router.run_until_idle() == {}   # nothing resurrected
        assert 'a' not in router.results

    def test_duplicate_replica_names_refused(self):
        clock = FakeClock()
        with pytest.raises(ValueError):
            Router([ReplicaHandle(FakeReplica('x', clock=clock)),
                    ReplicaHandle(FakeReplica('x', clock=clock))],
                   clock=clock)


# ---------------------------------------------------------------------------
# the health verdict + journal handoff (fake replicas; the real-engine
# drill is TestFleetChaosDrill)
# ---------------------------------------------------------------------------


class TestFailover:

    def test_kill_mid_stream_hot_and_cold_handoff(self):
        from tpusystem.observe.events import (ReplicaUnhealthy,
                                              RequestRerouted)
        clock = FakeClock()
        producer = Producer()
        seen = witness(producer, ReplicaUnhealthy, RequestRerouted)
        router, handles, stores = fake_fleet(clock, n=3)
        router.producer = producer
        # rep0 (rows=2) ends up with two seated rows and one queued one
        assert router.submit(Request('v-seated', [1], 8)) == 'rep0'
        assert router.submit(Request('bg1', [1], 4)) == 'rep1'
        assert router.submit(Request('bg2', [1], 4)) == 'rep2'
        assert router.submit(Request('v-seated2', [1], 8)) == 'rep0'
        clock.advance(1.0)
        router.step()                # seats both victims, emits 2 tokens
        assert router.submit(Request('bg3', [1], 4)) == 'rep1'
        assert router.submit(Request('bg4', [1], 4)) == 'rep2'
        # depths tie at 2 apiece: fleet order sends the victim to rep0
        assert router.submit(Request('v-queued', [1], 6)) == 'rep0'
        clock.advance(1.0)
        handles[0].kill()            # SIGKILL analogue; store survives
        tick = router.step()
        assert not handles[0].healthy
        moved = {event.id: event for event in tick.rerouted}
        assert moved['v-seated'].where == 'hot'
        assert moved['v-seated'].prefix >= 1
        assert moved['v-queued'].where == 'cold'
        assert {event.origin for event in tick.rerouted} == {'rep0'}
        placements_after = handles[0].placements
        results = router.run_until_idle()
        # never routed to the dead replica after the verdict
        assert handles[0].placements == placements_after
        # token-exact across the handoff: prefix + resumed == scripted
        for rid, budget in (('v-seated', 8), ('v-seated2', 8),
                            ('v-queued', 6)):
            assert results[rid].tokens == expected_tokens(rid, budget), rid
            assert results[rid].reason == 'length'
        kinds = {type(event).__name__ for event in seen}
        assert {'ReplicaUnhealthy', 'RequestRerouted'} <= kinds

    def test_cadence_gap_rows_resubmit_cold_from_routing_table(self):
        """A request routed AFTER the journal's last push exists only in
        the router's table — it must re-home cold, never drop."""
        clock = FakeClock()
        router, handles, stores = fake_fleet(clock, n=2, cadence=100)
        # cadence 100: nothing was ever pushed to the store
        assert router.submit(Request('a', [1], 5)) == 'rep0'
        handles[0].kill()
        tick = router.step()
        assert [event.id for event in tick.rerouted] == ['a']
        assert tick.rerouted[0].where == 'cold'
        results = router.run_until_idle()
        assert results['a'].tokens == expected_tokens('a', 5)

    def test_corrupt_local_journal_recovers_from_buddy(self, caplog):
        clock = FakeClock()
        store, buddy_store = MemStore(), MemStore()
        replica = FakeReplica('rep0', clock=clock, client=store)
        handle = ReplicaHandle(replica,
                               journal_clients=(store, buddy_store))
        survivor = ReplicaHandle(FakeReplica('rep1', clock=clock))
        router = Router([handle, survivor], clock=clock)
        assert router.submit(Request('a', [1], 6)) == 'rep0'
        router.step()                # seats + journals + pushes
        # mirror the push to the buddy (the supervisor replication
        # rider's job on a real pod), then corrupt the local copy
        entry = store.fetch('journal:rep0')
        buddy_store.put('journal:rep0', entry.step, entry.blob)
        store._slots[('journal:rep0', False)].blob = b'torn!'
        handle.kill()
        with caplog.at_level(logging.WARNING):
            tick = router.step()
        assert [event.id for event in tick.rerouted] == ['a']
        assert tick.rerouted[0].where == 'hot'   # the buddy copy had it
        results = router.run_until_idle()
        assert results['a'].tokens == expected_tokens('a', 6)

    def test_no_survivor_parks_orphans_until_adopt(self):
        clock = FakeClock()
        router, handles, stores = fake_fleet(clock, n=1)
        router.submit(Request('a', [1], 5))
        router.step()
        handles[0].kill()
        tick = router.step()
        assert tick.orphans == 1 and not tick.rerouted
        with pytest.raises(NoHealthyReplica):
            router.submit(Request('b', [1], 4))
        router.adopt(ReplicaHandle(FakeReplica('rep9', clock=clock)))
        results = router.run_until_idle()
        assert results['a'].tokens == expected_tokens('a', 5)
        assert results['a'].reason == 'length'

    def test_heartbeat_verdict_on_external_replica(self):
        clock = FakeClock()
        external = ReplicaHandle(FakeReplica('ext', clock=clock),
                                 external=True)
        survivor = ReplicaHandle(FakeReplica('rep1', clock=clock))
        router = Router([external, survivor], clock=clock,
                        heartbeat_timeout=5.0)
        assert router.submit(Request('a', [1], 4)) == 'ext'
        external.beat()
        router.step()                # beat stamped: still healthy
        assert external.healthy
        clock.advance(6.0)
        tick = router.step()         # stale: verdict + re-home
        assert not external.healthy
        assert external.cause.startswith('heartbeat')
        assert [event.id for event in tick.rerouted] == ['a']
        results = router.run_until_idle()
        assert results['a'].tokens == expected_tokens('a', 4)


# ---------------------------------------------------------------------------
# timeout retry + hedging (+ the TTFT-from-original-submission pin)
# ---------------------------------------------------------------------------


class TestRetryAndHedge:

    def test_timeout_reroutes_with_original_submission_accounting(self):
        """The satellite pin: a request retried on a second replica
        reports waited-time from ORIGINAL submission, not re-submission
        — threaded through restore(waited=) on the fake clock."""
        clock = FakeClock()
        wedged = ReplicaHandle(FakeReplica('wedge', clock=clock,
                                           wedged=True))
        healthy = ReplicaHandle(FakeReplica('ok', clock=clock))
        router = Router([wedged, healthy], clock=clock,
                        policy=RoutePolicy(timeout=10.0, max_retries=1))
        # empty fleet: ties break in fleet order, so the wedge seats it
        assert router.submit(Request('slow', [1], 4)) == 'wedge'
        router.step()                # seats, emits ONLY the admission token
        clock.advance(11.0)          # past the per-replica patience
        tick = router.step()
        moved = [event for event in tick.rerouted if event.id == 'slow']
        assert moved and moved[0].cause == 'timeout'
        assert moved[0].target == 'ok'
        assert moved[0].where == 'hot'   # the admission token carried over
        results = router.run_until_idle()
        completion = results['slow']
        # prefix (admission token on the wedge) + resumed, token-exact
        assert completion.tokens == expected_tokens('slow', 4)
        # latency counts from FIRST submission: the 11s on the wedge
        assert completion.seconds >= 11.0
        # TTFT on the second replica was accounted from the original
        # submission too: its scheduler saw a backdated submit time
        assert healthy.scheduler.results['slow'].seconds >= 11.0

    def test_retry_ladder_is_capped(self):
        clock = FakeClock()
        replicas = [ReplicaHandle(FakeReplica(f'w{i}', clock=clock,
                                              wedged=True))
                    for i in range(2)]
        router = Router(replicas, clock=clock,
                        policy=RoutePolicy(timeout=5.0, max_retries=2,
                                           retry_backoff=2.0))
        router.submit(Request('a', [1], 4))
        reroutes = 0
        for _ in range(30):
            clock.advance(21.0)      # far past every rung of the ladder
            reroutes += len(router.step().rerouted)
        assert reroutes == 2         # max_retries, then the ladder stops

    def test_hedge_first_completion_wins_loser_cancelled(self):
        clock = FakeClock()
        wedged = ReplicaHandle(FakeReplica('wedge', clock=clock,
                                           wedged=True))
        healthy = ReplicaHandle(FakeReplica('ok', clock=clock))
        router = Router([wedged, healthy], clock=clock,
                        policy=RoutePolicy(hedge_after=5.0))
        assert router.submit(Request('h', [1], 3)) == 'wedge'
        router.step()
        clock.advance(6.0)
        tick = router.step()         # hedge fires onto 'ok'
        hedges = [event for event in tick.rerouted
                  if event.cause == 'hedge']
        assert hedges and hedges[0].target == 'ok'
        assert router._routes['h'].hedged == 'ok'
        results = router.run_until_idle()
        assert results['h'].tokens == expected_tokens('h', 3)
        assert results['h'].reason == 'length'
        # the loser (the wedge) no longer holds the request
        assert wedged.scheduler.active == 0
        loser = wedged.scheduler.results.get('h')
        assert loser is not None and loser.reason == 'cancelled'

    def test_dead_hedge_leg_does_not_rehome_the_live_primary(self):
        clock = FakeClock()
        primary = ReplicaHandle(FakeReplica('p', clock=clock, wedged=True))
        hedge = ReplicaHandle(FakeReplica('h', clock=clock, wedged=True))
        router = Router([primary, hedge], clock=clock,
                        policy=RoutePolicy(hedge_after=2.0))
        assert router.submit(Request('a', [1], 4)) == 'p'
        router.step()
        clock.advance(3.0)
        router.step()                # hedged onto 'h'
        route = router._routes['a']
        assert route.hedged == 'h'
        hedge.kill()
        tick = router.step()
        assert route.hedged is None  # hedge leg cleared, primary lives
        assert not any(event.id == 'a' and event.cause == 'failover'
                       for event in tick.rerouted)
        assert route.handle == 'p' and primary.healthy


# ---------------------------------------------------------------------------
# fleet watermarks: global shed by slack, brownout front door
# ---------------------------------------------------------------------------


class TestFleetDegradation:

    def test_global_shed_picks_most_doomed_across_replicas(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(
            clock, n=2, rows=1,
            router_knobs={'watermarks': Watermarks(high=3, low=2)})
        # seat one long row per replica, then queue with distinct slacks
        router.submit(Request('seat0', [1], 20))
        router.submit(Request('seat1', [1], 20))
        router.step()
        router.submit(Request('doomed', [1], 8, deadline=2.0))   # rep0
        router.submit(Request('roomy', [1], 8, deadline=50.0))   # rep1
        router.submit(Request('patient', [1], 8))
        router.submit(Request('patient2', [1], 8))
        tick = router.step()
        # global depth 4 > high 3: shed to low 2 — deadline-carrying
        # victims first, ascending slack, ACROSS replicas ('doomed' on
        # rep0, then 'roomy' on rep1; no-deadline requests survive)
        shed_ids = [completion.request.id for completion, _ in tick.shed]
        assert shed_ids == ['doomed', 'roomy']
        assert router.results['doomed'].reason == 'shed'
        assert router.brownout

    def test_brownout_refuses_no_deadline_at_front_door(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(
            clock, n=2, rows=1,
            router_knobs={'watermarks': Watermarks(high=2, low=2)})
        for i in range(4):
            router.submit(Request(f'r{i}', [1], 30))
        router.step()                # seats one per replica, 2 queued
        router.submit(Request('q1', [1], 30))
        router.step()                # 3 queued > high 2 -> brownout
        assert router.brownout
        with pytest.raises(FleetSaturated):
            router.submit(Request('nodeadline', [1], 4))
        # deadline-carrying work still enters and competes by slack
        router.submit(Request('bounded', [1], 4, deadline=1e6))
        router.run_until_idle()
        assert not router.brownout   # drained back under the low mark
        router.submit(Request('after', [1], 3))
        assert router.run_until_idle()['after'].reason == 'length'

    def test_fleet_backpressure_narrated_on_toggle(self):
        from tpusystem.observe.events import Backpressure, LoadShed
        clock = FakeClock()
        producer = Producer()
        seen = witness(producer, Backpressure, LoadShed)
        router, handles, _ = fake_fleet(
            clock, n=1, rows=1,
            router_knobs={'watermarks': Watermarks(high=1, low=0),
                          'producer': producer})
        for i in range(4):
            router.submit(Request(f'r{i}', [1], 2))
        router.run_until_idle()
        router.step()                # the drained fleet re-crosses the
        toggles = [event.engaged for event in seen   # low mark: released
                   if type(event).__name__ == 'Backpressure']
        assert toggles and toggles[0] is True and toggles[-1] is False
        assert any(type(event).__name__ == 'LoadShed' for event in seen)


# ---------------------------------------------------------------------------
# autoscale: grow on sustained backpressure, shrink on ebb
# ---------------------------------------------------------------------------


class TestAutoscale:

    def _fleet(self, clock, **policy):
        built, released = [], []

        def provision():
            replica = FakeReplica(f'grown{len(built)}', clock=clock)
            built.append(replica.identity)
            return ReplicaHandle(replica)

        router, handles, _ = fake_fleet(
            clock, n=1,
            router_knobs={'autoscale': AutoscalePolicy(**policy),
                          'provision': provision,
                          'release': released.append})
        return router, handles, built, released

    def test_sustained_backpressure_grows_then_ebb_shrinks(self):
        clock = FakeClock()
        from tpusystem.observe.events import FleetResized
        router, handles, built, released = self._fleet(
            clock, min_replicas=1, max_replicas=3, grow_after=2,
            shrink_after=3, cooldown=0)
        producer = Producer()
        seen = witness(producer, FleetResized)
        router.producer = producer
        # the replica's own watermark flag is the pressure signal
        handles[0].scheduler.backpressure = True
        router.step()
        assert not built             # one pressured tick: not yet
        router.step()
        assert built == ['grown0']   # sustained -> grow
        handles[0].scheduler.backpressure = False
        for _ in range(4):
            router.step()            # sustained idleness -> shrink back
        assert released and released[0].name == 'grown0'
        resizes = [(event.action, event.replicas, event.name)
                   for event in seen
                   if type(event).__name__ == 'FleetResized']
        assert resizes == [('grow', 2, 'grown0'), ('shrink', 1, 'grown0')]

    def test_grow_capped_and_cooldown_rate_limits(self):
        clock = FakeClock()
        router, handles, built, _ = self._fleet(
            clock, min_replicas=1, max_replicas=2, grow_after=1,
            shrink_after=1000, cooldown=5)
        handles[0].scheduler.backpressure = True
        router.step()
        assert built == ['grown0']   # grow_after=1: first pressured tick
        for _ in range(4):
            router.step()            # inside the cooldown window
        assert built == ['grown0']
        for _ in range(5):
            router.step()            # cooldown over — but at max_replicas
        assert built == ['grown0']
        assert len(router.healthy) == 2

    def test_orphans_count_as_pressure_and_grow_adopts_them(self):
        clock = FakeClock()
        router, handles, built, _ = self._fleet(
            clock, min_replicas=1, max_replicas=2, grow_after=1,
            shrink_after=1000, cooldown=0)
        router.submit(Request('a', [1], 5))
        router.step()                # seats 'a', journals 2 tokens
        handles[0].kill()
        router.step()                # verdict: 'a' orphaned, then the
        assert built == ['grown0']   # orphan reads as pressure -> grow
        results = router.run_until_idle()
        assert results['a'].tokens == expected_tokens('a', 5)


# ---------------------------------------------------------------------------
# the real-engine fleet chaos drill (the acceptance drill)
# ---------------------------------------------------------------------------


@pytest.fixture(scope='module')
def served():
    module = gpt2_tiny(dtype='float32')
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    return module, params


def real_fleet(module, params, clock, n=3, *, cadence=1, rows=2,
               trace=False, **engine_knobs):
    """N supervised replicas over REAL engines, each journaling into its
    own supervisor-RAM MemStore (what a SIGKILL leaves behind). With
    ``trace=True`` every replica and the router carry a Tracer on the
    shared clock; returns them as the 4th element (else Nones). Extra
    keywords (``share_prefix``, ``decode_impl``, ...) reach every
    replica's Engine."""
    from tpusystem.observe import Tracer
    stores = [MemStore() for _ in range(n)]
    handles = []
    tracers = []
    for i in range(n):
        tracer = Tracer(f'rep{i}', clock=clock) if trace else None
        tracers.append(tracer)

        def build(i=i, tracer=tracer):
            return Scheduler(Engine(module, params, rows=rows,
                                    block_size=8, **engine_knobs),
                             clock=clock, tracer=tracer)
        replica = ServingReplica(build, identity=f'rep{i}',
                                 client=stores[i], cadence=cadence,
                                 clock=clock)
        handles.append(ReplicaHandle(replica))
    router_tracer = Tracer('router', clock=clock) if trace else None
    router = Router(handles, clock=clock, tracer=router_tracer)
    return router, handles, stores, (router_tracer, tracers)


def mixed_workload(vocab=256, seed=7):
    rng = np.random.default_rng(seed)
    lengths = (5, 9, 7, 4, 11, 6, 8, 5, 10)
    budgets = (10, 8, 12, 6, 9, 11, 7, 12, 8)
    prompts = [rng.integers(0, vocab, (n,)).tolist() for n in lengths]
    return prompts, list(budgets)


def drive(router, wave, victims=(), max_steps=400):
    """Step the fleet to idle, firing the wave at its scripted tick;
    returns (hot, cold, placements) — whether both handoff flavors were
    seen, and each victim's placement counter AS OF its health verdict
    (so the caller can assert nothing was routed there during the
    drain that follows)."""
    saw_hot = saw_cold = False
    placements = {}
    for _ in range(max_steps):
        if router.idle:
            break
        wave(router.ticks + 1)
        tick = router.step()
        for handle in victims:
            if not handle.healthy and handle.name not in placements:
                placements[handle.name] = handle.placements
        for event in tick.rerouted:
            saw_hot |= event.where == 'hot'
            saw_cold |= event.where == 'cold'
    assert router.idle, 'fleet never drained after the wave'
    return saw_hot, saw_cold, placements


class TestFleetChaosDrill:

    def test_preemption_wave_mid_stream_token_exact(self, served, tmp_path):
        """THE acceptance drill: 3 replicas serving a mixed workload, a
        PreemptionWave kills one mid-stream; every journaled request
        completes token-exact vs the uninterrupted fleet (hot handoff
        for seated rows on a different engine, cold re-submit for
        queued), nothing silently dropped, and the router never routes
        to the dead replica after the verdict."""
        module, params = served
        prompts, budgets = mixed_workload()
        clock = FakeClock()

        # the uninterrupted single-fleet reference
        reference_router, _, _, _ = real_fleet(module, params, clock, n=3)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            reference_router.submit(Request(f'r{index}', prompt, budget))
        reference = reference_router.run_until_idle()

        router, handles, stores, (router_tracer, tracers) = real_fleet(
            module, params, clock, n=3, trace=True)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            router.submit(Request(f'r{index}', prompt, budget))
        # rep0 now holds 2 seated rows + 1 queued: the kill exercises
        # BOTH handoff flavors
        wave = PreemptionWave(step=2, kills=(handles[0].kill,))
        saw_hot, saw_cold, placements = drive(router, wave,
                                              victims=(handles[0],))
        assert wave.fired and not handles[0].healthy
        # no silent drops, and token-exact against the reference
        assert set(router.results) == set(reference)
        for rid, completion in router.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid
        assert saw_hot and saw_cold, (saw_hot, saw_cold)
        # placement counter frozen at the verdict: the whole drain that
        # followed routed NOTHING onto the dead replica
        assert handles[0].placements == placements['rep0']

        # the trace plane: merge every process's spans (dead replica's
        # included — a real pod recovers them from its supervisor RAM
        # over the blob plane) and the export is valid Chrome trace JSON
        # holding ONE connected trace per request: the replayed/rerouted
        # spans on the survivors parent to the ORIGINAL submission's
        # trace_id, zero orphan spans
        import json

        from tpusystem.observe.trace import connected_traces

        for tracer in tracers:
            router_tracer.merge(tracer)
        path = router_tracer.export(tmp_path / 'fleet-trace.json')
        payload = json.loads(path.read_text())
        events = [event for event in payload['traceEvents']
                  if event['ph'] in ('X', 'i')]
        processes = {event['pid']: event['args']['name']
                     for event in payload['traceEvents']
                     if event['ph'] == 'M'}
        by_trace = connected_traces(payload['traceEvents'])   # 0 orphans
        for index in range(len(prompts)):
            rid = f'r{index}'
            roots = [event for event in events
                     if event['name'] == f'request {rid}']
            assert len(roots) == 1, (rid, len(roots))   # ONE trace each
            group = by_trace[roots[0]['args']['trace_id']]
            owners = {event['args'].get('request') for event in group}
            assert owners <= {rid, None}, (rid, owners)
        # every hot handoff's trace crosses engines: spans on the dead
        # replica AND on a survivor, linked by the one trace_id
        crossed = [trace_id for trace_id, group in by_trace.items()
                   if len({processes[event['pid']] for event in group
                           if processes[event['pid']].startswith('rep')})
                   >= 2]
        assert crossed, 'no trace crossed engines after the handoff'

    def test_preemption_wave_with_sharing_and_fused_on(self, served):
        """The kill-a-replica drill with this PR's levers engaged:
        ``share_prefix=True`` + ``decode_impl='fused'`` on every
        replica, a shared-system-prompt workload, one replica killed
        mid-stream. Replayed/rerouted rows re-prefill prompt + emitted
        prefix through the radix tree (adopting whatever prefix the
        survivor already holds) and every completion is token-exact vs
        the uninterrupted fleet — the levers compose with journal
        replay, they don't fork it."""
        module, params = served
        rng = np.random.default_rng(83)
        head = rng.integers(0, 256, (12,)).tolist()
        prompts = [head + rng.integers(0, 256, (k,)).tolist()
                   for k in (5, 2, 4, 1, 3, 2, 5, 4, 3)]
        budgets = [10, 8, 12, 6, 9, 11, 7, 12, 8]
        clock = FakeClock()
        levers = dict(share_prefix=True, decode_impl='fused')

        reference_router, _, _, _ = real_fleet(module, params, clock, n=3,
                                               **levers)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            reference_router.submit(Request(f'r{index}', prompt, budget))
        reference = reference_router.run_until_idle()

        router, handles, _, _ = real_fleet(module, params, clock, n=3,
                                           **levers)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            router.submit(Request(f'r{index}', prompt, budget))
        wave = PreemptionWave(step=2, kills=(handles[0].kill,))
        saw_hot, saw_cold, _ = drive(router, wave, victims=(handles[0],))
        assert wave.fired and not handles[0].healthy
        assert saw_hot or saw_cold, 'the kill rerouted nothing'
        assert set(router.results) == set(reference)
        for rid, completion in router.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid
        # the prefix blocks were ACTUALLY shared on the survivors, not
        # just configured: the radix trees scored hits during the drain
        hits = sum(handle.scheduler.engine.sharing['prefix_hits']
                   for handle in handles[1:])
        assert hits > 0, 'no survivor adopted a shared prefix'

    @pytest.mark.slow
    def test_double_kill_wave_with_buddy_journal(self, served):
        """The heavy multi-replica kill drill (slow): a staggered wave
        takes TWO of three replicas; the second victim's local store is
        torn, so its rows come back through the buddy's replica copy —
        the cross-host chain — and everything still lands token-exact
        on the lone survivor."""
        module, params = served
        prompts, budgets = mixed_workload()
        clock = FakeClock()
        reference_router, _, _, _ = real_fleet(module, params, clock, n=3)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            reference_router.submit(Request(f'r{index}', prompt, budget))
        reference = reference_router.run_until_idle()

        router, handles, stores, _ = real_fleet(module, params, clock, n=3)
        # rep1's journal ALSO lands in a buddy store (the supervisor
        # replication rider's landing zone on a real pod)
        buddy = MemStore()
        handles[1].journal_clients = (stores[1], buddy)
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            router.submit(Request(f'r{index}', prompt, budget))

        def tear_and_kill():
            entry = stores[1].fetch('journal:rep1')
            if entry is not None:    # mirror, then tear the local copy
                buddy.put('journal:rep1', entry.step, entry.blob)
                stores[1]._slots[('journal:rep1', False)].blob = b'torn'
            handles[1].kill()

        wave = PreemptionWave(step=3,
                              kills=(handles[0].kill, tear_and_kill))
        _, _, placements = drive(router, wave, victims=handles[:2])
        assert [h.healthy for h in handles] == [False, False, True]
        assert handles[0].placements == placements['rep0']
        assert handles[1].placements == placements['rep1']
        assert set(router.results) == set(reference)
        for rid, completion in router.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid


# ---------------------------------------------------------------------------
# observability: the fleet events chart like everything else
# ---------------------------------------------------------------------------


def test_tensorboard_fleet_handlers_chart_the_events(tmp_path):
    from tests.tb import read_scalars
    from tpusystem.observe.events import (FleetResized, ReplicaUnhealthy,
                                          RequestRerouted)
    from tpusystem.observe.tensorboard import (SummaryWriter,
                                               tensorboard_consumer, writer)

    consumer = tensorboard_consumer()
    board = SummaryWriter(tmp_path)
    consumer.dependency_overrides[writer] = lambda: board
    consumer.consume(ReplicaUnhealthy(name='rep0', cause='died mid-step',
                                      routed=3))
    consumer.consume(RequestRerouted(id='a', origin='rep0', target='rep1',
                                     where='hot', prefix=4,
                                     cause='failover'))
    consumer.consume(FleetResized(action='grow', replicas=4,
                                  cause='backpressure', name='rep3'))
    board.flush()
    scalars = read_scalars(tmp_path)        # parsed back, not byte-poked
    assert scalars['fleet/unhealthy_total'] == (1.0, 1)
    assert scalars['fleet/rehomed_requests'] == (3.0, 1)
    assert scalars['fleet/rerouted_total'] == (1.0, 1)
    assert scalars['fleet/reroute_prefix'] == (4.0, 1)
    assert scalars['fleet/replicas'] == (4.0, 1)


def test_refused_submit_resets_the_trace_for_a_retry():
    """FleetSaturated's contract is retry-later: the refusal must close
    its root span truthfully AND unbind it from the request, so the
    retry roots a FRESH trace instead of parenting into a closed one."""
    from tpusystem.observe import Tracer

    clock = FakeClock()
    tracer = Tracer('router', clock=clock)
    router, handles, _ = fake_fleet(clock, n=1, max_queued=1,
                                    router_knobs={'tracer': tracer})
    assert router.submit(Request('r0', [1], 8)) == 'rep0'
    refused = Request('r1', [1], 8)
    with pytest.raises(FleetSaturated):
        router.submit(refused)
    assert refused.trace is None         # unbound for the retry
    refusal_roots = [e for e in tracer.events()
                     if e['name'] == 'request r1' and e['ph'] == 'X']
    assert refusal_roots[0]['args']['reason'] == 'refused'
    # backlog drains; the retry lands and roots a SECOND, fresh trace
    router.run_until_idle()
    assert router.submit(refused) == 'rep0'
    router.run_until_idle()
    roots = [e for e in tracer.events() if e['name'] == 'request r1']
    assert len(roots) == 2
    assert len({e['args']['trace_id'] for e in roots}) == 2


def test_invalid_submit_closes_the_trace_root_before_reraising():
    """A request that can never run (oversized budget) re-raises the
    scheduler's ValueError to the caller — but with a tracer attached
    the router must close its root span (reason 'invalid') and unbind
    request.trace, not leak an open phantom root per bad submission."""
    from tpusystem.observe import Tracer

    clock = FakeClock()
    tracer = Tracer('router', clock=clock)

    class Oversized(FakeScheduler):
        def submit(self, request):
            raise ValueError(f'{request.id!r}: exceeds engine capacity')

    replica = FakeReplica('rep0', clock=clock)
    replica.scheduler = Oversized(clock=clock)
    router = Router([ReplicaHandle(replica)], clock=clock, tracer=tracer)
    bad = Request('bad', [1], 10_000)
    with pytest.raises(ValueError, match='capacity'):
        router.submit(bad)
    assert bad.trace is None
    assert router._trace_roots == {}
    (root,) = [e for e in tracer.events() if e['ph'] == 'X']
    assert root['args']['reason'] == 'invalid'
    assert 'open' not in root['args']
