"""Disaggregated serving: TP-sharded engine steps, KV handoff over the
blob plane, role-aware fleet routing (tpusystem/serve/{engine,disagg,
scheduler,fleet}.py + parallel/schedule.decode_tp_plan).

Three layers of drill:

* **Sharded steps** — an engine built with ``mesh=MeshSpec(model=N)``
  GSPMD-shards its compiled prefill/decode programs and the paged pool
  over the virtual CPU mesh; decode is TOKEN-EXACT vs a single-device
  engine for BOTH served families (GPT-2 and Llama), with the
  ``trace_count`` witness proving the sharded step still compiles once.
* **KV handoff** — ``export_prefill`` on engine A seats token-exact on
  engine B through ``admit_prefilled`` (the ``adopt_prefill``/
  ``write_tables`` seam); the wire payload is digest-verified end to
  end (``pack_handoff``/``unpack_handoff``/:class:`KVStripStore`).
* **Role-aware fleet** — a prefill-role replica admits prompts, the
  router pumps finished strips to decode-role replicas (blob plane when
  both ends carry a transport), and the chaos drills kill each role
  mid-flight: every completion stays token-exact vs an uninterrupted
  colocated fleet, journal and trace surviving the role hop.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_serve_fleet import FakeClock, witness
from tpusystem.models import gpt2_tiny, llama_tiny
from tpusystem.observe import Tracer
from tpusystem.observe.trace import connected_traces
from tpusystem.parallel import MeshSpec, decode_tp_plan
from tpusystem.parallel.chaos import PreemptionWave
from tpusystem.parallel.multihost import Loopback
from tpusystem.checkpoint.memstore import MemStore
from tpusystem.serve import (Engine, HandoffCorrupt, KVHandoff, KVStripStore,
                             PagedKVCache, ReplicaHandle, Request, RoleMismatch,
                             Router, SamplingParams, Scheduler, ServingReplica,
                             engine_unsupported_reason, fetch_handoff,
                             kv_namespace, pack_handoff, pool_shardings,
                             unpack_handoff)
from tpusystem.services.prodcon import Producer
from tpusystem.train.decode_fused import (fused_paged_reason,
                                          fused_unsupported_reason)


def submesh(count=2, **axes):
    """A live mesh over the first ``count`` virtual devices — the
    engine takes a built Mesh as readily as a MeshSpec, and tier-1's
    8-device harness rarely wants all of them on one axis."""
    return MeshSpec(**axes).build(jax.devices()[:count])


@pytest.fixture(scope='module')
def gpt2():
    module = gpt2_tiny(dtype='float32')
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    return module, params


@pytest.fixture(scope='module')
def llama():
    module = llama_tiny(dtype='float32')
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(1), prompt)['params']
    return module, params


def drain(engine, steps=64):
    """Step until every row retires; returns id -> emitted tokens."""
    tokens: dict = {}
    for _ in range(steps):
        report = engine.step()
        for tag, new in report.emitted.items():
            tokens.setdefault(tag, []).extend(int(t) for t in new)
        if not engine.active_rows:
            break
    return tokens


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


class TestDecodeTpPlan:

    def test_no_mesh_is_single(self):
        plan = decode_tp_plan(None)
        assert (plan.path, plan.model) == ('single', 1)

    def test_model_axis_of_one_is_single(self):
        plan = decode_tp_plan(submesh(1, model=1))
        assert plan.path == 'single'

    def test_model_axis_shards_gspmd(self):
        plan = decode_tp_plan(submesh(2, model=2))
        assert (plan.path, plan.model) == ('gspmd', 2)

    def test_nontrivial_data_axis_is_typed_unsupported(self):
        plan = decode_tp_plan(submesh(2, data=2))
        assert plan.path == 'unsupported'
        assert "'model' axis only" in plan.reason

    def test_engine_raises_the_plan_reason(self, gpt2):
        module, params = gpt2
        with pytest.raises(ValueError, match="'model' axis only"):
            Engine(module, params, rows=2, block_size=8,
                   mesh=submesh(2, data=2))


# ---------------------------------------------------------------------------
# TP-sharded engine: token-exact for both served families
# ---------------------------------------------------------------------------


class TestShardedEngine:

    def _exact(self, module, params, *, rows=2, budget=6):
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 256, (n,)).tolist() for n in (5, 9)]

        single = Engine(module, params, rows=rows, block_size=8)
        sharded = Engine(module, params, rows=rows, block_size=8,
                         mesh=submesh(2, model=2))
        assert sharded.tp_plan.path == 'gspmd'
        assert sharded.decode_impl == 'flax'
        for engine in (single, sharded):
            for index, prompt in enumerate(prompts):
                engine.admit(prompt, budget, tag=f'r{index}')
        reference, tokens = drain(single), drain(sharded)
        assert tokens == reference
        # the compile-once witness survives sharding: ONE decode trace
        # on each engine, however many steps the drain took
        assert single.trace_count == 1
        assert sharded.trace_count == 1

    def test_gpt2_tp_decode_token_exact(self, gpt2):
        self._exact(*gpt2)

    def test_llama_tp_decode_token_exact(self, llama):
        self._exact(*llama)

    def test_pool_shardings_split_heads_replicate_tables(self, gpt2):
        module, params = gpt2
        engine = Engine(module, params, rows=2, block_size=8)
        mesh = submesh(2, model=2)
        specs = pool_shardings(engine._cache, mesh)
        leaves = jax.tree_util.tree_leaves_with_path(specs)
        kv = [s for path, s in leaves
              if path[-1] in (jax.tree_util.DictKey('key'),
                              jax.tree_util.DictKey('value'))]
        rest = [s for path, s in leaves
                if path[-1] not in (jax.tree_util.DictKey('key'),
                                    jax.tree_util.DictKey('value'))]
        assert kv and all('model' in str(s.spec) for s in kv)
        assert rest and all(s.spec == jax.sharding.PartitionSpec()
                            for s in rest)

    def test_speculative_rows_refuse_the_mesh(self, gpt2):
        module, params = gpt2
        with pytest.raises(ValueError, match='speculative rows'):
            Engine(module, params, rows=2, block_size=8,
                   mesh=submesh(2, model=2), draft_module=module,
                   draft_params=params, speculate=2)


# ---------------------------------------------------------------------------
# satellite: the capability-gate reason matrix (docs/serving.md)
# ---------------------------------------------------------------------------


class TestReasonMatrix:
    """Every gate's reason string must match what docs/serving.md
    documents — the matrix rows below are the documented phrases, so a
    reworded gate fails here until the docs move with it."""

    def test_engine_serves_both_families_and_moe(self, gpt2, llama):
        assert engine_unsupported_reason(gpt2[0]) is None
        assert engine_unsupported_reason(llama[0]) is None
        assert engine_unsupported_reason(
            gpt2_tiny(moe_experts=4, dtype='float32')) is None

    def test_engine_gate_names_the_family_conventions(self):
        from tpusystem.models import MLP
        reason = engine_unsupported_reason(MLP(features=(8, 8)))
        assert 'family decode conventions' in reason
        reason = engine_unsupported_reason(gpt2_tiny(scan_layers=True))
        assert 'unrolled' in reason

    def test_fused_paged_gate_under_tp_names_the_fallback(self, gpt2):
        import dataclasses
        decoder = dataclasses.replace(gpt2[0], mesh=submesh(2, model=2))
        reason = fused_paged_reason(decoder)
        assert 'no ring arms' in reason
        assert 'sharded flax' in reason and 'token-exact' in reason
        # and an auto engine under the mesh actually takes that fallback
        engine = Engine(gpt2[0], gpt2[1], rows=2, block_size=8,
                        mesh=submesh(2, model=2), decode_impl='auto')
        assert engine.decode_impl == 'flax'

    def test_fused_paged_gate_matrix(self, gpt2, llama):
        paged = gpt2_tiny(decode_pages=(16, 8))      # dense GPT-2 runs
        assert fused_paged_reason(paged) is None
        assert 'GPT2 family only' in fused_paged_reason(llama[0])
        moe = fused_paged_reason(gpt2_tiny(moe_experts=4))
        assert 'flax paged step serves MoE' in moe
        assert 'full-capacity' in moe
        assert 'leading layer dim' in fused_paged_reason(
            gpt2_tiny(scan_layers=True))

    def test_fused_generate_gate_points_at_the_paged_step(self):
        assert 'flax paged step serves MoE' in fused_unsupported_reason(
            gpt2_tiny(moe_experts=4))
        assert 'build_fused_paged_step' in fused_unsupported_reason(
            gpt2_tiny(per_row_decode=True))

    def test_tp_mesh_rejection_reason_is_the_planner_text(self, gpt2):
        with pytest.raises(ValueError) as err:
            Engine(gpt2[0], gpt2[1], rows=2, block_size=8,
                   mesh=submesh(2, data=2))
        assert decode_tp_plan(
            submesh(2, data=2)).reason in str(err.value)


# ---------------------------------------------------------------------------
# the handoff payload + wire
# ---------------------------------------------------------------------------


class TestHandoffWire:

    def _handoff(self):
        return KVHandoff(request=Request('a', [1, 2, 3], 4), first=7,
                         kv={'k': np.arange(6, dtype=np.float32)},
                         prefix=[9], waited=1.5)

    def test_pack_unpack_roundtrip(self):
        received = unpack_handoff(pack_handoff(self._handoff()))
        assert received.request.id == 'a'
        assert (received.first, received.prefix,
                received.waited) == (7, [9], 1.5)
        np.testing.assert_array_equal(received.kv['k'], np.arange(6))

    def test_corrupt_payload_is_typed(self):
        data = bytearray(pack_handoff(self._handoff()))
        data[-1] ^= 0xFF
        with pytest.raises(HandoffCorrupt, match='digest'):
            unpack_handoff(bytes(data))
        with pytest.raises(HandoffCorrupt):
            unpack_handoff(data[: len(data) // 2])

    def test_wrong_object_is_typed(self):
        import pickle

        from tpusystem.parallel.multihost import _blob_digest
        payload = pickle.dumps({'not': 'a handoff'})
        framed = _blob_digest(payload).encode('ascii') + b':' + payload
        with pytest.raises(HandoffCorrupt, match='not KVHandoff'):
            unpack_handoff(framed)

    def test_strip_store_offers_answers_releases(self):
        wire = Loopback()
        store = KVStripStore()
        store.attach(wire)
        store.offer('a', b'payload')
        assert wire.fetch_blob(0, kv_namespace('a')) == b'payload'
        assert len(store) == 1
        store.release('a')
        assert len(store) == 0

    def test_strip_store_chains_the_prior_hook(self):
        wire = Loopback()
        wire.on_blob_request = lambda key: b'prior' if key == 'x' else None
        store = KVStripStore()
        store.attach(wire)
        store.offer('a', b'strip')
        assert wire.on_blob_request(kv_namespace('a')) == b'strip'
        assert wire.on_blob_request('x') == b'prior'  # falls through

    def test_fetch_handoff_verifies_end_to_end(self):
        wire = Loopback()
        store = KVStripStore()
        store.attach(wire)
        store.offer('a', pack_handoff(self._handoff()))
        received = fetch_handoff(wire, 0, 'a')
        assert received.request.id == 'a'
        corrupt = bytearray(pack_handoff(self._handoff()))
        corrupt[-1] ^= 0xFF
        store.offer('b', bytes(corrupt))
        with pytest.raises(HandoffCorrupt):
            fetch_handoff(wire, 0, 'b')


# ---------------------------------------------------------------------------
# export_prefill -> admit_prefilled: the engine seam
# ---------------------------------------------------------------------------


class TestExportAdmit:

    def test_prefill_on_a_decodes_on_b_token_exact(self, gpt2):
        module, params = gpt2
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 256, (n,)).tolist() for n in (5, 9)]

        colocated = Engine(module, params, rows=2, block_size=8)
        for index, prompt in enumerate(prompts):
            colocated.admit(prompt, 6, tag=f'r{index}')
        reference = drain(colocated)

        prefiller = Engine(module, params, rows=2, block_size=8)
        decoder = Engine(module, params, rows=2, block_size=8)
        for index, prompt in enumerate(prompts):
            first, kv = prefiller.export_prefill(prompt)
            # the strips cross a (simulated) wire digest-verified
            received = unpack_handoff(pack_handoff(KVHandoff(
                request=Request(f'r{index}', prompt, 6), first=first,
                kv=kv)))
            decoder.admit_prefilled(prompt, 6, received.first, received.kv,
                                    tag=f'r{index}')
        # export seats nothing on the prefill engine
        assert prefiller.active_rows == 0 and prefiller.pool.live_blocks == 0
        assert drain(decoder) == reference

    def test_export_validates_the_prompt(self, gpt2):
        module, params = gpt2
        engine = Engine(module, params, rows=2, block_size=8)
        with pytest.raises(ValueError, match='empty'):
            engine.export_prefill([])
        with pytest.raises(ValueError, match='decode room'):
            engine.export_prefill(list(range(module.max_seq)))

    def test_geometry_mismatch_is_caught_before_seating(self, gpt2):
        module, params = gpt2
        engine = Engine(module, params, rows=2, block_size=8)
        first, kv = engine.export_prefill([1, 2, 3])
        missing = dict(kv)
        missing.pop(sorted(missing)[0])
        with pytest.raises(ValueError, match='missing KV leaf'):
            engine.admit_prefilled([1, 2, 3], 4, first, missing)
        short = {name: strip[:, :-8] for name, strip in kv.items()}
        with pytest.raises(ValueError, match='same module geometry'):
            engine.admit_prefilled([1, 2, 3], 4, first, short)
        assert engine.active_rows == 0     # nothing half-seated

    def test_adopted_strips_share_prefix_blocks(self, gpt2):
        """Strip adoptions run through the radix index exactly like
        local admissions: the second adopted request with the same head
        scores a prefix hit and shares blocks."""
        module, params = gpt2
        source = Engine(module, params, rows=2, block_size=8)
        engine = Engine(module, params, rows=4, block_size=8,
                        share_prefix=True)
        head = list(range(1, 17))
        for index, tail in enumerate(([21, 22], [23, 24])):
            first, kv = source.export_prefill(head + tail)
            engine.admit_prefilled(head + tail, 4, first, kv,
                                   tag=f'r{index}')
        assert engine.sharing['prefix_hits'] >= 1
        assert engine.sharing['shared_tokens'] >= 16
        engine.pool.audit()


# ---------------------------------------------------------------------------
# satellite: pool audit under adopted-strip churn at refcount boundaries
# ---------------------------------------------------------------------------


class TestAuditUnderAdoptChurn:

    def test_audit_across_adopt_free_churn(self, gpt2):
        """Seat/evict adopted strips through the shared radix pool in a
        pattern that walks refcounts through every boundary (0 -> 1 ->
        2 -> 1 -> 0 -> warm -> re-owned), auditing after every
        transition — adoption must leave the pool indistinguishable
        from local admission."""
        module, params = gpt2
        source = Engine(module, params, rows=2, block_size=8)
        engine = Engine(module, params, rows=4, block_size=8,
                        share_prefix=True)
        head = list(range(1, 17))            # two full shared blocks

        def seat(tag, tail):
            first, kv = source.export_prefill(head + tail)
            return engine.admit_prefilled(head + tail, 3, first, kv,
                                          tag=tag)
        a = seat('a', [31, 32])              # refs 0 -> 1
        engine.pool.audit()
        b = seat('b', [33, 34])              # refs 1 -> 2 (shared head)
        engine.pool.audit()
        engine.evict(a.row)            # refs 2 -> 1: b still owns
        audit = engine.pool.audit()
        assert audit['live'] > 0
        c = seat('c', [35, 36])              # re-share while b holds
        engine.pool.audit()
        engine.evict(b.row)
        engine.evict(c.row)            # refs -> 0: head goes WARM
        audit = engine.pool.audit()
        assert audit['cached'] > 0, 'shared head should park warm'
        d = seat('d', [37, 38])              # warm -> re-owned
        engine.pool.audit()
        assert engine.sharing['prefix_hits'] >= 3
        engine.evict(d.row)
        final = engine.pool.audit()
        assert final['live'] == 0

    def test_audit_interleaved_local_and_adopted(self, gpt2):
        """Local admissions and adopted strips interleave over ONE pool
        (the colocated 'both' role under partial disaggregation) —
        audit holds at every step and eviction order doesn't matter."""
        module, params = gpt2
        source = Engine(module, params, rows=2, block_size=8)
        engine = Engine(module, params, rows=4, block_size=8,
                        share_prefix=True)
        head = list(range(40, 56))
        local = engine.admit(head + [1], 3, tag='local')
        engine.pool.audit()
        first, kv = source.export_prefill(head + [2])
        adopted = engine.admit_prefilled(head + [2], 3, first, kv,
                                         tag='adopted')
        engine.pool.audit()
        assert engine.sharing['prefix_hits'] >= 1
        engine.evict(local.row)        # the ORIGINAL owner first
        engine.pool.audit()
        engine.evict(adopted.row)
        assert engine.pool.audit()['live'] == 0


# ---------------------------------------------------------------------------
# the role-aware fleet
# ---------------------------------------------------------------------------


def role_fleet(module, params, clock, roles, *, wire=None, tracer=False,
               producer=None, rows=2, clients=None, **engine_knobs):
    """One replica per role string; a shared Loopback ``wire`` puts the
    handoffs on the blob plane; ``clients`` gives each replica a journal
    store that outlives a kill (the supervisor-RAM analogue). Returns
    (router, handles, tracers)."""
    handles, tracers = [], []
    for index, role in enumerate(roles):
        t = Tracer(f'rep{index}', clock=clock) if tracer else None
        tracers.append(t)

        def build(role=role, t=t):
            return Scheduler(
                Engine(module, params, rows=rows, block_size=8,
                       **engine_knobs),
                clock=clock, tracer=t, prefill_only=(role == 'prefill'))
        replica = ServingReplica(build, identity=f'rep{index}',
                                 clock=clock, role=role,
                                 client=clients[index] if clients else None)
        handles.append(ReplicaHandle(replica, transport=wire, rank=0))
    router_tracer = Tracer('router', clock=clock) if tracer else None
    router = Router(handles, clock=clock, tracer=router_tracer,
                    producer=producer)
    return router, handles, (router_tracer, tracers)


def reference_results(module, params, clock, requests, **engine_knobs):
    def build():
        return Scheduler(Engine(module, params, rows=2, block_size=8,
                                **engine_knobs), clock=clock)
    router = Router([ReplicaHandle(ServingReplica(build, identity='colo',
                                                  clock=clock))],
                    clock=clock)
    for rid, prompt, budget in requests:
        router.submit(Request(rid, list(prompt), budget))
    return router.run_until_idle()


def mixed_requests(seed=7, n=6):
    rng = np.random.default_rng(seed)
    lengths = (5, 9, 7, 4, 11, 6, 8, 5, 10)[:n]
    budgets = (8, 6, 9, 5, 7, 8, 6, 9, 7)[:n]
    return [(f'r{i}', rng.integers(0, 256, (k,)).tolist(), b)
            for i, (k, b) in enumerate(zip(lengths, budgets))]


def sampled_specs(seed=13, n=5):
    """Mixed greedy + seeded-sampled prompts sharing a system-prompt
    head, so ``share_prefix=True`` radix hits ride the drill too."""
    rng = np.random.default_rng(seed)
    head = rng.integers(0, 256, (10,)).tolist()
    specs = []
    for i, k in enumerate((3, 5, 2, 4, 6)[:n]):
        tail = rng.integers(0, 256, (k,)).tolist()
        sampling = (dict(temperature=0.7, seed=300 + i, top_k=16)
                    if i % 2 == 0 else None)
        specs.append((f's{i}', head + tail, 5 + (i % 3), sampling))
    return specs


def sampled_requests(specs):
    """Fresh Request objects from specs (one set per fleet — requests
    must never be shared between the reference and the chaos run)."""
    return [Request(rid, list(prompt), budget,
                    sampling=None if sampling is None
                    else SamplingParams(**sampling))
            for rid, prompt, budget, sampling in specs]


def sampled_reference(module, params, clock, specs):
    def build():
        return Scheduler(Engine(module, params, rows=2, block_size=8,
                                share_prefix=True), clock=clock)
    router = Router([ReplicaHandle(ServingReplica(build, identity='colo',
                                                  clock=clock))],
                    clock=clock)
    for request in sampled_requests(specs):
        router.submit(request)
    return router.run_until_idle()


class TestRoleFleet:

    def test_prefill_only_scheduler_refuses_hot_restores(self, gpt2):
        module, params = gpt2
        clock = FakeClock()
        scheduler = Scheduler(Engine(module, params, rows=2, block_size=8),
                              clock=clock, prefill_only=True)
        with pytest.raises(RoleMismatch):
            scheduler.restore(Request('a', [1, 2], 4), waited=1.0,
                              prefix=[5])
        assert not isinstance(RoleMismatch('x'), ValueError)

    def test_role_and_scheduler_contract_must_agree(self, gpt2):
        module, params = gpt2
        clock = FakeClock()
        with pytest.raises(ValueError, match='must agree'):
            ServingReplica(
                lambda: Scheduler(Engine(module, params, rows=2,
                                         block_size=8), clock=clock),
                identity='bad', clock=clock, role='prefill')

    def test_disagg_fleet_token_exact_over_blob_plane(self, gpt2):
        """The acceptance path: prompts admitted on the prefill replica,
        KV strips shipped over the (digest-verified) blob plane, every
        request decoded on a decode replica — token-exact vs colocated,
        strips released on ack, and the move narrated as
        ``PrefillHandoff`` with real byte weights."""
        from tpusystem.observe.events import PrefillHandoff
        module, params = gpt2
        requests = mixed_requests()
        clock = FakeClock()
        reference = reference_results(module, params, clock, requests)

        wire = Loopback()
        producer = Producer()
        router, handles, _ = role_fleet(
            module, params, clock, ('prefill', 'decode', 'decode'),
            wire=wire, producer=producer)
        seen = witness(producer, PrefillHandoff)
        for rid, prompt, budget in requests:
            assert router.submit(Request(rid, list(prompt), budget)) \
                == 'rep0'            # every prompt lands on the prefill tier
        moved = []
        for _ in range(400):
            if router.idle:
                break
            moved.extend(router.step().handoffs)
        assert router.idle
        assert sorted(moved) == sorted(rid for rid, _, _ in requests)
        assert set(router.results) == set(reference)
        for rid, completion in router.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid
        # narration carries the wire weight; the outbox store drained
        assert len(seen) == len(requests)
        assert all(event.origin == 'rep0' and event.bytes > 0
                   and event.target in ('rep1', 'rep2') for event in seen)
        assert handles[0].strips is not None and len(handles[0].strips) == 0
        # the prefill engine never seated a decode row
        assert handles[0].scheduler.engine.active_rows == 0

    def test_corrupt_handoff_falls_back_to_cold_prefill(self, gpt2):
        """A payload torn on the wire must NOT seat: the router re-
        places the request cold on the decode tier and the completion
        is still token-exact."""
        module, params = gpt2
        requests = mixed_requests(n=2)
        clock = FakeClock()
        reference = reference_results(module, params, clock, requests)
        wire = Loopback()
        router, handles, _ = role_fleet(
            module, params, clock, ('prefill', 'decode'), wire=wire)
        original = wire.fetch_blob

        def torn(peer, key, timeout=30.0):
            data = bytearray(original(peer, key, timeout))
            data[-1] ^= 0xFF
            return bytes(data)
        wire.fetch_blob = torn
        for rid, prompt, budget in requests:
            router.submit(Request(rid, list(prompt), budget))
        results = router.run_until_idle()
        for rid, _, _ in requests:
            assert results[rid].tokens == reference[rid].tokens, rid

    def test_handoff_parks_until_a_decode_replica_exists(self, gpt2):
        """No healthy decode target: the strip parks in the undelivered
        queue (the fleet is NOT idle) and delivers the moment a decode
        replica is adopted — no silent drop."""
        module, params = gpt2
        clock = FakeClock()
        router, handles, _ = role_fleet(module, params, clock, ('prefill',))
        router.submit(Request('a', [1, 2, 3, 4], 5))
        for _ in range(5):
            router.step()
        assert not router.idle and len(router._undelivered) == 1

        def build():
            return Scheduler(Engine(module, params, rows=2, block_size=8),
                             clock=clock)
        router.adopt(ReplicaHandle(
            ServingReplica(build, identity='late', clock=clock,
                           role='decode')))
        results = router.run_until_idle()
        reference = reference_results(module, params, clock,
                                      [('a', [1, 2, 3, 4], 5)])
        assert results['a'].tokens == reference['a'].tokens

    def test_sharing_counters_and_trace_parentage_survive_the_role_hop(
            self, gpt2, tmp_path):
        """Satellite drill: requests sharing a system prompt hop from
        the prefill replica to a decode replica — the decode-side radix
        pool scores the prefix hits (sharing works through adopted
        strips), and the merged trace export holds ONE connected trace
        per request whose spans cross both replicas (queued/handoff on
        the prefill process, seated/decode on the decode process), zero
        orphans."""
        module, params = gpt2
        rng = np.random.default_rng(19)
        head = rng.integers(0, 256, (12,)).tolist()
        requests = [(f'r{i}', head + rng.integers(0, 256, (k,)).tolist(), 5)
                    for i, k in enumerate((3, 2, 4))]
        clock = FakeClock()
        reference = reference_results(module, params, clock, requests,
                                      share_prefix=True)
        router, handles, (router_tracer, tracers) = role_fleet(
            module, params, clock, ('prefill', 'decode'),
            tracer=True, share_prefix=True)
        for rid, prompt, budget in requests:
            router.submit(Request(rid, list(prompt), budget))
        results = router.run_until_idle()
        for rid, _, _ in requests:
            assert results[rid].tokens == reference[rid].tokens, rid
        decode_engine = handles[1].scheduler.engine
        assert decode_engine.sharing['prefix_hits'] >= 2
        assert decode_engine.sharing['shared_tokens'] > 0

        for tracer in tracers:
            router_tracer.merge(tracer)
        payload = json.loads(
            router_tracer.export(tmp_path / 'disagg.json').read_text())
        by_trace = connected_traces(payload['traceEvents'])    # 0 orphans
        events = [e for e in payload['traceEvents'] if e['ph'] in ('X', 'i')]
        processes = {e['pid']: e['args']['name']
                     for e in payload['traceEvents'] if e['ph'] == 'M'}
        for rid, _, _ in requests:
            roots = [e for e in events if e['name'] == f'request {rid}']
            assert len(roots) == 1, rid              # ONE trace per request
            group = by_trace[roots[0]['args']['trace_id']]
            crossed = {processes[e['pid']] for e in group
                       if processes[e['pid']].startswith('rep')}
            assert crossed == {'rep0', 'rep1'}, (rid, crossed)
            names = {e['name'] for e in group}
            assert 'handoff' in names, (rid, names)


class TestRoleChaosDrill:

    def test_kill_prefill_mid_transfer_token_exact(self, gpt2):
        """SIGKILL the prefill replica while strips are queued/ready to
        ship: journal recovery re-homes its rows onto the second
        prefill replica (cold), nothing is dropped or double-decoded,
        and every completion is token-exact vs an uninterrupted
        colocated fleet."""
        module, params = gpt2
        requests = mixed_requests(n=6)
        clock = FakeClock()
        reference = reference_results(module, params, clock, requests)
        router, handles, _ = role_fleet(
            module, params, clock, ('prefill', 'prefill', 'decode'))
        for rid, prompt, budget in requests:
            router.submit(Request(rid, list(prompt), budget))
        wave = PreemptionWave(step=2, kills=(handles[0].kill,))
        for _ in range(400):
            if router.idle:
                break
            wave(router.ticks + 1)
            router.step()
        assert router.idle and wave.fired and not handles[0].healthy
        assert set(router.results) == set(reference)
        for rid, completion in router.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid

    def test_kill_decode_mid_stream_token_exact(self, gpt2):
        """SIGKILL a decode replica mid-decode: seated rows re-home HOT
        (prompt + emitted prefix replayed on the surviving decode
        replica — never onto the prefill tier), and every completion is
        token-exact vs uninterrupted."""
        module, params = gpt2
        requests = mixed_requests(n=6)
        clock = FakeClock()
        reference = reference_results(module, params, clock, requests)
        router, handles, _ = role_fleet(
            module, params, clock, ('prefill', 'decode', 'decode'))
        for rid, prompt, budget in requests:
            router.submit(Request(rid, list(prompt), budget))
        victim = handles[1]
        wave = PreemptionWave(step=4, kills=(victim.kill,))
        placements = {}
        for _ in range(400):
            if router.idle:
                break
            wave(router.ticks + 1)
            router.step()
            if not victim.healthy and 'v' not in placements:
                placements['v'] = victim.placements
            if not handles[0].healthy:
                raise AssertionError('prefill replica must survive')
        assert router.idle and wave.fired and not victim.healthy
        assert victim.placements == placements['v']  # never routed again
        assert set(router.results) == set(reference)
        for rid, completion in router.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid
        # hot rows landed on the decode survivor, not the prefill tier
        assert handles[0].scheduler.engine.active_rows == 0

    def test_kill_prefill_between_export_and_ship_token_exact(self, gpt2):
        """The undrilled window: the prefill replica dies AFTER
        ``export_prefill`` filled its outbox but BEFORE the router
        shipped a single strip. No prefill survivor exists, so every
        row re-prefills cold on the decode tier (the colocated degrade
        — role is placement policy, not capability), token-exact with
        ``share_prefix=True`` + seeded sampling in the pot."""
        from tpusystem.observe.events import RequestRerouted
        module, params = gpt2
        specs = sampled_specs()
        clock = FakeClock()
        reference = sampled_reference(module, params, clock, specs)
        producer = Producer()
        reroutes = witness(producer, RequestRerouted)
        router, handles, _ = role_fleet(
            module, params, clock, ('prefill', 'decode', 'decode'),
            producer=producer, share_prefix=True,
            clients=[MemStore() for _ in range(3)])
        for request in sampled_requests(specs):
            assert router.submit(request) == 'rep0'
        # drive the prefill replica's own loop WITHOUT the router pump:
        # strips are exported into the outbox but never shipped
        for _ in range(8):
            if handles[0].replica.scheduler.outbox:
                break
            handles[0].replica.step()
        exported = [handoff.request.id
                    for handoff in handles[0].replica.scheduler.outbox]
        assert exported, 'prefill never exported a strip'
        handles[0].kill()
        results = router.run_until_idle()
        assert not handles[0].healthy
        assert set(results) == {rid for rid, _, _, _ in specs}
        for rid, completion in results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid
        # every row (exported-but-unshipped AND still-queued) re-homed
        # cold onto a decode replica — never silently dropped
        moved = {event.id: event for event in reroutes}
        assert set(moved) == set(results)
        assert all(event.where == 'cold'
                   and event.target in ('rep1', 'rep2')
                   for event in moved.values())

    def test_kill_decode_holding_seated_handoffs_token_exact(self, gpt2):
        """The other undrilled window: a decode replica dies HOLDING
        rows it seated from shipped KV strips. Its journal (in the
        supervisor-RAM store the kill leaves behind) replays the rows
        HOT onto the decode survivor — emitted prefixes re-prefilled,
        never routed to the prefill tier — token-exact with
        ``share_prefix=True`` + seeded sampling."""
        from tpusystem.observe.events import RequestRerouted
        module, params = gpt2
        specs = sampled_specs(seed=17)
        clock = FakeClock()
        reference = sampled_reference(module, params, clock, specs)
        producer = Producer()
        reroutes = witness(producer, RequestRerouted)
        router, handles, _ = role_fleet(
            module, params, clock, ('prefill', 'decode', 'decode'),
            producer=producer, share_prefix=True,
            clients=[MemStore() for _ in range(3)])
        for request in sampled_requests(specs):
            router.submit(request)
        victim, shipped = None, []
        for _ in range(400):
            if router.idle:
                break
            shipped.extend(router.step().handoffs)
            if victim is None and shipped:
                seated = [handle for handle in handles[1:]
                          if handle.healthy
                          and handle.scheduler.engine.active_rows > 0]
                if seated:           # a decode replica holds seated rows
                    victim = seated[0]
                    victim.kill()
        assert router.idle and victim is not None and not victim.healthy
        assert handles[0].healthy, 'prefill replica must survive'
        assert set(router.results) == {rid for rid, _, _, _ in specs}
        for rid, completion in router.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid
        # the journal replayed the seated rows hot, onto the survivor
        # (still-queued cold rows may re-enter via the prefill front
        # door instead — that is the role-aware cold rung, not a leak)
        from_victim = [event for event in reroutes
                       if event.origin == victim.name]
        assert from_victim, 'the dead decode replica held no rows?'
        survivor = ({'rep1', 'rep2'} - {victim.name}).pop()
        hot = [event for event in from_victim if event.where == 'hot']
        assert hot, 'no seated row re-homed hot'
        assert all(event.target == survivor for event in hot)
        # the prefill engine never seated a decode row
        assert handles[0].scheduler.engine.active_rows == 0


class TestRoleAutoscale:

    def _provisioned(self, module, params, clock):
        built = []

        def provision(role='decode'):
            index = len(built)

            def build(role=role):
                return Scheduler(
                    Engine(module, params, rows=2, block_size=8),
                    clock=clock, prefill_only=(role == 'prefill'))
            replica = ServingReplica(build, identity=f'grown{index}',
                                     clock=clock, role=role)
            built.append(role)
            return ReplicaHandle(replica)
        return built, provision

    def test_breathe_grows_the_decode_tier_for_parked_handoffs(self, gpt2):
        """Undelivered handoffs are decode-tier pressure: the autoscaler
        provisions a DECODE replica (rebalancing the prefill:decode
        ratio) and the parked strip seats on it."""
        from tpusystem.serve import AutoscalePolicy
        module, params = gpt2
        clock = FakeClock()
        built = []
        router, handles, _ = role_fleet(module, params, clock, ('prefill',))
        built, provision = self._provisioned(module, params, clock)
        router.autoscale = AutoscalePolicy(min_replicas=1, max_replicas=3,
                                           grow_after=1, shrink_after=10_000,
                                           cooldown=0)
        router._provision = provision
        router.submit(Request('a', [1, 2, 3, 4], 5))
        results = router.run_until_idle()
        assert built and built[0] == 'decode'
        reference = reference_results(module, params, clock,
                                      [('a', [1, 2, 3, 4], 5)])
        assert results['a'].tokens == reference['a'].tokens

    def test_shrink_never_empties_a_tier(self, gpt2):
        """An idle split fleet shrinks, but never below one replica per
        tier — a fleet with prompts and no prefill tier (or strips and
        no decode tier) deadlocks until the next grow."""
        from tpusystem.serve import AutoscalePolicy
        module, params = gpt2
        clock = FakeClock()
        router, handles, _ = role_fleet(
            module, params, clock, ('prefill', 'decode'))
        router.autoscale = AutoscalePolicy(min_replicas=1, max_replicas=4,
                                           grow_after=10_000, shrink_after=1,
                                           cooldown=0)
        router._provision = lambda: None
        for _ in range(20):
            router.step()
        assert {handle.role for handle in router.healthy} \
            == {'prefill', 'decode'}
