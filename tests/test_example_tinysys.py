"""Integration test: the tinysys example app end to end, twice (resume).

The reference's example is its real test of the architecture; here the
whole composition root runs in-process — compiler pipeline, service
handlers, event consumers, document storage, async checkpointing — then
runs *again* to pin resume-by-identity (SURVEY.md §3.5).
"""

from __future__ import annotations

import importlib.util
import pathlib
import sys

import pytest

EXAMPLE = pathlib.Path(__file__).parent.parent / 'examples' / 'tinysys'


@pytest.fixture()
def tinysys_main(tmp_path, monkeypatch):
    monkeypatch.syspath_prepend(str(EXAMPLE))
    spec = importlib.util.spec_from_file_location('tinysys_main', EXAMPLE / 'main.py')
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    monkeypatch.setattr(module, 'ROOT', tmp_path)
    return module


@pytest.mark.slow
def test_trains_tracks_and_resumes(tinysys_main, capsys):
    tinysys_main.main(epochs=2)
    out = capsys.readouterr().out
    assert 'from epoch 0' in out

    store_path = tinysys_main.ROOT / 'experiments.json'
    assert store_path.exists()

    from tpusystem.storage import (DocumentMetrics, DocumentModels,
                                   DocumentStore)
    store = DocumentStore(store_path)
    models = DocumentModels(store).list('default')
    assert len(models) == 1 and models[0].epoch == 2
    rows = DocumentMetrics(store).list(models[0].hash)
    assert {row.name for row in rows} == {'loss', 'accuracy'}
    assert any(row.phase == 'evaluation' for row in rows)

    checkpoints = list((tinysys_main.ROOT / 'weights').iterdir())
    assert len(checkpoints) == 1  # one identity directory

    # --- second run resumes at the stored epoch, trains the remainder -----
    tinysys_main.main(epochs=3)
    out = capsys.readouterr().out
    assert 'from epoch 2' in out
    store = DocumentStore(store_path)
    models = DocumentModels(store).list('default')
    assert models[0].epoch == 3


def test_early_stop_epoch_still_dispatches_iterated(monkeypatch):
    """The epoch edge may unwind an early-stop exception; the Iterated event
    (store-row advance + checkpoint) must go out regardless — the stopping
    epoch is the one most worth keeping."""
    import types
    monkeypatch.syspath_prepend(str(EXAMPLE))
    from tinysys.services import training
    from tpusystem.observe.events import Iterated

    class StopModel:
        id = 'stop-model'

        def __init__(self):
            object.__setattr__(self, 'epoch', 0)
            object.__setattr__(self, 'phase', None)

        def shard_batch(self, batch):
            return batch

        def fit(self, inputs, targets):
            return targets, 0.0

        def evaluate(self, inputs, targets):
            return targets, 0.0

        def __setattr__(self, key, value):
            object.__setattr__(self, key, value)
            if key == 'epoch' and value > 0:
                raise StopIteration   # the aggregate's commit() unwinding

    class Metrics:
        def update(self, *parts):
            pass

        def compute(self):
            return {}

        def reset(self):
            pass

    events = []
    monkeypatch.setattr(training, 'producer',
                        types.SimpleNamespace(dispatch=events.append))
    model = StopModel()
    loaders = {'train': [((0,), (0,))], 'evaluation': [((0,), (0,))]}
    with pytest.raises(StopIteration):
        training.iterate(model, loaders, Metrics())
    assert model.epoch == 1
    assert any(isinstance(event, Iterated) for event in events)
