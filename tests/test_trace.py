"""The trace plane (observe/trace.py), the metric plane
(observe/metrics.py), and the flight recorder (observe/flight.py).

Test discipline mirrors the serving-fleet policy tests: fake clocks,
fake engines with the real surface, zero real sleeps, zero compiles —
the REAL-engine trace drill lives in tests/test_serve_fleet.py's chaos
acceptance test, which exports and validates a whole-fleet Chrome
trace. Histogram percentiles are pinned against a literal sorted-array
reference; merge-order invariance is pinned by merging shards in every
permutation. The flight-recorder SIGKILL contract is drilled with a
real subprocess (write-ahead cadence = what survives a kill that runs
no handler)."""

import dataclasses
import json
import os
import pathlib
import pickle
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusystem.observe import (FlightRecorder, Histogram, ServeLatency,
                               TraceContext, Tracer, serve_metrics_consumer)
from tpusystem.observe.flight import dump_installed
from tpusystem.parallel.multihost import Loopback
from tpusystem.serve import Request, Scheduler
from tpusystem.serve.failover import RequestJournal


class FakeClock:
    def __init__(self, now: float = 100.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


# ---------------------------------------------------------------------------
# a fake engine with the real admission surface (the fleet-test pattern)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Admission:
    row: int
    token: int
    finished: bool = False
    reason: str | None = None


@dataclasses.dataclass
class _Report:
    emitted: dict
    finished: list


@dataclasses.dataclass
class _Evicted:
    tokens: list


class _Pool:
    blocks = 100
    block_size = 8

    @staticmethod
    def blocks_for(tokens: int) -> int:
        return 1


class FakeEngine:
    """Deterministic token emission through the Scheduler's exact engine
    surface: token k of a request is ``base + k`` where base is the
    prompt length — enough to assert token-exactness without jax."""

    max_seq = 1024
    pool = _Pool()

    def __init__(self, rows: int = 2):
        self.rows = rows
        self.active: dict[int, list] = {}   # row -> [emitted, budget, base]

    def bucket(self, n: int) -> int:
        return n

    def admit_cost(self, prompt) -> int:
        return self.bucket(len(prompt))

    def can_admit(self, prompt_len: int, remaining: int,
                  prompt=None) -> bool:
        return len(self.active) < self.rows

    def _validate_sampling(self, sampling) -> None:
        pass                             # greedy-only fake

    def admit(self, prompt, remaining, stop_token=None, tag=None,
              sampling=None, emitted=()):
        row = next(r for r in range(self.rows) if r not in self.active)
        base = 1000 + len(prompt)
        if remaining == 1:
            return _Admission(row, base + 1, finished=True, reason='length')
        self.active[row] = [1, remaining, base]
        return _Admission(row, base + 1)

    def step(self):
        emitted, finished = {}, []
        for row, state in list(self.active.items()):
            state[0] += 1
            emitted[row] = [state[2] + state[0]]
            if state[0] >= state[1]:
                del self.active[row]
                tokens = [state[2] + k for k in range(1, state[0] + 1)]
                finished.append((row, 'length', tokens))
        return _Report(emitted, finished)

    def evict(self, row):
        state = self.active.pop(row)
        return _Evicted([state[2] + k for k in range(1, state[0] + 1)])


# the shared no-orphans validator IS the library's own
# (observe.trace.connected_traces — raises ValueError on a dangling
# parent); aliased here so every drill asserts through one contract
from tpusystem.observe.trace import connected_traces as connected  # noqa: E402


# ---------------------------------------------------------------------------
# tracer units
# ---------------------------------------------------------------------------


class TestTracer:

    def test_span_lifecycle_and_context_parentage(self):
        clock = FakeClock()
        tracer = Tracer('p0', clock=clock)
        root = tracer.begin('request r1', cat='request')
        clock.advance(1.0)
        child = tracer.begin('queued', trace=root.context)
        clock.advance(2.0)
        tracer.end(child)
        tracer.end(root)
        assert child.trace_id == root.trace_id
        assert child.parent == root.span_id and root.parent is None
        assert child.end - child.start == pytest.approx(2.0)
        assert root.end - root.start == pytest.approx(3.0)

    def test_end_is_idempotent_and_tolerates_none(self):
        tracer = Tracer('p0', clock=FakeClock())
        span = tracer.begin('s')
        tracer.end(span, reason='done')
        first_end = span.end
        tracer.end(span, reason='again')
        assert span.end == first_end and span.args['reason'] == 'done'
        assert tracer.end(None) is None

    def test_export_is_valid_chrome_trace_json(self, tmp_path):
        clock = FakeClock()
        tracer = Tracer('hostA', clock=clock)
        with tracer.span('work', args={'k': 1}):
            clock.advance(0.5)
            tracer.instant('mark')
        open_span = tracer.begin('died-holding-this')
        clock.advance(0.25)
        path = tracer.export(tmp_path / 'trace.json')
        payload = json.loads(path.read_text())
        assert set(payload) == {'traceEvents', 'displayTimeUnit'}
        events = payload['traceEvents']
        meta = [e for e in events if e['ph'] == 'M']
        assert [m['args']['name'] for m in meta] == ['hostA']
        complete = {e['name']: e for e in events if e['ph'] == 'X'}
        assert complete['work']['dur'] == pytest.approx(0.5e6)
        # an open span exports with a provisional end and open=True
        assert complete['died-holding-this']['args']['open'] is True
        assert complete['died-holding-this']['dur'] == pytest.approx(0.25e6)
        instants = [e for e in events if e['ph'] == 'i']
        assert len(instants) == 1 and instants[0]['s'] == 'p'
        assert open_span.end is None     # export did not mutate the span

    def test_record_subsumes_timeline_stages(self):
        tracer = Tracer('sup', clock=FakeClock())
        root = tracer.record('recovery', 10.0, 14.0, cat='recovery')
        tracer.record('detect→relaunch', 10.0, 11.0, trace=root.context)
        tracer.record('relaunch→restore', 11.0, 13.5, trace=root.context)
        by_trace = connected(tracer.events())
        (group,) = by_trace.values()
        assert len(group) == 3

    def test_merge_is_id_keyed_and_idempotent(self):
        clock = FakeClock()
        a, b = Tracer('a', clock=clock), Tracer('b', clock=clock)
        root = a.begin('request r', cat='request')
        b.begin('queued', trace=root.context)
        collector = Tracer('collector', clock=clock)
        collector.merge(a)
        collector.merge(b)
        collector.merge(b.pack())          # re-send: no duplicates
        assert len(collector) == 2
        by_trace = connected(collector.events())
        (group,) = by_trace.values()       # cross-process parent resolves
        assert {e['name'] for e in group} == {'request r', 'queued'}

    def test_merge_later_copy_carries_the_closed_end(self):
        clock = FakeClock()
        worker = Tracer('w', clock=clock)
        collector = Tracer('c', clock=clock)
        span = worker.begin('decode')
        collector.merge(worker.pack())     # pushed while still open
        clock.advance(1.0)
        worker.end(span)
        collector.merge(worker.pack())     # phase-cadence re-push
        (event,) = [e for e in collector.events() if e['ph'] == 'X']
        assert 'open' not in event['args']

    def test_blob_plane_collection_rides_send_blob(self):
        clock = FakeClock()
        collector = Tracer('rank0', clock=clock)
        transport = Loopback()
        transport.on_blob = collector.accept_blob
        worker = Tracer('rank1', clock=clock)
        worker.begin('step')
        worker.send_spans(transport, to=0)
        assert len(collector) == 1
        # non-trace blobs are ignored and reported as not-ours (chainable)
        assert collector.accept_blob(0, 'replica:x', b'...') is False
        assert len(collector) == 1

    def test_context_is_picklable_and_frozen(self):
        context = TraceContext(trace_id='t/1', parent='s/1')
        assert pickle.loads(pickle.dumps(context)) == context
        with pytest.raises(dataclasses.FrozenInstanceError):
            context.trace_id = 'other'


# ---------------------------------------------------------------------------
# request-scoped tracing through the scheduler (fake engine, fake clock)
# ---------------------------------------------------------------------------


class TestSchedulerTracing:

    def drain(self, scheduler, max_steps=50):
        for _ in range(max_steps):
            if scheduler.idle:
                return
            scheduler.step()

    def test_one_connected_trace_per_request(self):
        clock = FakeClock()
        tracer = Tracer('rep0', clock=clock)
        scheduler = Scheduler(FakeEngine(rows=2), clock=clock, tracer=tracer)
        for index, budget in enumerate((3, 2, 4)):   # r2 queues behind
            scheduler.submit(Request(f'r{index}', [1] * (index + 2), budget))
        self.drain(scheduler)
        by_trace = connected(tracer.events())
        assert len(by_trace) == 3
        for group in by_trace.values():
            names = [e['name'] for e in group]
            assert sum(n.startswith('request ') for n in names) == 1
            assert 'queued' in names and 'decode' in names
        # roots closed with the terminal verdict
        roots = [e for e in tracer.events()
                 if e.get('cat') == 'request' and e['ph'] == 'X']
        assert all(e['args']['reason'] == 'length' for e in roots)
        assert all('open' not in e['args'] for e in roots)

    def test_replayed_row_parents_to_the_original_trace(self):
        """The acceptance property, unit-scale: pack the journal mid-
        stream (trace context rides the pickled Request), replay onto a
        FRESH scheduler with its own tracer, and the merged export is
        still ONE connected trace per request."""
        clock = FakeClock()
        first = Tracer('rep0', clock=clock)
        scheduler = Scheduler(FakeEngine(rows=1), clock=clock, tracer=first)
        scheduler.journal = RequestJournal('drill', clock=clock)
        scheduler.submit(Request('hot', [1, 2], 5))
        scheduler.submit(Request('cold', [1, 2, 3], 4))
        scheduler.step()                 # 'hot' seated, 'cold' queued
        scheduler.step()
        packed = scheduler.journal.pack()    # ...then the engine dies

        tick, rows = RequestJournal.unpack(packed)
        survivor = Tracer('rep1', clock=clock)
        fresh = Scheduler(FakeEngine(rows=1), clock=clock, tracer=survivor)
        for request, waited, emitted in rows:
            fresh.restore(request, waited=waited, prefix=emitted)
        self.drain(fresh)

        collector = Tracer('collector', clock=clock)
        collector.merge(first)
        collector.merge(survivor)
        by_trace = connected(collector.events())
        assert len(by_trace) == 2        # one trace per request, still
        hot_group = next(group for group in by_trace.values()
                         if any(e['args'].get('request') == 'hot'
                                for e in group))
        replayed = [e for e in hot_group if e['args'].get('replayed')]
        # 2 ticks before the kill: 1 admission token + 2 decode emissions
        assert replayed and replayed[0]['args']['prefix'] == 3
        # the replay span lives on rep1 but parents into rep0's root
        processes = {e['pid'] for e in hot_group}
        assert len(processes) == 2

    def test_cancelled_queued_request_closes_its_spans(self):
        clock = FakeClock()
        tracer = Tracer('rep0', clock=clock)
        scheduler = Scheduler(FakeEngine(rows=1), clock=clock, tracer=tracer)
        scheduler.submit(Request('a', [1, 2], 5))
        scheduler.submit(Request('b', [1, 2], 5))
        scheduler.step()
        assert scheduler.cancel('b') == 'queued'
        scheduler.cancel('a')
        self.drain(scheduler)
        open_spans = [e for e in tracer.events()
                      if e['ph'] == 'X' and e['args'].get('open')]
        assert not open_spans
        connected(tracer.events())

    def test_tracer_off_records_nothing_and_changes_nothing(self):
        clock = FakeClock()
        def run(tracer):
            scheduler = Scheduler(FakeEngine(rows=2), clock=clock,
                                  tracer=tracer)
            scheduler.submit(Request('a', [1, 2, 3], 4))
            self.drain(scheduler)
            return scheduler.results['a'].tokens
        assert run(None) == run(Tracer('t', clock=clock))


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


class TestHistogram:

    def reference(self, samples, q):
        ordered = sorted(samples)
        rank = max(1, int(np.ceil(q * len(ordered))))
        return ordered[rank - 1]

    def test_percentiles_match_sorted_reference_within_resolution(self):
        rng = np.random.default_rng(0)
        # latencies spanning 5 orders of magnitude (µs-scale to minutes)
        samples = np.concatenate([
            rng.lognormal(mean=-6, sigma=1.0, size=4000),
            rng.lognormal(mean=0.5, sigma=0.8, size=1000),
        ]).tolist()
        histogram = Histogram(resolution=0.05)
        for value in samples:
            histogram.add(value)
        for q in (0.0, 0.1, 0.5, 0.9, 0.95, 0.99, 1.0):
            exact = self.reference(samples, q)
            estimate = histogram.percentile(q)
            assert abs(estimate - exact) <= histogram.resolution * exact, (
                q, estimate, exact)

    def test_merge_in_any_order_yields_identical_percentiles(self):
        import itertools
        rng = np.random.default_rng(1)
        shards = []
        for host in range(4):        # per-host shards with skewed loads
            shard = Histogram(resolution=0.05)
            for value in rng.lognormal(mean=-3 + host, sigma=1.0,
                                       size=500 + 100 * host):
                shard.add(float(value))
            shards.append(shard)
        readings = set()
        for order in itertools.permutations(range(4)):
            merged = Histogram.merged([shards[i] for i in order])
            readings.add(tuple(merged.percentile(q)
                               for q in (0.5, 0.95, 0.99)))
            assert merged.count == sum(s.count for s in shards)
        assert len(readings) == 1, readings   # bit-identical, any order

    def test_merged_percentiles_match_pooled_reference(self):
        rng = np.random.default_rng(2)
        pools = [rng.lognormal(mean=-4, sigma=1.2, size=800).tolist()
                 for _ in range(3)]
        shards = []
        for pool in pools:
            shard = Histogram()
            for value in pool:
                shard.add(value)
            shards.append(shard)
        merged = Histogram.merged(shards)
        everything = [v for pool in pools for v in pool]
        for q in (0.5, 0.95, 0.99):
            exact = self.reference(everything, q)
            assert abs(merged.percentile(q) - exact) <= 0.05 * exact

    def test_single_sample_reads_back_exactly(self):
        histogram = Histogram()
        histogram.add(0.125)
        for q in (0.0, 0.5, 1.0):
            assert histogram.percentile(q) == 0.125

    def test_state_round_trips_and_summary(self):
        histogram = Histogram()
        for value in (0.001, 0.01, 0.25, 3.0):
            histogram.add(value)
        clone = Histogram.from_state(
            json.loads(json.dumps(histogram.state())))
        assert clone.percentile(0.5) == histogram.percentile(0.5)
        assert clone.count == 4 and clone.max == 3.0
        summary = histogram.summary()
        assert summary['count'] == 4
        assert summary['mean'] == pytest.approx(sum((0.001, 0.01, 0.25, 3.0))
                                                / 4)

    def test_validation(self):
        with pytest.raises(ValueError, match='resolution'):
            Histogram(resolution=0.0)
        with pytest.raises(ValueError, match='empty'):
            Histogram().percentile(0.5)
        with pytest.raises(ValueError, match='share bucketing'):
            Histogram(resolution=0.05).merge(Histogram(resolution=0.1))
        with pytest.raises(ValueError, match='q must be'):
            Histogram().percentile(1.5)

    def test_serve_latency_feeds_from_events_and_charts(self, tmp_path):
        from tests.tb import read_scalars
        from tpusystem.observe import SummaryWriter
        from tpusystem.observe import tensorboard as tensorboard_module
        from tpusystem.observe.events import (EngineRestarted,
                                              RequestAdmitted,
                                              RequestCompleted)

        latency = ServeLatency()
        consumer = serve_metrics_consumer(latency, cadence=4)
        writer = SummaryWriter(tmp_path / 'run')
        consumer.dependency_overrides[tensorboard_module.writer] = \
            lambda: writer
        for index in range(8):
            consumer.consume(RequestAdmitted(
                id=f'r{index}', row=0, prompt_tokens=4,
                ttft=0.01 * (index + 1), queue_depth=1))
            consumer.consume(RequestCompleted(
                id=f'r{index}', produced=10, reason='length', seconds=1.0))
        consumer.consume(EngineRestarted(cause='stalled', replayed=1,
                                         resubmitted=0, seconds=0.5))
        writer.close()
        scalars = read_scalars(tmp_path / 'run', history=True)
        assert [step for _, step in scalars['serve/ttft_p50']] == [4, 8]
        value, _ = scalars['serve/ttft_p99'][-1]
        assert value == pytest.approx(0.08, rel=0.06)   # one bucket's worth
        assert scalars['serve/token_seconds_p50'][-1][0] == pytest.approx(
            0.1, rel=0.06)
        assert scalars['serve/recovery_p50'][0][0] == pytest.approx(
            0.5, rel=0.06)
        assert latency.ttft.count == 8 and latency.recovery.count == 1


# ---------------------------------------------------------------------------
# the flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:

    def test_ring_is_bounded_and_dump_round_trips(self, tmp_path):
        clock = FakeClock()
        recorder = FlightRecorder(tmp_path / 'flight.json', capacity=4,
                                  cadence=2, process='w0', clock=clock)
        for index in range(10):
            recorder.note('tick', step=index)
        payload = FlightRecorder.read(tmp_path / 'flight.json')
        assert payload['process'] == 'w0'
        assert [entry['step'] for entry in payload['entries']] == [6, 7, 8, 9]
        assert len(recorder.ring) == 4

    def test_write_ahead_cadence_is_what_a_kill_leaves(self, tmp_path):
        recorder = FlightRecorder(tmp_path / 'flight.json', cadence=3,
                                  clock=FakeClock())
        recorder.note('a')
        recorder.note('b')
        assert FlightRecorder.read(tmp_path / 'flight.json') is None
        recorder.note('c')               # cadence hit: ring on disk now
        payload = FlightRecorder.read(tmp_path / 'flight.json')
        assert [entry['kind'] for entry in payload['entries']] == \
            ['a', 'b', 'c']

    def test_tap_keeps_stable_fields_only(self, tmp_path):
        from tpusystem.observe.events import RequestAdmitted, Trained
        from tpusystem.services.prodcon import Producer

        recorder = FlightRecorder(tmp_path / 'f.json', clock=FakeClock())
        producer = Producer()
        recorder.tap(producer)
        producer.dispatch(RequestAdmitted(id='r1', row=0, prompt_tokens=5,
                                          ttft=0.01, queue_depth=2))
        producer.dispatch(Trained(model=object(), metrics={'loss': 1.0}))
        entries = FlightRecorder.read(tmp_path / 'f.json')['entries']
        assert entries[0]['kind'] == 'RequestAdmitted'
        assert entries[0]['id'] == 'r1' and entries[0]['ttft'] == 0.01
        assert 'model' not in entries[1] and 'metrics' not in entries[1]

    def test_watch_folds_finished_spans(self, tmp_path):
        clock = FakeClock()
        recorder = FlightRecorder(tmp_path / 'f.json', clock=clock)
        tracer = Tracer('w', clock=clock)
        recorder.watch(tracer)
        span = tracer.begin('decode')
        clock.advance(0.5)
        tracer.end(span)
        entries = FlightRecorder.read(tmp_path / 'f.json')['entries']
        assert entries[0]['kind'] == 'span'
        assert entries[0]['name'] == 'decode'
        assert entries[0]['seconds'] == pytest.approx(0.5)

    def test_exit_contract_dumps_installed_recorders(self, tmp_path):
        from tpusystem.parallel.recovery import (PREEMPTED_EXIT, Preempted,
                                                 exit_for_restart)

        recorder = FlightRecorder(tmp_path / 'f.json', cadence=1000,
                                  clock=FakeClock()).install()
        try:
            recorder.note('step', n=1)   # cadence 1000: nothing on disk yet
            assert FlightRecorder.read(tmp_path / 'f.json') is None
            exit = exit_for_restart(Preempted(signal.SIGTERM))
            assert exit.code == PREEMPTED_EXIT
            payload = FlightRecorder.read(tmp_path / 'f.json')
            assert payload['reason'] == 'Preempted'
            assert payload['code'] == PREEMPTED_EXIT
            assert payload['entries'][0]['kind'] == 'step'
        finally:
            recorder.uninstall()
        dump_installed()                 # uninstalled: no-op, no raise

    def test_dump_failure_degrades_and_logs_once(self, tmp_path, caplog):
        import logging
        target = tmp_path / 'not-a-dir'
        target.write_text('a file where the parent dir should be')
        recorder = FlightRecorder(target / 'f.json', clock=FakeClock())
        with caplog.at_level(logging.WARNING,
                             logger='tpusystem.observe.flight'):
            recorder.note('a')
            recorder.note('b')
        assert sum('dump' in record.message
                   for record in caplog.records) == 1

    def test_sigkilled_subprocess_leaves_the_write_ahead_ring(self, tmp_path):
        """The kill contract, for real: a worker that SIGKILLs itself
        (no handler, no atexit, nothing) leaves exactly the entries the
        write-ahead cadence had already persisted."""
        worker = tmp_path / 'worker.py'
        worker.write_text(
            "import os, signal, sys\n"
            "sys.path.insert(0, sys.argv[2])\n"
            "from tpusystem.observe.flight import FlightRecorder\n"
            "recorder = FlightRecorder(sys.argv[1], cadence=1)\n"
            "for step in range(5):\n"
            "    recorder.note('tick', step=step)\n"
            "os.kill(os.getpid(), signal.SIGKILL)\n")
        flight = tmp_path / 'flight.json'
        root = pathlib.Path(__file__).parent.parent
        done = subprocess.run([sys.executable, str(worker), str(flight),
                               str(root)], timeout=60)
        assert done.returncode == -signal.SIGKILL
        payload = FlightRecorder.read(flight)
        assert [entry['step'] for entry in payload['entries']] == \
            list(range(5))


# ---------------------------------------------------------------------------
# recovery / elastic / checkpoint spans
# ---------------------------------------------------------------------------


class TestSubsystemSpans:

    def test_supervisor_recovery_stages_become_spans(self):
        from tpusystem.parallel.supervisor import Supervisor

        clock = FakeClock()
        tracer = Tracer('sup0', clock=clock)
        supervisor = Supervisor(['worker'], memstore=False, tracer=tracer,
                                clock=clock, sleep=lambda seconds: None)
        supervisor._timeline = {'detect': 10.0}
        supervisor._restore_info = {'source': 'hot', 'step': 7}
        for stage, at in (('relaunch', 11.0), ('restore', 12.5),
                          ('first-step', 14.0)):
            supervisor._timeline[stage] = at
        supervisor._emit_timeline()
        by_trace = connected(tracer.events())
        (group,) = by_trace.values()
        names = {event['name'] for event in group}
        assert f'recovery rank0' in names
        assert 'detect→relaunch' in names and 'restore→first-step' in names
        root = next(e for e in group if e['name'] == 'recovery rank0')
        assert root['args']['source'] == 'hot'
        assert root['dur'] == pytest.approx(4.0e6)
        # the event form still rides the bus untouched
        assert supervisor.timelines[0].stages['first-step'] == \
            pytest.approx(4.0)

    def test_elastic_wave_becomes_spans(self):
        from tpusystem.parallel.elastic import (ElasticCoordinator,
                                                ResizeDecision)

        clock = FakeClock()
        tracer = Tracer('sup0', clock=clock)
        coordinator = ElasticCoordinator(Loopback(), rank=0, size=4,
                                         clock=clock, tracer=tracer)
        # a committed wave's bookkeeping (the protocol itself is drilled
        # in test_elastic.py; here: its trace-plane projection)
        coordinator.decisions.append(ResizeDecision(epoch=1,
                                                    members=(0, 1)))
        coordinator._committed_at = 50.0
        coordinator._commit_stages = {'propose': 0.5, 'commit': 1.5}
        clock.now = 53.0
        coordinator.resumed(step=12, source='hot-reshard')
        by_trace = connected(tracer.events())
        (group,) = by_trace.values()
        root = next(e for e in group
                    if e['name'] == 'elastic-resize epoch1')
        assert root['args']['source'] == 'hot-reshard'
        assert root['dur'] == pytest.approx(3.0e6)
        names = {e['name'] for e in group}
        assert 'wave-open→propose' in names and 'commit→resumed' in names

    def test_checkpointer_save_restore_spans(self, tmp_path):
        from tpusystem.checkpoint import Checkpointer

        clock = FakeClock()
        tracer = Tracer('host0', clock=clock)
        state = {'w': np.arange(4.0)}
        with Checkpointer(tmp_path / 'ckpt', async_save=False,
                          tracer=tracer) as checkpointer:
            checkpointer.save('m', 1, state)
            restored = checkpointer.restore('m', state, epoch=1)
        assert np.array_equal(restored['w'], state['w'])
        names = [e['name'] for e in tracer.events() if e['ph'] == 'X']
        assert names == ['checkpoint-save', 'checkpoint-restore']
        args = [e['args'] for e in tracer.events() if e['ph'] == 'X']
        assert all(a['identity'] == 'm' for a in args)


class TestFlightRecorderHardening:

    def test_concurrent_notes_and_dumps_do_not_crash(self, tmp_path):
        """Entries arrive from scheduler loops, supervisor threads and
        bus dispatch at once; with cadence=1 every note also dumps — a
        mid-iteration append from another thread must never raise."""
        import threading

        recorder = FlightRecorder(tmp_path / 'f.json', capacity=64,
                                  cadence=1, clock=time.monotonic)
        errors = []

        def hammer(label):
            try:
                for index in range(200):
                    recorder.note(label, n=index)
            except Exception as error:      # pragma: no cover
                errors.append(error)

        threads = [threading.Thread(target=hammer, args=(f't{i}',))
                   for i in range(4)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        assert not errors, errors
        payload = FlightRecorder.read(tmp_path / 'f.json')
        assert payload is not None and len(payload['entries']) <= 64

    def test_non_jsonable_breadcrumb_is_sanitized_at_intake(self, tmp_path):
        """One bad entry must not poison later dumps of the whole ring
        (that would void the write-ahead SIGKILL guarantee for up to
        ``capacity`` entries): it degrades to its repr, alone, and the
        ring keeps persisting."""
        recorder = FlightRecorder(tmp_path / 'f.json', clock=FakeClock())
        recorder.note('ok', n=1)
        recorder.note('bad', arr=np.arange(3))        # not JSON-able
        recorder.note('after', n=2)                   # ...still persists
        payload = FlightRecorder.read(tmp_path / 'f.json')
        kinds = [entry['kind'] for entry in payload['entries']]
        assert kinds == ['ok', 'bad', 'after']
        assert 'unserializable' in payload['entries'][1]
        assert payload['entries'][-1]['n'] == 2

    def test_watch_chains_an_existing_sink(self, tmp_path):
        clock = FakeClock()
        seen = []
        tracer = Tracer('w', clock=clock, sink=seen.append)
        recorder = FlightRecorder(tmp_path / 'f.json', clock=clock)
        recorder.watch(tracer)
        tracer.end(tracer.begin('span'))
        assert len(seen) == 1            # the original sink still fires
        entries = FlightRecorder.read(tmp_path / 'f.json')['entries']
        assert entries[0]['kind'] == 'span'


def test_connected_traces_raises_on_a_dangling_parent():
    """The shared validator itself: a span whose parent was never
    collected (e.g. only the survivor's tracer was merged) must be
    reported, not silently grouped."""
    clock = FakeClock()
    origin = Tracer('rep0', clock=clock)
    survivor = Tracer('rep1', clock=clock)
    root = origin.begin('request r', cat='request')
    survivor.begin('queued', trace=root.context)
    with pytest.raises(ValueError, match='orphan'):
        connected(survivor.events())     # origin's root never merged
    collector = Tracer('c', clock=clock)
    collector.merge(origin)
    collector.merge(survivor)
    connected(collector.events())        # merged: no raise
