"""REAL multi-process integration (VERDICT r1 weak #5): spawns separate
Python processes that run ``jax.distributed.initialize`` (via ``Runtime``)
plus the TCP control plane end to end — wired events with primary-only
consumer placement, collective agree, barrier, and one data-parallel train
step over the cross-process global mesh. Everything in-process tests
simulate with threads, this executes for real on CPU.
"""

from __future__ import annotations

import json
import os
import pathlib
import socket
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).parent.parent

WORKER = r'''
import json, sys
rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
coordinator, out_path = sys.argv[3], sys.argv[4]

import os
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp
import numpy as np

from tpusystem.models import gpt2_tiny
from tpusystem.parallel import MeshSpec, batch_sharding, replicated
from tpusystem.runtime import Runtime
from tpusystem.services import Consumer, event
from tpusystem.train import (NextTokenLoss, SGD, build_train_step, flax_apply,
                             init_state)


@event
class Ping:
    sender: int


record = {'rank': rank}
with Runtime(coordinator=coordinator, num_processes=nprocs, process_id=rank,
             heartbeat=2.0) as runtime:
    record['is_primary'] = runtime.is_primary
    record['process_count'] = runtime.world.process_count
    record['global_devices'] = jax.device_count()
    record['local_devices'] = jax.local_device_count()

    # control plane: the LAST rank dispatches a wired event; the consumer is
    # registered primary_only, so only rank 0 may observe it
    received = []
    consumer = Consumer()

    @consumer.handler
    def on_ping(ping: Ping):
        received.append(ping.sender)

    runtime.producer.wire(Ping)
    runtime.producer.register(consumer, primary_only=True)
    # rendezvous BEFORE dispatching: events are fire-and-forget, so a
    # dispatch racing another rank's hub registration would be dropped
    runtime.barrier()
    if rank == nprocs - 1:
        runtime.producer.dispatch(Ping(sender=rank))
    runtime.barrier()                    # checkpoint-style rendezvous
    runtime.sync()                       # drain remote events on this thread
    record['pings'] = received

    # collective agree: one rank wanting out stops everyone
    record['agree_none'] = runtime.should_stop(False)
    record['agree_one'] = runtime.should_stop(rank == 0)
    record['rank_sum'] = runtime.transport.allreduce(rank, op='sum')

    # one data-parallel train step over the cross-process global mesh
    mesh = MeshSpec(data=-1).build()
    module = gpt2_tiny(attention='xla', dtype='float32')
    optimizer = SGD(lr=0.1)
    tokens = np.random.default_rng(0).integers(0, 256, (4 * nprocs, 32)).astype(np.int32)
    state = init_state(module, optimizer, jnp.asarray(tokens[:1]))
    # become global arrays: params replicated, batch sharded over data —
    # each process contributes its local rows of the global batch
    sharding = batch_sharding(mesh)
    state = jax.tree.map(
        lambda leaf: jax.make_array_from_process_local_data(
            replicated(mesh), np.asarray(leaf)), state)
    per_process = tokens.shape[0] // nprocs
    local = tokens[rank * per_process:(rank + 1) * per_process]
    global_tokens = jax.make_array_from_process_local_data(sharding, local)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    state, (_, loss) = step(state, global_tokens, global_tokens)
    state, (_, loss2) = step(state, global_tokens, global_tokens)
    record['loss'] = float(loss)         # replicated -> addressable everywhere
    record['loss2'] = float(loss2)
    record['step'] = int(state.step)
    runtime.barrier()

with open(out_path, 'w') as handle:
    json.dump(record, handle)
'''


def _free_port() -> int:
    with socket.socket() as probe:
        probe.bind(('localhost', 0))
        return probe.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize('nprocs', [2, 3, 8])
def test_multi_process_runtime_end_to_end(tmp_path, nprocs):
    """nprocs=8 shakes out hub fan-out + barrier behavior beyond the
    4-process tier (VERDICT r4 #8): 8 real processes, 16 virtual devices,
    one DP step over the cross-process mesh."""
    procs, outputs = _launch_workers(tmp_path, WORKER, nprocs, timeout=420)
    for proc, output in zip(procs, outputs):
        assert proc.returncode == 0, f'worker failed:\n{output[-3000:]}'

    records = {rank: json.loads((tmp_path / f'out{rank}.json').read_text())
               for rank in range(nprocs)}
    for rank, record in records.items():
        assert record['process_count'] == nprocs
        assert record['global_devices'] == 2 * nprocs   # 2 virtual chips each
        assert record['local_devices'] == 2
        assert record['is_primary'] == (rank == 0)
        assert record['agree_none'] is False      # nobody wants to stop
        assert record['agree_one'] is True        # one rank stops everyone
        assert record['rank_sum'] == nprocs * (nprocs - 1) // 2
        assert record['step'] == 2
    # primary-only consumer placement: rank 0 saw the wired event from the
    # last rank, every other rank saw nothing
    assert records[0]['pings'] == [nprocs - 1]
    assert all(records[rank]['pings'] == [] for rank in range(1, nprocs))
    # the DP step is SPMD: the replicated loss must be identical everywhere,
    # and training moved it
    losses = {record['loss'] for record in records.values()}
    assert len(losses) == 1
    assert records[0]['loss2'] < records[0]['loss']


FAILURE_WORKER = r'''
import json, os, sys, time
rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
coordinator, out_path = sys.argv[3], sys.argv[4]

os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')

from tpusystem.parallel.multihost import WorkerLost
from tpusystem.runtime import Runtime
from tpusystem.services import Consumer

record = {'rank': rank, 'lost': []}
runtime = Runtime(coordinator=coordinator, num_processes=nprocs,
                  process_id=rank, heartbeat=0.5)
consumer = Consumer()
consumer.register(WorkerLost, lambda lost: record['lost'].append(lost.rank))
runtime.producer.register(consumer)

runtime.barrier()                 # everyone up, hub registrations done
if rank == nprocs - 1:
    os._exit(1)                   # abrupt death: no 'bye', no cleanup

deadline = time.monotonic() + 30
while not record['lost'] and time.monotonic() < deadline:
    runtime.sync()                # drain control-plane events
    time.sleep(0.05)

with open(out_path, 'w') as handle:
    json.dump(record, handle)
    handle.flush()
    os.fsync(handle.fileno())
if rank == 0:
    # rank 0 hosts the hub: linger so the 'lost' fanout reaches every
    # survivor before os._exit tears the hub thread down mid-broadcast
    time.sleep(2)
# skip atexit (jax.distributed shutdown would wait on the dead rank)
os._exit(0)
'''


def _launch_workers(out_dir, source: str, nprocs: int, timeout: int,
                    extra_args: tuple = ()):
    """Spawn ``nprocs`` worker processes from ``source`` sharing one
    coordinator + control-plane address; returns (procs, outputs) with
    every process reaped (killed if hung). ``extra_args`` append to every
    worker's argv after the output path."""
    coordinator = f'localhost:{_free_port()}'
    worker = out_dir / 'worker.py'
    worker.write_text(source)
    env = {**os.environ, 'PYTHONPATH': str(REPO),
           'TPUSYSTEM_CONTROL': f'localhost:{_free_port()}'}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(rank), str(nprocs), coordinator,
             str(out_dir / f'out{rank}.json'), *map(str, extra_args)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(nprocs)]
    try:
        outputs = [proc.communicate(timeout=timeout)[0].decode()
                   for proc in procs]
    finally:
        for proc in procs:   # a hung worker must not outlive the test
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    return procs, outputs


@pytest.mark.slow
def test_real_process_death_surfaces_worker_lost(tmp_path):
    """Failure detection over REAL processes: rank N-1 dies abruptly
    (os._exit — no 'bye' frame, a closed socket like a crashed host);
    every survivor's control plane must surface a WorkerLost event for
    exactly that rank. The thread-simulated versions live in
    tests/test_multihost.py; this is the cross-process proof."""
    nprocs = 4
    procs, outputs = _launch_workers(tmp_path, FAILURE_WORKER, nprocs,
                                     timeout=300)
    assert procs[nprocs - 1].returncode == 1      # the deliberate death
    for rank in range(nprocs - 1):
        assert procs[rank].returncode == 0, (
            f'survivor {rank} failed:\n{outputs[rank][-3000:]}')
        record = json.loads((tmp_path / f'out{rank}.json').read_text())
        assert record['lost'] == [nprocs - 1], record


RESUME_WORKER = r'''
import json, os, sys
rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
coordinator, out_path = sys.argv[3], sys.argv[4]
ckpt_root = sys.argv[5]

os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp
import numpy as np

from tpusystem.checkpoint import Checkpointer
from tpusystem.models import gpt2_tiny
from tpusystem.parallel import MeshSpec, batch_sharding, replicated
from tpusystem.registry import gethash
from tpusystem.runtime import Runtime
from tpusystem.train import (NextTokenLoss, SGD, build_train_step, flax_apply,
                             init_state)

record = {'rank': rank}
with Runtime(coordinator=coordinator, num_processes=nprocs, process_id=rank,
             heartbeat=2.0) as runtime:
    mesh = MeshSpec(data=-1).build()
    module = gpt2_tiny(attention='xla', dtype='float32')
    identity = gethash(module)           # deterministic across hosts
    record['identity'] = identity
    optimizer = SGD(lr=0.1)
    tokens = np.random.default_rng(0).integers(0, 256, (12, 32)).astype(np.int32)
    state = init_state(module, optimizer, jnp.asarray(tokens[:1]))
    state = jax.tree.map(
        lambda leaf: jax.make_array_from_process_local_data(
            replicated(mesh), np.asarray(leaf)), state)

    checkpointer = Checkpointer(ckpt_root)
    latest = checkpointer.latest(identity)
    record['start_epoch'] = 0 if latest is None else latest
    if latest is not None:
        # restore lands sharded for the CURRENT global mesh — the test's
        # second run resumes this 2-host checkpoint on a 3-host world
        state = checkpointer.restore(identity, state, latest)

    per_process = tokens.shape[0] // nprocs
    local = tokens[rank * per_process:(rank + 1) * per_process]
    sharding = batch_sharding(mesh)
    global_tokens = jax.make_array_from_process_local_data(sharding, local)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)

    losses = []
    for epoch in range(record['start_epoch'], record['start_epoch'] + 2):
        state, (_, loss) = step(state, global_tokens, global_tokens)
        losses.append(float(loss))
        checkpointer.save(identity, epoch + 1, state)
    checkpointer.wait()                  # saves committed before exiting
    runtime.barrier()
    record['losses'] = losses
    record['end_step'] = int(state.step)

with open(out_path, 'w') as handle:
    json.dump(record, handle)
'''


@pytest.mark.slow
def test_multi_process_checkpoint_restart_resume(tmp_path):
    """The preemption story over REAL processes: a 2-host job trains two
    epochs with collective checkpointing (orbax multihost save of the
    replicated global state), the whole job exits (preemption), and a
    fresh set of processes with the SAME registry identity resumes from
    the last committed epoch and keeps improving the loss."""
    ckpt_root = tmp_path / 'ckpt'

    def launch(run_dir, nprocs):
        run_dir.mkdir()
        procs, outputs = _launch_workers(run_dir, RESUME_WORKER, nprocs,
                                         timeout=300,
                                         extra_args=(ckpt_root,))
        for proc, output in zip(procs, outputs):
            assert proc.returncode == 0, f'worker failed:\n{output[-3000:]}'
        return {rank: json.loads((run_dir / f'out{rank}.json').read_text())
                for rank in range(nprocs)}

    # resume on a DIFFERENT topology: the 2-host (4-device) collective
    # checkpoint restores onto a 3-host (6-device) world — the exact claim
    # checkpoint/checkpointer.py makes ("resume a v4-8 run on a v4-32"):
    # orbax restores into the template sharded for the CURRENT mesh
    first = launch(tmp_path / 'run1', nprocs=2)
    second = launch(tmp_path / 'run2', nprocs=3)

    for records in (first, second):
        identities = {record['identity'] for record in records.values()}
        assert len(identities) == 1          # same id on every host
    assert all(r['start_epoch'] == 0 for r in first.values())
    # the restart resumed from the last committed epoch, not from scratch
    assert all(r['start_epoch'] == 2 for r in second.values())
    assert all(r['end_step'] == 4 for r in second.values())
    # training continued from the restored weights: the resumed run's
    # first loss beats even the fresh run's LAST loss (a partial restore
    # that lost the trained weights could not do that)
    assert second[0]['losses'][0] < first[0]['losses'][-1]
    assert second[0]['losses'][-1] < second[0]['losses'][0]
