"""End-to-end minimum slice: MLP aggregate trains on synthetic digits with
loss decreasing — every framework seam exercised (SURVEY.md §7.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.data import Loader, SyntheticDigits
from tpusystem.models import MLP
from tpusystem.registry import gethash, getarguments
from tpusystem.train import (
    Accuracy, Adam, CrossEntropyLoss, Mean, build_eval_step, build_train_step,
    flax_apply, init_state,
)


@pytest.fixture(scope='module')
def slice_setup():
    """Steps are shared (compile once); state is NOT — train steps donate
    their input state, so every test initializes its own."""
    module = MLP(features=(64,), classes=10, dropout=0.1)
    optimizer = Adam(lr=1e-3)
    criterion = CrossEntropyLoss()
    apply_fn = flax_apply(module)
    train_step = build_train_step(apply_fn, criterion, optimizer)
    eval_step = build_eval_step(apply_fn, criterion)

    def fresh_state(rng=0):
        return init_state(module, optimizer, jnp.zeros((8, 28, 28)), rng=rng)

    return module, optimizer, fresh_state, train_step, eval_step


def test_registered_flax_module_has_identity():
    module = MLP(features=(64,), classes=10)
    assert getarguments(module) == {'features': (64,), 'classes': 10}
    assert gethash(module) == gethash(MLP(features=(64,), classes=10))
    assert gethash(module) != gethash(MLP(features=(128,), classes=10))


def test_loss_decreases_over_training(slice_setup):
    _, _, fresh_state, train_step, eval_step = slice_setup
    state = fresh_state(0)
    dataset = SyntheticDigits(samples=512, seed=0)
    loader = Loader(dataset, batch_size=64, shuffle=True, seed=0)
    loss_metric = Mean()
    first_epoch_loss = None
    for epoch in range(3):
        loss_metric.reset()
        for inputs, targets in loader:
            state, (outputs, loss) = train_step(state, inputs, targets)
            loss_metric.update(loss)
        epoch_loss = loss_metric.compute()
        if first_epoch_loss is None:
            first_epoch_loss = epoch_loss
    assert epoch_loss < first_epoch_loss * 0.5, (first_epoch_loss, epoch_loss)

    accuracy = Accuracy()
    test_set = SyntheticDigits(samples=256, seed=0, train=False)
    for inputs, targets in Loader(test_set, batch_size=64):
        outputs, loss = eval_step(state, inputs, targets)
        accuracy.update(jnp.argmax(outputs, -1), targets)
    assert accuracy.compute() > 0.8


def test_train_step_increments_device_step_counter(slice_setup):
    _, _, fresh_state, train_step, _ = slice_setup
    state = fresh_state(1)
    inputs = jnp.zeros((8, 28, 28))
    targets = jnp.zeros((8,), jnp.int32)
    state, _ = train_step(state, inputs, targets)
    state, _ = train_step(state, inputs, targets)
    assert int(state.step) == 2


def test_eval_step_is_deterministic(slice_setup):
    _, _, fresh_state, _, eval_step = slice_setup
    state = fresh_state(2)
    inputs = jnp.ones((4, 28, 28))
    targets = jnp.zeros((4,), jnp.int32)
    out1, loss1 = eval_step(state, inputs, targets)
    out2, loss2 = eval_step(state, inputs, targets)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_loader_shapes_and_determinism():
    dataset = SyntheticDigits(samples=130, seed=3)
    loader = Loader(dataset, batch_size=32, shuffle=True, seed=7)
    batches = list(loader)
    assert len(batches) == 4  # remainder dropped
    assert batches[0][0].shape == (32, 28, 28)
    assert batches[0][1].dtype == jnp.int32
    # same seed -> same first-epoch order
    other = Loader(dataset, batch_size=32, shuffle=True, seed=7)
    np.testing.assert_array_equal(np.asarray(batches[0][1]),
                                  np.asarray(list(other)[0][1]))


def test_loader_prefetch_thread_shuts_down_on_early_close():
    """Abandoning the iterator mid-epoch must stop the background
    prefetch thread (no producer left blocked on a full queue)."""
    import threading
    dataset = SyntheticDigits(samples=256, seed=1)
    loader = Loader(dataset, batch_size=8, shuffle=False, prefetch=2)
    iterator = iter(loader)
    next(iterator)
    iterator.close()    # GeneratorExit -> stop flag -> thread joins
    for _ in range(100):
        if not any(t.name == 'loader-prefetch' and t.is_alive()
                   for t in threading.enumerate()):
            break
        import time
        time.sleep(0.02)
    assert not any(t.name == 'loader-prefetch' and t.is_alive()
                   for t in threading.enumerate())


def test_loader_prefetch_matches_direct_indexing():
    """The background-thread pipeline yields exactly the batches direct
    fancy indexing produces, in order."""
    dataset = SyntheticDigits(samples=96, seed=2)
    loader = Loader(dataset, batch_size=16, shuffle=True, seed=11)
    order = loader._order()
    batches = list(loader)
    assert len(batches) == 6
    for index, (inputs, targets) in enumerate(batches):
        span = order[index * 16:(index + 1) * 16]
        expected_inputs, expected_targets = dataset[span]
        np.testing.assert_array_equal(np.asarray(inputs),
                                      np.asarray(expected_inputs))
        np.testing.assert_array_equal(np.asarray(targets),
                                      np.asarray(expected_targets))


def test_loader_pytree_batches_prefetch_and_shapes():
    """Satellite (recsys loader): the background prefetch thread and
    device placement are pytree-clean — dict-of-arrays batches with
    ragged (-1-padded) multi-hot sparse fields come through structure-
    intact and bit-identical to direct indexing."""
    from tpusystem.data import SyntheticClicks
    dataset = SyntheticClicks(samples=96, vocabs=(32, 16), hot=4, seed=5)
    loader = Loader(dataset, batch_size=16, shuffle=True, seed=13,
                    prefetch=2)
    order = loader._order()
    batches = list(loader)
    assert len(batches) == 6
    features, labels = batches[0]
    assert set(features) == {'dense', 'ids'}
    assert features['ids'].shape == (16, 2, 4)
    assert labels.shape == (16,)
    assert (np.asarray(features['ids']) == -1).any()  # ragged padding
    for index, (got_features, got_labels) in enumerate(batches):
        span = order[index * 16:(index + 1) * 16]
        want_features, want_labels = dataset[span]
        np.testing.assert_array_equal(np.asarray(got_features['dense']),
                                      want_features['dense'])
        np.testing.assert_array_equal(np.asarray(got_features['ids']),
                                      want_features['ids'])
        np.testing.assert_array_equal(np.asarray(got_labels), want_labels)


def test_loader_pytree_cursor_resume():
    """Satellite (recsys loader): state()/seek() stay batch-content
    agnostic — a fresh loader seeked to a mid-epoch pytree cursor yields
    exactly the remaining batches."""
    from tpusystem.data import SyntheticClicks
    dataset = SyntheticClicks(samples=96, vocabs=(32,), seed=6)
    loader = Loader(dataset, batch_size=16, shuffle=True, seed=17)
    iterator = iter(loader)
    consumed = [next(iterator) for _ in range(2)]
    del consumed
    cursor = loader.state()
    assert cursor == {'epoch': 0, 'batch': 2}
    iterator.close()

    resumed = Loader(dataset, batch_size=16, shuffle=True, seed=17)
    resumed.seek(cursor)
    rest = list(resumed)
    assert len(rest) == 4
    reference = list(Loader(dataset, batch_size=16, shuffle=True, seed=17))
    for (got_features, got_labels), (want_features, want_labels) in zip(
            rest, reference[2:]):
        np.testing.assert_array_equal(np.asarray(got_features['ids']),
                                      np.asarray(want_features['ids']))
        np.testing.assert_array_equal(np.asarray(got_labels),
                                      np.asarray(want_labels))


def test_loader_pytree_sharded_placement():
    """Satellite (recsys loader): a batch-dim sharding applies leaf by
    leaf — dense [B, d], sparse [B, F, K] and label [B] leaves all land
    split over the data axis."""
    from jax.sharding import NamedSharding, PartitionSpec
    from tpusystem.data import SyntheticClicks
    from tpusystem.parallel import MeshSpec
    mesh = MeshSpec(data=2).build(jax.devices()[:2])
    sharding = NamedSharding(mesh, PartitionSpec('data'))
    dataset = SyntheticClicks(samples=32, vocabs=(32, 16), seed=7)
    loader = Loader(dataset, batch_size=8, sharding=sharding)
    features, labels = next(iter(loader))
    for leaf in jax.tree.leaves((features, labels)):
        assert leaf.sharding.spec == PartitionSpec('data'), leaf.sharding
        assert len(leaf.addressable_shards) >= 2


def test_loader_prefetch_propagates_worker_errors():
    """An exception in the prefetch thread re-raises on the consumer."""
    class Exploding:
        def __len__(self):
            return 64

        def __getitem__(self, index):
            raise RuntimeError('bad shard')

    loader = Loader(Exploding(), batch_size=16)
    with pytest.raises(RuntimeError, match='bad shard'):
        list(loader)


def test_loader_identity_excludes_dataset():
    dataset = SyntheticDigits(samples=64)
    loader = Loader(dataset, batch_size=16, shuffle=True, seed=5)
    assert getarguments(loader) == {'batch_size': 16, 'shuffle': True, 'seed': 5}


def test_optimizer_identity():
    assert gethash(Adam(lr=1e-3)) == gethash(Adam(lr=1e-3))
    assert gethash(Adam(lr=1e-3)) != gethash(Adam(lr=3e-4))


def test_gradient_accumulation_matches_full_batch():
    """accumulate=N averages microbatch gradients: with a per-example-mean
    loss and no dropout, the updated parameters match the full-batch step
    (float32, tight tolerance)."""
    module = MLP(features=(32,), classes=10, dropout=0.0)
    optimizer = Adam(lr=1e-2)
    criterion = CrossEntropyLoss()
    apply_fn = flax_apply(module)
    inputs = jnp.asarray(
        np.random.default_rng(5).standard_normal((8, 28, 28)), jnp.float32)
    targets = jnp.asarray(
        np.random.default_rng(6).integers(0, 10, (8,)), jnp.int32)

    full = build_train_step(apply_fn, criterion, optimizer, jit=False)
    accum = build_train_step(apply_fn, criterion, optimizer, accumulate=4,
                             jit=False)
    state_a = init_state(module, optimizer, inputs[:1], rng=0)
    state_b = init_state(module, optimizer, inputs[:1], rng=0)
    state_a, (_, loss_a) = full(state_a, inputs, targets)
    state_b, (outputs_b, loss_b) = accum(state_b, inputs, targets)

    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(state_a.params),
                    jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
    # outputs come from the final microbatch
    assert jax.tree.leaves(outputs_b)[0].shape[0] == 2


def test_gradient_accumulation_rejects_indivisible_batch():
    module = MLP(features=(16,), classes=10, dropout=0.0)
    optimizer = Adam(lr=1e-2)
    step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer,
                            accumulate=3, jit=False)
    state = init_state(module, optimizer, jnp.zeros((1, 28, 28)))
    with pytest.raises(AssertionError):
        step(state, jnp.zeros((8, 28, 28)), jnp.zeros((8,), jnp.int32))


@pytest.mark.slow
def test_gradient_accumulation_token_weighted_under_padding():
    """With a masked LM loss and uneven padding across microbatches,
    accumulate=N weights microbatches by unmasked-token count, so the
    result still equals the full-batch step (ADVICE r1 #1)."""
    from tpusystem.models import gpt2_tiny
    from tpusystem.train import NextTokenLoss

    from tpusystem.train import SGD
    module = gpt2_tiny(attention='xla', dtype='float32')
    # SGD: parameter deltas are lr*grad, so the comparison stays at float
    # precision (Adam's rsqrt amplifies reorder noise on tiny grads)
    optimizer = SGD(lr=1e-1)
    criterion = NextTokenLoss()
    apply_fn = flax_apply(module)
    rng = np.random.default_rng(7)
    tokens = np.asarray(rng.integers(0, 256, (8, 16)), np.int32)
    # microbatch 0 (rows 0-3) heavily padded, the rest untouched:
    # per-microbatch token counts differ, so equal-weight averaging drifts
    tokens[:3, 4:] = -1
    tokens = jnp.asarray(tokens)

    full = build_train_step(apply_fn, criterion, optimizer, jit=False)
    accum = build_train_step(apply_fn, criterion, optimizer, accumulate=4,
                             jit=False)
    state_a = init_state(module, optimizer, tokens[:1], rng=0)
    state_b = init_state(module, optimizer, tokens[:1], rng=0)
    state_a, (_, loss_a) = full(state_a, tokens, tokens)
    state_b, (_, loss_b) = accum(state_b, tokens, tokens)

    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state_a.params),
                    jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


class MaskedCrossEntropy:
    """CrossEntropyLoss with pad ids < 0 masked out, exposing the
    ``weight`` seam (unmasked-example count) the accumulation path keys on
    — the minimal criterion shape of the masked LM losses."""

    def __call__(self, logits, targets):
        import optax
        mask = (targets >= 0).astype(jnp.float32)
        safe = jnp.maximum(targets, 0)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), safe)
        return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def weight(self, targets):
        return jnp.sum((targets >= 0).astype(jnp.float32))


def test_gradient_accumulation_fully_padded_microbatch_no_nan():
    """Satellite: a FULLY padded microbatch contributes weight 0 — its
    (0-weighted) grads and loss must drop out of the weighted mean without
    poisoning it, matching the full-batch step on the valid rows."""
    module = MLP(features=(16,), classes=10, dropout=0.0)
    optimizer = Adam(lr=1e-2)
    criterion = MaskedCrossEntropy()
    apply_fn = flax_apply(module)
    rng = np.random.default_rng(9)
    inputs = jnp.asarray(rng.standard_normal((8, 28, 28)), jnp.float32)
    targets = np.asarray(rng.integers(0, 10, (8,)), np.int32)
    targets[:2] = -1                 # microbatch 0 of accumulate=4: all pad
    targets = jnp.asarray(targets)

    full = build_train_step(apply_fn, criterion, optimizer, jit=False)
    accum = build_train_step(apply_fn, criterion, optimizer, accumulate=4,
                             jit=False)
    state_a = init_state(module, optimizer, inputs[:1], rng=0)
    state_b = init_state(module, optimizer, inputs[:1], rng=0)
    state_a, (_, loss_a) = full(state_a, inputs, targets)
    state_b, (_, loss_b) = accum(state_b, inputs, targets)
    assert np.isfinite(float(loss_b))
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(state_a.params),
                    jax.tree.leaves(state_b.params)):
        assert np.all(np.isfinite(np.asarray(b)))
        # Adam's rsqrt amplifies the f32-accumulation reorder on tiny
        # grads (same caveat as the token-weighted sibling test)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=2e-6)


def test_gradient_accumulation_all_pad_batch_epsilon_guard():
    """Satellite (train/step.py weight_sum epsilon): EVERY microbatch fully
    padded — weight_sum hits the epsilon floor, the step must produce
    finite zero-ish grads (params bitwise unchanged for SGD-free Adam
    moments at zero grads is not guaranteed; finiteness and a zero loss
    are), never NaN."""
    module = MLP(features=(16,), classes=10, dropout=0.0)
    optimizer = Adam(lr=1e-2)
    criterion = MaskedCrossEntropy()
    step = build_train_step(flax_apply(module), criterion, optimizer,
                            accumulate=4, jit=False)
    inputs = jnp.asarray(np.random.default_rng(9).standard_normal((8, 28, 28)),
                         jnp.float32)
    targets = jnp.full((8,), -1, jnp.int32)     # nothing valid anywhere
    state = init_state(module, optimizer, inputs[:1], rng=0)
    state, (_, loss) = step(state, inputs, targets)
    assert float(loss) == 0.0
    for leaf in jax.tree.leaves(state.params):
        assert np.all(np.isfinite(np.asarray(leaf)))
    for leaf in jax.tree.leaves(state.opt_state):
        assert np.all(np.isfinite(np.asarray(leaf)))


@pytest.mark.slow
def test_gradient_accumulation_bf16_params_compile():
    """Weighted accumulation keeps the scan carry well-typed when params are
    low-precision (grads accumulate in f32, cast back to the param dtype)."""
    from tpusystem.models import gpt2_tiny
    from tpusystem.train import NextTokenLoss

    module = gpt2_tiny(attention='xla')
    optimizer = Adam(lr=1e-3)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer,
                            accumulate=2, jit=False)
    tokens = jnp.zeros((4, 16), jnp.int32)
    state = init_state(module, optimizer, tokens[:1],
                       param_dtype=jnp.bfloat16)
    state, (_, loss) = step(state, tokens, tokens)
    assert jax.tree.leaves(state.params)[0].dtype == jnp.bfloat16
    assert np.isfinite(float(loss))
