"""Registry identity contracts (reference parity: tests/registry/test_func.py,
test_nest.py, test_core.py, test_reg.py)."""

from hashlib import md5
from json import dumps

import pytest

from tpusystem.registry import (
    Registry, getarguments, gethash, getmetadata, getname,
    register, sethash, setname,
)


def test_capture_and_accessors():
    @register
    class Model:
        def __init__(self, a: int, b: float, c: str):
            self.a, self.b, self.c = a, b, c

    model = Model(1, 2.0, '3')
    assert getname(model) == 'Model'
    assert getarguments(model) == {'a': 1, 'b': 2.0, 'c': '3'}
    expected = md5(('Model' + dumps({'a': 1, 'b': 2.0, 'c': '3'})).encode()).hexdigest()
    assert gethash(model) == expected


def test_pinned_digest_parity_with_reference():
    """Identity hashes are portable: same inputs produce the exact digest the
    reference pins (tests/registry/test_func.py:35), so checkpoints keyed by
    hash remain addressable across framework implementations."""
    @register
    class Model:
        def __init__(self, x: int, y: float, z, t: str = '5'):
            ...

    model = Model(1, 2.0, '3')
    assert getarguments(model) == {'x': 1, 'y': 2.0, 'z': '3'}
    assert gethash(model) == 'b12461be073bff9f5847f3f423767aa2'


def test_hash_is_deterministic_across_instances():
    @register
    class Net:
        def __init__(self, width: int):
            self.width = width

    assert gethash(Net(128)) == gethash(Net(128))
    assert gethash(Net(128)) != gethash(Net(256))


def test_unregistered_object_raises():
    class Plain:
        ...
    with pytest.raises(AttributeError):
        getarguments(Plain())
    with pytest.raises(AttributeError):
        gethash(Plain())


def test_rename_decorator():
    @register('Criterion')
    class SoftmaxLoss:
        def __init__(self, smoothing: float = 0.0):
            self.smoothing = smoothing

    loss = SoftmaxLoss(smoothing=0.1)
    assert getname(loss) == 'Criterion'
    assert getarguments(loss) == {'smoothing': 0.1}


def test_excluded_args_for_optimizer_style_ctors():
    @register
    class Net:
        def __init__(self, width: int):
            self.width = width

    class Optim:
        def __init__(self, params, lr: float):
            self.params, self.lr = params, lr

    register(Optim, excluded_args=[0])
    optimizer = Optim(object(), lr=0.01)
    assert getarguments(optimizer) == {'lr': 0.01}


def test_manual_hash_and_name():
    class Anything:
        ...
    thing = Anything()
    sethash(thing, 'cafebabe')
    setname(thing, 'Thing')
    assert gethash(thing) == 'cafebabe'
    assert getname(thing) == 'Thing'
    assert getmetadata(thing) == {'hash': 'cafebabe', 'name': 'Thing'}


def test_metadata_roundtrip():
    @register
    class Widget:
        def __init__(self, size: int):
            self.size = size

    widget = Widget(3)
    metadata = getmetadata(widget)
    assert metadata == {'arguments': {'size': 3}}
    sethash(widget)
    assert getmetadata(widget)['hash'] == gethash(widget)


def test_nested_registered_objects_serialize_recursively():
    @register
    class Inner:
        def __init__(self, depth: int):
            self.depth = depth

    @register
    class Leaf:
        def __init__(self):
            ...

    @register
    class Outer:
        def __init__(self, inner, leaf):
            self.inner, self.leaf = inner, leaf

    outer = Outer(Inner(2), Leaf())
    assert getarguments(outer) == {
        'inner': {'name': 'Inner', 'arguments': {'depth': 2}},
        'leaf': 'Leaf',
    }


def test_registry_catalog():
    registry = Registry()

    @registry.register
    class Encoder:
        def __init__(self, layers: int, width: int):
            ...

    @registry.register('Head')
    class Classifier:
        def __init__(self, classes: int):
            ...

    assert registry.get('Encoder') is Encoder
    assert registry.get('Head') is Classifier
    assert registry.get('Missing') is None
    assert set(registry.keys()) == {'Encoder', 'Head'}
    assert registry.signature('Encoder') == {'layers': 'int', 'width': 'int'}
    assert registry.signature('Head') == {'classes': 'int'}

    head = registry.get('Head')(classes=10)
    assert getname(head) == 'Head'
    assert getarguments(head) == {'classes': 10}


def test_frozen_dataclass_capture():
    """Side-table storage works where instance attributes cannot — frozen
    dataclasses model flax linen Modules."""
    from dataclasses import dataclass

    @register
    @dataclass(frozen=True)
    class FrozenModule:
        features: int = 32

    module = FrozenModule(features=64)
    assert getarguments(module) == {'features': 64}
    assert gethash(module) == gethash(FrozenModule(features=64))
