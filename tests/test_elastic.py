"""Elastic training: membership epochs, hot resharding, the wave drill.

Three tiers, cheapest first:

* **protocol** — the :class:`ElasticCoordinator` settle/propose/commit
  machine driven by a fake clock over stub transports: a multi-host wave
  folds into ONE resize, commits need every proposed member's echo,
  ``min_world`` holds the line, flaps cancel, cooldown rate-limits,
  replacement hosts bootstrap from the first proposal that includes
  them, and a straggler whose pre-commit frames were dropped completes
  via the re-echo;
* **resharding** — :class:`ShardedLeaf` piece merging and
  re-layout onto a different mesh, bitwise, with typed failures on
  missing coverage and mixed steps;
* **the drill** (the acceptance contract) — a real Hub + transports +
  supervisors pod: a :class:`PreemptionWave` kills 2 of 4 hosts mid-run,
  the survivors converge on ONE resize within the settle window,
  training state hot-reshards onto the shrunk mesh **bitwise-equivalent
  to restoring the same step from disk**, training takes another finite
  step at the new size, and a returning host grows the world back —
  never a cold full-world restart.
"""

from __future__ import annotations

import time

import pytest

from tpusystem.parallel.elastic import (ElasticCoordinator, ElasticPolicy,
                                        ResizeDecision, collect_pieces,
                                        elastic_resume, split_pieces)
from tpusystem.observe.events import (ElasticTimeline, WorldResizeProposed,
                                      WorldResized)
from tpusystem.services.prodcon import Consumer, Producer

IDENTITY = 'elastic-drill'


def wait_until(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


# ---------------------------------------------------------------------------
# protocol: fake clock, stub transports, no sockets


class FakeClock:
    def __init__(self):
        self.time = 0.0

    def __call__(self):
        return self.time

    def advance(self, seconds):
        self.time += seconds


class StubTransport:
    """The coordinator-facing transport surface, wires replaced by lists."""

    def __init__(self, rank):
        self.rank = rank
        self._channels = {}
        self.on_control = None
        self.outbox = []

    def subscribe(self, channel, callback):
        self._channels[channel] = callback

    def send_event(self, channel, message):
        self.outbox.append((channel, message))

    def deliver(self, channel, message):
        self._channels[channel](message)


def capture_elastic(producer=None):
    producer = producer or Producer()
    seen = []
    consumer = Consumer()
    for kind in (WorldResizeProposed, WorldResized, ElasticTimeline):
        consumer.register(kind, seen.append)
    producer.register(consumer)
    return producer, seen


class Pod:
    """A stub supervisor pod: coordinators + hand-cranked frame routing."""

    def __init__(self, size, clock, policy, capture_rank=0):
        self.clock = clock
        self.live = set(range(size))
        self.stubs = [StubTransport(rank) for rank in range(size)]
        self.producer, self.seen = capture_elastic()
        self.coords = [
            ElasticCoordinator(
                self.stubs[rank], rank, size, policy=policy, clock=clock,
                producer=self.producer if rank == capture_rank else None)
            for rank in range(size)]

    def lose(self, rank):
        """The hub's 'lost' fanout: every other live host hears it (and
        ingests it now — the live coordinator's poll thread would)."""
        self.live.discard(rank)
        for survivor in self.live:
            self.stubs[survivor].on_control(('lost', rank, 0.0, 'socket'))
        for survivor in sorted(self.live):
            self.coords[survivor].step()

    def join(self, rank):
        """The hub's 'joined' fanout (excludes the joiner itself) — for a
        host whose original coordinator is still running (a flapped
        link, a fast rejoin)."""
        for other in self.live:
            if other != rank:
                self.stubs[other].on_control(('joined', rank))
        self.live.add(rank)
        for member in sorted(self.live):
            self.coords[member].step()

    def replace(self, rank, policy):
        """A replacement host: fresh transport + bootstrapping coordinator
        (``members=None`` — it adopts the first proposal that includes
        it)."""
        while len(self.stubs) <= rank:
            self.stubs.append(None)
            self.coords.append(None)
        self.stubs[rank] = StubTransport(rank)
        self.coords[rank] = ElasticCoordinator(
            self.stubs[rank], rank, policy=policy, clock=self.clock,
            members=None)
        self.join(rank)

    def pump(self, rounds=6):
        """Step every live coordinator and route every broadcast frame to
        every OTHER live host — the hub's event fanout, hand-cranked."""
        for _ in range(rounds):
            for rank in sorted(self.live):
                self.coords[rank].step()
            for rank in sorted(self.live):
                stub = self.stubs[rank]
                while stub.outbox:
                    channel, message = stub.outbox.pop(0)
                    for other in sorted(self.live):
                        if other != rank:
                            self.stubs[other].deliver(channel, message)


class TestProtocol:

    def policy(self, **overrides):
        knobs = dict(settle_window=1.0, rebroadcast=100.0)
        knobs.update(overrides)
        return ElasticPolicy(**knobs)

    def test_wave_folds_multiple_losses_into_one_resize(self):
        """The headline property: 2 losses inside one settle window are
        ONE membership epoch, not two resizes."""
        clock = FakeClock()
        pod = Pod(5, clock, self.policy())
        pod.lose(3)
        pod.pump()
        assert not any(coord.decisions for coord in pod.coords)
        clock.advance(0.5)
        pod.lose(4)                        # extends the settle window
        pod.pump()
        clock.advance(0.9)                 # 1.4 < 0.5 + 1.0... just under
        pod.pump()
        assert not any(pod.coords[rank].decisions for rank in pod.live)
        clock.advance(0.2)                 # the window closes
        pod.pump()
        for rank in pod.live:
            assert pod.coords[rank].decisions == [
                ResizeDecision(epoch=1, members=(0, 1, 2))]
        resized = [e for e in pod.seen if isinstance(e, WorldResized)]
        assert len(resized) == 1           # ONE resize for the whole wave
        assert resized[0].size == 3 and resized[0].epoch == 1
        proposed = [e for e in pod.seen
                    if isinstance(e, WorldResizeProposed)]
        assert proposed and proposed[0].cause == 'loss'

    def test_commit_requires_every_proposed_member(self):
        clock = FakeClock()
        pod = Pod(3, clock, self.policy())
        pod.lose(2)
        clock.advance(1.1)
        pod.coords[0].step()               # proposes; only its own vote
        assert pod.coords[0].step() is None
        pod.coords[1].step()               # rank 1 proposes too
        channel, message = pod.stubs[1].outbox.pop(0)
        pod.stubs[0].deliver(channel, message)
        decision = pod.coords[0].step()    # now every member voted
        assert decision == ResizeDecision(epoch=1, members=(0, 1))

    def test_min_world_holds_until_capacity_returns(self):
        clock = FakeClock()
        pod = Pod(4, clock, self.policy(min_world=3))
        pod.lose(2)
        pod.lose(3)
        clock.advance(1.1)
        pod.pump()
        assert not pod.coords[0].decisions     # would shrink below min
        pod.join(3)                            # capacity returns
        clock.advance(1.1)
        pod.pump()
        for rank in pod.live:
            assert pod.coords[rank].decisions[-1].members == (0, 1, 3)

    def test_loss_flapping_back_within_the_window_cancels_the_wave(self):
        clock = FakeClock()
        pod = Pod(3, clock, self.policy())
        pod.lose(2)
        clock.advance(0.5)
        pod.join(2)                        # the link flaked, host is back
        clock.advance(1.1)
        pod.pump()
        assert not any(coord.decisions for coord in pod.coords)
        assert pod.coords[0].members == (0, 1, 2)

    def test_cooldown_defers_the_next_wave(self):
        clock = FakeClock()
        pod = Pod(4, clock, self.policy(cooldown=5.0))
        pod.lose(3)
        clock.advance(1.1)
        pod.pump()
        assert pod.coords[0].decisions[-1].epoch == 1
        pod.lose(2)
        clock.advance(1.1)                 # settle passed, cooldown not
        pod.pump()
        assert len(pod.coords[0].decisions) == 1
        clock.advance(5.0)                 # cooldown expires
        pod.pump()
        assert pod.coords[0].decisions[-1] == ResizeDecision(
            epoch=2, members=(0, 1))

    def test_replacement_host_bootstraps_from_the_first_proposal(self):
        clock = FakeClock()
        policy = self.policy()
        pod = Pod(3, clock, policy)
        pod.lose(2)
        clock.advance(1.1)
        pod.pump()
        assert pod.coords[0].members == (0, 1)
        pod.replace(2, policy)             # fresh coordinator, members=None
        clock.advance(1.1)
        pod.pump()
        for rank in (0, 1, 2):
            assert pod.coords[rank].decisions[-1] == ResizeDecision(
                epoch=2, members=(0, 1, 2))
        assert pod.coords[2].members == (0, 1, 2)
        assert pod.coords[2].epoch == 2

    def test_max_world_caps_the_grow(self):
        clock = FakeClock()
        policy = self.policy(max_world=3)
        pod = Pod(2, clock, policy)
        pod.replace(2, policy)
        pod.replace(3, policy)             # one joiner too many
        clock.advance(1.1)
        pod.pump()
        assert pod.coords[0].decisions[-1].members == (0, 1, 2)
        assert pod.coords[3].members is None     # left pending the cap

    def test_flapped_out_host_adopts_the_readmission_epoch(self):
        """Review regression: a host flapped OUT of a committed shrink
        (it never saw the epoch) is later re-admitted — the peers'
        higher-epoch proposal names the host's own stale member set, so
        the old code computed no diff and silently dropped it, stalling
        the commit forever. It must adopt-and-echo like a bootstrap."""
        clock = FakeClock()
        pod = Pod(3, clock, self.policy())
        pod.lose(0)                        # rank 0 flaps out, sees nothing
        clock.advance(1.1)
        pod.pump()
        for rank in (1, 2):
            assert pod.coords[rank].decisions == [
                ResizeDecision(epoch=1, members=(1, 2))]
        assert pod.coords[0].epoch == 0    # it missed the whole epoch
        pod.join(0)                        # the link comes back
        clock.advance(1.1)
        pod.pump()
        for rank in (0, 1, 2):
            assert pod.coords[rank].decisions[-1] == ResizeDecision(
                epoch=2, members=(0, 1, 2)), rank
        assert pod.coords[0].epoch == 2

    def test_capped_joiner_stays_pending_for_the_next_wave(self):
        """Review regression: a joiner held out by max_world used to be
        silently cleared when the settle window closed; the policy's
        contract is that it waits for a later wave with room."""
        clock = FakeClock()
        policy = self.policy(max_world=2)
        pod = Pod(2, clock, policy)
        pod.replace(2, policy)             # no room: world is at the cap
        clock.advance(1.1)
        pod.pump()
        assert not pod.coords[0].decisions
        pod.lose(1)                        # room opens
        clock.advance(1.1)
        pod.pump()
        assert pod.coords[0].decisions[-1] == ResizeDecision(
            epoch=1, members=(0, 2))       # the pending joiner folded in
        assert pod.coords[2].members == (0, 2)

    def test_close_unhooks_the_transport_and_ignores_late_frames(self):
        """A coordinator outlived by its transport (a replacement host
        builds a NEW coordinator on the same wire) must go inert on
        close: no unbounded inbox growth, and the on_control chain head
        restored."""
        clock = FakeClock()
        stub = StubTransport(0)
        policy = self.policy()
        first = ElasticCoordinator(stub, 0, 3, policy=policy, clock=clock)
        second = ElasticCoordinator(stub, 0, 3, policy=policy, clock=clock)
        second.close()
        stub.on_control(('lost', 2, 0.0, 'socket'))   # reaches FIRST only
        first.step()
        assert first._lost == {2}
        assert second._inbox.empty()       # closed: frames not hoarded
        second._ingest(('lost', 1, 0.0, 'socket'))
        assert second._inbox.empty()
        first.close()
        assert stub.on_control is None     # fully unhooked

    def test_elastic_consumer_raises_at_the_drain(self):
        """The worker-side 46 path: a committed WorldResized event raises
        WorldResizedError from the bus drain, mapping to RESIZED_EXIT."""
        from tpusystem.parallel.elastic import elastic_consumer
        from tpusystem.parallel.recovery import (RESIZED_EXIT,
                                                 WorldResizedError,
                                                 exit_for_restart)
        producer = Producer()
        producer.register(elastic_consumer())
        with pytest.raises(WorldResizedError) as excinfo:
            producer.dispatch(WorldResized(epoch=2, members=[0, 2], size=2,
                                           seconds=0.1))
        assert excinfo.value.epoch == 2
        assert excinfo.value.members == (0, 2)
        assert exit_for_restart(excinfo.value).code == RESIZED_EXIT

    def test_straggler_completes_via_the_reecho(self):
        """Events are at-most-once: a rank whose pre-commit proposals were
        all dropped must still commit — the committed side re-echoes when
        it sees the straggler's rebroadcast."""
        clock = FakeClock()
        pod = Pod(3, clock, self.policy(rebroadcast=0.5))
        pod.lose(2)
        clock.advance(1.1)
        pod.coords[0].step()
        pod.stubs[0].outbox.clear()        # 0's proposal is dropped
        pod.coords[1].step()               # 1 proposes
        channel, message = pod.stubs[1].outbox.pop(0)
        pod.stubs[0].deliver(channel, message)
        assert pod.coords[0].step() is not None      # 0 commits
        assert not pod.coords[1].decisions           # 1 is the straggler
        clock.advance(0.6)                           # 1 rebroadcasts
        pod.coords[1].step()
        channel, message = pod.stubs[1].outbox.pop(0)
        pod.stubs[0].deliver(channel, message)
        pod.coords[0].step()                         # committed 0 re-echoes
        channel, message = pod.stubs[0].outbox.pop(0)
        pod.stubs[1].deliver(channel, message)
        assert pod.coords[1].step() is not None
        assert pod.coords[1].decisions == pod.coords[0].decisions


class TestResizeDecision:

    def test_rank_and_buddy_derivation(self):
        decision = ResizeDecision(epoch=3, members=(0, 2, 5, 7))
        assert decision.size == 4
        assert [decision.rank_of(m) for m in decision.members] == [0, 1, 2, 3]
        # buddies pair within the NEW dense ordering: (0,2) and (5,7)
        assert decision.buddy_of(0) == 2 and decision.buddy_of(2) == 0
        assert decision.buddy_of(5) == 7 and decision.buddy_of(7) == 5
        odd = ResizeDecision(epoch=1, members=(1, 4, 6))
        assert odd.buddy_of(6) is None     # the unpaired last member

    def test_env_round_trip(self):
        decision = ResizeDecision(epoch=2, members=(0, 3))
        env = decision.env(3)
        assert ResizeDecision.from_env(env) == (decision, 3)
        assert ResizeDecision.from_env({}) is None
        assert ResizeDecision.from_env(
            {'TPUSYSTEM_ELASTIC': 'not json'}) is None


# ---------------------------------------------------------------------------
# resharding: piece merge + re-layout, bitwise


class TestResharding:

    def test_sharded_leaf_merges_and_reshards_across_meshes(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec
        from tpusystem.checkpoint.memstore import ShardedLeaf
        from tpusystem.parallel import MeshSpec
        devices = jax.devices('cpu')
        mesh4 = MeshSpec(data=4).build(devices[:4])
        values = jnp.arange(64, dtype=jnp.float32).reshape(8, 8) * 1.37
        sharded = jax.device_put(
            values, NamedSharding(mesh4, PartitionSpec('data')))
        whole = ShardedLeaf.from_array(sharded)
        # split into two 'hosts' of 2 pieces each, then merge back
        keys = sorted(whole.shards)
        hosts = [ShardedLeaf(whole.shape, whole.dtype,
                             {key: whole.shards[key] for key in keys[:2]}),
                 ShardedLeaf(whole.shape, whole.dtype,
                             {key: whole.shards[key] for key in keys[2:]})]
        merged = hosts[0].merged(hosts[1])
        assert len(merged.shards) == 4
        # the 2-device mesh wants DIFFERENT slice boundaries: exact
        # placement refuses, the reshard path reassembles bitwise
        mesh2 = MeshSpec(data=2).build(devices[:2])
        target = jax.device_put(
            jnp.zeros_like(values), NamedSharding(mesh2,
                                                  PartitionSpec('data')))
        with pytest.raises(ValueError, match='do not cover'):
            merged.place(target)
        placed = merged.place(target, reshard=True)
        np.testing.assert_array_equal(np.asarray(placed), np.asarray(values))
        assert placed.sharding == target.sharding
        # one host's pieces alone do not cover: typed failure -> disk
        with pytest.raises(ValueError, match='cover only'):
            hosts[0].place(target, reshard=True)

    def test_merge_hot_refuses_mixed_steps(self):
        from tpusystem.checkpoint.memstore import (HotState, blob_digest,
                                                   merge_hot)
        import pickle
        blob = pickle.dumps([1])
        entries = [HotState(step=3, digest=blob_digest(blob), blob=blob),
                   HotState(step=4, digest=blob_digest(blob), blob=blob)]
        with pytest.raises(ValueError, match='disagree on the step'):
            merge_hot(entries)


# ---------------------------------------------------------------------------
# the drill: real Hub + transports + supervisors, a wave, one resize,
# bitwise-equivalent reshard, grow back


class TestWaveDrill:

    def cell(self, mesh):
        """One training cell on the given mesh: state, jitted step,
        placed batch."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from tpusystem.models import gpt2_tiny
        from tpusystem.parallel import (TensorParallel, batch_sharding)
        from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                                     flax_apply, init_state)
        module = gpt2_tiny(layers=2, dim=32, heads=2, max_seq=32)
        optimizer = AdamW(lr=1e-3)
        policy = TensorParallel(module.partition_rules(), fsdp=True,
                                fsdp_min_size=16)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 256, (4, 16)), jnp.int32)
        state = policy.place(init_state(module, optimizer, tokens[:1]), mesh)
        step = build_train_step(flax_apply(module), NextTokenLoss(),
                                optimizer)
        placed = jax.device_put(tokens, batch_sharding(mesh))
        return state, step, placed, policy, module, optimizer, tokens

    def assert_bitwise(self, left, right):
        import jax
        import numpy as np
        for a, b in zip(jax.tree.leaves(left), jax.tree.leaves(right)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_kill_two_of_four_resize_once_reshard_bitwise_grow_back(
            self, tmp_path):
        import jax
        import numpy as np
        from tpusystem.checkpoint import Checkpointer, MemStoreClient
        from tpusystem.parallel import Hub, MeshSpec, TcpTransport, Supervisor
        from tpusystem.parallel.chaos import ChaosTransport, PreemptionWave
        from tpusystem.parallel import batch_sharding

        devices = jax.devices('cpu')
        spec = MeshSpec(fsdp=4)            # every host holds UNIQUE shards
        mesh4 = spec.build(devices[:4])
        hub = Hub(4)
        # ChaosTransport everywhere: the real wire, and the doomed ranks'
        # kill() is the crashed-host signature (EOF, no 'bye')
        transports = [ChaosTransport(hub.address, rank, 4)
                      for rank in range(4)]
        assert wait_until(lambda: len(hub._clients) == 4)
        supervisors = [Supervisor(['w'], rank=rank, transport=transports[rank],
                                  buddy=rank ^ 1) for rank in range(4)]
        producer, seen = capture_elastic()
        policy = ElasticPolicy(settle_window=0.25, rebroadcast=0.1)
        coords = [ElasticCoordinator(transports[rank], rank, 4, policy=policy,
                                     producer=producer if rank == 0 else None,
                                     on_resize=None).start()
                  for rank in range(4)]
        grow_extras = []
        clients = []
        checkpointer = Checkpointer(tmp_path, async_save=False)
        try:
            state, step, placed, place_policy, module, optimizer, tokens = \
                self.cell(mesh4)
            die_at = 2
            wave = PreemptionWave(step=die_at,
                                  kills=(transports[1].kill,
                                         transports[3].kill))
            clients = [MemStoreClient(supervisor.server.address)
                       for supervisor in supervisors]
            while int(state.step) < die_at:
                state, (_, loss) = step(state, placed, placed)
                at = int(state.step)
                checkpointer.save(IDENTITY, at, state, extras={'step': at})
                # each "host" pushes only ITS pieces (the multi-host
                # serialize_state contract, simulated on virtual devices)
                for rank, blob in enumerate(split_pieces(state, mesh4, 4)):
                    clients[rank].push(IDENTITY, at, blob,
                                       extras={'step': at})
                if at == die_at:
                    # buddy replication is async behind the push ack; the
                    # drill pins the HOT reshard path, so the wave must
                    # not beat the step-die_at replicas to the survivors
                    # (a wave that DOES beat replication is the disk-
                    # fallback case, drilled in test_chaos.py)
                    assert wait_until(lambda: all(
                        (held := supervisors[rank].store.newest(
                            IDENTITY, replica=True)) is not None
                        and held.step == die_at for rank in (0, 2)))
                wave(at)
            assert wave.fired

            # --- ONE resize for the whole 2-host wave ------------------
            assert wait_until(lambda: bool(coords[0].decisions
                                           and coords[2].decisions))
            time.sleep(3 * policy.settle_window)     # no second epoch
            for rank in (0, 2):
                assert coords[rank].decisions == [
                    ResizeDecision(epoch=1, members=(0, 2))]
            resized = [e for e in seen if isinstance(e, WorldResized)]
            assert len(resized) == 1 and resized[0].size == 2
            decision = coords[0].decisions[0]
            assert decision.buddy_of(0) == 2         # pairs re-derived

            # --- hot reshard onto the shrunk mesh, bitwise vs disk -----
            mesh2 = spec.resized(2).build(devices[:2])
            from tpusystem.train import init_state
            blank = place_policy.place(
                init_state(module, optimizer, tokens[:1]), mesh2)
            restored = {}
            for rank in decision.members:
                pieces = collect_pieces(
                    IDENTITY, rank=rank, members=range(4),
                    survivors=decision.members,
                    store=supervisors[rank].store,
                    transport=transports[rank],
                    buddy_of=lambda member: member ^ 1)
                assert len(pieces) == 4              # all four hosts' shards
                restored[rank] = elastic_resume(checkpointer, IDENTITY,
                                                blank, pieces)
            for rank, (got, at, extras, source) in restored.items():
                assert source == 'hot-reshard', (rank, source)
                assert at == die_at and extras == {'step': die_at}
            disk = checkpointer.restore(IDENTITY, blank, epoch=die_at)
            self.assert_bitwise(restored[0][0], disk)
            self.assert_bitwise(restored[2][0], disk)

            # --- training continues at n-k with a finite loss ----------
            state2 = restored[0][0]
            placed2 = jax.device_put(tokens, batch_sharding(mesh2))
            state2, (_, loss2) = step(state2, placed2, placed2)
            assert int(state2.step) == die_at + 1
            assert np.isfinite(float(loss2))
            coords[0].resumed(step=int(state2.step), source='hot-reshard')
            timelines = [e for e in seen if isinstance(e, ElasticTimeline)]
            assert len(timelines) == 1
            assert timelines[0].source == 'hot-reshard'
            assert timelines[0].size == 2

            # --- a returning host grows the world back -----------------
            hub.readmit(3)
            replacement = TcpTransport(hub.address, 3, 4)
            transports.append(replacement)
            grow_extras.append(ElasticCoordinator(
                replacement, 3, policy=policy, members=None).start())
            assert wait_until(lambda: all(
                coord.decisions and coord.decisions[-1].epoch == 2
                for coord in (coords[0], coords[2], grow_extras[0])))
            for coord in (coords[0], coords[2], grow_extras[0]):
                assert coord.decisions[-1].members == (0, 2, 3)
            assert [e.size for e in seen
                    if isinstance(e, WorldResized)] == [2, 3]
        finally:
            for client in clients:
                client.close()
            for coord in coords + grow_extras:
                coord.close()
            for supervisor in supervisors:
                supervisor.close()
            checkpointer.close()
            for transport in transports:
                transport.close()
            hub.close()
