"""Test configuration.

All tests run on CPU with 8 virtual XLA devices so mesh/collective code paths
(DP/FSDP/TP/PP/SP/EP, ring attention) execute in CI without TPU hardware —
the strategy the reference lacks entirely (SURVEY.md §4: reference tests are
single-process CPU-only; we add simulated-multi-device coverage).
"""

import os

flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_count=8').strip()

import jax

# The environment tunnels a real TPU chip and its plugin *prepends* itself to
# jax_platforms (config becomes 'axon,cpu'), so neither JAX_PLATFORMS=cpu in
# the env nor setdefault wins. Forcing the config after import does.
jax.config.update('jax_platforms', 'cpu')

import pathlib
import shutil

import pytest


@pytest.fixture(scope='session')
def data_directory():
    path = pathlib.Path(__file__).parent / 'data' / 'test'
    path.mkdir(parents=True, exist_ok=True)
    yield path
    shutil.rmtree(path.parent, ignore_errors=True)
