"""Test configuration.

All tests run on CPU with 8 virtual XLA devices so mesh/collective code paths
(DP/FSDP/TP/PP/SP/EP, ring attention) execute in CI without TPU hardware —
the strategy the reference lacks entirely (SURVEY.md §4: reference tests are
single-process CPU-only; we add simulated-multi-device coverage).
"""

# The environment tunnels a real TPU chip and its plugin *prepends* itself to
# jax_platforms (config becomes 'axon,cpu'), so JAX_PLATFORMS=cpu in the env
# does not win; force_host_platform handles the env flag + config ordering.
from tpusystem.parallel import force_host_platform

force_host_platform(8)

import os
import pathlib
import shutil
import time

import pytest


@pytest.fixture(scope='session')
def data_directory():
    path = pathlib.Path(__file__).parent / 'data' / 'test'
    path.mkdir(parents=True, exist_ok=True)
    yield path
    shutil.rmtree(path.parent, ignore_errors=True)


# tier-1 wall-time hygiene: the fast profile (`-m 'not slow'`) has an 870s
# budget, and a multi-process drill that silently grows past ~10s of compile
# time erodes it for everyone. Any unmarked test that exceeds the threshold
# fails with an instruction to carry @pytest.mark.slow. The clock starts
# after session/module-scoped fixtures (their one-time compiles are shared,
# not this test's bill). ~10s is the review guideline; the ENFORCED floor
# is calibrated above the slowest legitimate unmarked test under full-suite
# CPU contention (test_schedule's ragged-exchange parity measures ~48s
# there), so the guard catches runaway additions without flaking the
# existing matrix. Override with TPUSYSTEM_TIER1_SLOW (seconds, <= 0
# disables — for instrumented or heavily-loaded CI hosts).
TIER1_SLOW_SECONDS = float(os.environ.get('TPUSYSTEM_TIER1_SLOW', '60'))


@pytest.fixture(autouse=True)
def _tier1_wall_budget(request):
    if (TIER1_SLOW_SECONDS <= 0
            or request.node.get_closest_marker('slow') is not None):
        yield
        return
    started = time.monotonic()
    yield
    elapsed = time.monotonic() - started
    if elapsed > TIER1_SLOW_SECONDS:
        pytest.fail(
            f'{request.node.nodeid} took {elapsed:.1f}s without '
            f'@pytest.mark.slow — mark it slow (tier-1 keeps its 870s '
            f'budget) or speed it up; TPUSYSTEM_TIER1_SLOW={TIER1_SLOW_SECONDS:g}s',
            pytrace=False)
