"""Test configuration.

All tests run on CPU with 8 virtual XLA devices so mesh/collective code paths
(DP/FSDP/TP/PP/SP/EP, ring attention) execute in CI without TPU hardware —
the strategy the reference lacks entirely (SURVEY.md §4: reference tests are
single-process CPU-only; we add simulated-multi-device coverage).
"""

# The environment tunnels a real TPU chip and its plugin *prepends* itself to
# jax_platforms (config becomes 'axon,cpu'), so JAX_PLATFORMS=cpu in the env
# does not win; force_host_platform handles the env flag + config ordering.
from tpusystem.parallel import force_host_platform

force_host_platform(8)

import pathlib
import shutil

import pytest


@pytest.fixture(scope='session')
def data_directory():
    path = pathlib.Path(__file__).parent / 'data' / 'test'
    path.mkdir(parents=True, exist_ok=True)
    yield path
    shutil.rmtree(path.parent, ignore_errors=True)
