"""DI kernel contracts (reference parity: tests/test_deps.py:25-45)."""

from unittest.mock import Mock

from tpusystem.depends import Depends, Provider, inject


def test_plain_dependency_resolves():
    provider = Provider()

    def dependency():
        return 42

    @inject(provider)
    def function(value: int = Depends(dependency)):
        return value

    assert function() == 42


def test_generator_dependency_opens_and_closes():
    provider = Provider()
    witness = Mock()

    def dependency():
        witness.opened()
        yield 'resource'
        witness.closed()

    @inject(provider)
    def function(resource: str = Depends(dependency)):
        assert not witness.closed.called
        return resource

    assert function() == 'resource'
    witness.opened.assert_called_once()
    witness.closed.assert_called_once()


def test_override_replaces_plain_with_generator():
    provider = Provider()
    witness = Mock()

    def dependency():
        raise NotImplementedError

    def replacement():
        yield 'late-bound'
        witness.closed()

    provider.override(dependency, replacement)

    @inject(provider)
    def function(value=Depends(dependency)):
        return value

    assert function() == 'late-bound'
    witness.closed.assert_called_once()


def test_explicit_argument_wins_over_dependency():
    provider = Provider()

    @inject(provider)
    def function(value=Depends(lambda: 'injected')):
        return value

    assert function('explicit') == 'explicit'


def test_nested_dependencies_resolve_recursively():
    provider = Provider()

    def config():
        return {'device_count': 8}

    def mesh(cfg=Depends(config)):
        return f"mesh[{cfg['device_count']}]"

    @inject(provider)
    def function(m=Depends(mesh)):
        return m

    assert function() == 'mesh[8]'
    provider.override(config, lambda: {'device_count': 2})
    assert function() == 'mesh[2]'


def test_shared_dependency_materialized_once_per_call():
    provider = Provider()
    calls = []

    def shared():
        calls.append(1)
        return object()

    def left(s=Depends(shared)):
        return s

    def right(s=Depends(shared)):
        return s

    @inject(provider)
    def function(a=Depends(left), b=Depends(right)):
        return a is b

    assert function() is True
    assert len(calls) == 1
