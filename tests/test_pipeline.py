"""Pipeline parallelism: GPipe schedule over the ``stage`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4); these tests are
the simulated-multi-device coverage the TPU build adds: numerical parity of
the pipelined forward/backward against a sequential reference, and a full
sharded train step on a (data x stage) mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.models import GPT2Pipelined
from tpusystem.parallel import (MeshSpec, PipelineParallel, ShardingPolicy,
                               batch_sharding, pipeline_apply)
from tpusystem.parallel.mesh import partial_manual_skip_reason
from tpusystem.train import AdamW, NextTokenLoss, build_train_step, flax_apply, init_state

# PP x TP rides a *partially manual* shard_map (stage manual, model auto)
# that needs this jaxlib to lower PartitionId under SPMD on CPU; the
# probe compiles the miniature composition in a subprocess and returns
# the failure line as the skip reason where it cannot.
_PARTIAL_MANUAL_REASON = partial_manual_skip_reason()
needs_partial_manual = pytest.mark.skipif(
    _PARTIAL_MANUAL_REASON is not None,
    reason=_PARTIAL_MANUAL_REASON or 'partial-manual shard_map supported')


def make_model(stages=4, data=2, microbatches=2, model=1, **overrides):
    mesh = MeshSpec(data=data, stage=stages, model=model).build()
    config = dict(vocab_size=64, layers=4, dim=32, heads=4, max_seq=32,
                  dtype='float32', microbatches=microbatches, mesh=mesh)
    config.update(overrides)
    return GPT2Pipelined(**config), mesh


def test_pipelined_forward_matches_sequential():
    model, mesh = make_model()
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    variables = model.init(jax.random.PRNGKey(0), tokens)
    pipelined = jax.jit(model.apply)(variables, tokens)
    sequential = jax.jit(model.sequential_apply)(variables, tokens)
    np.testing.assert_allclose(np.asarray(pipelined), np.asarray(sequential),
                               rtol=1e-4, atol=1e-4)


def test_pipelined_gradients_match_sequential():
    model, mesh = make_model()
    tokens = jnp.asarray(np.random.default_rng(1).integers(0, 64, (4, 16)))
    variables = model.init(jax.random.PRNGKey(1), tokens)

    def loss_pipe(params):
        logits = model.apply({'params': params}, tokens)
        return jnp.mean((logits.astype(jnp.float32)) ** 2)

    def loss_seq(params):
        logits = model.sequential_apply({'params': params}, tokens)
        return jnp.mean((logits.astype(jnp.float32)) ** 2)

    grads_pipe = jax.jit(jax.grad(loss_pipe))(variables['params'])
    grads_seq = jax.jit(jax.grad(loss_seq))(variables['params'])
    flat_pipe = jax.tree.leaves(grads_pipe)
    flat_seq = jax.tree.leaves(grads_seq)
    for a, b in zip(flat_pipe, flat_seq):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_pipeline_train_step_on_stage_mesh():
    model, mesh = make_model()
    tokens = jnp.asarray(np.random.default_rng(2).integers(0, 64, (8, 16)))
    optimizer = AdamW(lr=1e-2)
    state = init_state(model, optimizer, tokens[:4])
    policy = PipelineParallel(fsdp=False)
    state = policy.place(state, mesh)
    tokens = jax.device_put(tokens, batch_sharding(mesh))

    step = build_train_step(flax_apply(model), NextTokenLoss(), optimizer)
    losses = []
    for _ in range(4):
        state, (_, loss) = step(state, tokens, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_stage_sharding_placement():
    model, mesh = make_model(stages=4, data=2)
    tokens = jnp.zeros((2, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    placed = PipelineParallel().place(variables['params'], mesh)
    spec = placed['h']['attn']['qkv']['kernel'].sharding.spec
    assert spec[0] == 'stage', spec
    assert placed['wte']['embedding'].sharding.spec == ()


@pytest.mark.slow
def test_layers_not_divisible_by_stages_raises():
    model, mesh = make_model(stages=4, layers=6)
    tokens = jnp.zeros((2, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    with pytest.raises(ValueError, match='divisible'):
        model.apply(variables, tokens)


def test_pipeline_apply_plain_stack():
    """pipeline_apply works on any stacked layer fn, not just transformers."""
    mesh = MeshSpec(stage=4, data=2).build()
    layers, batch, dim = 8, 4, 16
    keys = jax.random.split(jax.random.PRNGKey(0), layers)
    weights = jax.vmap(lambda k: jax.random.normal(k, (dim, dim)) / dim)(keys)
    inputs = jax.random.normal(jax.random.PRNGKey(1), (batch, dim))

    def block_fn(layer_params, x):
        return jnp.tanh(x @ layer_params['w'])

    out = pipeline_apply(block_fn, {'w': weights}, inputs, mesh, microbatches=2)

    reference = inputs
    for index in range(layers):
        reference = jnp.tanh(reference @ weights[index])
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_1f1b_matches_gpipe_autodiff_step():
    """The 1F1B interleaved schedule produces the same loss and updated
    parameters as autodiffing the GPipe pipeline_apply path — including
    the tied embedding whose gradient merges head and tail contributions."""
    from tpusystem.models import GPT2Pipelined
    from tpusystem.train import (NextTokenLoss, SGD, build_1f1b_train_step,
                                 build_train_step, flax_apply, init_state)
    mesh = MeshSpec(data=2, stage=4).build()
    model = GPT2Pipelined(vocab_size=256, layers=4, dim=64, heads=4,
                          max_seq=64, dtype='float32', microbatches=8,
                          mesh=mesh)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (16, 32)), jnp.int32)

    def one_step(build):
        state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)
        step = build()
        state, (_, loss) = step(state, tokens, tokens)
        return float(loss), state.params

    gpipe_loss, gpipe_params = one_step(lambda: build_train_step(
        flax_apply(model), NextTokenLoss(), SGD(lr=0.1)))
    f1b_loss, f1b_params = one_step(lambda: build_1f1b_train_step(
        model, NextTokenLoss(), SGD(lr=0.1)))

    np.testing.assert_allclose(gpipe_loss, f1b_loss, rtol=1e-5)
    flat_a = jax.tree.leaves(gpipe_params)
    flat_b = jax.tree.leaves(f1b_params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


@pytest.mark.slow
def test_1f1b_single_stage_degenerates_to_microbatch_loop():
    from tpusystem.models import GPT2Pipelined
    from tpusystem.train import (NextTokenLoss, SGD, build_1f1b_train_step,
                                 build_train_step, flax_apply, init_state)
    mesh = MeshSpec(data=2).build(jax.devices()[:2])
    model = GPT2Pipelined(vocab_size=128, layers=2, dim=32, heads=2,
                          max_seq=32, dtype='float32', microbatches=2,
                          mesh=mesh)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 128, (4, 16)), jnp.int32)
    state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)
    step = build_1f1b_train_step(model, NextTokenLoss(), SGD(lr=0.1))
    state, (_, loss) = step(state, tokens, tokens)
    reference = build_train_step(flax_apply(model), NextTokenLoss(), SGD(lr=0.1))
    ref_state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)
    ref_state, (_, ref_loss) = reference(ref_state, tokens, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    # params too: loss alone cannot catch dropped embedding gradients
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


@pytest.mark.slow
def test_interleaved_1f1b_matches_gpipe_autodiff_step():
    """interleave=2: each device owns two non-contiguous layer chunks
    (virtual stages), microbatches ride the ring twice — loss and updated
    params still match the GPipe autodiff reference exactly."""
    from tpusystem.models import GPT2Pipelined
    from tpusystem.train import (NextTokenLoss, SGD, build_1f1b_train_step,
                                 build_train_step, flax_apply, init_state)
    mesh = MeshSpec(data=2, stage=4).build()
    model = GPT2Pipelined(vocab_size=256, layers=8, dim=64, heads=4,
                          max_seq=64, dtype='float32', microbatches=8,
                          mesh=mesh, interleave=2)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 256, (16, 32)), jnp.int32)

    def one_step(build):
        state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)
        step = build()
        state, (_, loss) = step(state, tokens, tokens)
        return float(loss), state.params

    gpipe_loss, gpipe_params = one_step(lambda: build_train_step(
        flax_apply(model), NextTokenLoss(), SGD(lr=0.1)))
    f1b_loss, f1b_params = one_step(lambda: build_1f1b_train_step(
        model, NextTokenLoss(), SGD(lr=0.1)))

    np.testing.assert_allclose(gpipe_loss, f1b_loss, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gpipe_params),
                    jax.tree.leaves(f1b_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


@pytest.mark.slow
def test_interleaved_1f1b_partial_last_group():
    """microbatches not a multiple of stages: the schedule pads the last
    chunk sweep with idle units instead of clipping onto real microbatches
    (which would silently duplicate some and skip others) — parity with
    the GPipe autodiff reference must still hold exactly."""
    from tpusystem.models import GPT2Pipelined
    from tpusystem.train import (NextTokenLoss, SGD, build_1f1b_train_step,
                                 build_train_step, flax_apply, init_state)
    mesh = MeshSpec(stage=4).build(jax.devices()[:4])
    model = GPT2Pipelined(vocab_size=128, layers=8, dim=32, heads=2,
                          max_seq=32, dtype='float32', microbatches=6,
                          mesh=mesh, interleave=2)
    tokens = jnp.asarray(
        np.random.default_rng(8).integers(0, 128, (6, 16)), jnp.int32)

    def one_step(build):
        state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)
        state, (_, loss) = build()(state, tokens, tokens)
        return float(loss), state.params

    gpipe_loss, gpipe_params = one_step(lambda: build_train_step(
        flax_apply(model), NextTokenLoss(), SGD(lr=0.1)))
    f1b_loss, f1b_params = one_step(lambda: build_1f1b_train_step(
        model, NextTokenLoss(), SGD(lr=0.1)))
    np.testing.assert_allclose(gpipe_loss, f1b_loss, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gpipe_params),
                    jax.tree.leaves(f1b_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


def test_interleaved_schedule_units_and_bubble():
    """Round-unit accounting for the interleaved schedule: every (chunk,
    microbatch) unit executes exactly once per device at a
    dependency-consistent tick, and the fill/drain bubble shrinks with the
    interleave factor instead of growing with stage count alone."""
    from tpusystem.parallel.pipeline import _stash_slots

    def fwd_tick(S, v, s, c, m):
        g, pos = divmod(m, S)
        return s + g * v * S + c * S + pos

    def bwd_tick(S, v, s, c, m):
        g, pos = divmod(m, S)
        return (v * S + S - 2 - s) + g * v * S + (v - 1 - c) * S + pos

    for S, v, M in [(4, 1, 8), (4, 2, 8), (4, 4, 16), (2, 3, 6), (8, 2, 16)]:
        rounds = v * M + v * S + S - 2
        for s in range(S):
            fwd = [(c, m, fwd_tick(S, v, s, c, m))
                   for c in range(v) for m in range(M)]
            bwd = [(c, m, bwd_tick(S, v, s, c, m))
                   for c in range(v) for m in range(M)]
            # one unit per slot per tick, all within the round budget
            assert len({t for _, _, t in fwd}) == v * M
            assert len({t for _, _, t in bwd}) == v * M
            assert all(0 <= t < rounds for _, _, t in fwd + bwd)
            for c in range(v):
                for m in range(M):
                    # virtual-stage dependency: stage q consumes what q-1
                    # produced one tick earlier (ring latency 1)
                    q = c * S + s
                    if q > 0:
                        prev_s, prev_c = (s - 1, c) if s else (S - 1, c - 1)
                        assert (fwd_tick(S, v, prev_s, prev_c, m)
                                == fwd_tick(S, v, s, c, m) - 1)
                    # backward runs at/after the forward, and the stash
                    # slot m % slots is never clobbered while live
                    assert bwd_tick(S, v, s, c, m) >= fwd_tick(S, v, s, c, m)
            slots = _stash_slots(S, v, M)
            for c in range(v):
                for m in range(M - slots):
                    assert (fwd_tick(S, v, s, c, m + slots)
                            > bwd_tick(S, v, s, c, m))
    # v=1 recovers the classic 1F1B accounting
    assert _stash_slots(4, 1, 8) <= 2 * 4 - 1
    # bubble (idle chunk-ticks per fwd slot) = rounds - busy units:
    # interleave 2 at S=4, M=8 idles 10 chunk-ticks where plain 1F1B
    # idles 6 *stage*-ticks = 12 chunk-ticks of real compute
    plain = (8 + 2 * 4 - 2) - 8          # rounds - busy, stage units
    inter = (2 * 8 + 2 * 4 + 4 - 2) - 2 * 8  # chunk units
    assert inter < plain * 2             # chunk units vs v * stage units


def test_interleaved_placement_shards_chunk_stack():
    """PipelineParallel(interleave=v) shards the chunk-major stack's second
    dim over stage, so each device holds v non-contiguous chunks."""
    model, mesh = make_model(stages=4, layers=8, interleave=2)
    tokens = jnp.zeros((2, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    kernel = variables['params']['h']['attn']['qkv']['kernel']
    assert kernel.shape[:2] == (2, 4), kernel.shape
    placed = PipelineParallel(interleave=2).place(variables['params'], mesh)
    spec = placed['h']['attn']['qkv']['kernel'].sharding.spec
    assert spec[:2] == (None, 'stage'), spec
    # sequential reference still runs on the chunk-major storage
    out = jax.jit(model.sequential_apply)(variables, tokens)
    assert out.shape == (2, 8, 64)


@pytest.mark.slow
def test_1f1b_token_weighted_under_padding():
    """With a masked LM loss and pad-heavy microbatches, the 1F1B step
    weights microbatches by unmasked-token count like
    build_train_step(accumulate=...) — the full-batch reference and the
    pipelined step still agree."""
    from tpusystem.models import GPT2Pipelined
    from tpusystem.train import (NextTokenLoss, SGD, build_1f1b_train_step,
                                 build_train_step, flax_apply, init_state)
    mesh = MeshSpec(stage=4).build(jax.devices()[:4])
    model = GPT2Pipelined(vocab_size=128, layers=4, dim=32, heads=2,
                          max_seq=32, dtype='float32', microbatches=4,
                          mesh=mesh)
    tokens = np.random.default_rng(2).integers(0, 128, (8, 16)).astype(np.int32)
    tokens[:3, 4:] = -1                  # uneven padding across microbatches
    tokens = jnp.asarray(tokens)

    state = init_state(model, SGD(lr=0.1), jnp.abs(tokens[:1]), rng=0)
    step = build_1f1b_train_step(model, NextTokenLoss(), SGD(lr=0.1))
    state, (_, loss) = step(state, jnp.abs(tokens), tokens)

    reference = build_train_step(flax_apply(model), NextTokenLoss(), SGD(lr=0.1))
    ref_state = init_state(model, SGD(lr=0.1), jnp.abs(tokens[:1]), rng=0)
    ref_state, (_, ref_loss) = reference(ref_state, jnp.abs(tokens), tokens)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


@pytest.mark.parametrize('microbatches', [8, 6])
def test_interleaved_gpipe_forward_matches_sequential(microbatches):
    """pipeline_apply(interleave=2): the chunk-major stack rides the ring
    twice through chunk-sized units (pipeline_train's forward slot) —
    outputs must match the sequential reference, including a microbatch
    count that does not divide the stage count (padded last group)."""
    model, mesh = make_model(stages=4, data=2, layers=8,
                             microbatches=microbatches, interleave=2)
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, (2 * microbatches, 16)))
    variables = model.init(jax.random.PRNGKey(2), tokens)
    pipelined = jax.jit(model.apply)(variables, tokens)
    sequential = jax.jit(model.sequential_apply)(variables, tokens)
    np.testing.assert_allclose(np.asarray(pipelined), np.asarray(sequential),
                               rtol=1e-4, atol=1e-4)


def test_interleaved_gpipe_gradients_match_sequential():
    """Autodiff through the interleaved GPipe forward (cond-gated idle
    units, gathered emission ticks) matches the sequential reference."""
    model, mesh = make_model(stages=4, data=2, layers=8, microbatches=8,
                             interleave=2)
    tokens = jnp.asarray(np.random.default_rng(6).integers(0, 64, (16, 16)))
    variables = model.init(jax.random.PRNGKey(3), tokens)

    def loss_pipe(params):
        logits = model.apply({'params': params}, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    def loss_seq(params):
        logits = model.sequential_apply({'params': params}, tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    grads_pipe = jax.jit(jax.grad(loss_pipe))(variables['params'])
    grads_seq = jax.jit(jax.grad(loss_seq))(variables['params'])
    for a, b in zip(jax.tree.leaves(grads_pipe), jax.tree.leaves(grads_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_interleaved_gpipe_fill_drain_units():
    """Forward-schedule unit accounting for pipeline_apply(interleave=v):
    every (chunk, microbatch) unit runs exactly once per device, emission
    ticks are where the gather expects them, and the fill/drain bubble is
    S-1 chunk-units (vs S-1 *stage*-units = v(S-1) chunk-units
    contiguous)."""
    def fwd_tick(S, v, s, c, m):
        g, pos = divmod(m, S)
        return s + g * v * S + c * S + pos

    for S, v, M in [(4, 2, 8), (4, 2, 6), (2, 3, 6), (8, 2, 16)]:
        padded = -(-M // S) * S
        ticks = v * padded + S - 1
        for s in range(S):
            units = [(c, m, fwd_tick(S, v, s, c, m))
                     for c in range(v) for m in range(M)]
            assert len({t for *_, t in units}) == v * M   # one unit per tick
            assert all(0 <= t < ticks for *_, t in units)
        # last stage emits microbatch m's final chunk at the gathered tick
        for m in range(M):
            expected = ((m // S) * v * S + (v - 1) * S + (m % S) + S - 1)
            assert fwd_tick(S, v, S - 1, v - 1, m) == expected
        # fill/drain bubble: idle ticks on the last stage's final chunk
        # slot shrink from v*(S-1) contiguous chunk-units to (S-1) + the
        # partial-group padding v*(padded-M)
        busy = v * M
        assert ticks - busy == (S - 1) + v * (padded - M)


def test_pp_tp_placement_shards_stage_and_model():
    """stacked_rules compose: a qkv kernel lands P(stage, None, model)."""
    model, mesh = make_model(stages=2, data=2, model=2)
    tokens = jnp.zeros((2, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    policy = PipelineParallel(
        stacked_rules=GPT2Pipelined.block_partition_rules())
    placed = policy.place(variables['params'], mesh)
    qkv = placed['h']['attn']['qkv']['kernel'].sharding.spec
    assert tuple(qkv) == ('stage', None, 'model'), qkv
    out = placed['h']['attn']['out']['kernel'].sharding.spec
    assert tuple(out) == ('stage', 'model'), out
    # the model's own partition_rules build the same composition
    own = ShardingPolicy(rules=model.partition_rules()).place(
        variables['params'], mesh)
    assert tuple(own['h']['fc']['kernel'].sharding.spec) == \
        ('stage', None, 'model')


@needs_partial_manual
def test_pp_tp_forward_matches_sequential():
    """PP x TP: with the model axis live (stage=2 x model=2) and stacked
    params model-sharded, the pipelined forward still matches the
    sequential reference — the partial-manual shard_map lets GSPMD
    partition the stage bodies over `model`."""
    model, mesh = make_model(stages=2, data=2, model=2)
    tokens = jnp.asarray(np.random.default_rng(4).integers(0, 64, (4, 16)))
    variables = model.init(jax.random.PRNGKey(2), tokens)
    params = ShardingPolicy(rules=model.partition_rules()).place(
        variables['params'], mesh)
    pipelined = jax.jit(model.apply)({'params': params}, tokens)
    sequential = jax.jit(model.sequential_apply)(variables, tokens)
    np.testing.assert_allclose(np.asarray(pipelined), np.asarray(sequential),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow
@needs_partial_manual
def test_pp_tp_1f1b_matches_gpipe_autodiff_step():
    """The 1F1B schedule composes with within-stage TP: loss and updated
    params on a stage=2 x model=2 mesh match the GPipe autodiff path."""
    from tpusystem.train import (SGD, build_1f1b_train_step,
                                 build_train_step)
    mesh = MeshSpec(data=2, stage=2, model=2).build()
    model = GPT2Pipelined(vocab_size=256, layers=4, dim=64, heads=4,
                          max_seq=64, dtype='float32', microbatches=4,
                          mesh=mesh)
    tokens = jnp.asarray(
        np.random.default_rng(6).integers(0, 256, (8, 32)), jnp.int32)
    policy = PipelineParallel(
        stacked_rules=GPT2Pipelined.block_partition_rules())

    def one_step(build):
        state = init_state(model, SGD(lr=0.1), tokens[:1], rng=0)
        state = policy.place(state, mesh)
        step = build()
        state, (_, loss) = step(state, tokens, tokens)
        return float(loss), state.params

    gpipe_loss, gpipe_params = one_step(lambda: build_train_step(
        flax_apply(model), NextTokenLoss(), SGD(lr=0.1)))
    f1b_loss, f1b_params = one_step(lambda: build_1f1b_train_step(
        model, NextTokenLoss(), SGD(lr=0.1)))

    np.testing.assert_allclose(gpipe_loss, f1b_loss, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gpipe_params),
                    jax.tree.leaves(f1b_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)
