"""Recommender workload: sharded embedding tables, the Pallas row-gather/
scatter-add kernel pair, DLRM on a DP x model mesh, and streaming eval
(ROADMAP item 5 — the second "real workload" every LLM-shaped assumption
gets stress-tested against)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpusystem.data import Loader, SyntheticClicks
from tpusystem.models import DLRM, TwoTower, dlrm_tiny, two_tower_tiny
from tpusystem.ops.pallas.embedding_lookup import (embedding_lookup,
                                                   gather_rows, lookup_plan,
                                                   scatter_add_rows)
from tpusystem.parallel import (DataParallel, MeshSpec, TensorParallel,
                                batch_sharding)
from tpusystem.recsys import (RecallAtK, RecsysEvaluator, ShardedEmbedding,
                              StreamingAUC, dedup_ids, evaluation_consumer,
                              lookup, route_plan)
from tpusystem.registry import gethash
from tpusystem.train import (SGD, AdamW, BCEWithLogitsLoss, CrossEntropyLoss,
                             build_train_step, flax_apply, init_state)


def _random_case(seed=0, rows=48, dim=16, count=40, dtype=jnp.float32):
    """Ids with the three hard cases baked in: a duplicate pair (the
    scatter-add collision), -1 padding (the empty row), and the full id
    range."""
    rng = np.random.default_rng(seed)
    table = jnp.asarray(rng.standard_normal((rows, dim)), dtype)
    ids = np.asarray(rng.integers(0, rows, (count,)), np.int32)
    ids[3] = -1                     # padded slot
    ids[7] = ids[5]                 # guaranteed duplicate
    weights = jnp.asarray(rng.uniform(0.5, 1.5, (count,)), jnp.float32)
    cotangent = jnp.asarray(rng.standard_normal((count, dim)), jnp.float32)
    return table, jnp.asarray(ids), weights, cotangent


class TestLookupKernels:
    """Kernel-vs-reference parity for the hoisted row-movement pair
    (interpret mode on CPU — the grouped_matmul discipline)."""

    def test_forward_bitwise_f32(self):
        table, ids, weights, _ = _random_case()
        reference = embedding_lookup(table, ids, weights, impl='take')
        fused = embedding_lookup(table, ids, weights, impl='fused')
        np.testing.assert_array_equal(np.asarray(reference),
                                      np.asarray(fused))

    def test_gather_rows_direct(self):
        table, ids, weights, _ = _random_case()
        clamped = jnp.clip(ids, 0, table.shape[0] - 1)
        scale = weights * (ids >= 0)
        out = gather_rows(table, clamped, scale)
        expected = (np.asarray(table)[np.asarray(clamped)]
                    * np.asarray(scale)[:, None])
        np.testing.assert_array_equal(np.asarray(out), expected)

    def test_scatter_add_collisions_match_segment_sum(self):
        """Duplicate destination rows accumulate exactly — the per-row
        sequential RMW the batched combine kernel cannot do."""
        table_rows, dim = 12, 16
        rng = np.random.default_rng(1)
        rows = jnp.asarray(rng.standard_normal((32, dim)), jnp.float32)
        # heavily colliding ids + sentinel rows that must move nothing
        ids = np.asarray(rng.integers(0, 4, (32,)), np.int32)
        ids[5] = table_rows             # sentinel
        scale = jnp.asarray(rng.uniform(0.5, 1.5, (32,)), jnp.float32)
        out = scatter_add_rows(rows, jnp.asarray(ids), scale, table_rows)
        expected = np.zeros((table_rows, dim), np.float32)
        for j, row in enumerate(ids):
            if row < table_rows:
                expected[row] += np.asarray(rows)[j] * float(scale[j])
        np.testing.assert_allclose(np.asarray(out), expected,
                                   rtol=1e-6, atol=1e-6)

    def test_grads_tight_with_duplicates(self):
        """d_table through the f32 scatter-add custom_vjp vs autodiff of
        the take path — tight in f32, incl. the duplicate-id collision."""
        table, ids, weights, cotangent = _random_case()

        def objective(impl):
            def run(tab, wts):
                return jnp.sum(embedding_lookup(tab, ids, wts, impl=impl)
                               * cotangent)
            return run

        d_table_ref, d_w_ref = jax.grad(objective('take'),
                                        argnums=(0, 1))(table, weights)
        d_table, d_w = jax.grad(objective('fused'),
                                argnums=(0, 1))(table, weights)
        np.testing.assert_allclose(np.asarray(d_table_ref),
                                   np.asarray(d_table),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(d_w_ref), np.asarray(d_w),
                                   rtol=1e-6, atol=1e-6)

    def test_empty_rows_zero_forward_and_grad(self):
        table, ids, weights, cotangent = _random_case()
        out = embedding_lookup(table, ids, weights, impl='fused')
        np.testing.assert_array_equal(np.asarray(out[3]),
                                      np.zeros(table.shape[1], np.float32))
        d_w = jax.grad(lambda wts: jnp.sum(
            embedding_lookup(table, ids, wts, impl='fused') * cotangent))(
                weights)
        assert float(d_w[3]) == 0.0     # padding never sees a gradient

    def test_bf16_bounded(self):
        table, ids, weights, cotangent = _random_case(dtype=jnp.bfloat16)
        reference = embedding_lookup(table, ids, weights, impl='take')
        fused = embedding_lookup(table, ids, weights, impl='fused')
        np.testing.assert_allclose(
            np.asarray(fused, np.float32), np.asarray(reference, np.float32),
            rtol=1e-2, atol=1e-2)
        d_ref = jax.grad(lambda t: jnp.sum(
            embedding_lookup(t, ids, weights, impl='take').astype(jnp.float32)
            * cotangent))(table)
        d_fused = jax.grad(lambda t: jnp.sum(
            embedding_lookup(t, ids, weights, impl='fused').astype(jnp.float32)
            * cotangent))(table)
        np.testing.assert_allclose(np.asarray(d_fused, np.float32),
                                   np.asarray(d_ref, np.float32),
                                   rtol=5e-2, atol=5e-2)

    def test_lookup_plan_pinned(self):
        """The fallback decision is pure and pinned: interpret mode (off-
        TPU) and untileable dims refuse; TPU-tileable shapes block."""
        assert lookup_plan(256, 128, jnp.float32, interpret=True) is None
        assert lookup_plan(256, 100, jnp.float32, interpret=False) is None
        assert lookup_plan(256, 128, jnp.float32, interpret=False) == 256
        assert lookup_plan(512, 128, jnp.float32,
                           interpret=False, want_rows=256) == 256
        # id counts with no sublane-multiple divisor refuse too
        assert lookup_plan(7, 128, jnp.float32, interpret=False) is None

    def test_auto_takes_fallback_off_tpu(self):
        """impl='auto' must never interpret a kernel inside the training
        hot path: off-TPU it compiles to the take path (same values)."""
        table, ids, weights, _ = _random_case()
        auto = embedding_lookup(table, ids, weights, impl='auto')
        take = embedding_lookup(table, ids, weights, impl='take')
        np.testing.assert_array_equal(np.asarray(auto), np.asarray(take))

    def test_unknown_impl_raises(self):
        table, ids, weights, _ = _random_case()
        with pytest.raises(ValueError, match='unknown impl'):
            embedding_lookup(table, ids, weights, impl='turbo')


class TestDedup:

    def test_inverse_reconstructs(self):
        ids = jnp.asarray([5, 3, 5, -1, 3, 7, 5, -1], jnp.int32)
        sent = jnp.where(ids >= 0, ids, 99)
        reps, inverse = dedup_ids(sent, 99)
        np.testing.assert_array_equal(np.asarray(reps)[np.asarray(inverse)],
                                      np.asarray(sent))
        packed = np.asarray(reps)
        distinct = {3, 5, 7, 99}
        assert set(packed[:len(distinct)]) == distinct
        assert all(value == 99 for value in packed[len(distinct):])

    def test_lookup_dedup_bitwise_and_grads_tight(self):
        table, ids, weights, cotangent = _random_case()
        plain = lookup(table, ids, weights, dedup=False)
        deduped = lookup(table, ids, weights, dedup=True)
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(deduped))

        def objective(dedup):
            return lambda tab: jnp.sum(
                lookup(tab, ids, weights, dedup=dedup) * cotangent)

        d_plain = jax.grad(objective(False))(table)
        d_dedup = jax.grad(objective(True))(table)
        np.testing.assert_allclose(np.asarray(d_plain), np.asarray(d_dedup),
                                   rtol=1e-6, atol=1e-6)


@pytest.fixture(scope='module')
def table_mesh():
    """data x fsdp=1 x model x expert: tables shard 4-way (expert-major),
    the batch 2-way — the DP x table-sharding composition."""
    return MeshSpec(data=2, model=2, expert=2).build(jax.devices()[:8])


class TestShardedEmbedding:

    def test_route_plan_pinned(self, table_mesh):
        assert route_plan(64, 48, table_mesh) is None
        assert route_plan(64, 48, None) == 'no mesh'
        assert 'not divisible' in route_plan(63, 48, table_mesh)
        assert 'not divisible' in route_plan(64, 7, table_mesh)
        single = MeshSpec(data=8).build(jax.devices()[:8])
        assert 'size 1' in route_plan(64, 48, single)

    def test_init_mesh_invariant(self, table_mesh):
        ids = jnp.zeros((8, 3), jnp.int32)
        sharded = ShardedEmbedding(64, 8, mesh=table_mesh)
        local = ShardedEmbedding(64, 8)
        params_s = sharded.init(jax.random.PRNGKey(0), ids)
        params_l = local.init(jax.random.PRNGKey(0), ids)
        for a, b in zip(jax.tree.leaves(params_s), jax.tree.leaves(params_l)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sharded_forward_bitwise(self, table_mesh):
        """Device-side id->shard routing + psum: every row comes wholly
        from one shard, the others add exact zeros — bitwise."""
        rng = np.random.default_rng(3)
        ids = jnp.asarray(rng.integers(-1, 64, (16, 3)), jnp.int32)
        weights = jnp.asarray(rng.uniform(0.5, 1.5, (16, 3)), jnp.float32)
        local = ShardedEmbedding(64, 8)
        sharded = ShardedEmbedding(64, 8, mesh=table_mesh)
        params = local.init(jax.random.PRNGKey(1), ids)
        out_local = local.apply(params, ids, weights)
        out_sharded = jax.jit(
            lambda p, i, w: sharded.apply(p, i, w))(params, ids, weights)
        np.testing.assert_array_equal(np.asarray(out_local),
                                      np.asarray(out_sharded))

    def test_constrain_table_rows_annotation_point(self, table_mesh):
        """The sharding.py seam: values untouched, placement pinned to
        the expert-major table spec; hand-built meshes missing a table
        axis drop it instead of erroring; size-1/no-mesh are no-ops."""
        from jax.sharding import Mesh, PartitionSpec
        from tpusystem.parallel.sharding import constrain_table_rows
        table = jnp.asarray(np.random.default_rng(14).standard_normal(
            (64, 8)), jnp.float32)
        pinned = jax.jit(
            lambda t: constrain_table_rows(t, table_mesh))(table)
        np.testing.assert_array_equal(np.asarray(pinned), np.asarray(table))
        assert pinned.sharding.spec == PartitionSpec(('expert', 'model'))
        # hand-built mesh without an 'expert' axis: the absent axis drops
        bare = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                    ('data', 'model'))
        pinned = jax.jit(lambda t: constrain_table_rows(t, bare))(table)
        np.testing.assert_array_equal(np.asarray(pinned), np.asarray(table))
        assert pinned.sharding.spec == PartitionSpec('model')
        assert constrain_table_rows(table, None) is table
        single = MeshSpec(data=8).build(jax.devices()[:8])
        assert constrain_table_rows(table, single) is table

    def test_sharded_grads_tight(self, table_mesh):
        rng = np.random.default_rng(4)
        ids = jnp.asarray(rng.integers(-1, 64, (16,)), jnp.int32)
        cot = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        local = ShardedEmbedding(64, 8)
        sharded = ShardedEmbedding(64, 8, mesh=table_mesh)
        params = local.init(jax.random.PRNGKey(2), ids)

        def objective(module):
            return lambda p: jnp.sum(module.apply(p, ids) * cot)

        d_local = jax.grad(objective(local))(params)
        d_sharded = jax.jit(jax.grad(objective(sharded)))(params)
        for a, b in zip(jax.tree.leaves(d_local), jax.tree.leaves(d_sharded)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)


def _click_batch(rng, batch=8, features=2, vocab_lo=32):
    return ({'dense': jnp.asarray(rng.standard_normal((batch, 4)),
                                  jnp.float32),
             'ids': jnp.asarray(rng.integers(-1, vocab_lo, (batch, features, 4)),
                                jnp.int32)},
            jnp.asarray(rng.integers(0, 2, (batch,)), jnp.float32))


class TestDLRM:

    def test_forward_shape_and_padding(self):
        rng = np.random.default_rng(5)
        module = dlrm_tiny()
        batch, labels = _click_batch(rng)
        params = module.init(jax.random.PRNGKey(0), batch)['params']
        logits = module.apply({'params': params}, batch)
        assert logits.shape == (8,)
        assert np.all(np.isfinite(np.asarray(logits)))

    def test_dp_times_table_sharding_bitwise(self, table_mesh):
        """The acceptance drill: the tiny DLRM on the DP x model virtual
        mesh with row-sharded tables — per-step losses AND end params
        bitwise equal to the same-mesh run with replicated (unsharded)
        tables. Table sharding is placement, not math. (Vs a literal
        single-device run the batch-mean reduction order differs by
        design — pinned below at 1-ulp-class tolerance.)"""
        rng = np.random.default_rng(6)
        batch, labels = _click_batch(rng)
        optimizer = AdamW(lr=1e-2)

        def run(module, policy, mesh=None):
            state = init_state(module, optimizer, batch)
            if mesh is not None:
                state = policy.place(state, mesh)
            step = build_train_step(flax_apply(module), BCEWithLogitsLoss(),
                                    optimizer)
            inputs = (jax.device_put(batch, batch_sharding(mesh))
                      if mesh is not None else batch)
            targets = (jax.device_put(labels, batch_sharding(mesh))
                       if mesh is not None else labels)
            losses = []
            for _ in range(3):
                state, (_, loss) = step(state, inputs, targets)
                losses.append(float(loss))
            return losses, state

        sharded_module = dlrm_tiny(mesh=table_mesh)
        losses_sharded, state_sharded = run(
            sharded_module, TensorParallel(sharded_module.partition_rules()),
            table_mesh)
        losses_replicated, state_replicated = run(
            dlrm_tiny(), DataParallel(), table_mesh)
        assert losses_sharded == losses_replicated
        for a, b in zip(jax.tree.leaves(state_sharded.params),
                        jax.tree.leaves(state_replicated.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # single-device reference: same math, different (single-reduction)
        # batch-mean order — near-bitwise, pinned tight
        losses_single, _ = run(dlrm_tiny(), None)
        np.testing.assert_allclose(losses_sharded, losses_single,
                                   rtol=1e-6, atol=0)

    def test_sharded_step_compiles_once(self, table_mesh):
        """The routed lookup (shard_map + dedup sort) must not retrace
        across steps — the compile-guard discipline from test_schedule."""
        rng = np.random.default_rng(7)
        batch, labels = _click_batch(rng)
        module = dlrm_tiny(mesh=table_mesh)
        optimizer = SGD(lr=1e-2)
        state = init_state(module, optimizer, batch)
        state = TensorParallel(module.partition_rules()).place(state,
                                                               table_mesh)
        traces = []
        raw = build_train_step(flax_apply(module), BCEWithLogitsLoss(),
                               optimizer, jit=False)

        def counted(state, inputs, targets):
            traces.append(1)
            return raw(state, inputs, targets)

        step = jax.jit(counted, donate_argnums=0)
        inputs = jax.device_put(batch, batch_sharding(table_mesh))
        targets = jax.device_put(labels, batch_sharding(table_mesh))
        for _ in range(3):
            state, _ = step(state, inputs, targets)
        assert len(traces) == 1, f'{len(traces)} traces across 3 steps'

    @pytest.mark.slow
    def test_trains_on_click_log(self):
        """End-to-end: train loss drops and held-out AUC beats chance on
        the planted-logistic click log (slow profile — the fast tier
        keeps the bitwise step drills and the dryrun stage)."""
        dataset = SyntheticClicks(samples=512, vocabs=(64, 32), seed=0)
        module = dlrm_tiny()
        optimizer = AdamW(lr=1e-2)
        loader = Loader(dataset, batch_size=64, shuffle=True, seed=0)
        sample = dataset[np.arange(2)][0]
        state = init_state(module, optimizer, sample)
        step = build_train_step(flax_apply(module), BCEWithLogitsLoss(),
                                optimizer)
        first = last = None
        for _ in range(6):
            epoch_losses = []
            for features, labels in loader:
                state, (_, loss) = step(state, features, labels)
                epoch_losses.append(float(loss))
            last = float(np.mean(epoch_losses))
            first = first or last
        assert last < first * 0.9, (first, last)
        holdout = Loader(SyntheticClicks(samples=512, vocabs=(64, 32),
                                         seed=0, train=False), batch_size=64)
        metrics = RecsysEvaluator(module, holdout).run(state)
        assert metrics['auc'] > 0.6, metrics
        assert np.isfinite(metrics['loss'])


class TestTwoTower:

    def test_in_batch_scores_and_training(self):
        rng = np.random.default_rng(8)
        module = two_tower_tiny()
        optimizer = AdamW(lr=1e-2)
        # planted preference: user u clicks item u % items
        users = jnp.asarray(rng.integers(0, 64, (64,)), jnp.int32)
        items = jnp.asarray(np.asarray(users) % 32, jnp.int32)
        batch = {'user': users, 'item': items}
        state = init_state(module, optimizer, batch)
        criterion = CrossEntropyLoss()
        step = build_train_step(flax_apply(module), criterion, optimizer)
        targets = jnp.arange(64, dtype=jnp.int32)
        losses = []
        for _ in range(20):
            state, (scores, loss) = step(state, batch, targets)
            losses.append(float(loss))
        assert scores.shape == (64, 64)
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
        recall = RecallAtK(k=5)
        recall.update(scores, targets)
        assert recall.compute() > 0.3

    def test_multi_hot_user_history_pools(self):
        rng = np.random.default_rng(9)
        module = two_tower_tiny()
        history = np.asarray(rng.integers(0, 64, (8, 5)), np.int32)
        history[:, 3:] = -1                       # ragged histories
        batch = {'user': jnp.asarray(history),
                 'item': jnp.asarray(rng.integers(0, 32, (8,)), jnp.int32)}
        params = module.init(jax.random.PRNGKey(0), batch)
        scores = module.apply(params, batch)
        assert scores.shape == (8, 8)
        assert np.all(np.isfinite(np.asarray(scores)))


class TestRegistryAndCheckpoint:
    """The registry/storage drill: constructor-capture identity across
    table-size/sharding variants, and the sharded-table checkpoint round
    trip (tables are the first params bigger than any single shard)."""

    def test_identity_stable_and_distinct(self, table_mesh):
        base = DLRM(vocabs=(64, 32), dim=8)
        again = DLRM(vocabs=(64, 32), dim=8)
        bigger = DLRM(vocabs=(128, 32), dim=8)
        wider = DLRM(vocabs=(64, 32), dim=16)
        assert gethash(base) == gethash(again)
        assert len({gethash(base), gethash(bigger), gethash(wider)}) == 3
        # the mesh is a runtime fact, not identity: a sharded variant of
        # the same architecture restores the same checkpoints
        assert gethash(base) == gethash(DLRM(vocabs=(64, 32), dim=8,
                                             mesh=table_mesh))
        # but the lookup impl is captured (it changes the compiled step)
        assert gethash(base) != gethash(DLRM(vocabs=(64, 32), dim=8,
                                             impl='take'))

    def test_checkpoint_round_trip_sharded_tables(self, table_mesh,
                                                  tmp_path):
        from tpusystem.checkpoint import Checkpointer
        rng = np.random.default_rng(10)
        batch, labels = _click_batch(rng)
        module = dlrm_tiny(mesh=table_mesh)
        optimizer = AdamW(lr=1e-2)
        policy = TensorParallel(module.partition_rules())
        state = policy.place(init_state(module, optimizer, batch),
                             table_mesh)
        step = build_train_step(flax_apply(module), BCEWithLogitsLoss(),
                                optimizer)
        inputs = jax.device_put(batch, batch_sharding(table_mesh))
        targets = jax.device_put(labels, batch_sharding(table_mesh))
        state, _ = step(state, inputs, targets)
        with Checkpointer(str(tmp_path), async_save=False) as checkpointer:
            checkpointer.save('recsys', 1, state)
            blank = policy.place(init_state(module, optimizer, batch),
                                 table_mesh)
            restored = checkpointer.restore('recsys', blank, epoch=1)
        for a, b in zip(jax.tree.leaves(state.params),
                        jax.tree.leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_memstore_sharded_leaf_round_trip(self, table_mesh):
        from tpusystem.checkpoint import deserialize_state, serialize_state
        from tpusystem.checkpoint.memstore import ShardedLeaf
        rng = np.random.default_rng(11)
        batch, _ = _click_batch(rng)
        module = dlrm_tiny(mesh=table_mesh)
        optimizer = AdamW(lr=1e-2)
        policy = TensorParallel(module.partition_rules())
        state = policy.place(init_state(module, optimizer, batch),
                             table_mesh)
        blob = serialize_state(state)
        blank = policy.place(init_state(module, optimizer, batch),
                             table_mesh)
        restored = deserialize_state(blob, blank)
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the piece path an elastic reshard takes: per-slice shards of a
        # row-sharded table reassemble to the exact global array
        table = state.params['table_0']['embedding']
        piece = ShardedLeaf.from_array(table)
        np.testing.assert_array_equal(piece.reassemble(), np.asarray(table))


class TestStreamingEval:

    def test_streaming_auc_matches_exact(self):
        rng = np.random.default_rng(12)
        logits = rng.standard_normal(2000).astype(np.float32)
        labels = (rng.uniform(size=2000)
                  < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
        metric = StreamingAUC(buckets=512)
        for start in range(0, 2000, 250):     # streaming: 8 updates
            metric.update(jnp.asarray(logits[start:start + 250]),
                          jnp.asarray(labels[start:start + 250]))
        scores = 1.0 / (1.0 + np.exp(-logits))
        positives = scores[labels == 1.0]
        negatives = scores[labels == 0.0]
        exact = (np.mean(positives[:, None] > negatives[None, :])
                 + 0.5 * np.mean(positives[:, None] == negatives[None, :]))
        assert abs(metric.compute() - float(exact)) < 2e-3

    def test_streaming_auc_degenerate(self):
        metric = StreamingAUC()
        assert metric.compute() == 0.5
        metric.update(jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 1.0]))
        assert metric.compute() == 0.5        # no negatives yet

    def test_recall_at_k(self):
        scores = jnp.asarray([[9.0, 1.0, 0.0],
                              [0.0, 1.0, 9.0],
                              [9.0, 1.0, 0.0]], jnp.float32)
        relevant = jnp.asarray([0, 2, 2], jnp.int32)
        metric = RecallAtK(k=1)
        metric.update(scores, relevant)
        assert metric.compute() == pytest.approx(2 / 3)

    def test_retrieval_evaluator_needs_explicit_criterion_for_loss(self):
        """A [B, B] retrieval model under the DEFAULT (BCE) criterion
        reports recall@k only — the broadcast BCE scalar would be
        meaningless; passing the training criterion brings loss back."""
        rng = np.random.default_rng(13)
        module = two_tower_tiny()
        batch = {'user': jnp.asarray(rng.integers(0, 64, (16,)), jnp.int32),
                 'item': jnp.asarray(rng.integers(0, 32, (16,)), jnp.int32)}
        state = init_state(module, AdamW(lr=1e-2), batch)

        class Pairs:
            def __len__(self):
                return 32

            def __getitem__(self, index):
                count = len(index)
                return ({'user': rng.integers(0, 64, (count,)).astype(np.int32),
                         'item': rng.integers(0, 32, (count,)).astype(np.int32)},
                        np.arange(count, dtype=np.int32))

        defaulted = RecsysEvaluator(module, Loader(Pairs(), batch_size=16),
                                    k=5).run(state)
        assert set(defaulted) == {'recall@5'}, defaulted
        explicit = RecsysEvaluator(module, Loader(Pairs(), batch_size=16),
                                   criterion=CrossEntropyLoss(),
                                   k=5).run(state)
        assert set(explicit) == {'loss', 'recall@5'}, explicit
        assert np.isfinite(explicit['loss'])

    def test_evaluation_consumer_phase_cadence(self):
        """The bus wiring: a Trained event triggers one streaming pass
        and a RecsysEvaluated with materialized floats rides out."""
        from tpusystem.observe.events import RecsysEvaluated, Trained
        from tpusystem.services import Producer

        dataset = SyntheticClicks(samples=128, vocabs=(64, 32), seed=1)
        module = dlrm_tiny()
        optimizer = AdamW(lr=1e-2)
        sample = dataset[np.arange(2)][0]
        state = init_state(module, optimizer, sample)
        loader = Loader(dataset, batch_size=32)
        evaluator = RecsysEvaluator(module, loader)

        class Model:
            id = 'dlrm-test'
            epoch = 0
        model = Model()
        model.state = state

        seen = []
        producer = Producer()
        producer.register(evaluation_consumer(evaluator, producer=producer))

        from tpusystem.services import Consumer
        collector = Consumer('collector')

        @collector.handler
        def on_evaluated(event: RecsysEvaluated) -> None:
            seen.append(event.metrics)

        producer.register(collector)
        producer.dispatch(Trained(model, {'loss': 1.0}))
        assert len(seen) == 1
        assert set(seen[0]) == {'auc', 'loss'}
        assert all(isinstance(value, float) for value in seen[0].values())

        # subject-scoped wiring on a shared bus: another model's Trained
        # must not push a foreign state through this evaluator's step
        scoped = Producer()
        scoped.register(evaluation_consumer(evaluator, producer=scoped,
                                            subject='dlrm-test'))
        scoped.register(collector)

        class Other:
            id = 'llama'
            state = object()      # would crash the DLRM eval step
        scoped.dispatch(Trained(Other(), {'loss': 1.0}))
        assert len(seen) == 1     # ignored
        scoped.dispatch(Trained(model, {'loss': 1.0}))
        assert len(seen) == 2     # matching id still evaluated

    def test_tensorboard_charts_recsys(self, tmp_path):
        from tpusystem.observe.events import RecsysEvaluated
        from tpusystem.observe.tensorboard import (SummaryWriter,
                                                   tensorboard_consumer,
                                                   writer)

        consumer = tensorboard_consumer()
        board = SummaryWriter(tmp_path)
        consumer.dependency_overrides[writer] = lambda: board

        class Model:
            id = 'dlrm-test'
            epoch = 3
        consumer.consume(RecsysEvaluated(Model(), {'auc': 0.7,
                                                   'recall@10': 0.4}))
        board.close()
        from tests.tb import read_scalars
        scalars = read_scalars(tmp_path)    # parsed back, not size-poked
        value, step = scalars['dlrm-test/recsys/auc']
        assert value == pytest.approx(0.7) and step == 3
        value, step = scalars['dlrm-test/recsys/recall@10']
        assert value == pytest.approx(0.4) and step == 3


class TestSyntheticClicks:

    def test_shapes_and_ragged_padding(self):
        dataset = SyntheticClicks(samples=64, vocabs=(32, 16), hot=4,
                                  dense=3, seed=2)
        features, labels = dataset[np.arange(8)]
        assert features['dense'].shape == (8, 3)
        assert features['ids'].shape == (8, 2, 4)
        assert labels.shape == (8,)
        ids = dataset[np.arange(64)][0]['ids']
        assert (ids == -1).any(), 'no ragged padding drawn'
        assert ids.max() < 32 and ids[:, 1].max() < 16
        # every row keeps at least one hot id
        assert (ids[:, :, 0] >= 0).all()

    def test_zipfian_skew(self):
        dataset = SyntheticClicks(samples=1024, vocabs=(64,), seed=3)
        ids = dataset[np.arange(1024)][0]['ids'].reshape(-1)
        valid = ids[ids >= 0]
        head = float(np.mean(valid == 0))
        tail = float(np.mean(valid == 63))
        assert head > 0.15 and head > 20 * max(tail, 1e-4), (head, tail)

    def test_deterministic_and_split(self):
        first = SyntheticClicks(samples=32, seed=4)
        again = SyntheticClicks(samples=32, seed=4)
        np.testing.assert_array_equal(first[np.arange(32)][1],
                                      again[np.arange(32)][1])
        holdout = SyntheticClicks(samples=32, seed=4, train=False)
        assert not np.array_equal(first[np.arange(32)][0]['ids'],
                                  holdout[np.arange(32)][0]['ids'])
