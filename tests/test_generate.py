"""KV-cache autoregressive decoding: exact parity with full re-forward.

The decode path (cache variables, cursor-offset positions/rotary, masked
attention over the filled prefix) must produce token-for-token the same
greedy continuation as rerunning the full forward per step — in float32
the two are exactly equal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.models import gpt2_tiny, llama_tiny
from tpusystem.train import generate


def full_forward_greedy(module, params, prompt, steps):
    sequence = prompt
    for _ in range(steps):
        out = module.apply({'params': params}, sequence)
        logits = out[0] if isinstance(out, tuple) else out   # MoE: (logits, aux)
        next_token = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        sequence = jnp.concatenate([sequence, next_token[:, None]], axis=1)
    return sequence


@pytest.fixture(scope='module')
def prompt():
    return jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 7)), jnp.int32)


@pytest.mark.parametrize('family', [gpt2_tiny, llama_tiny])
@pytest.mark.slow
def test_greedy_decode_matches_full_forward(family, prompt):
    module = family(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    cached = generate(module, params, prompt, steps=5)
    reference = full_forward_greedy(module, params, prompt, 5)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(reference))


def test_prompt_is_preserved_and_shapes(prompt):
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    out = generate(module, params, prompt, steps=3)
    assert out.shape == (2, 10) and out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out[:, :7]), np.asarray(prompt))


def test_temperature_sampling_stays_in_vocab(prompt):
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    out = generate(module, params, prompt, steps=6, temperature=1.0,
                   rng=jax.random.PRNGKey(7))
    tail = np.asarray(out[:, 7:])
    assert ((tail >= 0) & (tail < module.vocab_size)).all()
    # a different key gives a different draw (overwhelmingly)
    other = generate(module, params, prompt, steps=6, temperature=1.0,
                     rng=jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(out), np.asarray(other))


def test_temperature_without_rng_raises(prompt):
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    with pytest.raises(ValueError):
        generate(module, params, prompt, steps=2, temperature=0.5)


def test_capacity_overflow_raises(prompt):
    module = gpt2_tiny(dtype='float32')   # max_seq = 128
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    with pytest.raises(ValueError):
        generate(module, params, prompt, steps=128)


@pytest.mark.slow
def test_moe_model_decodes_matching_full_forward(prompt):
    """MoE decode drops the training-only aux output; in a no-drop config
    (k == experts, capacity covers every token — chosen deliberately) it
    matches the full re-forward exactly. Drop-configs may route
    differently at decode (capacity derives from per-call token counts);
    the model-side comment documents that standard asymmetry."""
    module = gpt2_tiny(dtype='float32', moe_experts=2, moe_every=2)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    cached = generate(module, params, prompt, steps=4)
    reference = full_forward_greedy(module, params, prompt, 4)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(reference))


def test_zero_steps_raises(prompt):
    module = gpt2_tiny(dtype='float32')
    with pytest.raises(ValueError):
        generate(module, {}, prompt, steps=0)


def test_repeat_call_reuses_compiled_program(prompt):
    import importlib
    generate_module = importlib.import_module('tpusystem.train.generate')
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    generate(module, params, prompt, steps=2)
    before = generate_module._compiled.cache_info().hits
    generate(module, params, prompt, steps=2)
    assert generate_module._compiled.cache_info().hits == before + 1


def test_decode_clone_strips_training_settings(prompt):
    """flash attention / dropout / fused-loss output must not leak into the
    decode clone — generate works straight off a training-configured module."""
    module = gpt2_tiny(dtype='float32', attention='flash', dropout=0.1,
                       return_features=True)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    out = generate(module, params, prompt, steps=2)
    assert out.shape == (2, 9)


@pytest.mark.slow
def test_long_prompt_prefill_uses_flash_and_matches_xla(monkeypatch):
    """Prompts >= 512 tokens prefill through the flash kernel (O(seq)
    memory) instead of building the O(seq^2) einsum scores tensor — and
    the prefill logits are unchanged."""
    from tpusystem.ops.pallas import flash as flash_module

    module = gpt2_tiny(dtype='float32', max_seq=1024)
    long_prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, 256, (1, 512)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), long_prompt)['params']

    calls = []
    real_flash = flash_module.flash_attention

    def counting_flash(*args, **kwargs):
        calls.append(args[0].shape)
        return real_flash(*args, **kwargs)

    import tpusystem.ops.pallas.flash
    monkeypatch.setattr(tpusystem.ops.pallas.flash, 'flash_attention',
                        counting_flash)

    import dataclasses
    decoder = dataclasses.replace(module, decode=True)
    logits, _ = decoder.apply({'params': params}, long_prompt,
                              mutable=['cache'])
    assert len(calls) == module.layers, calls      # every layer's prefill
    reference = module.apply({'params': params}, long_prompt)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(reference),
                               atol=2e-4)


@pytest.mark.slow
def test_speculative_decode_equals_greedy_regardless_of_draft():
    """The speculative output must be EXACTLY the target's greedy decode —
    the draft only affects speed. Pinned with a random-weight draft (worst
    case: near-zero acceptance) and with the target itself as draft (best
    case: full acceptance), across speculate widths."""
    from tpusystem.train import generate, speculative_generate
    target = gpt2_tiny(dtype='float32', max_seq=128)
    draft = gpt2_tiny(dtype='float32', layers=1, dim=32, heads=2, max_seq=128)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 8)), jnp.int32)
    params = target.init(jax.random.PRNGKey(0), tokens)['params']
    draft_params = draft.init(jax.random.PRNGKey(9), tokens)['params']

    reference = np.asarray(generate(target, params, tokens, steps=24))
    for speculate in (1, 3, 5):
        out = speculative_generate(
            target, params, tokens, steps=24, draft_module=draft,
            draft_params=draft_params, speculate=speculate)
        np.testing.assert_array_equal(np.asarray(out), reference)

    # perfect draft: the target drafting for itself accepts everything
    out = speculative_generate(
        target, params, tokens, steps=24, draft_module=target,
        draft_params=params, speculate=4)
    np.testing.assert_array_equal(np.asarray(out), reference)


def test_speculative_decode_validates_capacity_and_args():
    from tpusystem.train import speculative_generate
    target = gpt2_tiny(dtype='float32', max_seq=32)
    tokens = jnp.zeros((1, 16), jnp.int32)
    params = target.init(jax.random.PRNGKey(0), tokens)['params']
    with pytest.raises(ValueError, match='capacity'):
        speculative_generate(target, params, tokens, steps=16,
                             draft_module=target, draft_params=params,
                             speculate=4)
    with pytest.raises(ValueError, match='speculate'):
        speculative_generate(target, params, tokens, steps=4,
                             draft_module=target, draft_params=params,
                             speculate=0)


@pytest.mark.slow
def test_speculative_decode_llama_rotary_positions():
    """Cursor rewind must also restore Llama's rotary positions (read from
    the per-layer cache index)."""
    from tpusystem.train import generate, speculative_generate
    target = llama_tiny(dtype='float32', max_seq=128)
    draft = llama_tiny(dtype='float32', layers=1, ffn_dim=64, max_seq=128)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 256, (2, 8)), jnp.int32)
    params = target.init(jax.random.PRNGKey(1), tokens)['params']
    draft_params = draft.init(jax.random.PRNGKey(7), tokens)['params']
    reference = np.asarray(generate(target, params, tokens, steps=20))
    out = speculative_generate(target, params, tokens, steps=20,
                               draft_module=draft, draft_params=draft_params,
                               speculate=3)
    np.testing.assert_array_equal(np.asarray(out), reference)


@pytest.mark.slow
def test_speculative_sampling_matches_target_distribution():
    """temperature>0: rejection-sampling acceptance keeps the OUTPUT
    DISTRIBUTION equal to the target's own sampling distribution even with
    a disagreeing random draft (Leviathan et al.) — checked empirically on
    per-position marginals over a small vocab. Seeds pinned: the empirical
    draws are deterministic, so the tolerance cannot flake."""
    from tpusystem.train import generate, speculative_generate
    target = gpt2_tiny(dtype='float32', vocab_size=32, layers=2, dim=32,
                       heads=2, max_seq=64)
    draft = gpt2_tiny(dtype='float32', vocab_size=32, layers=1, dim=16,
                      heads=2, max_seq=64)
    batch, prefix, steps = 4096, 4, 3
    prompt = jnp.tile(jnp.asarray([[3, 1, 4, 1]], jnp.int32), (batch, 1))
    params = target.init(jax.random.PRNGKey(0), prompt[:1])['params']
    draft_params = draft.init(jax.random.PRNGKey(5), prompt[:1])['params']

    reference = np.asarray(generate(
        target, params, prompt, steps=steps, temperature=1.0,
        rng=jax.random.PRNGKey(11)))
    speculative = np.asarray(speculative_generate(
        target, params, prompt, steps=steps, draft_module=draft,
        draft_params=draft_params, speculate=3, temperature=1.0,
        rng=jax.random.PRNGKey(17)))

    for position in range(prefix, prefix + steps):
        ref_hist = np.bincount(reference[:, position], minlength=32) / batch
        spec_hist = np.bincount(speculative[:, position], minlength=32) / batch
        distance = np.abs(ref_hist - spec_hist).sum()
        assert distance < 0.12, (position, distance)
        # the test has teeth: the distribution is genuinely spread out
        assert ref_hist.max() < 0.9


@pytest.mark.slow
@pytest.mark.parametrize('family', ['gpt2', 'llama'])
def test_generate_on_scanned_model_matches_unrolled(family):
    """Decode-mode KV caches ride nn.scan (variable_axes={'cache': 0}):
    generation from a scanned model must equal the unrolled model's
    token-for-token, given transplanted weights."""
    import jax
    from tpusystem.models import gpt2_tiny, llama_tiny
    if family == 'gpt2':
        unrolled = gpt2_tiny(layers=4, dtype='float32')
        scanned = gpt2_tiny(layers=4, scan_layers=True, dtype='float32')
        prefix, stacked_key = 'h_', 'hs'
    else:
        unrolled = llama_tiny(layers=4, dtype='float32')
        scanned = llama_tiny(layers=4, scan_layers=True, dtype='float32')
        prefix, stacked_key = 'layer_', 'blocks'
    prompt = jnp.asarray(
        np.random.default_rng(11).integers(0, 256, (2, 8)), jnp.int32)
    params = unrolled.init(jax.random.PRNGKey(3), prompt)['params']
    per_layer = [params[f'{prefix}{i}'] for i in range(4)]
    stacked = {k: v for k, v in params.items() if not k.startswith(prefix)}
    stacked[stacked_key] = jax.tree.map(
        lambda *leaves: jnp.stack(leaves), *per_layer)
    out_u = generate(unrolled, params, prompt, steps=6)
    out_s = generate(scanned, stacked, prompt, steps=6)
    np.testing.assert_array_equal(np.asarray(out_u), np.asarray(out_s))


@pytest.mark.slow
def test_speculative_decode_on_scanned_target():
    """Speculative decoding on a scan_layers target: per-row cache cursors
    live at a leading layer dim (variable_axes={'cache': 0}) and _rewind
    broadcasts the [batch] cursor into that shape — output must still be
    exactly the target's greedy decode."""
    from tpusystem.train import speculative_generate
    target = gpt2_tiny(dtype='float32', max_seq=128, layers=4,
                       scan_layers=True)
    draft = gpt2_tiny(dtype='float32', layers=1, dim=32, heads=2,
                      max_seq=128)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 256, (2, 8)), jnp.int32)
    params = target.init(jax.random.PRNGKey(5), tokens)['params']
    draft_params = draft.init(jax.random.PRNGKey(6), tokens)['params']
    reference = np.asarray(generate(target, params, tokens, steps=16))
    out = speculative_generate(
        target, params, tokens, steps=16, draft_module=draft,
        draft_params=draft_params, speculate=3)
    np.testing.assert_array_equal(np.asarray(out), reference)


def test_stream_dtype_auto_matches_f32_streaming_exactly():
    """For a bf16-compute model, pre-casting f32 matrix masters to bf16
    (stream_dtype='auto') must produce bit-identical generations to
    streaming the f32 masters: the model casts weights to bf16 at every
    use anyway, so only the HBM bytes change (the decode bandwidth
    optimization — see BASELINE.md decode roofline)."""
    module = gpt2_tiny(dtype='bfloat16')
    prompt = jnp.asarray(
        np.random.default_rng(23).integers(0, 256, (2, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    auto = generate(module, params, prompt, steps=12)
    f32 = generate(module, params, prompt, steps=12, stream_dtype='float32')
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(f32))


def test_stream_dtype_bfloat16_matches_auto_token_exact(prompt):
    """'bfloat16' on a bf16-compute model is the identical program to
    'auto' (both pre-cast the f32 matrix masters to bf16) — token-exact;
    on an f32-compute model it bf16-rounds the weights but still decodes
    in-vocab tokens."""
    module = gpt2_tiny(dtype='bfloat16')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    auto = generate(module, params, prompt, steps=10)
    forced = generate(module, params, prompt, steps=10,
                      stream_dtype='bfloat16')
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(forced))

    f32_module = gpt2_tiny(dtype='float32')
    f32_params = f32_module.init(jax.random.PRNGKey(0), prompt)['params']
    out = np.asarray(generate(f32_module, f32_params, prompt, steps=6,
                              stream_dtype='bfloat16'))
    assert ((out >= 0) & (out < f32_module.vocab_size)).all()


def test_stream_dtype_unknown_raises_enumerating_the_valid_set(prompt):
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    with pytest.raises(ValueError) as excinfo:
        generate(module, params, prompt, steps=2, stream_dtype='int4')
    for mode in ('auto', 'float32', 'bfloat16', 'int8', 'fp8'):
        assert mode in str(excinfo.value)


def test_quantizer_cache_reuses_compiled_program(prompt):
    """The caster-cache regression pin, quantize flavored: _quantizer must
    be one cached jitted program per mode — an uncached jit would retrace
    the whole-tree quantization every generate() call (the round-5 8x
    decode slowdown)."""
    import importlib
    generate_module = importlib.import_module('tpusystem.train.generate')
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    generate(module, params, prompt, steps=2, stream_dtype='int8')
    before = generate_module._quantizer.cache_info().hits
    generate(module, params, prompt, steps=2, stream_dtype='int8')
    assert generate_module._quantizer.cache_info().hits == before + 1


def test_int8_streaming_bounded_logit_divergence_and_finite_decode(prompt):
    """int8 weight streaming is lossy but bounded: the dequantized tree's
    logits stay within a small absolute band of the master tree's, and
    greedy decode emits finite in-vocab tokens."""
    from tpusystem.ops.precision import dequantize_streamed, quantize_streamed
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    exact = module.apply({'params': params}, prompt)
    quantized = dequantize_streamed(quantize_streamed(params, 'int8'))
    approximate = module.apply({'params': quantized}, prompt)
    divergence = float(jnp.max(jnp.abs(exact - approximate)))
    assert np.isfinite(np.asarray(approximate)).all()
    assert 0.0 < divergence < 0.5, divergence   # lossy, but bounded

    out = np.asarray(generate(module, params, prompt, steps=8,
                              stream_dtype='int8'))
    assert ((out >= 0) & (out < module.vocab_size)).all()


def test_fp8_streaming_bounded_divergence_or_clear_gate(prompt):
    """Where the jaxlib supports float8_e4m3fn the fp8 stream decodes
    finite in-vocab tokens with bounded logit divergence; elsewhere the
    capability probe's reason surfaces in the ValueError."""
    from tpusystem.ops.precision import (dequantize_streamed,
                                         fp8_unsupported_reason,
                                         quantize_streamed)
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    reason = fp8_unsupported_reason()
    if reason is not None:
        with pytest.raises(ValueError, match='fp8'):
            generate(module, params, prompt, steps=2, stream_dtype='fp8')
        return
    exact = module.apply({'params': params}, prompt)
    quantized = dequantize_streamed(quantize_streamed(params, 'fp8'))
    approximate = module.apply({'params': quantized}, prompt)
    assert float(jnp.max(jnp.abs(exact - approximate))) < 0.5
    out = np.asarray(generate(module, params, prompt, steps=6,
                              stream_dtype='fp8'))
    assert ((out >= 0) & (out < module.vocab_size)).all()


@pytest.mark.slow
def test_batched_speculative_matches_batch1_trajectories_row_wise():
    """The batched verify forward amortizes one weight pass across the
    whole batch; per-row acceptance bookkeeping must reproduce each
    row's batch-1 trajectory exactly — a batch of prompts decodes to the
    same tokens as each prompt alone."""
    from tpusystem.train import speculative_generate
    target = gpt2_tiny(dtype='float32', max_seq=128)
    draft = gpt2_tiny(dtype='float32', layers=1, dim=32, heads=2,
                      max_seq=128)
    prompts = jnp.asarray(
        np.random.default_rng(31).integers(0, 256, (3, 8)), jnp.int32)
    params = target.init(jax.random.PRNGKey(0), prompts)['params']
    draft_params = draft.init(jax.random.PRNGKey(9), prompts)['params']
    batched = np.asarray(speculative_generate(
        target, params, prompts, steps=16, draft_module=draft,
        draft_params=draft_params, speculate=3))
    for row in range(prompts.shape[0]):
        alone = np.asarray(speculative_generate(
            target, params, prompts[row:row + 1], steps=16,
            draft_module=draft, draft_params=draft_params, speculate=3))
        np.testing.assert_array_equal(batched[row:row + 1], alone,
                                      err_msg=f'row {row}')


@pytest.mark.slow
def test_tree_speculative_verify_equals_greedy():
    """Token-tree verify (tree_fanout=F): F draft branches per sequence
    verified as extra batch rows in one target forward — output must
    still be EXACTLY the target's greedy decode, for any fanout and any
    draft quality (including the full-acceptance self-draft)."""
    from tpusystem.train import generate, speculative_generate
    target = gpt2_tiny(dtype='float32', max_seq=128)
    draft = gpt2_tiny(dtype='float32', layers=1, dim=32, heads=2,
                      max_seq=128)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 8)), jnp.int32)
    params = target.init(jax.random.PRNGKey(0), tokens)['params']
    draft_params = draft.init(jax.random.PRNGKey(9), tokens)['params']
    reference = np.asarray(generate(target, params, tokens, steps=20))
    for fanout in (2, 3):
        out = speculative_generate(
            target, params, tokens, steps=20, draft_module=draft,
            draft_params=draft_params, speculate=3, tree_fanout=fanout)
        np.testing.assert_array_equal(np.asarray(out), reference,
                                      err_msg=f'fanout {fanout}')
    out = speculative_generate(
        target, params, tokens, steps=20, draft_module=target,
        draft_params=params, speculate=4, tree_fanout=2)
    np.testing.assert_array_equal(np.asarray(out), reference)


def test_tree_speculative_validates_args():
    from tpusystem.train import speculative_generate
    target = gpt2_tiny(dtype='float32', max_seq=64)
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = target.init(jax.random.PRNGKey(0), tokens)['params']
    with pytest.raises(ValueError, match='tree_fanout'):
        speculative_generate(target, params, tokens, steps=4,
                             draft_module=target, draft_params=params,
                             speculate=2, tree_fanout=0)
    with pytest.raises(ValueError, match='greedy'):
        speculative_generate(target, params, tokens, steps=4,
                             draft_module=target, draft_params=params,
                             speculate=2, tree_fanout=2, temperature=1.0,
                             rng=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match='vocab'):
        speculative_generate(target, params, tokens, steps=4,
                             draft_module=target, draft_params=params,
                             speculate=2, tree_fanout=1000)


@pytest.mark.slow
def test_speculative_quantized_streaming_decodes_in_vocab():
    """stream_dtype='int8' applies to BOTH trees of the speculative path
    (the verify forward streams narrow bytes too) — output stays
    finite/in-vocab with per-row bookkeeping intact."""
    from tpusystem.train import speculative_generate
    target = gpt2_tiny(dtype='float32', max_seq=128)
    draft = gpt2_tiny(dtype='float32', layers=1, dim=32, heads=2,
                      max_seq=128)
    tokens = jnp.asarray(
        np.random.default_rng(13).integers(0, 256, (2, 8)), jnp.int32)
    params = target.init(jax.random.PRNGKey(0), tokens)['params']
    draft_params = draft.init(jax.random.PRNGKey(9), tokens)['params']
    out = np.asarray(speculative_generate(
        target, params, tokens, steps=12, draft_module=draft,
        draft_params=draft_params, speculate=3, stream_dtype='int8'))
    assert ((out >= 0) & (out < target.vocab_size)).all()
    np.testing.assert_array_equal(out[:, :8], np.asarray(tokens))


def test_cursor_authority_is_the_shared_module():
    """The speculative path and the serving engine must edit cache
    cursors through ONE implementation (tpusystem.train.cursors) — a
    private copy in either would let the two drift on which leaves count
    as cursors or how scanned stacks broadcast."""
    import importlib

    import tpusystem.serve.engine as serve_engine
    from tpusystem.train import cursors
    generate_module = importlib.import_module('tpusystem.train.generate')
    assert generate_module._rewind is cursors.rewind
    assert generate_module._gather_rows is cursors.gather_rows
    assert serve_engine.rewind is cursors.rewind


def test_cursors_rewind_and_gather_cover_scanned_and_flat_caches():
    """Unit pin of the shared authority: rewind broadcasts a [batch]
    cursor into flat AND layer-stacked cursor leaves (touching nothing
    else); gather_rows copies KV on the batch axis and cursors on the
    last axis; read_cursor returns the per-row cursor either way."""
    import jax.numpy as jnp

    from tpusystem.train import cursors
    flat = {'h_0': {'attn': {'index': jnp.array([3, 5], jnp.int32),
                             'key': jnp.arange(2 * 4 * 1 * 1, dtype=jnp.float32)
                             .reshape(2, 4, 1, 1)}},
            'position': jnp.array([3, 5], jnp.int32)}
    rewound = cursors.rewind(flat, jnp.array([1, 2], jnp.int32))
    np.testing.assert_array_equal(rewound['h_0']['attn']['index'], [1, 2])
    np.testing.assert_array_equal(rewound['position'], [1, 2])
    np.testing.assert_array_equal(rewound['h_0']['attn']['key'],
                                  flat['h_0']['attn']['key'])
    np.testing.assert_array_equal(cursors.read_cursor(flat), [3, 5])

    stacked = {'hs': {'attn': {'index': jnp.tile(
        jnp.array([[3, 5]], jnp.int32), (4, 1))}}}   # [layers, batch]
    rewound = cursors.rewind(stacked, jnp.array([7, 9], jnp.int32))
    assert rewound['hs']['attn']['index'].shape == (4, 2)
    np.testing.assert_array_equal(rewound['hs']['attn']['index'][2], [7, 9])
    np.testing.assert_array_equal(cursors.read_cursor(stacked), [3, 5])

    gathered = cursors.gather_rows(flat, jnp.array([1, 1], jnp.int32))
    np.testing.assert_array_equal(gathered['h_0']['attn']['index'], [5, 5])
    np.testing.assert_array_equal(gathered['h_0']['attn']['key'][0],
                                  flat['h_0']['attn']['key'][1])
    with pytest.raises(ValueError, match='index'):
        cursors.read_cursor({'h_0': {'attn': {'key': jnp.zeros((1,))}}})


@pytest.mark.slow
def test_bucketed_cache_attention_crosses_bucket_boundary():
    """max_seq 512 decode buckets cache reads at [256, 512]; a generation
    crossing the 256-token boundary must stay token-exact with the full
    re-forward reference (the switch picks a wider window mid-scan)."""
    module = gpt2_tiny(dtype='float32', max_seq=512)
    prompt = jnp.asarray(
        np.random.default_rng(29).integers(0, 256, (2, 250)), jnp.int32)
    params = module.init(jax.random.PRNGKey(1), prompt[:, :8])['params']
    decoded = generate(module, params, prompt, steps=20)   # 250 -> 270
    reference = full_forward_greedy(module, params, prompt, 20)
    np.testing.assert_array_equal(np.asarray(decoded), np.asarray(reference))
