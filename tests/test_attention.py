"""Attention op tests: flash kernel parity, ring/ulysses sequence parallelism
vs the single-device reference (SURVEY.md §7.2 item 7 correctness harness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.ops.attention import dot_product_attention
from tpusystem.ops.pallas.flash import flash_attention
from tpusystem.ops.ring import ring_self_attention
from tpusystem.parallel import MeshSpec


@pytest.fixture(scope='module')
def qkv():
    rng = np.random.default_rng(7)
    shape = (2, 128, 4, 32)
    return tuple(jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3))


def test_flash_forward_matches_reference(qkv):
    q, k, v = qkv
    reference = dot_product_attention(q, k, v, causal=True)
    flash = flash_attention(q, k, v, causal=True, block_q=32, block_kv=64,
                            interpret=True)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(flash),
                               atol=2e-5)


def test_flash_noncausal(qkv):
    q, k, v = qkv
    reference = dot_product_attention(q, k, v, causal=False)
    flash = flash_attention(q, k, v, causal=False, block_q=32, block_kv=64,
                            interpret=True)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(flash),
                               atol=2e-5)


def test_flash_gradients_match_reference(qkv):
    q, k, v = qkv

    def loss_reference(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=32,
                                       block_kv=64, interpret=True) ** 2)

    grads_reference = jax.grad(loss_reference, argnums=(0, 1, 2))(q, k, v)
    grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for reference, flash in zip(grads_reference, grads_flash):
        np.testing.assert_allclose(np.asarray(reference), np.asarray(flash),
                                   atol=5e-4)


def test_flash_gqa_broadcast():
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 64, 8, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 64, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 64, 2, 16)), jnp.float32)
    reference = dot_product_attention(q, k, v, causal=True)
    flash = flash_attention(q, k, v, causal=True, block_q=32, block_kv=32,
                            interpret=True)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(flash),
                               atol=2e-5)


def test_flash_falls_back_on_indivisible_lengths():
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(1, 100, 2, 16)), jnp.float32)  # 100 odd
    out = flash_attention(q, q, q, causal=True, block_q=64, block_kv=64,
                          interpret=True)
    reference = dot_product_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference), atol=2e-5)


@pytest.mark.parametrize('variant', ['ring', 'ulysses'])
@pytest.mark.slow
def test_sequence_parallel_matches_single_device(qkv, variant):
    q, k, v = qkv
    reference = dot_product_attention(q, k, v, causal=True)
    mesh = MeshSpec(data=2, seq=4).build()
    sharded = ring_self_attention(q, k, v, mesh, causal=True, variant=variant)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)


@pytest.mark.parametrize('variant', ['ring', 'ulysses'])
@pytest.mark.slow
def test_sequence_parallel_gradients(qkv, variant):
    q, k, v = qkv
    mesh = MeshSpec(data=2, seq=4).build()

    def loss_single(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_sharded(q, k, v):
        return jnp.mean(ring_self_attention(q, k, v, mesh, causal=True,
                                            variant=variant) ** 2)

    # argnums=(0,1,2): dK/dV exercise the transpose of the rotating-K/V
    # collectives (ppermute ring reversal / all_to_all axis swap), where a
    # direction bug would leave dQ correct but dK/dV permuted.
    grads_single = jax.grad(loss_single, argnums=(0, 1, 2))(q, k, v)
    grads_sharded = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    for single, sharded in zip(grads_single, grads_sharded):
        np.testing.assert_allclose(np.asarray(single), np.asarray(sharded),
                                   atol=5e-5)


@pytest.mark.slow
def test_ring_noncausal():
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
    mesh = MeshSpec(seq=8).build()
    reference = dot_product_attention(q, q, q, causal=False)
    sharded = ring_self_attention(q, q, q, mesh, causal=False, variant='ring')
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)


@pytest.mark.slow
def test_gpt2_ring_attention_long_context_trains():
    """GPT-2 with seq-sharded ring attention: activations shard over the seq
    axis, attention runs on the ppermute ring, loss matches the dense model."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from tpusystem.models import gpt2_tiny
    from tpusystem.parallel import MeshSpec
    from tpusystem.train import AdamW, NextTokenLoss, build_train_step, flax_apply, init_state

    mesh = MeshSpec(data=2, seq=4).build()
    dense = gpt2_tiny(attention='xla')
    ringed = gpt2_tiny(attention='ring', mesh=mesh)
    optimizer = AdamW(lr=1e-3)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (4, 128)), jnp.int32)

    def losses(module, place):
        state = init_state(module, optimizer, tokens[:1], rng=0)
        toks = tokens
        if place:
            state = jax.device_put(
                state, NamedSharding(mesh, P()))
            toks = jax.device_put(tokens, NamedSharding(mesh, P('data', 'seq')))
        step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
        out = []
        for _ in range(3):
            state, (_, loss) = step(state, toks, toks)
            out.append(float(loss))
        return out

    np.testing.assert_allclose(losses(dense, False), losses(ringed, True), rtol=2e-4)


def _reference_keep_mask(seed, bh, seq_q, seq_kv, dropout):
    """The kernel's positional hash, recomputed outside the kernel."""
    from tpusystem.ops.pallas.flash import _keep_mask
    masks = [_keep_mask(jnp.int32(seed), jnp.int32(row), jnp.int32(0),
                        jnp.int32(0), seq_q, seq_kv, dropout)
             for row in range(bh)]
    return jnp.stack(masks)                      # [bh, seq_q, seq_kv]


def test_flash_dropout_matches_masked_reference():
    """In-kernel dropout == plain-JAX attention with the SAME positional
    mask: exact forward and gradient parity (the mask is a pure hash of
    positions, so the reference regenerates it outside the kernel)."""
    rng = np.random.default_rng(21)
    batch, seq, heads, dim, p = 2, 64, 2, 16, 0.3
    q, k, v = (jnp.asarray(rng.normal(size=(batch, seq, heads, dim)),
                           jnp.float32) for _ in range(3))
    key = jax.random.PRNGKey(5)
    seed = int(jax.random.randint(key, (1,), 0, jnp.iinfo(jnp.int32).max,
                                  dtype=jnp.int32)[0])
    keep = _reference_keep_mask(seed, batch * heads, seq, seq, p)
    keep = keep.reshape(batch, heads, seq, seq)

    def reference(q, k, v):
        scores = jnp.einsum('bqhd,bkhd->bhqk', q, k) * dim ** -0.5
        scores = jnp.where(np.tril(np.ones((seq, seq), bool)), scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1)
        weights = jnp.where(keep, weights / (1 - p), 0.0)
        return jnp.einsum('bhqk,bkhd->bqhd', weights, v)

    def kernelized(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=32, block_kv=32,
                               interpret=True, dropout=p, dropout_rng=key)

    np.testing.assert_allclose(np.asarray(kernelized(q, k, v)),
                               np.asarray(reference(q, k, v)), atol=2e-5)

    loss = lambda fn: lambda q, k, v: jnp.mean(fn(q, k, v) ** 2)
    got = jax.grad(loss(kernelized), argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss(reference), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_flash_dropout_keep_rate_and_determinism():
    """Statistical semantics: with uniform attention and all-ones values,
    each output element reads off its row's keep count — the measured keep
    rate matches 1 - p and survivors are scaled by 1/(1-p). Same key =>
    identical masks; different key => different."""
    p, seq, dim = 0.25, 128, 16
    q = jnp.zeros((1, seq, 1, dim), jnp.float32)       # uniform probs
    v = jnp.ones((1, seq, 1, dim), jnp.float32)
    run = lambda key: flash_attention(
        q, q, v, causal=False, block_q=64, block_kv=64, interpret=True,
        dropout=p, dropout_rng=key)
    out = np.asarray(run(jax.random.PRNGKey(0)))[0, :, 0, 0]
    keep_rate = out * (1 - p)                           # count / seq
    assert abs(keep_rate.mean() - (1 - p)) < 3 * np.sqrt(p * (1 - p) / seq), (
        keep_rate.mean())
    assert keep_rate.std() > 0                          # a real mask, not a scale
    again = np.asarray(run(jax.random.PRNGKey(0)))[0, :, 0, 0]
    np.testing.assert_array_equal(out, again)
    other = np.asarray(run(jax.random.PRNGKey(1)))[0, :, 0, 0]
    assert not np.array_equal(out, other)
    # dropout=0 path unchanged
    clean = flash_attention(q, q, v, causal=False, block_q=64, block_kv=64,
                            interpret=True)
    np.testing.assert_allclose(np.asarray(clean)[0, :, 0, 0], 1.0, atol=1e-5)


@pytest.mark.slow
def test_gpt2_flash_attention_dropout_trains():
    """attention='flash' with dropout > 0 now trains (the regularization
    caveat is gone): one step runs and the loss is finite."""
    from tpusystem.models import gpt2_tiny
    from tpusystem.train import AdamW, NextTokenLoss, build_train_step, flax_apply, init_state
    module = gpt2_tiny(attention='flash', dropout=0.1, dtype='float32')
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 64)),
                         jnp.int32)
    optimizer = AdamW(lr=1e-3)
    state = init_state(module, optimizer, tokens[:1])
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    state, (_, loss) = step(state, tokens, tokens)
    assert np.isfinite(float(loss))


def test_block_fitting_keeps_midsize_lengths_on_the_kernel():
    """Defaults that do not divide the sequence shrink to the largest
    lane-aligned divisor instead of silently dropping to the O(seq^2) XLA
    path; unalignable lengths still fall back."""
    from tpusystem.ops.pallas.flash import _block_sizes
    assert _block_sizes(1024, 1024, 512, 1024) == (512, 1024)
    assert _block_sizes(1536, 1536, 512, 1024) == (512, 768)
    assert _block_sizes(768, 768, 512, 1024) == (384, 768)
    assert _block_sizes(16, 16, 512, 1024) == (16, 16)   # tiny single block
    assert _block_sizes(100, 100, 64, 64) is None        # not sublane-aligned
    assert _block_sizes(200, 200, 512, 1024) is None


def test_flash_matches_reference_at_shrunk_blocks():
    """Parity at a mid-size length where the tile is auto-shrunk."""
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.normal(size=(1, 384, 2, 16)), jnp.float32)
    out = flash_attention(q, q, q, causal=True, interpret=True)
    reference = dot_product_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               atol=2e-5)


def test_sharded_flash_matches_reference(qkv):
    """Flash under GSPMD: batch over data, heads over model, kernel parity."""
    from tpusystem.ops.pallas.flash import sharded_flash_attention
    q, k, v = qkv
    mesh = MeshSpec(data=2, model=2).build(jax.devices()[:4])
    reference = dot_product_attention(q, k, v, causal=True)
    sharded = sharded_flash_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)


def test_sharded_flash_gradients(qkv):
    """The kernel's custom_vjp composes with shard_map's transpose."""
    from tpusystem.ops.pallas.flash import sharded_flash_attention
    q, k, v = qkv
    mesh = MeshSpec(data=2, model=2).build(jax.devices()[:4])

    def loss_single(q, k, v):
        return jnp.mean(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_sharded(q, k, v):
        return jnp.mean(sharded_flash_attention(q, k, v, mesh, causal=True) ** 2)

    grads_single = jax.grad(loss_single, argnums=(0, 1, 2))(q, k, v)
    grads_sharded = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    for single, sharded in zip(grads_single, grads_sharded):
        np.testing.assert_allclose(np.asarray(single), np.asarray(sharded),
                                   atol=5e-5)


def test_sharded_flash_gqa_kv_heads_shard_over_model():
    """GQA under TP: 4 query heads / 2 KV heads both divide model=2, so the
    KV cache shards instead of being broadcast up front."""
    from tpusystem.ops.pallas.flash import sharded_flash_attention
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(2, 64, 4, 32)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 64, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 64, 2, 32)), jnp.float32)
    mesh = MeshSpec(model=2).build(jax.devices()[:2])
    reference = dot_product_attention(q, k, v, causal=True)
    sharded = sharded_flash_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)


def test_sharded_flash_indivisible_axes_replicate():
    """Batch 3 over data=2 and heads 3 over model=2: both axes fall back to
    replication instead of erroring, and parity still holds."""
    from tpusystem.ops.pallas.flash import sharded_flash_attention
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(3, 64, 3, 16)), jnp.float32)
    mesh = MeshSpec(data=2, model=2).build(jax.devices()[:4])
    reference = dot_product_attention(q, q, q, causal=True)
    sharded = sharded_flash_attention(q, q, q, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)


@pytest.mark.slow
def test_gpt2_flash_trains_under_tensor_parallel_fsdp():
    """attention='flash' composes with the TensorParallel(fsdp=True) policy:
    one full sharded train step runs and the loss matches the xla kernel."""
    from tpusystem.models import gpt2_tiny
    from tpusystem.parallel import TensorParallel, batch_sharding
    from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                                 flax_apply, init_state)
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 256, (8, 64)), jnp.int32)

    def one_step(attention):
        module = gpt2_tiny(attention=attention,
                           mesh=mesh if attention == 'flash' else None)
        optimizer = AdamW(lr=1e-3)
        state = init_state(module, optimizer, tokens[:1])
        policy = TensorParallel(module.partition_rules(), fsdp=True,
                                fsdp_min_size=64)
        state = policy.place(state, mesh)
        placed = jax.device_put(tokens, batch_sharding(mesh))
        step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
        _, (_, loss) = step(state, placed, placed)
        return float(loss)

    np.testing.assert_allclose(one_step('flash'), one_step('xla'), rtol=2e-4)


def test_flash_lse_matches_reference_and_grads(qkv):
    """(out, lse) kernel parity, and gradient flow through BOTH outputs —
    the lse cotangent is what ring attention's merge differentiates."""
    from tpusystem.ops.pallas.flash import (_xla_attention_lse,
                                            flash_attention_lse)
    q, k, v = qkv
    ref_out, ref_lse = _xla_attention_lse(q, k, v, causal=True, scale=q.shape[-1] ** -0.5)
    out, lse = flash_attention_lse(q, k, v, causal=True, block_q=32,
                                   block_kv=64, interpret=True)
    np.testing.assert_allclose(np.asarray(ref_out), np.asarray(out), atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref_lse), np.asarray(lse), atol=2e-5)

    def loss(fn):
        def wrapped(q, k, v):
            out, lse = fn(q, k, v)
            return jnp.mean(out ** 2) + jnp.mean(jnp.sin(lse))
        return wrapped

    flash_fn = loss(lambda q, k, v: flash_attention_lse(
        q, k, v, causal=True, block_q=32, block_kv=64, interpret=True))
    ref_fn = loss(lambda q, k, v: _xla_attention_lse(
        q, k, v, causal=True, scale=q.shape[-1] ** -0.5))
    grads = jax.grad(flash_fn, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=5e-5)


def test_zigzag_halves_ring_flops(qkv, monkeypatch):
    """Per-device attention block area: the contiguous causal ring computes
    n full chunk-pair attentions (discarding the invisible ones); zigzag
    computes (2n+1) stripe blocks = ~half the area at n=4 and falling
    toward exactly half as n grows."""
    import tpusystem.ops.ring as ring_module
    q, k, v = qkv
    mesh = MeshSpec(seq=4).build(jax.devices()[:4])

    area = []
    real = ring_module._attention_lse

    def counting(query, key, value, **kwargs):
        area.append(query.shape[1] * key.shape[1])
        return real(query, key, value, **kwargs)

    monkeypatch.setattr(ring_module, '_attention_lse', counting)

    def measure(variant):
        area.clear()
        jax.eval_shape(lambda: ring_module.ring_self_attention(
            q, k, v, mesh, causal=True, variant=variant))
        return sum(area)     # shard_map traces once: per-device area

    ring = 4
    chunk = q.shape[1] // ring
    stripe = chunk // 2
    zigzag = measure('zigzag')
    # contiguous ring: n chunk-pair attentions per device, all computed
    # (invisible ones discarded post-hoc) = n * chunk^2 block area
    naive = ring * chunk * chunk
    assert zigzag == (2 * ring + 1) * stripe * stripe, zigzag
    assert zigzag <= 0.6 * naive, (zigzag, naive)


def test_ring_variant_auto_upgrades_to_zigzag(qkv, monkeypatch):
    """variant='ring' + causal + stripeable length routes through zigzag."""
    import tpusystem.ops.ring as ring_module
    q, k, v = qkv
    mesh = MeshSpec(seq=4).build(jax.devices()[:4])
    used = []
    real = ring_module.zigzag_ring_attention
    monkeypatch.setattr(ring_module, 'zigzag_ring_attention',
                        lambda *a, **kw: used.append(1) or real(*a, **kw))
    jax.eval_shape(lambda: ring_module.ring_self_attention(
        q, k, v, mesh, causal=True, variant='ring'))
    assert used


@pytest.mark.slow
@pytest.mark.parametrize('variant', ['ring', 'ulysses'])
def test_ring_gqa_keeps_kv_grouped(variant, monkeypatch):
    """Grouped-query attention on the sequence-parallel paths: KV rotates
    at its own head count (group-factor fewer ppermute bytes on the ring
    variants), output matches the broadcast reference — fwd and grads."""
    import tpusystem.ops.ring as ring_module
    from tpusystem.ops.attention import repeat_kv_heads
    rng = np.random.default_rng(17)
    q = jnp.asarray(rng.normal(size=(2, 128, 8, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 128, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 128, 2, 16)), jnp.float32)
    mesh = MeshSpec(data=2, seq=4).build()

    rotated_heads = []
    real_permute = ring_module._ring_permute

    def spying_permute(axis, ring):
        permute = real_permute(axis, ring)
        def wrapped(tensor):
            rotated_heads.append(tensor.shape[2])
            return permute(tensor)
        return wrapped

    monkeypatch.setattr(ring_module, '_ring_permute', spying_permute)

    kk, vv = repeat_kv_heads(q, k, v)
    reference = dot_product_attention(q, kk, vv, causal=True)
    sharded = ring_self_attention(q, k, v, mesh, causal=True, variant=variant)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)
    if variant == 'ring':   # zigzag path: rotating tensors carry 2 KV heads
        assert rotated_heads and set(rotated_heads) == {2}, rotated_heads

    def loss_single(q, k, v):
        kk, vv = repeat_kv_heads(q, k, v)
        return jnp.mean(dot_product_attention(q, kk, vv, causal=True) ** 2)

    def loss_sharded(q, k, v):
        return jnp.mean(ring_self_attention(q, k, v, mesh, causal=True,
                                            variant=variant) ** 2)

    grads_single = jax.grad(loss_single, argnums=(0, 1, 2))(q, k, v)
    grads_sharded = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    for single, sharded in zip(grads_single, grads_sharded):
        assert single.shape == sharded.shape
        np.testing.assert_allclose(np.asarray(single), np.asarray(sharded),
                                   atol=5e-5)


@pytest.mark.slow
def test_ring_einsum_inner_fallback_matches(qkv):
    """inner='einsum' (the XLA fallback path) stays at parity too."""
    q, k, v = qkv
    reference = dot_product_attention(q, k, v, causal=True)
    mesh = MeshSpec(data=2, seq=4).build()
    sharded = ring_self_attention(q, k, v, mesh, causal=True, variant='ring',
                                  inner='einsum')
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)


def test_flash_gqa_gradients_accumulate_over_group():
    """GQA in-kernel: dK/dV for one KV head must accumulate over every
    query head in its group (the backward sweeps (member, q block) pairs),
    matching the broadcast-KV reference exactly."""
    rng = np.random.default_rng(13)
    q = jnp.asarray(rng.normal(size=(2, 64, 8, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=True, block_q=32, block_kv=32,
                              interpret=True)
        return jnp.mean(out ** 2)

    def loss_reference(q, k, v):
        from tpusystem.ops.attention import repeat_kv_heads
        kk, vv = repeat_kv_heads(q, k, v)
        out = dot_product_attention(q, kk, vv, causal=True)
        # dK/dV of the broadcast reference sum over the group implicitly
        return jnp.mean(out ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_reference, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.parametrize('causal', [True, False])
def test_fused_backward_matches_split(causal):
    """The single-pass dq+dk+dv backward must agree with the split dq / dkv
    kernels bit-for-bit in structure (same math, different sweep): GQA
    grouping, multi-block tiling (kv_steps > 1 exercises the partial-dq
    reduction), and in-kernel dropout all covered."""
    rng = np.random.default_rng(29)
    q = jnp.asarray(rng.normal(size=(2, 128, 4, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 128, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 128, 2, 16)), jnp.float32)
    key = jax.random.PRNGKey(5)

    def loss(backward, dropout):
        def inner(q, k, v):
            out = flash_attention(q, k, v, causal=causal, block_q=32,
                                  block_kv=32, interpret=True,
                                  dropout=dropout, dropout_rng=key,
                                  backward=backward)
            return jnp.sum(out * jnp.cos(out))
        return inner

    for dropout in (0.0, 0.25):
        fused = jax.grad(loss('fused', dropout), argnums=(0, 1, 2))(q, k, v)
        split = jax.grad(loss('split', dropout), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(fused, split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)


def test_fused_backward_bf16_partials_stay_f32():
    """kv_steps > 1 in bf16: the fused backward's dq partials accumulate
    in float32 before the cross-step sum (bf16 partials would round 4+
    times per element where the split path rounds once) — fused and split
    gradients must agree to bf16-roundoff, not worse."""
    rng = np.random.default_rng(31)
    shape = (1, 256, 2, 32)
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
               for _ in range(3))

    def loss(backward):
        def inner(q, k, v):
            out = flash_attention(q, k, v, causal=True, block_q=64,
                                  block_kv=64, interpret=True,
                                  backward=backward)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return inner

    fused = jax.grad(loss('fused'), argnums=(0, 1, 2))(q, k, v)
    split = jax.grad(loss('split'), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(fused, split):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=2e-2, rtol=2e-2)


def test_sharded_flash_gqa_broadcast_fallback_warns():
    """KV heads that don't divide the model axis broadcast up to the query
    head count — correct, but it forfeits the GQA memory saving, so the
    fallback must announce itself."""
    from tpusystem.ops.pallas.flash import sharded_flash_attention
    rng = np.random.default_rng(13)
    q = jnp.asarray(rng.normal(size=(2, 64, 4, 32)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 64, 1, 32)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 64, 1, 32)), jnp.float32)
    mesh = MeshSpec(model=2).build(jax.devices()[:2])
    reference = dot_product_attention(q, k, v, causal=True)
    with pytest.warns(UserWarning, match='GQA KV memory saving'):
        sharded = sharded_flash_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(reference), np.asarray(sharded),
                               atol=2e-5)


def test_fused_backward_vmem_overflow_falls_back_to_split(monkeypatch):
    """The resident-dq fused variant auto-routes to the split sweeps (with
    a warning) when its estimated VMEM working set exceeds the requested
    limit, instead of failing the pallas_call."""
    from tpusystem.ops.pallas import flash as flash_mod
    rng = np.random.default_rng(17)
    shape = (1, 256, 2, 32)                      # MHA: group == 1
    q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.float32)
               for _ in range(3))

    def loss(q, k, v):
        out = flash_attention(q, k, v, causal=True, block_q=128,
                              block_kv=128, interpret=True)  # kv_steps = 2
        return jnp.sum(out ** 2)

    expected = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setattr(flash_mod, 'G1_VMEM_LIMIT', 1024)
    jax.clear_caches()        # drop the cached fused-backward trace
    with pytest.warns(UserWarning, match='falling back to\n?.*split'):
        fallback = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(expected, fallback):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_cached_attention_debug_guard_catches_nonuniform_cursor(monkeypatch):
    """TPUSYSTEM_DEBUG_CACHE=1 turns the per_row=False uniformity contract
    into a runtime check: a cache whose rows sit at different depths (e.g.
    left behind by a speculative run) raises instead of silently
    corrupting every row but row 0."""
    import flax.linen as nn

    from tpusystem.ops.attention import cached_attention

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, q, k, v):
            return cached_attention(self, q, k, v, max_seq=8, per_row=False)

    rng = np.random.default_rng(19)
    q = jnp.asarray(rng.normal(size=(2, 1, 2, 16)), jnp.float32)
    probe = Probe()
    variables = probe.init(jax.random.PRNGKey(0), q, q, q)
    cache = dict(variables['cache'])
    cache['index'] = jnp.asarray([1, 3], jnp.int32)          # non-uniform
    monkeypatch.setenv('TPUSYSTEM_DEBUG_CACHE', '1')
    with pytest.raises(Exception, match='uniform cache'):
        probe.apply({'cache': cache}, q, q, q, mutable=['cache'])
    # uniform cursor passes the check
    cache['index'] = jnp.asarray([2, 2], jnp.int32)
    probe.apply({'cache': cache}, q, q, q, mutable=['cache'])
