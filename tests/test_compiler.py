"""Compiler pipeline contracts (reference parity: tests/test_compiler.py:44-69),
exercised on a real jitted JAX step instead of torch.compile."""

import jax
import jax.numpy as jnp

from tpusystem import Compiler, Depends


def test_pipeline_folds_results_and_injects_dependencies():
    compiler = Compiler()
    trace = []

    def epochs():
        raise NotImplementedError

    @compiler.step
    def build(a, b):
        trace.append('build')
        return a + b

    @compiler.step
    def annotate(total, epochs=Depends(epochs)):
        trace.append('annotate')
        return (total, epochs)

    @compiler.step
    def finish(total, epochs):
        trace.append('finish')
        return {'total': total, 'epochs': epochs}

    compiler.dependency_overrides[epochs] = lambda: 10
    result = compiler.compile(2, 3)
    assert result == {'total': 5, 'epochs': 10}
    assert trace == ['build', 'annotate', 'finish']


def test_none_returning_step_is_side_effect_stage():
    compiler = Compiler()
    seen = []

    @compiler.step
    def produce(x):
        return x * 2

    @compiler.step
    def log(value):
        seen.append(value)  # returns None

    @compiler.step
    def consume(value):
        return value + 1

    assert compiler.compile(10) == 21
    assert seen == [20]


def test_compiles_real_jitted_step():
    """End-to-end: build params -> jit a step -> run it, all through the
    pipeline (the TPU analogue of the reference's torch.compile step)."""
    compiler = Compiler()

    @compiler.step
    def build(width):
        key = jax.random.PRNGKey(0)
        params = {'w': jax.random.normal(key, (width, width))}
        return params

    @compiler.step
    def lower(params):
        @jax.jit
        def step(params, x):
            return x @ params['w']
        return (params, step)

    params, step = compiler.compile(4)
    out = step(params, jnp.ones((2, 4)))
    assert out.shape == (2, 4)


def test_empty_pipeline_returns_none():
    assert Compiler().compile(1, 2) is None
