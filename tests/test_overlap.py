"""Latency-hiding TP collectives: decomposed all-gather/reduce-scatter
matmuls (``tpusystem/parallel/overlap.py``).

Parity harness on the virtual CPU mesh: the decomposed ring kernels must
match the GSPMD reference (a plain global matmul — what the partitioner
computes via its monolithic collectives) in forward AND gradients, f32 at
tight tolerance and bf16 bounded (f32 accumulation, different summation
order), with the one-shot fallback taken exactly where chunk shapes
cannot tile. Model-level: ``tp_impl='overlap'`` is a pure implementation
knob for GPT-2 and Llama — identical param trees, matching logits/grads.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpusystem.models import GPT2
from tpusystem.models.llama import llama_tiny
from tpusystem.parallel import (MeshSpec, ShardingPolicy, batch_sharding,
                                allgather_matmul, allgather_plan,
                                matmul_reducescatter, reducescatter_plan)
from tpusystem.parallel.mesh import MODEL, shard_map

RING = 4           # >= 4-device virtual mesh (conftest forces 8 devices)


def tp_mesh():
    return MeshSpec(model=RING).build(jax.devices()[:RING])


def _operands(dtype, rows=16, inner=12, cols=24, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(rows, inner)) * 0.5, dtype)
    w = jnp.asarray(rng.normal(size=(inner, cols)) * 0.5, dtype)
    return x, w


def _mapped_allgather(mesh, chunks):
    # x row-sharded over model (the sequence-sharded activation), w
    # column-sharded (Megatron up-projection): the gathered matmul
    @functools.partial(shard_map, mesh=mesh, check_vma=False,
                       in_specs=(P(MODEL, None), P(None, MODEL)),
                       out_specs=P(None, MODEL))
    def mapped(x, w):
        return allgather_matmul(x, w, MODEL, chunks=chunks)
    return mapped


def _mapped_reducescatter(mesh, chunks):
    # x column-sharded (the grown activation), w row-sharded (Megatron
    # down-projection): partial products sum + scatter rows
    @functools.partial(shard_map, mesh=mesh, check_vma=False,
                       in_specs=(P(None, MODEL), P(MODEL, None)),
                       out_specs=P(MODEL, None))
    def mapped(x, w):
        return matmul_reducescatter(x, w, MODEL, chunks=chunks)
    return mapped


@pytest.mark.parametrize('chunks', [1, 2])
def test_allgather_matmul_forward_matches_gspmd_reference(chunks):
    mesh = tp_mesh()
    x, w = _operands(jnp.float32)
    out = jax.jit(_mapped_allgather(mesh, chunks))(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w),
                               rtol=2e-6, atol=2e-6)


@pytest.mark.parametrize('chunks', [1, 2])
def test_matmul_reducescatter_forward_matches_gspmd_reference(chunks):
    mesh = tp_mesh()
    x, w = _operands(jnp.float32)
    out = jax.jit(_mapped_reducescatter(mesh, chunks))(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w),
                               rtol=2e-6, atol=2e-6)


@pytest.mark.parametrize('mapped_builder', [_mapped_allgather,
                                            _mapped_reducescatter])
def test_overlap_grads_match_gspmd_reference_f32(mapped_builder):
    """The custom_vjp (each decomposition's transpose is its dual with
    swapped operands) reproduces the reference cotangents."""
    mesh = tp_mesh()
    x, w = _operands(jnp.float32)
    mapped = mapped_builder(mesh, 2)

    def loss(x, w):
        return jnp.sum(jnp.square(mapped(x, w)))

    def reference(x, w):
        return jnp.sum(jnp.square(x @ w))

    dx, dw = jax.jit(jax.grad(loss, argnums=(0, 1)))(x, w)
    rx, rw = jax.grad(reference, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rx),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(rw),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('mapped_builder', [_mapped_allgather,
                                            _mapped_reducescatter])
def test_overlap_grads_match_gspmd_reference_bf16(mapped_builder):
    """bf16 compute with f32 accumulation: bounded tolerance against the
    reference computed the GSPMD way (bf16 matmul), mirroring the MoE
    three-impl bf16 grad-parity case."""
    mesh = tp_mesh()
    x, w = _operands(jnp.bfloat16)
    mapped = mapped_builder(mesh, 1)

    def loss(x, w):
        return jnp.sum(jnp.square(mapped(x, w).astype(jnp.float32)))

    def reference(x, w):
        return jnp.sum(jnp.square(jnp.matmul(x, w).astype(jnp.float32)))

    out = jax.jit(mapped)(x, w)
    ref = jnp.matmul(x, w)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0.05, atol=0.1)
    dx, dw = jax.jit(jax.grad(loss, argnums=(0, 1)))(x, w)
    rx, rw = jax.grad(reference, argnums=(0, 1))(x, w)
    assert dx.dtype == jnp.bfloat16 and dw.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(dx, np.float32),
                               np.asarray(rx, np.float32),
                               rtol=0.1, atol=0.5)
    np.testing.assert_allclose(np.asarray(dw, np.float32),
                               np.asarray(rw, np.float32),
                               rtol=0.1, atol=0.5)


# ---------------------------------------------------------------------------
# fallback planning
# ---------------------------------------------------------------------------


def test_plans_pick_one_shot_when_chunks_cannot_tile():
    # trivial ring: nothing to decompose
    assert allgather_plan(16, 1).path == 'one-shot'
    assert reducescatter_plan(16, 1).path == 'one-shot'
    # 16 shard rows cannot split into 3 ppermute chunks
    plan = allgather_plan(16, RING, chunks=3)
    assert plan.path == 'one-shot' and 'chunks' in plan.reason
    # scatter block 16/4 = 4 rows cannot split into 3
    plan = reducescatter_plan(16, RING, chunks=3)
    assert plan.path == 'one-shot' and 'chunks' in plan.reason
    # tiling shapes decompose
    assert allgather_plan(16, RING, chunks=2).path == 'overlap'
    assert reducescatter_plan(16, RING, chunks=2).path == 'overlap'
    # rows that cannot scatter at all have no semantics on either path
    with pytest.raises(ValueError):
        reducescatter_plan(18, RING)


def test_one_shot_fallback_still_matches_reference():
    """chunks=3 cannot tile the 4-row shards -> the one-shot collective
    path runs (pinned by the plan above) and stays correct, grads too."""
    mesh = tp_mesh()
    x, w = _operands(jnp.float32)
    assert allgather_plan(x.shape[0] // RING, RING, 3).path == 'one-shot'
    mapped = _mapped_allgather(mesh, 3)
    out = jax.jit(mapped)(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w),
                               rtol=2e-6, atol=2e-6)
    dx = jax.jit(jax.grad(lambda x, w: jnp.sum(jnp.square(mapped(x, w)))))(x, w)
    rx = jax.grad(lambda x, w: jnp.sum(jnp.square(x @ w)))(x, w)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(rx),
                               rtol=2e-5, atol=2e-5)

    assert reducescatter_plan(x.shape[0], RING, 3).path == 'one-shot'
    mapped = _mapped_reducescatter(mesh, 3)
    out = jax.jit(mapped)(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w),
                               rtol=2e-6, atol=2e-6)


# ---------------------------------------------------------------------------
# model-level: the tp_impl knob
# ---------------------------------------------------------------------------


def _model_mesh():
    return MeshSpec(data=2, model=2).build(jax.devices()[:4])


def _run_model(model, rules, tokens, mesh):
    variables = model.init(jax.random.PRNGKey(0), tokens[:1, :8])
    params = ShardingPolicy(rules=rules).place(variables['params'], mesh)
    placed_tokens = jax.device_put(tokens, batch_sharding(mesh))
    out = jax.jit(lambda p, t: model.apply({'params': p}, t))(
        params, placed_tokens)

    def loss(p):
        logits = model.apply({'params': p}, placed_tokens)
        return jnp.sum(jnp.square(logits.astype(jnp.float32))) * 1e-3

    grads = jax.jit(jax.grad(loss))(params)
    return variables, out, grads


@pytest.mark.parametrize('family', ['gpt2', 'llama'])
def test_tp_impl_overlap_matches_gspmd_model_level(family):
    """Same params, logits and grads either way: 'overlap' is purely an
    implementation knob for the TP FFN projections."""
    mesh = _model_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 16)), jnp.int32)

    def build(impl):
        if family == 'gpt2':
            model = GPT2(vocab_size=256, layers=2, dim=64, heads=4,
                         max_seq=128, dropout=0.0, dtype='float32',
                         mesh=mesh, tp_impl=impl, tp_chunks=2)
            return model, GPT2.partition_rules()
        model = llama_tiny(dtype='float32', mesh=mesh, tp_impl=impl,
                           tp_chunks=2)
        return model, type(model).partition_rules()

    v_ref, out_ref, grads_ref = _run_model(*build('gspmd'),
                                           tokens=tokens, mesh=mesh)
    v_ovl, out_ovl, grads_ovl = _run_model(*build('overlap'),
                                           tokens=tokens, mesh=mesh)
    # the knob never changes the checkpoint: identical trees, identical init
    assert (jax.tree_util.tree_structure(v_ref)
            == jax.tree_util.tree_structure(v_ovl))
    for ref, ovl in zip(jax.tree.leaves(v_ref), jax.tree.leaves(v_ovl)):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(ovl))
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_ovl),
                               rtol=2e-5, atol=2e-5)
    for ref, ovl in zip(jax.tree.leaves(grads_ref),
                        jax.tree.leaves(grads_ovl)):
        np.testing.assert_allclose(np.asarray(ref), np.asarray(ovl),
                                   rtol=2e-4, atol=2e-5)


def test_tp_impl_overlap_falls_back_on_non_tiling_sequence():
    """seq=15 cannot shard over the model axis -> the Dense/GSPMD path
    runs under the same params and the forward still matches."""
    mesh = _model_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, (4, 15)), jnp.int32)
    common = dict(vocab_size=256, layers=2, dim=64, heads=4, max_seq=128,
                  dropout=0.0, dtype='float32', mesh=mesh)
    reference = GPT2(**common, tp_impl='gspmd')
    model = GPT2(**common, tp_impl='overlap')
    variables = reference.init(jax.random.PRNGKey(0), tokens[:1, :8])
    out_ref = jax.jit(lambda v, t: reference.apply(v, t))(variables, tokens)
    out_ovl = jax.jit(lambda v, t: model.apply(v, t))(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_ovl),
                               rtol=1e-6, atol=1e-6)


def test_tp_impl_rejects_unknown_value():
    model = GPT2(vocab_size=64, layers=1, dim=32, heads=4, max_seq=32,
                 dropout=0.0, dtype='float32', tp_impl='magic')
    tokens = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match='tp_impl'):
        model.init(jax.random.PRNGKey(0), tokens)
