"""Continuous-batching serving engine: token-exact under churn.

The engine's contract (tpusystem/serve/): greedy outputs are exactly
standalone ``generate()``'s for every request REGARDLESS of co-batched
traffic — admissions, evictions and cancellations of neighbors must not
change a row's tokens — and batch membership changes never retrace the
one compiled decode step. Free-list exhaustion queues (never crashes),
prompt-length bucketing bounds the prefill program count, and the
request lifecycle narrates on the service bus.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.models import gpt2_tiny, llama_tiny
from tpusystem.serve import (Engine, InferenceService, PagedKVCache,
                             Request, Saturated, Scheduler, TRASH_BLOCK,
                             engine_unsupported_reason, prefill_bucket,
                             serve_levers)
from tpusystem.train import generate


def reference(module, params, prompt, steps, **kwargs):
    """Standalone greedy decode of one prompt — the parity oracle."""
    out = generate(module, params, jnp.asarray(prompt, jnp.int32)[None],
                   steps=steps, **kwargs)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


@pytest.fixture(scope='module')
def served():
    module = gpt2_tiny(dtype='float32')
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    return module, params


# ---------------------------------------------------------------------------
# paged pool: free-list + block tables (pure host bookkeeping)
# ---------------------------------------------------------------------------


class TestPagedKVCache:
    def test_free_list_allocates_and_frees(self):
        pool = PagedKVCache(rows=2, blocks=9, block_size=4, max_seq=32)
        assert pool.free_blocks == 8          # block 0 is reserved trash
        pool.admit(0, tokens=10)              # 3 blocks
        assert pool.free_blocks == 5
        assert (pool.table[0, :3] != TRASH_BLOCK).all()
        assert (pool.table[0, 3:] == TRASH_BLOCK).all()
        assert pool.evict(0) == 3
        assert pool.free_blocks == 8
        assert (pool.table[0] == TRASH_BLOCK).all()

    def test_slots_map_logical_positions_through_the_table(self):
        pool = PagedKVCache(rows=1, blocks=5, block_size=4, max_seq=16)
        pool.admit(0, tokens=6)               # blocks for positions 0..7
        slots = pool.slots(0)
        first, second = pool.table[0, 0], pool.table[0, 1]
        np.testing.assert_array_equal(slots[:4], first * 4 + np.arange(4))
        np.testing.assert_array_equal(slots[4:8], second * 4 + np.arange(4))
        # unmapped positions land in the trash block
        assert (slots[8:] < 4).all()

    def test_admission_beyond_free_blocks_raises_and_can_admit_gates(self):
        pool = PagedKVCache(rows=4, blocks=4, block_size=4, max_seq=32)
        assert pool.can_admit(12) and not pool.can_admit(13)
        pool.admit(0, tokens=12)              # all 3 allocatable blocks
        assert not pool.can_admit(1)
        with pytest.raises(ValueError, match='free'):
            pool.admit(1, tokens=4)
        with pytest.raises(ValueError, match='evict first'):
            pool.admit(0, tokens=4)

    def test_sequences_never_share_blocks(self):
        pool = PagedKVCache(rows=3, blocks=10, block_size=4, max_seq=32)
        for row in range(3):
            pool.admit(row, tokens=10)
        owned = pool.table[:, :3]
        assert len(set(owned.flatten().tolist())) == 9


# ---------------------------------------------------------------------------
# engine scope + capacity validation
# ---------------------------------------------------------------------------


def test_engine_gates_unsupported_modules(served):
    _, params = served
    assert engine_unsupported_reason(gpt2_tiny()) is None
    assert 'scan_layers' in engine_unsupported_reason(
        gpt2_tiny(scan_layers=True))
    # the MoE gate is LIFTED: decode dispatch runs full-capacity (no
    # token drops => per-token independence), so MoE modules serve
    assert engine_unsupported_reason(
        gpt2_tiny(moe_experts=2, moe_every=2)) is None
    with pytest.raises(ValueError, match='scan_layers'):
        Engine(gpt2_tiny(scan_layers=True), params)


def test_generate_strips_decode_pages_from_its_clone(served):
    """generate() on a module constructed with decode_pages set must
    decode through its own contiguous cache (the paged layout needs
    externally managed tables — only the engine provides them), token-
    exact with the plain module (found in review: an unstripped field
    silently aliased every row onto the trash block)."""
    module, params = served
    prompt = jnp.asarray(
        np.random.default_rng(37).integers(0, 256, (2, 6)), jnp.int32)
    plain = np.asarray(generate(module, params, prompt, steps=6))
    paged_field = np.asarray(generate(
        gpt2_tiny(dtype='float32', decode_pages=(16, 8)), params, prompt,
        steps=6))
    np.testing.assert_array_equal(paged_field, plain)


def test_engine_validates_capacity_and_saturation(served):
    module, params = served
    engine = Engine(module, params, rows=1, block_size=8)
    with pytest.raises(ValueError, match='max_seq'):
        engine.admit(np.arange(8), max_new=121)    # 8 + 121 > 128
    with pytest.raises(ValueError, match='max_new'):
        engine.admit(np.arange(8), max_new=0)
    engine.admit(np.arange(4) + 1, max_new=4)
    with pytest.raises(Saturated, match='free row'):
        engine.admit(np.arange(4) + 1, max_new=4)


def test_prefill_bucketing_is_bounded_powers_of_two():
    assert prefill_bucket(3, 16, 128) == 16       # floor at block_size
    assert prefill_bucket(17, 16, 128) == 32
    assert prefill_bucket(33, 16, 128) == 64
    assert prefill_bucket(100, 16, 128) == 128
    assert prefill_bucket(128, 16, 128) == 128    # capped at max_seq


def test_prefill_compile_count_is_bounded_by_buckets():
    """A stream of varied prompt lengths compiles one prefill program
    per BUCKET, not one per length (the round-5 retrace-trap
    discipline, applied to serving admission)."""
    from tpusystem.serve import engine as engine_module
    # a config no other test decodes, so the program-cache delta is ours
    module = gpt2_tiny(dtype='float32', max_seq=256)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))['params']
    engine = Engine(module, params, rows=1, block_size=16)
    before = engine_module._compiled_prefill.cache_info().currsize
    for length in (3, 5, 9, 14, 16, 17, 20, 30):   # buckets: 16, 32
        row = engine.admit(np.arange(length) % 250 + 1, max_new=1)
        assert row.finished                        # max_new=1: done at admit
    added = engine_module._compiled_prefill.cache_info().currsize - before
    assert added == 2, f'{added} prefill programs for 2 buckets'


# ---------------------------------------------------------------------------
# token-exact parity vs standalone generate(), under churn
# ---------------------------------------------------------------------------


def test_engine_single_request_matches_generate(served):
    module, params = served
    prompt = np.random.default_rng(3).integers(0, 256, (7,))
    expected = reference(module, params, prompt, 8)
    engine = Engine(module, params, rows=2, block_size=8)
    engine.admit(prompt, max_new=8)
    tokens = None
    while engine.active_rows:
        for _row, reason, out in engine.step().finished:
            tokens, why = out, reason
    assert tokens == expected and why == 'length'


@pytest.mark.slow
@pytest.mark.parametrize('family', [gpt2_tiny, llama_tiny])
def test_engine_parity_under_churn(family):
    """Admit at step k, evict at step m: every request's tokens equal
    its standalone generate() regardless of co-batched rows — the
    engine's core contract."""
    module = family(dtype='float32')
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 256, (n,)) for n in (5, 11, 8, 3)]
    steps = [14, 6, 10, 9]
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray(prompts[0][None]))['params']
    expected = [reference(module, params, p, s)
                for p, s in zip(prompts, steps)]

    engine = Engine(module, params, rows=2, block_size=8)
    scheduler = Scheduler(engine)
    # r0+r1 start together; r2 joins mid-stream (free-row churn: r1
    # finishes first, r2 takes its row); r3 joins after r0 retires
    scheduler.submit(Request('r0', list(prompts[0]), steps[0]))
    scheduler.submit(Request('r1', list(prompts[1]), steps[1]))
    for _ in range(4):
        scheduler.step()
    scheduler.submit(Request('r2', list(prompts[2]), steps[2]))
    for _ in range(6):
        scheduler.step()
    scheduler.submit(Request('r3', list(prompts[3]), steps[3]))
    results = scheduler.run()
    for index in range(4):
        got = results[f'r{index}']
        assert got.tokens == expected[index], f'r{index} diverged'
        assert got.reason == 'length'
    assert engine.trace_count == 1


def test_compile_guard_one_decode_trace_across_churn(served):
    """Admission/eviction NEVER retraces the decode step: one trace for
    the engine's whole life, across row churn and pool recycling."""
    module, params = served
    rng = np.random.default_rng(9)
    engine = Engine(module, params, rows=2, block_size=8)
    for wave in range(3):
        engine.admit(rng.integers(0, 256, (4 + wave,)), max_new=3)
        engine.admit(rng.integers(0, 256, (6,)), max_new=2 + wave)
        while engine.active_rows:
            engine.step()
    assert engine.trace_count == 1, (
        f'decode step retraced: {engine.trace_count} traces')


@pytest.mark.slow
def test_engine_int8_streaming_matches_generate_int8(served):
    """The PR-7 serving lever composes: an int8-streaming engine is
    token-exact against generate(stream_dtype='int8') — dequantization
    stays inside the one compiled step."""
    module, params = served
    prompt = np.random.default_rng(11).integers(0, 256, (9,))
    expected = reference(module, params, prompt, 10, stream_dtype='int8')
    engine = Engine(module, params, rows=2, block_size=8,
                    stream_dtype='int8')
    engine.admit(prompt, max_new=10)
    tokens = None
    while engine.active_rows:
        for _row, _reason, out in engine.step().finished:
            tokens = out
    assert tokens == expected


@pytest.mark.slow
def test_paged_read_crosses_block_bucket_boundary():
    """A generation whose filled depth crosses the paged read's
    power-of-2 block-window boundary stays token-exact (the switch picks
    a wider gather mid-stream — cached_attention's bucket test, paged
    flavored)."""
    module = gpt2_tiny(dtype='float32', max_seq=512)
    prompt = np.random.default_rng(29).integers(0, 256, (250,))
    params = module.init(jax.random.PRNGKey(1),
                         jnp.asarray(prompt[None, :8]))['params']
    expected = reference(module, params, prompt, 20)       # 250 -> 270
    engine = Engine(module, params, rows=2, block_size=16)
    engine.admit(prompt, max_new=20)
    tokens = None
    while engine.active_rows:
        for _row, _reason, out in engine.step().finished:
            tokens = out
    assert tokens == expected


# ---------------------------------------------------------------------------
# scheduler: exhaustion queues, budgets, cancellation
# ---------------------------------------------------------------------------


def test_free_list_exhaustion_queues_not_crashes(served):
    """More requests than the pool can seat: the overflow WAITS in the
    queue and drains in as rows/blocks free — never a crash, never a
    dropped request."""
    module, params = served
    rng = np.random.default_rng(13)
    # 8 allocatable blocks of 4 = 32 tokens; each request needs 3 blocks
    engine = Engine(module, params, rows=2, block_size=4, blocks=7)
    scheduler = Scheduler(engine)
    prompts = [rng.integers(0, 256, (4,)) for _ in range(5)]
    for index, prompt in enumerate(prompts):
        scheduler.submit(Request(f'r{index}', list(prompt), max_new=6))
    saw_backlog = False
    for _ in range(200):
        if scheduler.idle:
            break
        tick = scheduler.step()
        saw_backlog |= tick.queue_depth > 0
        assert tick.active <= 2
    assert scheduler.idle, 'queue never drained'
    assert saw_backlog, 'workload never actually queued — test has no teeth'
    for index, prompt in enumerate(prompts):
        assert scheduler.results[f'r{index}'].tokens == reference(
            module, params, prompt, 6), f'r{index} diverged under backlog'


def test_scheduler_refuses_never_fitting_requests(served):
    module, params = served
    engine = Engine(module, params, rows=2, block_size=8, blocks=4)
    scheduler = Scheduler(engine)
    with pytest.raises(ValueError, match='capacity'):
        scheduler.submit(Request('big', list(range(1, 100)), max_new=120))
    with pytest.raises(ValueError, match='blocks'):
        scheduler.submit(Request('wide', list(range(1, 30)), max_new=10))
    with pytest.raises(ValueError, match='non-empty'):
        scheduler.submit(Request('empty', [], max_new=4))


def test_prefill_budget_caps_admissions_per_step(served):
    """The prefill token budget separates phases: a step admits at most
    budget-worth of (bucket-padded) prompt tokens, so decode latency is
    bounded even under an admission burst — but one admission always
    proceeds, so an over-budget prompt cannot starve."""
    module, params = served
    rng = np.random.default_rng(17)
    engine = Engine(module, params, rows=4, block_size=16)
    scheduler = Scheduler(engine, prefill_budget=16)   # one 16-bucket/step
    for index in range(3):
        scheduler.submit(Request(f'r{index}',
                                 list(rng.integers(0, 256, (5,))),
                                 max_new=8))
    assert len(scheduler.step().admitted) == 1         # budget, not rows
    assert len(scheduler.step().admitted) == 1
    # a prompt wider than the whole budget still admits (alone)
    scheduler.submit(Request('wide', list(rng.integers(0, 256, (30,))),
                             max_new=4))
    admitted = {request.id
                for request, _, _ in scheduler.step().admitted}
    assert admitted == {'r2'}
    assert {request.id for request, _, _
            in scheduler.step().admitted} == {'wide'}
    scheduler.run()


def test_cancellation_mid_decode_frees_the_row_and_spares_neighbors(served):
    """Cancelling an active request evicts it mid-decode (partial tokens
    kept, reason 'cancelled'), frees its row for the queue, and leaves
    co-batched rows token-exact."""
    module, params = served
    rng = np.random.default_rng(19)
    keep_prompt = rng.integers(0, 256, (6,))
    expected = reference(module, params, keep_prompt, 12)
    engine = Engine(module, params, rows=2, block_size=8)
    scheduler = Scheduler(engine)
    scheduler.submit(Request('keep', list(keep_prompt), max_new=12))
    scheduler.submit(Request('dead', list(rng.integers(0, 256, (5,))),
                             max_new=12))
    scheduler.submit(Request('next', list(rng.integers(0, 256, (4,))),
                             max_new=3))                 # waits for a row
    scheduler.step()
    assert scheduler.queue_depth == 1
    scheduler.step()
    assert scheduler.cancel('dead') == 'active'
    cancelled = scheduler.results['dead']
    assert cancelled.reason == 'cancelled'
    assert 0 < len(cancelled.tokens) < 12
    results = scheduler.run()
    assert results['keep'].tokens == expected
    assert results['next'].reason == 'length'
    assert scheduler.cancel('keep') is None              # already done


def test_scheduler_tolerates_rows_admitted_directly_on_the_engine(served):
    """A row seated via engine.admit() (not through the scheduler)
    retires without a scheduler seat — the scheduler must skip it, not
    KeyError, and its own queued request must still drain in behind it
    (found by the verify drive)."""
    module, params = served
    rng = np.random.default_rng(41)
    engine = Engine(module, params, rows=1, block_size=8, blocks=5)
    engine.admit(rng.integers(0, 256, (5,)), max_new=6)   # foreign row
    scheduler = Scheduler(engine)
    scheduler.submit(Request('late', list(rng.integers(0, 256, (4,))),
                             max_new=5))
    results = scheduler.run()
    assert results['late'].reason == 'length'
    assert len(results['late'].tokens) == 5


def test_cancelling_a_queued_request_drops_it(served):
    module, params = served
    engine = Engine(module, params, rows=1, block_size=8)
    scheduler = Scheduler(engine)
    scheduler.submit(Request('q', [1, 2, 3], max_new=4))
    assert scheduler.cancel('q') == 'queued'
    assert scheduler.idle and 'q' not in scheduler.results


def test_stop_token_completes_early(served):
    module, params = served
    prompt = np.random.default_rng(23).integers(0, 256, (7,))
    expected = reference(module, params, prompt, 12)
    stop = expected[3]
    first_hit = expected.index(stop)                     # tokens repeat
    engine = Engine(module, params, rows=1, block_size=8)
    scheduler = Scheduler(engine)
    scheduler.submit(Request('s', list(prompt), max_new=12,
                             stop_token=stop))
    results = scheduler.run()
    assert results['s'].reason == 'stop'
    assert results['s'].tokens == expected[:first_hit + 1]  # stop included


# ---------------------------------------------------------------------------
# deadlines: saturation starvation becomes a typed expiry, never silence
# ---------------------------------------------------------------------------


def test_deadline_expires_queued_requests_instead_of_starving(served):
    """The fixed gap: under saturation a queued request could wait
    forever. With a deadline it expires — typed reason, empty tokens —
    and the seated neighbor is untouched (still token-exact)."""
    module, params = served
    rng = np.random.default_rng(21)
    engine = Engine(module, params, rows=1, block_size=8)
    scheduler = Scheduler(engine)
    prompt = list(rng.integers(0, 256, (4,)))
    scheduler.submit(Request('hog', prompt, max_new=12))
    scheduler.submit(Request('starved', prompt, max_new=4, deadline=0.05))
    tick = scheduler.step()                # hog seats; starved waits
    assert tick.queue_depth == 1 and not tick.expired
    time.sleep(0.08)
    tick = scheduler.step()
    assert [(completion.request.id, where)
            for completion, where in tick.expired] == [('starved', 'queued')]
    starved = scheduler.results['starved']
    assert starved.reason == 'expired' and starved.tokens == []
    assert starved.seconds >= 0.05
    scheduler.run()
    hog = scheduler.results['hog']
    assert hog.reason == 'length'
    assert hog.tokens == reference(module, params, prompt, 12)


def test_deadline_evicts_active_requests_mid_decode(served):
    """An ACTIVE request past its deadline is evicted mid-decode: partial
    tokens kept, row and blocks freed, neighbors token-exact."""
    module, params = served
    rng = np.random.default_rng(23)
    engine = Engine(module, params, rows=2, block_size=8)
    scheduler = Scheduler(engine)
    slow = list(rng.integers(0, 256, (5,)))
    quick = list(rng.integers(0, 256, (6,)))
    scheduler.submit(Request('slow', slow, max_new=50, deadline=0.05))
    scheduler.submit(Request('quick', quick, max_new=6))
    scheduler.step()                       # both seated, decoding
    time.sleep(0.08)
    tick = scheduler.step()
    assert [(completion.request.id, where)
            for completion, where in tick.expired] == [('slow', 'active')]
    expired = scheduler.results['slow']
    assert expired.reason == 'expired'
    assert 0 < len(expired.tokens) < 50    # partial output survives
    scheduler.run()
    assert scheduler.results['quick'].tokens == reference(
        module, params, quick, 6)


def test_deadline_validation(served):
    module, params = served
    engine = Engine(module, params, rows=1, block_size=8)
    scheduler = Scheduler(engine)
    with pytest.raises(ValueError, match='deadline'):
        scheduler.submit(Request('bad', [1, 2, 3], max_new=4, deadline=0.0))


def test_service_narrates_request_expired(served):
    from tpusystem.observe.events import RequestExpired
    from tpusystem.services.prodcon import Consumer, Producer

    module, params = served
    rng = np.random.default_rng(27)
    witnessed = []
    consumer = Consumer('probe')
    consumer.register(RequestExpired, witnessed.append)
    producer = Producer()
    producer.register(consumer)
    service = InferenceService(module, params, producer=producer, rows=1,
                               block_size=8)
    prompt = list(rng.integers(0, 256, (4,)))
    service.submit(Request('hog', prompt, max_new=8))
    service.submit(Request('starved', prompt, max_new=4, deadline=0.05))
    service.step()
    time.sleep(0.08)
    service.run_until_idle()
    assert len(witnessed) == 1
    event = witnessed[0]
    assert event.id == 'starved' and event.where == 'queued'
    assert event.produced == 0 and event.waited >= 0.05


# ---------------------------------------------------------------------------
# the bus front door
# ---------------------------------------------------------------------------


def test_service_narrates_the_request_lifecycle(served):
    from tpusystem.observe.events import (RequestAdmitted, RequestCompleted,
                                          RequestEvicted, ServeStepped)
    from tpusystem.services.prodcon import Consumer, Producer

    module, params = served
    rng = np.random.default_rng(29)
    witnessed = []
    consumer = Consumer('probe')

    @consumer.handler
    def on_serving(event: RequestAdmitted | RequestCompleted
                   | RequestEvicted | ServeStepped):
        witnessed.append(event)

    producer = Producer()
    producer.register(consumer)
    service = InferenceService(module, params, producer=producer, rows=2,
                               block_size=8)
    service.service.handle('submit',
                           Request('a', list(rng.integers(0, 256, (5,))),
                                   max_new=4))
    service.service.handle('submit',
                           Request('b', list(rng.integers(0, 256, (6,))),
                                   max_new=20))
    service.step()
    service.service.handle('cancel', 'b')
    service.run_until_idle()

    kinds = {type(event).__name__ for event in witnessed}
    assert kinds == {'RequestAdmitted', 'RequestCompleted',
                     'RequestEvicted', 'ServeStepped'}
    admitted = [e for e in witnessed if isinstance(e, RequestAdmitted)]
    assert {e.id for e in admitted} == {'a', 'b'}
    assert all(e.ttft >= 0 for e in admitted)
    evicted = [e for e in witnessed if isinstance(e, RequestEvicted)]
    assert evicted[0].id == 'b' and evicted[0].reason == 'cancelled'
    completed = [e for e in witnessed if isinstance(e, RequestCompleted)]
    assert completed[0].id == 'a' and completed[0].reason == 'length'
    stepped = [e for e in witnessed if isinstance(e, ServeStepped)]
    assert stepped[-1].queue_depth == 0 and stepped[-1].active == 0


def test_tensorboard_serve_handlers_chart_the_events(tmp_path):
    import pytest

    from tests.tb import read_scalars
    from tpusystem.observe.events import RequestAdmitted, ServeStepped
    from tpusystem.observe.tensorboard import (SummaryWriter,
                                               tensorboard_consumer, writer)

    consumer = tensorboard_consumer()
    board = SummaryWriter(tmp_path)
    consumer.dependency_overrides[writer] = lambda: board
    consumer.consume(RequestAdmitted(id='r', row=0, prompt_tokens=5,
                                     ttft=0.01, queue_depth=2))
    consumer.consume(ServeStepped(step=3, active=2, queue_depth=1,
                                  emitted=2, tokens_per_sec=123.4))
    board.flush()
    scalars = read_scalars(tmp_path)        # parsed back, not byte-poked
    value, step = scalars['serve/ttft_seconds']
    assert value == pytest.approx(0.01) and step == 1   # admission counter
    assert scalars['serve/queue_depth_at_admit'] == (2.0, 1)
    assert scalars['serve/queue_depth'] == (1.0, 3)
    assert scalars['serve/active_rows'] == (2.0, 3)
    value, step = scalars['serve/tok_s']
    assert value == pytest.approx(123.4) and step == 3


def test_serve_levers_pick_the_backend_default():
    levers = serve_levers()
    assert levers['stream_dtype'] == (
        'int8' if jax.default_backend() in ('tpu', 'axon') else 'auto')


# ---------------------------------------------------------------------------
# radix prefix sharing: refcounted blocks, token-exact adoption
# ---------------------------------------------------------------------------


class TestRadixPrefixSharing:
    def test_refcounted_free_list_survives_interleaved_churn(self):
        """Admit/retire with interleaved shared prefixes: blocks are
        shared only between rows whose prompts actually share the
        prefix, refcounts return to zero on retirement, and the pool's
        accounting matches a from-scratch audit."""
        pool = PagedKVCache(rows=4, blocks=32, block_size=4, max_seq=64,
                            share_prefix=True)
        head = list(range(1, 13))            # 3 full blocks
        other = list(range(100, 112))        # a DIFFERENT 3-block prefix
        pool.admit(0, tokens=14, prompt=head + [50, 51])
        pool.admit(1, tokens=14, prompt=head + [60, 61])
        pool.admit(2, tokens=14, prompt=other + [70, 71])
        # rows 0/1 share exactly the 3 head blocks; row 2 shares nothing
        assert pool.shared_tokens(0) == 0    # first arrival populated it
        assert pool.shared_tokens(1) == 12
        assert pool.shared_tokens(2) == 0
        np.testing.assert_array_equal(pool.table[0, :3], pool.table[1, :3])
        shared = set(pool.table[0, :3].tolist())
        assert not shared & set(pool.table[2, :4].tolist())
        # suffix blocks are PRIVATE even between the sharing rows
        assert pool.table[0, 3] != pool.table[1, 3]
        audit = pool.audit()
        # churn: retire the first owner — the adopter keeps the blocks
        pool.evict(0)
        assert pool.shared_tokens(1) == 12
        pool.admit(3, tokens=14, prompt=head + [80, 81])
        assert pool.shared_tokens(3) == 12
        np.testing.assert_array_equal(pool.table[1, :3], pool.table[3, :3])
        for row in (1, 2, 3):
            pool.evict(row)
        # refcounts all back to zero: nothing live, accounting exact
        audit = pool.audit()
        assert audit['live'] == 0
        assert audit['free'] + audit['cached'] == pool.blocks - 1
        assert pool.free_blocks == pool.blocks - 1

    def test_cached_blocks_are_reclaimed_lru_under_pressure(self):
        pool = PagedKVCache(rows=2, blocks=8, block_size=4, max_seq=64,
                            share_prefix=True)
        pool.admit(0, tokens=10, prompt=list(range(1, 11)))   # 3 blocks
        pool.evict(0)                        # 2 registered blocks go warm
        assert pool.audit()['cached'] == 2
        # a new admission needing every block reclaims the warm ones
        pool.admit(1, tokens=28, prompt=list(range(50, 78)))  # 7 blocks
        assert pool.audit()['cached'] == 0
        pool.evict(1)

    def test_engine_sharing_is_token_exact_and_counts_hits(self, served):
        """Co-batched requests sharing a system prompt adopt its blocks
        and stay token-exact vs standalone generate(); the retired
        prefix is re-adopted warm by a later wave."""
        module, params = served
        rng = np.random.default_rng(43)
        engine = Engine(module, params, rows=4, block_size=4, blocks=64,
                        share_prefix=True)
        scheduler = Scheduler(engine)
        head = [int(t) for t in rng.integers(0, 256, (21,))]
        prompts = [head + [int(t) for t in rng.integers(0, 256, (k,))]
                   for k in (3, 4, 5, 2)]
        for index, prompt in enumerate(prompts):
            scheduler.submit(Request(f'r{index}', prompt, max_new=5))
        results = scheduler.run()
        for index, prompt in enumerate(prompts):
            assert results[f'r{index}'].tokens == reference(
                module, params, prompt, 5), f'r{index} diverged'
        assert engine.sharing['prefix_hits'] == 3      # all but the first
        assert engine.prefix_hit_rate() > 0.5
        assert engine.trace_count == 1
        # second wave: the whole prefix is warm in the radix tree
        assert engine.prefix_cached_len(head + [9]) == 20   # (21-1)//4*4
        scheduler.submit(Request('warm', head + [9, 9], max_new=4))
        results = scheduler.run()
        assert results['warm'].tokens == reference(
            module, params, head + [9, 9], 4)

    def test_sharing_row_tokens_independent_of_cobatched_traffic(self, served):
        """The engine contract under sharing: a row's tokens equal its
        solo run even when neighbors share (or don't share) its
        prefix."""
        module, params = served
        rng = np.random.default_rng(47)
        prompt = [int(t) for t in rng.integers(0, 256, (13,))]
        solo_engine = Engine(module, params, rows=4, block_size=4,
                             share_prefix=True)
        solo_engine.admit(prompt, max_new=6)
        solo = None
        while solo_engine.active_rows:
            for _row, _reason, out in solo_engine.step().finished:
                solo = out
        engine = Engine(module, params, rows=4, block_size=4,
                        share_prefix=True)
        engine.admit(prompt[:9] + [3, 1, 4, 1], max_new=6)   # partial share
        admission = engine.admit(prompt, max_new=6)
        engine.admit([int(t) for t in rng.integers(0, 256, (7,))], max_new=6)
        tokens = {}
        while engine.active_rows:
            for row, _reason, out in engine.step().finished:
                tokens[row] = out
        assert tokens[admission.row] == solo


# ---------------------------------------------------------------------------
# fused decode_impl: the Pallas chain behind the paged step
# ---------------------------------------------------------------------------


class TestFusedDecodeImpl:
    def test_fused_step_is_token_exact_vs_flax(self, served):
        module, params = served
        rng = np.random.default_rng(53)
        prompts = [[int(t) for t in rng.integers(0, 256, (k,))]
                   for k in (7, 5)]
        engine = Engine(module, params, rows=2, block_size=8,
                        decode_impl='fused')
        for prompt in prompts:
            engine.admit(prompt, max_new=6)
        tokens = {}
        while engine.active_rows:
            for row, _reason, out in engine.step().finished:
                tokens[row] = out
        for row, prompt in enumerate(prompts):
            assert tokens[row] == reference(module, params, prompt, 6)
        assert engine.trace_count == 1

    @pytest.mark.slow
    def test_fused_step_composes_with_int8_streaming(self, served):
        module, params = served
        prompt = np.random.default_rng(59).integers(0, 256, (9,))
        expected = reference(module, params, prompt, 8, stream_dtype='int8')
        engine = Engine(module, params, rows=2, block_size=8,
                        decode_impl='fused', stream_dtype='int8')
        engine.admit(prompt, max_new=8)
        tokens = None
        while engine.active_rows:
            for _row, _reason, out in engine.step().finished:
                tokens = out
        assert tokens == expected

    def test_fused_refuses_unsupported_and_auto_falls_back(self, served):
        module, params = served
        probe = jnp.zeros((1, 8), jnp.int32)
        moe = gpt2_tiny(dtype='float32', moe_experts=2, moe_every=2)
        moe_params = moe.init(jax.random.PRNGKey(0), probe)['params']
        with pytest.raises(ValueError, match='fused'):
            Engine(moe, moe_params, rows=2, block_size=8,
                   decode_impl='fused')
        # 'auto' serves the same module through the flax step instead
        engine = Engine(moe, moe_params, rows=2, block_size=8,
                        decode_impl='auto')
        assert engine.decode_impl == 'flax'
        with pytest.raises(ValueError, match='decode_impl'):
            Engine(module, params, rows=2, block_size=8,
                   decode_impl='nonsense')


# ---------------------------------------------------------------------------
# the MoE gate, lifted: full-capacity decode dispatch
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_moe_engine_is_token_exact_under_cobatching():
    """Serving an MoE module: full-capacity decode dispatch drops no
    token, so each row's experts see it regardless of co-batched
    neighbors — token-exact vs standalone generate()."""
    module = gpt2_tiny(dtype='float32', moe_experts=2, moe_every=2)
    rng = np.random.default_rng(61)
    probe = jnp.asarray(rng.integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), probe)['params']
    engine = Engine(module, params, rows=3, block_size=4)
    prompts = [[int(t) for t in rng.integers(0, 256, (k,))]
               for k in (7, 5, 9)]
    admissions = [engine.admit(p, max_new=6) for p in prompts]
    tokens = {}
    while engine.active_rows:
        for row, _reason, out in engine.step().finished:
            tokens[row] = out
    for admission, prompt in zip(admissions, prompts):
        assert tokens[admission.row] == reference(module, params, prompt, 6)
    assert engine.trace_count == 1


# ---------------------------------------------------------------------------
# speculative rows: draft/verify riding the paged pool
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize('fanout', [1, 2])
def test_speculative_rows_match_target_greedy(served, fanout):
    """Draft rows ride the paged pool as extra batch rows; the output is
    exactly the target's greedy decode and the multi-token steps beat
    one-token-per-step (fewer engine steps than tokens)."""
    module, params = served
    rng = np.random.default_rng(67)
    engine = Engine(module, params, rows=4, block_size=4,
                    draft_module=module, draft_params=params,
                    speculate=3, tree_fanout=fanout)
    prompts = [[int(t) for t in rng.integers(0, 256, (k,))]
               for k in (7, 5)]
    admissions = [engine.admit(p, max_new=8) for p in prompts]
    tokens, steps = {}, 0
    while engine.active_rows:
        engine_report = engine.step()
        steps += 1
        for row, _reason, out in engine_report.finished:
            tokens[row] = out
    for admission, prompt in zip(admissions, prompts):
        assert tokens[admission.row] == reference(module, params, prompt, 8)
    # a self-draft accepts every token: 8 tokens in ceil(8/4) steps
    assert steps < 8
    assert engine.trace_count == 1
    assert engine.pool.free_blocks == engine.pool.blocks - 1


@pytest.mark.slow
def test_speculative_rows_compose_with_sharing_through_scheduler(served):
    module, params = served
    rng = np.random.default_rng(71)
    engine = Engine(module, params, rows=4, block_size=4,
                    share_prefix=True, draft_module=module,
                    draft_params=params, speculate=3, tree_fanout=2)
    scheduler = Scheduler(engine)
    head = [int(t) for t in rng.integers(0, 256, (13,))]
    prompts = [head + [int(t) for t in rng.integers(0, 256, (k,))]
               for k in (3, 2)]
    for index, prompt in enumerate(prompts):
        scheduler.submit(Request(f'r{index}', prompt, max_new=6))
    results = scheduler.run()
    for index, prompt in enumerate(prompts):
        assert results[f'r{index}'].tokens == reference(
            module, params, prompt, 6)
    assert engine.sharing['prefix_hits'] >= 1


def test_speculative_validates_budget_and_stop_token(served):
    module, params = served
    rng = np.random.default_rng(73)
    engine = Engine(module, params, rows=2, block_size=8,
                    draft_module=module, draft_params=params, speculate=3)
    with pytest.raises(ValueError, match='speculate'):
        # 8 + 117 + 3 + 1 > 128: the draft chain would overrun max_seq
        engine.admit(list(rng.integers(0, 256, (8,))), max_new=117)
    prompt = [int(t) for t in rng.integers(0, 256, (7,))]
    expected = reference(module, params, prompt, 12)
    stop = expected[3]
    scheduler = Scheduler(engine)
    scheduler.submit(Request('s', prompt, max_new=12, stop_token=stop))
    results = scheduler.run()
    assert results['s'].reason == 'stop'
    assert results['s'].tokens == expected[:expected.index(stop) + 1]


# ---------------------------------------------------------------------------
# scheduler: suffix-only prefill budgeting
# ---------------------------------------------------------------------------


def test_prefill_budget_counts_only_the_uncached_suffix(served):
    """With sharing on, a second wave of shared-prefix requests costs
    the budget only its uncached suffix buckets — so a budget that
    admits ONE cold request a step admits the whole warm wave at once.
    And a FULLY cached prompt still charges bucket(1): the one-
    admission rule can't spin on zero-cost admissions."""
    module, params = served
    rng = np.random.default_rng(79)
    engine = Engine(module, params, rows=4, block_size=16, blocks=64,
                    share_prefix=True)
    head = [int(t) for t in rng.integers(0, 256, (33,))]   # 2 full blocks
    scheduler = Scheduler(engine, prefill_budget=64)
    scheduler.submit(Request('cold', head + [1], max_new=3))
    scheduler.run()                       # radix tree now holds the head
    assert engine.prefix_cached_len(head + [2]) == 32
    # cold cost: bucket(34) = 64 — one per step under this budget.
    # warm cost: bucket(2) = 16 — four fit in one step's budget
    for index in range(4):
        scheduler.submit(Request(f'w{index}', head + [2 + index], max_new=3))
    tick = scheduler.step()
    assert len(tick.admitted) == 4, [r.id for r, _, _ in tick.admitted]
    assert engine.admit_cost(head + [2]) == 16
    assert engine.admit_cost(head) == 16  # fully cached still costs >0
    scheduler.run()
