"""Deterministic sampled, structured, and streaming decode
(tpusystem/serve/engine.py sampling + tpusystem/serve/service.py
streaming).

The contract under drill: seeded counter-based sampling makes sampled
decode exactly as reproducible as greedy — the token at stream position
``p`` is a pure function of ``(seed, p)`` and the logits, with no RNG
state beyond the emitted prefix — so every robustness move the serving
tier already owns (journal replay after SIGKILL, fleet reroute onto a
different engine, hedged duplicates) stays BITWISE-exact with sampling
on. Per-request SamplingParams ride the one compiled step as batched
device arrays (trace_count stays 1 across churn), grammar masks
constrain the same step, the one non-reproducible configuration
(unseeded sampling) is refused typed at every front door, and streaming
delivers each token the step it materializes — truthful about partial
output under cancel and deadline expiry.
"""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.checkpoint.memstore import MemStore
from tpusystem.models import gpt2_tiny
from tpusystem.parallel.chaos import PreemptionWave
from tpusystem.parallel.multihost import _blob_digest
from tpusystem.serve import (Engine, InferenceService, ReplicaHandle,
                             Request, RequestJournal, RoutePolicy, Router,
                             SamplingParams, Scheduler, ServingReplica,
                             UnseededSampling, replay)
from tpusystem.train import generate


class FakeClock:
    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


@pytest.fixture(scope='module')
def served():
    module = gpt2_tiny(dtype='float32')
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    return module, params


SAMPLED = SamplingParams(seed=11, temperature=0.9, top_k=16, top_p=0.95)


def greedy_reference(module, params, prompt, steps):
    out = generate(module, params, jnp.asarray(prompt, jnp.int32)[None],
                   steps=steps)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


def sampled_reference(module, params, prompt, steps, sampling,
                      **engine_knobs):
    """The sampled parity oracle: one request on a fresh engine,
    uninterrupted — what every drilled path must reproduce bitwise."""
    knobs = dict(rows=2, block_size=8)
    knobs.update(engine_knobs)
    scheduler = Scheduler(Engine(module, params, **knobs))
    scheduler.submit(Request('ref', list(prompt), steps, sampling=sampling))
    return scheduler.run()['ref'].tokens


# ---------------------------------------------------------------------------
# SamplingParams: validation and the typed refusals
# ---------------------------------------------------------------------------


def test_sampling_params_validation():
    assert not SamplingParams().sampled                   # default = greedy
    assert SamplingParams(seed=1, temperature=0.5).sampled
    with pytest.raises(ValueError, match='temperature'):
        SamplingParams(temperature=-0.1)
    with pytest.raises(ValueError, match='top_k'):
        SamplingParams(top_k=-1)
    with pytest.raises(ValueError, match='top_p'):
        SamplingParams(top_p=0.0)
    with pytest.raises(ValueError, match='top_p'):
        SamplingParams(top_p=1.5)


def test_unseeded_sampled_refused_typed_at_scheduler(served):
    """Sampled decode without a seed is the ONE non-reproducible
    configuration — refused typed at submit, before any device work, and
    the refusal leaves the engine perfectly serviceable."""
    module, params = served
    scheduler = Scheduler(Engine(module, params, rows=1, block_size=8))
    prompt = list(np.random.default_rng(2).integers(0, 256, (5,)))
    unseeded = SamplingParams(temperature=0.8)
    with pytest.raises(UnseededSampling, match='seed'):
        scheduler.submit(Request('bad', prompt, 4, sampling=unseeded))
    with pytest.raises(UnseededSampling):
        scheduler.engine.admit(prompt, 4, sampling=unseeded)
    assert isinstance(UnseededSampling('x'), ValueError)  # fleet contract
    scheduler.submit(Request('ok', prompt, 4))
    assert scheduler.run()['ok'].tokens == greedy_reference(
        module, params, prompt, 4)


def test_unseeded_sampled_refused_at_fleet_front_door():
    """The router refuses an unseeded sampled request BEFORE placement —
    no replica is ever touched (the stub would explode if one were)."""
    class _Stub:
        identity = 'stub'
        client = None
        fallbacks = ()
        scheduler = None

    router = Router([ReplicaHandle(_Stub())])
    with pytest.raises(UnseededSampling, match='seed'):
        router.submit(Request('bad', [1, 2], 4,
                              sampling=SamplingParams(temperature=1.0)))


# ---------------------------------------------------------------------------
# the compiled step: compile-once, determinism, greedy purity
# ---------------------------------------------------------------------------


def test_sampling_churn_never_retraces_and_greedy_stays_bitwise(served):
    """Per-request SamplingParams are batched device arrays, not trace
    constants: seed/temperature/top-k/top-p churn across admissions
    keeps trace_count == 1, and a greedy row co-batched with sampled
    neighbors emits EXACTLY its standalone greedy stream."""
    module, params = served
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(0, 256, (n,))) for n in (5, 7, 6)]
    greedy_ref = greedy_reference(module, params, prompts[0], 8)
    engine = Engine(module, params, rows=2, block_size=8)
    scheduler = Scheduler(engine)
    scheduler.submit(Request('greedy', prompts[0], 8))
    scheduler.submit(Request('s1', prompts[1], 8, sampling=SAMPLED))
    scheduler.submit(Request('s2', prompts[2], 8, sampling=SamplingParams(
        seed=3, temperature=1.3, top_k=0, top_p=0.8)))
    results = scheduler.run()
    assert results['greedy'].tokens == greedy_ref
    assert engine.trace_count == 1, (
        f'sampling churn retraced the decode step: {engine.trace_count}')


def test_same_seed_is_bitwise_reproducible_across_engines(served):
    """Two independent engines, same seed → the identical stream; a
    different seed diverges (sampling is real, not greedy in disguise)."""
    module, params = served
    prompt = list(np.random.default_rng(7).integers(0, 256, (6,)))
    first = sampled_reference(module, params, prompt, 10, SAMPLED)
    again = sampled_reference(module, params, prompt, 10, SAMPLED)
    other = sampled_reference(module, params, prompt, 10,
                              SamplingParams(seed=12, temperature=0.9,
                                             top_k=16, top_p=0.95))
    assert first == again
    assert first != other
    assert first != greedy_reference(module, params, prompt, 10)


def test_top_k_one_matches_greedy(served):
    module, params = served
    prompt = list(np.random.default_rng(9).integers(0, 256, (5,)))
    narrowed = sampled_reference(
        module, params, prompt, 6,
        SamplingParams(seed=4, temperature=2.0, top_k=1))
    assert narrowed == greedy_reference(module, params, prompt, 6)


# ---------------------------------------------------------------------------
# grammar masks: the structured-decode hook in the same compiled step
# ---------------------------------------------------------------------------


def test_grammar_mask_constrains_sampled_and_greedy_rows(served):
    module, params = served
    vocab = module.vocab_size
    even = np.zeros(vocab, bool)
    even[::2] = True

    def even_mask(emitted):
        return even

    prompt = list(np.random.default_rng(13).integers(0, 256, (5,)))
    engine = Engine(module, params, rows=2, block_size=8)
    scheduler = Scheduler(engine)
    scheduler.submit(Request('sg', prompt, 6, sampling=SamplingParams(
        seed=8, temperature=0.9, mask_fn=even_mask)))
    scheduler.submit(Request('gg', prompt, 6, sampling=SamplingParams(
        mask_fn=even_mask)))                     # greedy under the grammar
    results = scheduler.run()
    for rid in ('sg', 'gg'):
        assert all(t % 2 == 0 for t in results[rid].tokens), rid
    assert engine.trace_count == 1
    # the structured streams actually obeyed the mask (not vacuous)
    assert any(t % 2 for t in greedy_reference(module, params, prompt, 6))


def test_grammar_mask_dead_end_and_spec_composition_refused(served):
    module, params = served
    engine = Engine(module, params, rows=1, block_size=8)

    def dead_end(emitted):
        return np.zeros(module.vocab_size, bool)

    with pytest.raises(ValueError, match='mask'):
        engine.admit([1, 2, 3], 4,
                     sampling=SamplingParams(mask_fn=dead_end))
    spec = Engine(module, params, rows=2, block_size=8,
                  draft_module=module, draft_params=params, speculate=2)
    with pytest.raises(ValueError, match='speculative'):
        spec.admit([1, 2, 3], 4, sampling=SamplingParams(
            mask_fn=lambda emitted: np.ones(module.vocab_size, bool)))


# ---------------------------------------------------------------------------
# replay: SIGKILL mid-sample -> journal -> bitwise-equal completions
# ---------------------------------------------------------------------------


def test_kill_mid_sample_replay_is_bitwise(served):
    """THE acceptance drill, in-process form: a replica serving sampled
    + greedy traffic dies mid-stream (objects abandoned; only the
    replicated journal survives); the relaunch replays hot from the
    emitted prefix and every completion — sampled included — is
    BITWISE-equal to an uninterrupted reference, on ONE compiled trace."""
    module, params = served
    rng = np.random.default_rng(17)
    prompts = [list(rng.integers(0, 256, (n,))) for n in (6, 5)]
    specs = [('samp', prompts[0], 8, SAMPLED),
             ('greedy', prompts[1], 6, None)]

    def build():
        return Scheduler(Engine(module, params, rows=2, block_size=8))

    uninterrupted = build()
    for rid, prompt, budget, sampling in specs:
        uninterrupted.submit(Request(rid, prompt, budget, sampling=sampling))
    refs = {rid: c.tokens for rid, c in uninterrupted.run().items()}

    store = MemStore()
    replica = ServingReplica(build, identity='drill', client=store,
                             cadence=1)
    for rid, prompt, budget, sampling in specs:
        replica.submit(Request(rid, prompt, budget, sampling=sampling))
    for _ in range(3):
        replica.step()              # mid-sample: prefixes journaled out
    relaunched = ServingReplica(build, identity='drill', client=store,
                                cadence=1)
    assert relaunched.recovered
    assert 'samp' in relaunched.report.replayed        # hot, mid-stream
    results = relaunched.run_until_idle()
    for rid, _prompt, _budget, _sampling in specs:
        assert results[rid].tokens == refs[rid], f'{rid} diverged'
        assert results[rid].reason == 'length'
    assert relaunched.scheduler.engine.trace_count == 1


def test_pre_sampling_journal_blob_reads_as_greedy(served):
    """Wire compatibility regression: a journal packed BEFORE sampling
    existed (its pickled requests carry no ``sampling`` attribute at
    all) unpacks with ``sampling = None`` and replays token-exact as
    greedy — an upgrade mid-incident never crashes on the old format."""
    module, params = served
    prompt = list(np.random.default_rng(19).integers(0, 256, (5,)))
    ref = greedy_reference(module, params, prompt, 6)

    request = Request('old', prompt, 6)
    del request.__dict__['sampling']       # the pre-sampling pickle shape
    payload = pickle.dumps(
        (4, [(request, 2.5, list(ref[:2]))]),
        protocol=pickle.HIGHEST_PROTOCOL)
    blob = _blob_digest(payload).encode('ascii') + b':' + payload

    tick, rows = RequestJournal.unpack(blob)
    assert tick == 4
    restored = rows[0][0]
    assert 'sampling' in vars(restored) and restored.sampling is None

    scheduler = Scheduler(Engine(module, params, rows=1, block_size=8))
    report = replay(scheduler, rows)
    assert report.replayed == ['old']
    assert scheduler.run()['old'].tokens == ref


# ---------------------------------------------------------------------------
# the fleet: reroute and hedging stay bitwise with sampling on
# ---------------------------------------------------------------------------


def _fleet(module, params, clock, n=2):
    stores = [MemStore() for _ in range(n)]
    handles = []
    for i in range(n):
        def build():
            return Scheduler(Engine(module, params, rows=2, block_size=8),
                             clock=clock)
        handles.append(ReplicaHandle(ServingReplica(
            build, identity=f'rep{i}', client=stores[i], cadence=1,
            clock=clock)))
    return Router(handles, clock=clock), handles


@pytest.mark.slow
def test_fleet_reroute_mid_sample_is_bitwise(served):
    """The SIGKILL drill across the fleet: a replica dies mid-sample,
    the journal hands its rows to a DIFFERENT engine, and the sampled
    completions are bitwise-equal to an uninterrupted fleet — the
    counter needs nothing from the dead engine but the emitted prefix."""
    module, params = served
    rng = np.random.default_rng(23)
    specs = [('s0', list(rng.integers(0, 256, (6,))), 9, SAMPLED),
             ('s1', list(rng.integers(0, 256, (5,))), 8, SamplingParams(
                 seed=29, temperature=1.1, top_p=0.9)),
             ('g0', list(rng.integers(0, 256, (7,))), 8, None)]

    reference_router, _ = _fleet(module, params, FakeClock(), n=2)
    for rid, prompt, budget, sampling in specs:
        reference_router.submit(Request(rid, prompt, budget,
                                        sampling=sampling))
    reference = reference_router.run_until_idle()

    router, handles = _fleet(module, params, FakeClock(), n=2)
    for rid, prompt, budget, sampling in specs:
        router.submit(Request(rid, prompt, budget, sampling=sampling))
    wave = PreemptionWave(step=2, kills=(handles[0].kill,))
    moved = []
    for _ in range(200):
        if router.idle:
            break
        wave(router.ticks + 1)
        moved += [e for e in router.step().rerouted if e.cause == 'failover']
    assert router.idle and wave.fired and not handles[0].healthy
    assert any(e.where == 'hot' for e in moved)    # seated rows moved hot
    assert set(router.results) == set(reference)
    for rid, completion in router.results.items():
        assert completion.tokens == reference[rid].tokens, rid


@pytest.mark.slow
def test_hedged_sampled_duplicates_emit_identical_streams(served):
    """Hedging with sampling on: the duplicate leg runs the SAME seeded
    counter, so by the time first-completion-wins cancels the loser, the
    loser's partial stream is a bitwise prefix of the winner's — the
    race can never surface two different answers."""
    module, params = served
    prompt = list(np.random.default_rng(31).integers(0, 256, (5,)))
    ref = sampled_reference(module, params, prompt, 8, SAMPLED)
    clock = FakeClock()
    stores = [MemStore(), MemStore()]
    handles = []
    for i in range(2):
        def build():
            return Scheduler(Engine(module, params, rows=2, block_size=8),
                             clock=clock)
        handles.append(ReplicaHandle(ServingReplica(
            build, identity=f'rep{i}', client=stores[i], cadence=1,
            clock=clock)))
    router = Router(handles, clock=clock,
                    policy=RoutePolicy(hedge_after=5.0))
    origin = router.submit(Request('h', prompt, 8, sampling=SAMPLED))
    router.step()
    clock.advance(6.0)
    tick = router.step()               # the duplicate fires
    hedges = [e for e in tick.rerouted if e.cause == 'hedge']
    assert hedges and hedges[0].target != origin
    results = router.run_until_idle()
    assert results['h'].tokens == ref and results['h'].reason == 'length'
    loser_name = hedges[0].target
    loser = next(h for h in handles if h.name == loser_name)
    partial = loser.scheduler.results['h']
    assert partial.reason == 'cancelled'
    assert 0 < len(partial.tokens) < len(ref)
    assert partial.tokens == ref[:len(partial.tokens)]   # identical stream


# ---------------------------------------------------------------------------
# speculative rows and disaggregated prefill under sampling
# ---------------------------------------------------------------------------


def test_speculative_sampled_matches_plain_and_stops_in_window(served):
    """Draft/verify under sampling: greedy drafts are accepted only
    where they equal the seeded sampled targets, so the speculative
    stream is BITWISE the sequential sampled stream — including a stop
    token that lands mid-window (truncated at the stop, never past)."""
    module, params = served
    prompt = list(np.random.default_rng(37).integers(0, 256, (6,)))
    ref = sampled_reference(module, params, prompt, 10, SAMPLED)
    spec = Engine(module, params, rows=2, block_size=8,
                  draft_module=module, draft_params=params, speculate=3)
    scheduler = Scheduler(spec)
    scheduler.submit(Request('full', prompt, 10, sampling=SAMPLED))
    stop = ref[4]
    first_hit = ref.index(stop)
    scheduler.submit(Request('stopped', prompt, 10, stop_token=stop,
                             sampling=SAMPLED))
    results = scheduler.run()
    assert results['full'].tokens == ref
    assert results['full'].reason == 'length'
    assert results['stopped'].tokens == ref[:first_hit + 1]
    assert results['stopped'].reason == 'stop'


def test_disagg_sampled_first_token_is_role_invariant(served):
    """Disaggregated prefill under sampling: the prefill replica's
    exported first token samples at the SAME ``(seed, position)``
    counter the decode replica would use, so the handed-off stream is
    bitwise the colocated one."""
    module, params = served
    prompt = list(np.random.default_rng(41).integers(0, 256, (5,)))
    ref = sampled_reference(module, params, prompt, 7, SAMPLED)
    prefiller = Engine(module, params, rows=1, block_size=8)
    first, kv = prefiller.export_prefill(prompt, sampling=SAMPLED)
    assert first == ref[0]
    decoder = Engine(module, params, rows=1, block_size=8)
    decoder.admit_prefilled(prompt, 7, first, kv, sampling=SAMPLED)
    tokens = None
    while decoder.active_rows:
        for _row, reason, out in decoder.step().finished:
            tokens = out
    assert tokens == ref


# ---------------------------------------------------------------------------
# streaming: incremental delivery, truthful under cancel and expiry
# ---------------------------------------------------------------------------


def _witness(producer, *event_types):
    from tpusystem.services.prodcon import Consumer
    seen = []
    consumer = Consumer('probe')
    for event_type in event_types:
        consumer.register(event_type, seen.append)
    producer.register(consumer)
    return seen


def test_streaming_delivers_each_token_and_narrates(served):
    """``submit(..., on_token=)``: index 0 arrives at admission (its
    latency IS the charted TTFT), later tokens one step each; the full
    delivered stream equals the completion bitwise; every delivery is a
    TokenStreamed event and ServeStepped gauges the sampled rows."""
    from tpusystem.observe.events import ServeStepped, TokenStreamed
    from tpusystem.services.prodcon import Producer

    module, params = served
    producer = Producer()
    streamed = _witness(producer, TokenStreamed)
    stepped = _witness(producer, ServeStepped)
    service = InferenceService(module, params, producer=producer, rows=2,
                               block_size=8)
    prompt = list(np.random.default_rng(43).integers(0, 256, (5,)))
    delivered = []
    service.submit(Request('s', prompt, 6, sampling=SAMPLED),
                   on_token=lambda index, token: delivered.append(
                       (index, token)))
    service.submit(Request('quiet', prompt, 4))      # non-streaming
    results = service.run_until_idle()
    assert [i for i, _ in delivered] == list(range(6))
    assert [t for _, t in delivered] == results['s'].tokens
    assert [(e.index, e.token) for e in streamed] == delivered
    assert {e.id for e in streamed} == {'s'}         # quiet stays quiet
    assert max(e.sampled for e in stepped) == 1      # the sampled gauge
    assert stepped[-1].sampled == 0                  # drained


def test_cancel_mid_stream_keeps_delivered_tokens(served):
    module, params = served
    service = InferenceService(module, params, rows=1, block_size=8)
    prompt = list(np.random.default_rng(47).integers(0, 256, (5,)))
    delivered = []
    service.submit(Request('c', prompt, 20),
                   on_token=lambda index, token: delivered.append(token))
    for _ in range(3):
        service.step()
    frozen = list(delivered)
    assert 0 < len(frozen) < 20
    assert service.cancel('c') == 'active'
    for _ in range(3):
        service.step()
    assert delivered == frozen                  # stream went silent
    assert service.results['c'].tokens == frozen  # nothing un-delivered


def test_deadline_expiry_mid_stream_is_truthful_about_partials(served):
    """A streaming request whose deadline passes mid-decode keeps every
    token delivered before the expiry, and the ``expired`` verdict's
    ``produced`` equals exactly what the consumer saw — no more, no
    less."""
    from tpusystem.observe.events import RequestExpired
    from tpusystem.services.prodcon import Producer

    module, params = served
    clock = FakeClock()
    producer = Producer()
    expired = _witness(producer, RequestExpired)
    service = InferenceService(module, params, producer=producer, rows=1,
                               block_size=8, clock=clock)
    prompt = list(np.random.default_rng(53).integers(0, 256, (4,)))
    delivered = []
    service.submit(Request('d', prompt, 30, deadline=5.0,
                           sampling=SAMPLED),
                   on_token=lambda index, token: delivered.append(token))
    for _ in range(3):
        service.step()
    assert delivered
    clock.advance(10.0)
    service.step()
    assert expired and expired[0].id == 'd'
    assert expired[0].where == 'active'
    assert expired[0].produced == len(delivered)
    frozen = list(delivered)
    service.step()
    assert delivered == frozen
    assert service.results['d'].tokens == frozen
