"""Producer/Consumer contracts (reference parity: tests/test_prodcon.py:24-47)."""

import typing

from tpusystem.services import Consumer, Producer, event
from tpusystem.depends import Depends


@event
class ModelTrained:
    model: object
    metrics: list


@event
class ModelEvaluated:
    model: object
    metrics: list


@event
class Iterated:
    epoch: int


def test_union_annotation_registers_both_types():
    consumer = Consumer()
    seen = []

    @consumer.handler
    def on_iterated(event: ModelTrained | ModelEvaluated):
        seen.append(type(event).__name__)

    consumer.consume(ModelTrained('m', []))
    consumer.consume(ModelEvaluated('m', []))
    assert seen == ['ModelTrained', 'ModelEvaluated']
    assert set(consumer.handlers) == {'model-trained', 'model-evaluated'}


def test_typing_union_form_also_registers():
    consumer = Consumer()
    seen = []

    @consumer.handler
    def on_any(event: typing.Union[ModelTrained, Iterated]):
        seen.append(type(event).__name__)

    consumer.consume(Iterated(3))
    assert seen == ['Iterated']


def test_unknown_event_type_silently_ignored():
    consumer = Consumer()

    @consumer.handler
    def on_trained(event: ModelTrained):
        raise AssertionError('should not run')

    consumer.consume(Iterated(1))  # no handler -> ignored


def test_dependency_injection_into_handlers():
    consumer = Consumer()
    database = []

    def db():
        raise NotImplementedError

    @consumer.handler
    def persist(event: Iterated, db: list = Depends(db)):
        db.append(event.epoch)

    consumer.dependency_overrides[db] = lambda: database
    consumer.consume(Iterated(7))
    assert database == [7]


def test_producer_fans_out_to_all_consumers():
    first, second = Consumer(), Consumer()
    calls = []

    @first.handler
    def a(event: Iterated):
        calls.append(('first', event.epoch))

    @second.handler
    def b(event: Iterated):
        calls.append(('second', event.epoch))

    producer = Producer()
    producer.register(first, second)
    producer.dispatch(Iterated(1))
    assert calls == [('first', 1), ('second', 1)]


def test_multiple_handlers_per_event_type():
    consumer = Consumer()
    calls = []

    @consumer.handler
    def one(event: Iterated):
        calls.append(1)

    @consumer.handler
    def two(event: Iterated):
        calls.append(2)

    consumer.consume(Iterated(0))
    assert calls == [1, 2]


def test_kebab_name_generation():
    consumer = Consumer()
    assert consumer.generator('ModelTrained') == 'model-trained'
    assert consumer.generator('Trained') == 'trained'
