"""No single point of failure: crash-recoverable Router, warm-standby
takeover, and the randomized fleet chaos certification
(tpusystem/serve/{failover,fleet,service,certify}.py +
parallel/{chaos,recovery}.py).

Layers of drill, same two-tier discipline as test_serve_fleet:

* **Wire + policy** — RouterJournal framing (digest-verified, corrupt
  reads as absent, term-fenced pushes), RouterLease (acquire / renew /
  watch / fence, the echo discipline over the memstore plane — no new
  consensus), submit idempotency, FleetClient redial with capped
  seeded backoff. Fake replicas, fake clock, zero sleeps.
* **Kill-the-router** — the incumbent dies mid-stream with greedy,
  seeded-sampled and streamed rows in flight; a standby fences the
  term, replays the journal, and every accepted request either keeps
  streaming (reseated) or re-places — bitwise-token-exact against an
  undisturbed reference, nothing double-completed. Drilled on fakes
  AND on real engines.
* **Chaos certification** — :func:`~tpusystem.serve.certify_fleet`
  over fixed seeds: a uniformly-chosen component (router / standby /
  replica / supervisor plane) dies at a uniformly-chosen tick and the
  completion invariant holds; a red run replays from its seed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.test_serve_fleet import (FakeClock, expected_tokens, fake_fleet,
                                    scripted_token, witness)
from tests.test_supervisor import FakeWorker
from tests.test_supervisor import FakeClock as SupervisorClock
from tests.test_supervisor import policy_supervisor, scripted
from tpusystem.checkpoint.memstore import MemStore
from tpusystem.models import gpt2_tiny
from tpusystem.observe.events import (RequestRerouted, RouterDeposed,
                                      RouterTakeover)
from tpusystem.parallel.chaos import ChaosPick, pick_chaos
from tpusystem.parallel.recovery import (RESTART_EXITS, ROUTER_FENCED_EXIT,
                                         exit_for_restart)
from tpusystem.serve import (Engine, FleetClient, FleetHarness,
                             JournalCorrupt, ReplicaHandle, Request, Router,
                             RouterFenced, RouterJournal, RouterLease,
                             SamplingParams, Scheduler, ServingReplica,
                             certify_fleet, recover_router_journal,
                             router_identity)
from tpusystem.serve.certify import _stream_ok
from tpusystem.services.prodcon import Producer


# ---------------------------------------------------------------------------
# harness: journaled fake fleets and the standby takeover move
# ---------------------------------------------------------------------------


def journaled_fleet(clock, n=3, *, plane=None, producer=None, cadence=1,
                    **knobs):
    """A fake fleet whose router journals + holds the lease on ``plane``
    (the buddy-replicated memstore stand-in that outlives the router)."""
    plane = plane if plane is not None else MemStore()
    lease = RouterLease(client=plane, clock=clock)
    router, handles, stores = fake_fleet(
        clock, n=n,
        router_knobs=dict(journal=RouterJournal(client=plane,
                                                cadence=cadence),
                          lease=lease, producer=producer),
        **knobs)
    lease.acquire()
    return router, handles, plane


def standby_takeover(old_router, plane, clock, *, producer=None):
    """What a warm standby does the moment ``watch()`` trips: fence the
    term, rebuild from the journal + health sweep, serve. The replica
    handles are the SAME objects — replicas outlive their router."""
    lease = RouterLease(client=plane, clock=clock, holder='standby')
    standby = Router(old_router.handles, clock=clock, producer=producer,
                     journal=RouterJournal(client=plane), lease=lease)
    lease.acquire()
    report = standby.recover((plane,))
    return standby, report


def drain(router, max_steps=400):
    completions = []
    for _ in range(max_steps):
        if router.idle:
            return completions
        completions.extend(router.step().completed)
    raise AssertionError('fleet never drained')


# ---------------------------------------------------------------------------
# the router journal wire
# ---------------------------------------------------------------------------


class TestRouterJournal:

    def test_pack_unpack_roundtrip(self):
        journal = RouterJournal()
        journal.tick = 7
        state = {'brownout': True, 'results': {}, 'routes': [('r', 1.5)]}
        tick, restored = RouterJournal.unpack(journal.pack(state))
        assert tick == 7 and restored == state

    def test_corrupt_reads_as_absent_and_falls_through(self):
        """The failover discipline one tier up: a torn router journal
        must never restore — it reads as absent and recovery falls to
        the next client in the preference chain."""
        clock = FakeClock()
        good, torn = MemStore(), MemStore()
        journal = RouterJournal(client=good)
        journal.tick = 3
        assert journal.replicate({'routes': []})
        torn.put(router_identity(), 1, b'x:not a journal')
        with pytest.raises(JournalCorrupt):
            RouterJournal.unpack(b'x:not a journal')
        assert recover_router_journal('router', (torn,)) is None
        tick, state = recover_router_journal('router', (torn, good))
        assert tick == 3 and state == {'routes': []}
        # an unreachable plane likewise falls through, never raises
        class Dead:
            def fetch(self, identity):
                raise OSError('plane down')
        assert recover_router_journal('router', (Dead(), good)) is not None

    def test_cadence_gates_replication(self):
        plane = MemStore()
        journal = RouterJournal(client=plane, cadence=3)
        for _ in range(7):
            journal.observe_tick(lambda: {'routes': []})
        assert journal.tick == 7 and journal.pushes == 2   # ticks 3 and 6
        with pytest.raises(ValueError, match='cadence'):
            RouterJournal(cadence=0)

    def test_zombie_term_cannot_overwrite_the_incumbent(self):
        """The auto-fence: pushes encode ``term * 1M + tick`` as the
        memstore step, so a deposed router's journal — even at a much
        later tick — never replaces the new incumbent's state."""
        plane = MemStore()
        zombie = RouterJournal(client=plane)
        zombie.term, zombie.tick = 1, 500
        incumbent = RouterJournal(client=plane)
        incumbent.term, incumbent.tick = 2, 1
        assert incumbent.replicate({'holder': 'incumbent'})
        zombie.tick = 900
        zombie.replicate({'holder': 'zombie'})
        _tick, state = recover_router_journal('router', (plane,))
        assert state == {'holder': 'incumbent'}

    def test_push_failure_degrades_log_once(self, caplog):
        class Wedged:
            def push(self, identity, step, blob):
                raise OSError('plane down')
        journal = RouterJournal(client=Wedged())
        with caplog.at_level('WARNING'):
            for _ in range(4):
                journal.observe_tick(lambda: {})
        warnings = [record for record in caplog.records
                    if 'router journal' in record.message]
        assert len(warnings) == 1    # log-once, routing never interrupted


# ---------------------------------------------------------------------------
# the lease: acquire / renew / watch / fence
# ---------------------------------------------------------------------------


class TestRouterLease:

    def test_acquire_renew_and_watch_patience(self):
        clock = FakeClock()
        plane = MemStore()
        active = RouterLease(client=plane, clock=clock, renew_every=1.0)
        assert active.acquire() == 1
        standby = RouterLease(client=plane, clock=clock, holder='standby',
                              miss_after=3.0)
        assert standby.watch() is False      # first observation seeds it
        for _ in range(6):                   # renewals advancing = patience
            clock.advance(1.0)
            active.renew()
            assert standby.watch() is False
        clock.advance(3.0)                   # incumbent silent past the miss
        assert standby.watch() is True

    def test_renew_self_gates_to_renew_every(self):
        clock = FakeClock()
        plane = MemStore()
        lease = RouterLease(client=plane, clock=clock, renew_every=2.0)
        lease.acquire()
        before = lease.count
        lease.renew()                        # clock unchanged: gated
        assert lease.count == before
        clock.advance(2.0)
        lease.renew()
        assert lease.count == before + 1

    def test_renew_before_acquire_is_a_caller_error(self):
        lease = RouterLease(client=MemStore(), clock=FakeClock())
        with pytest.raises(ValueError, match='acquire'):
            lease.renew()

    def test_standby_fences_and_the_zombie_renewal_is_typed(self):
        """The split-brain guard: the standby publishes term + 1; the
        deposed incumbent's next renewal reads the higher term back
        (the elastic echo discipline) and raises RouterFenced."""
        clock = FakeClock()
        plane = MemStore()
        active = RouterLease(client=plane, clock=clock)
        active.acquire()
        standby = RouterLease(client=plane, clock=clock, holder='standby')
        assert standby.acquire() == 2
        clock.advance(1.5)
        with pytest.raises(RouterFenced) as caught:
            active.renew()
        assert caught.value.term == 1 and caught.value.observed == 2
        # ... and the zombie's renewal never landed in the store
        assert active.observe()[0] == 2

    def test_store_outage_is_not_a_router_death(self):
        """watch() must never fence on a plane hiccup — an unreachable
        store returns False (the incumbent may be perfectly healthy)."""
        clock = FakeClock()

        class Flaky:
            dead = False

            def put(self, identity, step, blob, **kw):
                return MemStore.put(self.store, identity, step, blob, **kw)

            def fetch(self, identity):
                if self.dead:
                    raise OSError('plane down')
                return self.store.fetch(identity)
        flaky = Flaky()
        flaky.store = MemStore()
        active = RouterLease(client=flaky, clock=clock)
        active.acquire()
        standby = RouterLease(client=flaky, clock=clock, holder='standby')
        standby.watch()
        flaky.dead = True
        clock.advance(100.0)
        assert standby.watch() is False

    def test_fenced_maps_to_exit_47_and_halts(self):
        """Satellite: the supervisor contract. RouterFenced carries exit
        47 through the generic ``exit_code`` rung; 47 is deliberately
        NOT restartable (the standby IS the restart) — a supervised
        zombie router halts instead of split-braining."""
        verdict = exit_for_restart(RouterFenced(1, 2))
        assert verdict.code == ROUTER_FENCED_EXIT == 47
        assert ROUTER_FENCED_EXIT not in RESTART_EXITS
        from tpusystem.parallel.supervisor import _CODE_NAMES
        assert _CODE_NAMES[ROUTER_FENCED_EXIT] == 'router-fenced'

    def test_supervised_fenced_router_halts_for_triage(self):
        from tpusystem.observe.events import WorkerExited
        from tpusystem.services.prodcon import Consumer, Producer
        clock = SupervisorClock()
        popen = scripted(FakeWorker(ROUTER_FENCED_EXIT))
        supervisor = policy_supervisor(popen, clock)
        producer, seen = Producer(), []
        consumer = Consumer()
        consumer.register(WorkerExited, seen.append)
        producer.register(consumer)
        supervisor.producer = producer
        assert supervisor.run() == ROUTER_FENCED_EXIT
        assert len(popen.launched) == 1      # never relaunched
        assert [event.action for event in seen] == ['halt']


# ---------------------------------------------------------------------------
# idempotent submission: the redial contract's other half
# ---------------------------------------------------------------------------


class TestSubmitIdempotency:

    def test_in_flight_resubmit_returns_placement_without_doubling(self):
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=2)
        placed = router.submit(Request('a', [1], 8))
        depth = handles[0].placements + handles[1].placements
        assert router.submit(Request('a', [1], 8)) == placed
        assert handles[0].placements + handles[1].placements == depth

    def test_settled_resubmit_returns_sentinel(self):
        clock = FakeClock()
        router, _, _ = fake_fleet(clock, n=1)
        router.submit(Request('a', [1], 3))
        drain(router)
        assert router.submit(Request('a', [1], 3)) == 'settled'
        assert router.results['a'].tokens == expected_tokens('a', 3)


# ---------------------------------------------------------------------------
# kill the router: journal rebuild, standby takeover (fakes)
# ---------------------------------------------------------------------------


class TestRouterTakeover:

    def test_kill_router_mid_stream_journal_rebuild_token_exact(self):
        """THE tentpole drill (fake tier): the incumbent dies with rows
        seated AND queued; the standby fences, replays the journal, and
        every request completes token-exact — reseated rows keep
        streaming from their position, queued rows re-place, nothing
        double-completes."""
        clock = FakeClock()
        producer = Producer()
        takeovers = witness(producer, RouterTakeover)
        reroutes = witness(producer, RequestRerouted)
        # 2 replicas x 2 rows: 4 seated, 4 queued at the kill
        router, handles, plane = journaled_fleet(clock, n=2,
                                                 producer=producer)
        for i in range(8):
            router.submit(Request(f'r{i}', [1 + i], 6))
        for _ in range(2):
            router.step()
        seated = {rid for handle in handles
                  for rid in handle.scheduler._seated}
        assert len(seated) == 4
        # the incumbent is never stepped again: the in-process crash
        standby, report = standby_takeover(router, plane, clock,
                                           producer=producer)
        assert report['source'] == 'journal'
        assert report['term'] == 2
        assert report['reseated'] >= 4       # the seated rows re-attach
        assert takeovers and takeovers[0].term == 2
        # reseated rows were NOT re-placed — they keep streaming
        assert not {event.id for event in reroutes} & seated
        first = standby.step()
        for rid in seated & set(first.emitted):
            position = len(handles[0].scheduler._seated.get(
                rid, handles[1].scheduler._seated.get(rid, [0, 0, []]))[2])
            assert first.emitted[rid] == scripted_token(rid, position - 1)
        completions = drain(standby)
        assert set(standby.results) == {f'r{i}' for i in range(8)}
        for i in range(8):
            assert standby.results[f'r{i}'].tokens \
                == expected_tokens(f'r{i}', 6), f'r{i}'
        # no duplicate completions across the whole incident
        assert sorted(completions) == sorted(set(completions))

    def test_settled_results_survive_and_never_double_complete(self):
        """The completion-edge idempotency table rides the journal: a
        request the old router settled stays settled — the standby
        answers 'settled' to a resubmit and never re-runs it."""
        clock = FakeClock()
        router, _, plane = journaled_fleet(clock, n=1)
        router.submit(Request('done', [1], 2))
        drain(router)
        router.submit(Request('live', [2], 8))
        router.step()
        standby, report = standby_takeover(router, plane, clock)
        assert report['settled'] >= 1
        assert standby.submit(Request('done', [1], 2)) == 'settled'
        assert standby.results['done'].tokens == expected_tokens('done', 2)
        completions = drain(standby)
        assert 'done' not in completions     # never re-ran
        assert standby.results['live'].tokens == expected_tokens('live', 8)

    def test_cold_sweep_rebuild_without_a_router_journal(self):
        """No router journal survives (cold rung): the health sweep
        alone rebuilds the tables from the replicas' own results dicts
        and request journals — slower to rebuild, still token-exact."""
        clock = FakeClock()
        router, handles, _ = fake_fleet(clock, n=2)
        for i in range(6):
            router.submit(Request(f'r{i}', [1 + i], 5))
        for _ in range(2):
            router.step()
        standby = Router(router.handles, clock=clock)
        report = standby.recover(())
        assert report['source'] == 'sweep'
        assert report['reseated'] >= 1
        drain(standby)
        assert set(standby.results) == {f'r{i}' for i in range(6)}
        for i in range(6):
            assert standby.results[f'r{i}'].tokens \
                == expected_tokens(f'r{i}', 5)

    def test_brownout_flag_rides_the_journal(self):
        clock = FakeClock()
        router, _, plane = journaled_fleet(clock, n=1)
        router.brownout = True
        router.step()
        standby, _ = standby_takeover(router, plane, clock)
        assert standby.brownout is True

    def test_zombie_router_step_raises_fenced_and_narrates(self):
        """A not-yet-dead incumbent that lost its lease must STOP at the
        top of its next tick — before placing anything — with the typed
        verdict narrated as RouterDeposed."""
        clock = FakeClock()
        producer = Producer()
        deposed = witness(producer, RouterDeposed)
        router, _, plane = journaled_fleet(clock, n=1, producer=producer)
        router.submit(Request('a', [1], 8))
        router.step()
        standby, _ = standby_takeover(router, plane, clock)
        clock.advance(1.5)                   # past the renew gate
        with pytest.raises(RouterFenced):
            router.step()
        assert deposed and deposed[0].term == 1 and deposed[0].observed == 2
        drain(standby)
        assert standby.results['a'].tokens == expected_tokens('a', 8)


# ---------------------------------------------------------------------------
# the client side: redial with capped seeded backoff, resubmit by id
# ---------------------------------------------------------------------------


class TestFleetClient:

    def test_redials_until_the_standby_answers(self):
        calls, sleeps = [0], []

        class Standby:
            @staticmethod
            def submit(request):
                return 'rep0'

        def resolve():
            calls[0] += 1
            if calls[0] <= 2:
                raise ConnectionError('router socket died')
            return Standby()
        client = FleetClient(resolve, sleep=sleeps.append, seed=3)
        assert client.submit(Request('a', [1], 4)) == 'rep0'
        assert client.redials == 2 and len(sleeps) == 2
        # capped exponential with bounded jitter, deterministic by seed
        import random
        rng = random.Random(3)
        for attempt, slept in enumerate(sleeps):
            base = min(2.0, 0.05 * 2 ** attempt)
            assert slept == base * (1.0 + 0.25 * rng.random())

    def test_zombie_fenced_router_is_a_redial_signal(self):
        class Zombie:
            @staticmethod
            def submit(request):
                raise RouterFenced(1, 2)

        class Standby:
            @staticmethod
            def submit(request):
                return 'rep1'
        answers = [Zombie(), Standby()]
        client = FleetClient(lambda: answers.pop(0), sleep=lambda s: None)
        assert client.submit(Request('a', [1], 4)) == 'rep1'
        assert client.redials == 1

    def test_exhausted_redials_raise_typed(self):
        def resolve():
            raise OSError('nobody home')
        client = FleetClient(resolve, max_redials=2, sleep=lambda s: None)
        with pytest.raises(ConnectionError, match='no standby took over'):
            client.submit(Request('a', [1], 4))
        assert client.redials == 2

    def test_validation(self):
        with pytest.raises(ValueError):
            FleetClient(lambda: None, max_redials=-1)
        with pytest.raises(ValueError):
            FleetClient(lambda: None, backoff_base=0.5, backoff_cap=0.1)

    def test_backoff_schedule_is_deterministic_by_seed(self):
        """The PR-19 capped-backoff path, pinned: the jitter schedule is
        a pure function of the seed — two clients with the same seed
        sleep the identical schedule, a different seed diverges, and a
        red redial storm replays exactly from its seed (the Faults /
        pick_chaos discipline applied to client backoff)."""
        def schedule(seed, failures=6):
            calls, sleeps = [0], []

            class Standby:
                @staticmethod
                def submit(request):
                    return 'rep0'

            def resolve():
                calls[0] += 1
                if calls[0] <= failures:
                    raise ConnectionError('router socket died')
                return Standby()
            client = FleetClient(resolve, sleep=sleeps.append, seed=seed)
            client.submit(Request('a', [1], 4))
            return sleeps

        first, again = schedule(seed=11), schedule(seed=11)
        assert first == again                 # same seed, same schedule
        assert len(first) == 6
        assert schedule(seed=12) != first     # the jitter is really there
        # every slept value obeys the cap and the bounded-jitter window
        for attempt, slept in enumerate(first):
            base = min(2.0, 0.05 * 2 ** attempt)
            assert base <= slept <= base * 1.25

    def test_end_to_end_resubmit_across_a_takeover(self):
        """The whole client contract in one move: submit, router dies,
        redial finds the standby, resubmit by id is idempotent, and
        the result reads from the journal-carried idempotency table."""
        clock = FakeClock()
        router, _, plane = journaled_fleet(clock, n=1)
        current = {'router': router, 'dead': False}

        def resolve():
            if current['dead']:
                raise ConnectionError('router gone')
            return current['router']
        client = FleetClient(resolve, sleep=lambda s: None)
        assert client.submit(Request('a', [1], 3)) == 'rep0'
        drain(router)
        current['dead'] = True               # the crash ...
        standby, _ = standby_takeover(router, plane, clock)

        def heal():
            current['dead'] = False
            current['router'] = standby
        healer = FleetClient(resolve, sleep=lambda s: heal())
        assert healer.submit(Request('a', [1], 3)) == 'settled'
        assert healer.result('a').tokens == expected_tokens('a', 3)


# ---------------------------------------------------------------------------
# the double-failure window: the standby dies before its takeover
# completes, the fenced incumbent is already gone
# ---------------------------------------------------------------------------


class TestDoubleFailureWindow:

    def test_standby_death_mid_takeover_leaves_journal_recoverable(self):
        """Standby #1 fences the term and dies BEFORE ``recover()``
        completes, with the fenced incumbent already gone — the worst
        moment. The journal (pushed by the incumbent, untouched by the
        half-takeover) must still recover a FRESH standby, which fences
        a higher term and drains every in-flight row token-exact."""
        clock = FakeClock()
        router, _, plane = journaled_fleet(clock, n=2)
        for request_id in ('a', 'b', 'c'):
            router.submit(Request(request_id, [1], 6))
        router.step()
        router.step()
        # the incumbent is gone (SIGKILL form: the object is abandoned,
        # its last journal push outlives it on the plane)
        half_lease = RouterLease(client=plane, clock=clock,
                                 holder='standby-1')
        half_lease.acquire()                 # the fence landed (term 2)...
        # ...and standby-1 died right here: no recover(), no serving.
        # A fresh standby must not be blocked by the orphaned fence:
        fresh_lease = RouterLease(client=plane, clock=clock,
                                  holder='standby-2')
        standby = Router(router.handles, clock=clock,
                         journal=RouterJournal(client=plane),
                         lease=fresh_lease)
        fresh_lease.acquire()
        assert fresh_lease.term > half_lease.term   # a THIRD term
        report = standby.recover((plane,))
        assert report['reseated'] + report['replaced'] >= 1
        drain(standby)
        for request_id in ('a', 'b', 'c'):
            assert (standby.results[request_id].tokens
                    == expected_tokens(request_id, 6))
        # and the orphan's late renewal is fenced out like any zombie's
        clock.advance(1.5)
        with pytest.raises(RouterFenced):
            half_lease.renew()

    def test_supervisor_narrates_the_standby_death_and_relaunches(self):
        """The supervised form of the same window: the standby process
        is killed mid-takeover (signal death — restartable by the exit
        table), the supervisor narrates the exit and relaunches, and
        the relaunched standby is exactly the 'fresh standby' of the
        drill above."""
        from tpusystem.observe.events import WorkerExited, WorkerRelaunched
        from tpusystem.services.prodcon import Consumer
        clock = SupervisorClock()
        popen = scripted(FakeWorker(-9), FakeWorker(0))
        supervisor = policy_supervisor(popen, clock)
        producer, seen = Producer(), []
        consumer = Consumer()
        consumer.register(WorkerExited, seen.append)
        consumer.register(WorkerRelaunched, seen.append)
        producer.register(consumer)
        supervisor.producer = producer
        assert supervisor.run() == 0
        assert len(popen.launched) == 2      # killed once, relaunched once
        actions = [event.action for event in seen
                   if isinstance(event, WorkerExited)]
        assert actions == ['relaunch', 'done']


# ---------------------------------------------------------------------------
# the chaos picker + certification over fixed seeds
# ---------------------------------------------------------------------------


def certifiable(clock_box=None):
    """A FleetHarness builder over the fake fleet with all five ISSUE
    components wired: router (standby takeover), standby (no-op death),
    a replica kill, and the supervisor plane (journal pushes wedge)."""
    def build():
        clock = FakeClock()
        if clock_box is not None:
            clock_box.append(clock)
        plane = MemStore()
        wedge = {'dead': False}

        class Plane:
            @staticmethod
            def put(identity, step, blob, **kw):
                if wedge['dead']:
                    raise OSError('supervisor plane down')
                return plane.put(identity, step, blob, **kw)

            @staticmethod
            def fetch(identity):
                if wedge['dead']:
                    raise OSError('supervisor plane down')
                return plane.fetch(identity)
        router, handles, _ = fake_fleet(clock, n=3, router_knobs=dict(
            journal=RouterJournal(client=Plane()),
            lease=RouterLease(client=Plane(), clock=clock)))
        router.lease.acquire()
        workload = [Request(f'r{i}', [1 + i], 4 + (i % 4))
                    for i in range(7)]

        def kill_router():
            standby, report = standby_takeover(router, plane, clock)
            return standby, report

        def kill_supervisor():
            wedge['dead'] = True             # journal degrades, serving on

        kills = {'router': kill_router,
                 'standby': lambda: None,
                 'prefill': handles[1].kill,
                 'decode': handles[2].kill,
                 'supervisor': kill_supervisor}
        return FleetHarness(router=router, workload=workload, kills=kills,
                            advance=lambda: clock.advance(0.1))
    return build


class TestChaosCertification:

    def test_pick_is_seeded_and_validated(self):
        components = ('router', 'standby', 'prefill', 'decode', 'supervisor')
        picks = {seed: pick_chaos(seed, components, lo=1, hi=8)
                 for seed in range(16)}
        assert all(picks[seed] == pick_chaos(seed, components, lo=1, hi=8)
                   for seed in picks)        # same seed, same scenario
        assert {pick.component for pick in picks.values()} == set(components)
        assert all(1 <= pick.step <= 8 for pick in picks.values())
        with pytest.raises(ValueError):
            pick_chaos(0, ())
        with pytest.raises(ValueError):
            pick_chaos(0, components, lo=5, hi=2)

    @pytest.mark.parametrize('seed', [0, 1, 2])
    def test_certify_fleet_fixed_seeds(self, seed):
        """The acceptance invariant, three fixed seeds in tier-1: every
        accepted request completes exactly or fails typed; no hung
        requests, no duplicate completions."""
        report = certify_fleet(certifiable(), seed=seed, lo=1, hi=6)
        assert report.ok, report.summary()
        assert report.accepted == 7
        assert report.completed + len(report.degraded) == 7

    def test_certify_covers_every_component(self):
        """Sweep seeds until each of the five components has been the
        victim at least once — the uniform pick genuinely reaches them
        all, and the invariant holds for each."""
        survived = set()
        for seed in range(24):
            if len(survived) == 5:
                break
            report = certify_fleet(certifiable(), seed=seed, lo=1, hi=6)
            assert report.ok, report.summary()
            survived.add(report.component)
        assert survived == {'router', 'standby', 'prefill', 'decode',
                            'supervisor'}

    def test_certify_validates_the_harness(self):
        with pytest.raises(ValueError, match='lo must be >= 1'):
            certify_fleet(certifiable(), seed=0, lo=0)
        with pytest.raises(ValueError, match='no kill thunk'):
            certify_fleet(certifiable(), seed=0,
                          components=('volcano',))

    def test_stream_subsequence_check(self):
        assert _stream_ok([2, 3, 5], [1, 2, 3, 4, 5])
        assert _stream_ok([], [1, 2])
        assert not _stream_ok([3, 2], [1, 2, 3])   # order violated
        assert not _stream_ok([9], [1, 2, 3])      # token never completed


# ---------------------------------------------------------------------------
# real engines: the kill-the-router acceptance drill
# ---------------------------------------------------------------------------


@pytest.fixture(scope='module')
def served():
    module = gpt2_tiny(dtype='float32')
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    return module, params


def real_journaled_fleet(module, params, clock, plane, n=3):
    stores = [MemStore() for _ in range(n)]
    handles = []
    for index in range(n):
        def build(index=index):
            return Scheduler(Engine(module, params, rows=2, block_size=8),
                             clock=clock)
        handles.append(ReplicaHandle(ServingReplica(
            build, identity=f'rep{index}', client=stores[index],
            clock=clock)))
    lease = RouterLease(client=plane, clock=clock)
    router = Router(handles, clock=clock,
                    journal=RouterJournal(client=plane), lease=lease)
    lease.acquire()
    return router, handles


def failover_workload(seed=11):
    """Greedy, seeded-sampled and streamed rows in one pot — the three
    decode configurations the takeover must carry, all reproducible."""
    rng = np.random.default_rng(seed)
    requests = []
    for index in range(6):
        prompt = rng.integers(0, 256, (5 + (index % 4),)).tolist()
        sampling = (SamplingParams(temperature=0.8, seed=100 + index,
                                   top_k=16)
                    if index % 2 else None)
        requests.append(Request(f'r{index}', prompt, 6 + (index % 3),
                                sampling=sampling))
    return requests


class TestKillTheRouterReal:

    def test_kill_router_mid_stream_token_exact(self, served):
        """The ISSUE acceptance drill on real engines: SIGKILL-analogue
        the active Router mid-stream with greedy + seeded-sampled rows
        in flight while streaming tokens; the standby takes over from
        the journal and every accepted request's final tokens are
        bitwise-identical to an undisturbed fleet — streamed
        transcripts consistent across the takeover, trace_count == 1
        on every engine (the takeover never bought a retrace)."""
        module, params = served
        clock = FakeClock()
        reference_router, _ = real_journaled_fleet(
            module, params, clock, MemStore(), n=3)
        for request in failover_workload():
            reference_router.submit(request)
        reference = reference_router.run_until_idle()

        plane = MemStore()
        router, handles = real_journaled_fleet(module, params, clock,
                                               plane, n=3)
        streamed: dict = {}

        def collect(tick):
            for rid, tokens in tick.emitted.items():
                streamed.setdefault(rid, []).extend(
                    int(token) for token in tokens)
        for request in failover_workload():
            router.submit(request)
        for _ in range(2):
            collect(router.step())           # rows seated, streaming
        producer = Producer()
        takeovers = witness(producer, RouterTakeover)
        standby, report = standby_takeover(router, plane, clock,
                                           producer=producer)
        assert report['source'] == 'journal' and takeovers
        # the deposed incumbent is typed-fenced, not silently wrong
        clock.advance(1.5)
        with pytest.raises(RouterFenced):
            router.step()
        completions = []
        for _ in range(400):
            if standby.idle:
                break
            tick = standby.step()
            collect(tick)
            completions.extend(tick.completed)
        assert standby.idle, 'takeover fleet never drained'
        assert set(standby.results) == set(reference)
        for rid, completion in standby.results.items():
            assert completion.tokens == reference[rid].tokens, rid
            assert completion.reason == reference[rid].reason, rid
            assert _stream_ok(streamed.get(rid, []),
                              list(completion.tokens)), rid
        assert sorted(completions) == sorted(set(completions))
        for handle in standby.handles:
            assert handle.scheduler.engine.trace_count == 1

    @pytest.mark.slow
    def test_certify_fleet_real_engines(self, served):
        """One seeded certification over real engines — the dryrun
        stage's tier-1 twin (more seeds run there)."""
        module, params = served

        def build():
            clock = FakeClock()
            plane = MemStore()
            router, handles = real_journaled_fleet(module, params, clock,
                                                   plane, n=3)
            kills = {
                'router': lambda: standby_takeover(router, plane, clock),
                'standby': lambda: None,
                'decode': handles[2].kill,
            }
            return FleetHarness(router=router,
                                workload=failover_workload(),
                                kills=kills,
                                advance=lambda: clock.advance(0.05))
        report = certify_fleet(build, seed=1, lo=1, hi=4)
        assert report.ok, report.summary()
