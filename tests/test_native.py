"""Native batch-assembly core: build, parity with numpy, graceful fallback.

The reference ships no native code at all (SURVEY.md §2.3); this is the
TPU build's host-side bandwidth component. The contract under test: the
native gather is bit-identical to numpy fancy indexing, and its absence
(or any unusual input) degrades to numpy silently.
"""

import numpy as np
import pytest

from tpusystem.data import ArrayDataset, native


@pytest.fixture(scope='module')
def lib():
    library = native.library()
    if library is None:
        pytest.skip('no C++ toolchain available')
    return library


def test_builds_and_reports_abi(lib):
    assert lib.ts_abi_version() == 2
    assert native.available()


@pytest.mark.parametrize('dtype', [np.float32, np.int32, np.uint8, np.float64])
@pytest.mark.parametrize('shape', [(), (17,), (28, 28), (3, 8, 8)])
def test_gather_matches_numpy(lib, dtype, shape):
    rng = np.random.default_rng(0)
    array = rng.integers(0, 200, size=(64,) + shape).astype(dtype)
    indices = rng.integers(0, 64, size=33)
    np.testing.assert_array_equal(native.gather(array, indices), array[indices])


def test_gather_into_preallocated_buffer(lib):
    array = np.arange(40, dtype=np.float32).reshape(10, 4)
    indices = np.array([9, 0, 3])
    out = np.empty((3, 4), np.float32)
    result = native.gather(array, indices, out=out)
    assert result is out
    np.testing.assert_array_equal(out, array[indices])


def test_gather_large_enough_to_go_multithreaded(lib):
    # > 1 MiB/worker threshold: exercises the threaded path
    rng = np.random.default_rng(1)
    array = rng.standard_normal((4096, 1024)).astype(np.float32)  # 16 MiB
    indices = rng.permutation(4096)
    np.testing.assert_array_equal(native.gather(array, indices), array[indices])


def test_negative_and_out_of_range_keep_numpy_semantics(lib):
    array = np.arange(12, dtype=np.int64).reshape(6, 2)
    np.testing.assert_array_equal(
        native.gather(array, np.array([-1, 0])), array[np.array([-1, 0])])
    with pytest.raises(IndexError):
        native.gather(array, np.array([6]))


def test_boolean_mask_keeps_numpy_selection_semantics(lib):
    array = np.arange(12, dtype=np.int64).reshape(6, 2)
    mask = np.array([False, True, False, True, False, False])
    np.testing.assert_array_equal(native.gather(array, mask), array[mask])


def test_float_indices_raise_like_numpy(lib):
    array = np.arange(12, dtype=np.int64).reshape(6, 2)
    with pytest.raises(IndexError):
        native.gather(array, np.array([1.0, 2.0]))


def test_mismatched_out_buffer_is_validated_not_corrupted(lib):
    array = np.arange(40, dtype=np.float32).reshape(10, 4)
    indices = np.array([1, 2, 3])
    wrong_dtype = np.empty((3, 4), np.float64)
    result = native.gather(array, indices, out=wrong_dtype)  # numpy copyto path
    np.testing.assert_array_equal(result, array[indices])
    with pytest.raises(ValueError):
        native.gather(array, indices, out=np.empty((2, 4), np.float32))


def test_overlapping_out_buffer_stays_correct(lib):
    """out aliasing the source must not be fed to the raw memcpy — numpy
    materializes array[indices] first, so [5, 0] into a[:2] is [a5, a0]."""
    array = np.arange(12, dtype=np.float32).reshape(6, 2)
    expected = array[np.array([5, 0])].copy()
    result = native.gather(array, np.array([5, 0]), out=array[:2])
    np.testing.assert_array_equal(result, expected)


def test_non_contiguous_falls_back(lib):
    array = np.arange(48, dtype=np.float32).reshape(12, 4)[:, ::2]
    assert not array.flags.c_contiguous
    indices = np.array([1, 5, 0])
    np.testing.assert_array_equal(native.gather(array, indices), array[indices])


def test_disabled_by_env_falls_back(monkeypatch):
    monkeypatch.setattr(native, '_lib', False)
    monkeypatch.setenv('TPUSYSTEM_NO_NATIVE', '1')
    assert not native.available()
    array = np.arange(10, dtype=np.float32).reshape(5, 2)
    np.testing.assert_array_equal(
        native.gather(array, np.array([4, 2])), array[[4, 2]])
    monkeypatch.setattr(native, '_lib', False)   # re-probe for other tests


def test_array_dataset_uses_native_path(lib):
    rng = np.random.default_rng(2)
    inputs = rng.standard_normal((50, 7)).astype(np.float32)
    targets = rng.integers(0, 10, size=50)
    dataset = ArrayDataset(inputs, targets)
    span = np.array([3, 1, 4, 1, 5])
    got_inputs, got_targets = dataset[span]
    np.testing.assert_array_equal(got_inputs, inputs[span])
    np.testing.assert_array_equal(got_targets, targets[span])


class TestMemmapTokens:
    @pytest.fixture()
    def corpus(self, tmp_path):
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 50000, size=1000, dtype=np.uint16)
        path = tmp_path / 'corpus.bin'
        tokens.tofile(path)
        return path, tokens

    def test_windows_and_dtype(self, corpus):
        from tpusystem.data import MemmapTokens
        path, tokens = corpus
        ds = MemmapTokens(path, sequence_length=128)
        assert len(ds) == (1000 - 129) // 128 + 1
        (window,) = ds[2]
        assert window.dtype == np.int32 and window.shape == (129,)
        np.testing.assert_array_equal(window, tokens[256:256 + 129])

    def test_batched_gather(self, corpus):
        from tpusystem.data import MemmapTokens
        path, tokens = corpus
        ds = MemmapTokens(path, sequence_length=64, stride=32)
        span = np.array([0, 3, 5])
        (batch,) = ds[span]
        assert batch.shape == (3, 65)
        np.testing.assert_array_equal(batch[1], tokens[96:96 + 65])

    def test_loader_integration(self, corpus):
        from tpusystem.data import Loader, MemmapTokens
        path, _ = corpus
        ds = MemmapTokens(path, sequence_length=64)
        loader = Loader(ds, batch_size=4, shuffle=True, seed=7)
        batches = list(loader)
        assert len(batches) == len(ds) // 4
        (first,) = batches[0]
        assert first.shape == (4, 65)

    def test_too_small_corpus_raises(self, tmp_path):
        from tpusystem.data import MemmapTokens
        path = tmp_path / 'tiny.bin'
        np.arange(10, dtype=np.uint16).tofile(path)
        with pytest.raises(ValueError):
            MemmapTokens(path, sequence_length=128)

    def test_registered_identity_excludes_nothing(self, corpus):
        from tpusystem.data import MemmapTokens
        from tpusystem.registry import getarguments
        path, _ = corpus
        ds = MemmapTokens(path, sequence_length=64)
        assert getarguments(ds)['sequence_length'] == 64


@pytest.mark.parametrize('dtype', [np.uint16, np.int32, np.float32])
def test_gather_windows_matches_numpy(lib, dtype):
    rng = np.random.default_rng(3)
    corpus = rng.integers(0, 500, size=4096).astype(dtype)
    starts = rng.integers(0, 4096 - 65, size=48)
    window = 65
    reference = corpus[starts[:, None] + np.arange(window)[None, :]]
    np.testing.assert_array_equal(
        native.gather_windows(corpus, starts, window), reference)


def test_gather_windows_overlapping_and_from_memmap(lib, tmp_path):
    corpus = np.arange(10_000, dtype=np.uint16)
    path = tmp_path / 'corpus.bin'
    corpus.tofile(path)
    mapped = np.memmap(path, dtype=np.uint16, mode='r')
    starts = np.arange(0, 9000, 7)          # overlapping windows
    window = 129
    reference = mapped[starts[:, None] + np.arange(window)[None, :]]
    np.testing.assert_array_equal(
        native.gather_windows(mapped, starts, window), reference)


def test_gather_windows_falls_back_out_of_range(lib):
    corpus = np.arange(100, dtype=np.int32)
    with pytest.raises(IndexError):
        native.gather_windows(corpus, np.array([90]), 20)  # numpy semantics


def test_memmap_tokens_batched_windows(tmp_path):
    from tpusystem.data import MemmapTokens
    corpus = np.arange(5000, dtype=np.uint16)
    path = tmp_path / 'tokens.bin'
    corpus.tofile(path)
    data = MemmapTokens(path, sequence_length=64)
    batch = data[np.asarray([0, 3, 7])][0]
    assert batch.shape == (3, 65) and batch.dtype == np.int32
    np.testing.assert_array_equal(batch[1], np.arange(3 * 64, 3 * 64 + 65))
