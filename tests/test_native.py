"""Native batch-assembly core: build, parity with numpy, graceful fallback.

The reference ships no native code at all (SURVEY.md §2.3); this is the
TPU build's host-side bandwidth component. The contract under test: the
native gather is bit-identical to numpy fancy indexing, and its absence
(or any unusual input) degrades to numpy silently.
"""

import numpy as np
import pytest

from tpusystem.data import ArrayDataset, native


@pytest.fixture(scope='module')
def lib():
    library = native.library()
    if library is None:
        pytest.skip('no C++ toolchain available')
    return library


def test_builds_and_reports_abi(lib):
    assert lib.ts_abi_version() == 1
    assert native.available()


@pytest.mark.parametrize('dtype', [np.float32, np.int32, np.uint8, np.float64])
@pytest.mark.parametrize('shape', [(), (17,), (28, 28), (3, 8, 8)])
def test_gather_matches_numpy(lib, dtype, shape):
    rng = np.random.default_rng(0)
    array = rng.integers(0, 200, size=(64,) + shape).astype(dtype)
    indices = rng.integers(0, 64, size=33)
    np.testing.assert_array_equal(native.gather(array, indices), array[indices])


def test_gather_into_preallocated_buffer(lib):
    array = np.arange(40, dtype=np.float32).reshape(10, 4)
    indices = np.array([9, 0, 3])
    out = np.empty((3, 4), np.float32)
    result = native.gather(array, indices, out=out)
    assert result is out
    np.testing.assert_array_equal(out, array[indices])


def test_gather_large_enough_to_go_multithreaded(lib):
    # > 1 MiB/worker threshold: exercises the threaded path
    rng = np.random.default_rng(1)
    array = rng.standard_normal((4096, 1024)).astype(np.float32)  # 16 MiB
    indices = rng.permutation(4096)
    np.testing.assert_array_equal(native.gather(array, indices), array[indices])


def test_negative_and_out_of_range_keep_numpy_semantics(lib):
    array = np.arange(12, dtype=np.int64).reshape(6, 2)
    np.testing.assert_array_equal(
        native.gather(array, np.array([-1, 0])), array[np.array([-1, 0])])
    with pytest.raises(IndexError):
        native.gather(array, np.array([6]))


def test_boolean_mask_keeps_numpy_selection_semantics(lib):
    array = np.arange(12, dtype=np.int64).reshape(6, 2)
    mask = np.array([False, True, False, True, False, False])
    np.testing.assert_array_equal(native.gather(array, mask), array[mask])


def test_float_indices_raise_like_numpy(lib):
    array = np.arange(12, dtype=np.int64).reshape(6, 2)
    with pytest.raises(IndexError):
        native.gather(array, np.array([1.0, 2.0]))


def test_mismatched_out_buffer_is_validated_not_corrupted(lib):
    array = np.arange(40, dtype=np.float32).reshape(10, 4)
    indices = np.array([1, 2, 3])
    wrong_dtype = np.empty((3, 4), np.float64)
    result = native.gather(array, indices, out=wrong_dtype)  # numpy copyto path
    np.testing.assert_array_equal(result, array[indices])
    with pytest.raises(ValueError):
        native.gather(array, indices, out=np.empty((2, 4), np.float32))


def test_non_contiguous_falls_back(lib):
    array = np.arange(48, dtype=np.float32).reshape(12, 4)[:, ::2]
    assert not array.flags.c_contiguous
    indices = np.array([1, 5, 0])
    np.testing.assert_array_equal(native.gather(array, indices), array[indices])


def test_disabled_by_env_falls_back(monkeypatch):
    monkeypatch.setattr(native, '_lib', False)
    monkeypatch.setenv('TPUSYSTEM_NO_NATIVE', '1')
    assert not native.available()
    array = np.arange(10, dtype=np.float32).reshape(5, 2)
    np.testing.assert_array_equal(
        native.gather(array, np.array([4, 2])), array[[4, 2]])
    monkeypatch.setattr(native, '_lib', False)   # re-probe for other tests


def test_array_dataset_uses_native_path(lib):
    rng = np.random.default_rng(2)
    inputs = rng.standard_normal((50, 7)).astype(np.float32)
    targets = rng.integers(0, 10, size=50)
    dataset = ArrayDataset(inputs, targets)
    span = np.array([3, 1, 4, 1, 5])
    got_inputs, got_targets = dataset[span]
    np.testing.assert_array_equal(got_inputs, inputs[span])
    np.testing.assert_array_equal(got_targets, targets[span])
