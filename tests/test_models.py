"""Model zoo tests (tiny configs, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.parallel import MeshSpec, TensorParallel, batch_sharding
from tpusystem.train import AdamW, NextTokenLoss, build_train_step, flax_apply, init_state


def test_gpt2_forward_shape_and_dtype():
    module = gpt2_tiny()
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)['params']
    logits = module.apply({'params': params}, tokens)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32  # loss-stable head


def test_gpt2_causality():
    """Changing a future token must not affect past logits."""
    module = gpt2_tiny()
    tokens = jnp.asarray(np.arange(16)[None, :] % 256, jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)['params']
    logits_a = module.apply({'params': params}, tokens)
    perturbed = tokens.at[0, 10].set(99)
    logits_b = module.apply({'params': params}, perturbed)
    np.testing.assert_allclose(np.asarray(logits_a[0, :10]),
                               np.asarray(logits_b[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(logits_a[0, 10:]),
                           np.asarray(logits_b[0, 10:]))


def test_gpt2_memorizes_one_batch():
    module = gpt2_tiny()
    optimizer = AdamW(lr=1e-3)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32)), jnp.int32)
    state = init_state(module, optimizer, tokens)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    first = None
    for _ in range(30):
        state, (_, loss) = step(state, tokens, tokens)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.2


def test_gpt2_tensor_parallel_shards_and_trains():
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()
    module = gpt2_tiny()
    optimizer = AdamW(lr=1e-3)
    policy = TensorParallel(module.partition_rules(), fsdp=True, fsdp_min_size=64)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (8, 32)), jnp.int32)
    state = init_state(module, optimizer, tokens[:1])
    state = policy.place(state, mesh)
    qkv = state.params['h_0']['attn']['qkv']['kernel']
    assert qkv.sharding.spec == P('fsdp', 'model')
    tokens = jax.device_put(tokens, batch_sharding(mesh))
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    state, (_, loss) = step(state, tokens, tokens)
    assert np.isfinite(float(loss))


def test_gpt2_gspmd_matches_single_device():
    """TP+FSDP sharded training reproduces single-device numerics."""
    def run(mesh, policy):
        module = gpt2_tiny()
        optimizer = AdamW(lr=1e-3)
        tokens = jnp.asarray(np.random.default_rng(1).integers(0, 256, (8, 32)), jnp.int32)
        state = init_state(module, optimizer, tokens[:1], rng=0)
        state = policy.place(state, mesh)
        tokens = jax.device_put(tokens, batch_sharding(mesh))
        step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
        losses = []
        for _ in range(3):
            state, (_, loss) = step(state, tokens, tokens)
            losses.append(float(loss))
        return losses

    from tpusystem.parallel import DataParallel, single_device_mesh
    single = run(single_device_mesh(), DataParallel())
    sharded = run(MeshSpec(data=2, fsdp=2, model=2).build(),
                  TensorParallel(gpt2_tiny().partition_rules(), fsdp=True, fsdp_min_size=64))
    np.testing.assert_allclose(single, sharded, rtol=2e-4)


def test_graft_entry_dryrun():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)
