"""Model zoo tests (tiny configs, CPU)."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.parallel import MeshSpec, TensorParallel, batch_sharding
from tpusystem.train import AdamW, NextTokenLoss, build_train_step, flax_apply, init_state


def test_gpt2_forward_shape_and_dtype():
    module = gpt2_tiny()
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)['params']
    logits = module.apply({'params': params}, tokens)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32  # loss-stable head


def test_gpt2_causality():
    """Changing a future token must not affect past logits."""
    module = gpt2_tiny()
    tokens = jnp.asarray(np.arange(16)[None, :] % 256, jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)['params']
    logits_a = module.apply({'params': params}, tokens)
    perturbed = tokens.at[0, 10].set(99)
    logits_b = module.apply({'params': params}, perturbed)
    np.testing.assert_allclose(np.asarray(logits_a[0, :10]),
                               np.asarray(logits_b[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(logits_a[0, 10:]),
                           np.asarray(logits_b[0, 10:]))


@pytest.mark.slow
def test_gpt2_memorizes_one_batch():
    module = gpt2_tiny()
    optimizer = AdamW(lr=1e-3)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32)), jnp.int32)
    state = init_state(module, optimizer, tokens)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    first = None
    for _ in range(30):
        state, (_, loss) = step(state, tokens, tokens)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.2


@pytest.mark.slow
def test_gpt2_tensor_parallel_shards_and_trains():
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()
    module = gpt2_tiny()
    optimizer = AdamW(lr=1e-3)
    policy = TensorParallel(module.partition_rules(), fsdp=True, fsdp_min_size=64)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (8, 32)), jnp.int32)
    state = init_state(module, optimizer, tokens[:1])
    state = policy.place(state, mesh)
    qkv = state.params['h_0']['attn']['qkv']['kernel']
    assert qkv.sharding.spec == P('fsdp', 'model')
    tokens = jax.device_put(tokens, batch_sharding(mesh))
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    state, (_, loss) = step(state, tokens, tokens)
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_gpt2_gspmd_matches_single_device():
    """TP+FSDP sharded training reproduces single-device numerics."""
    def run(mesh, policy):
        module = gpt2_tiny()
        optimizer = AdamW(lr=1e-3)
        tokens = jnp.asarray(np.random.default_rng(1).integers(0, 256, (8, 32)), jnp.int32)
        state = init_state(module, optimizer, tokens[:1], rng=0)
        state = policy.place(state, mesh)
        tokens = jax.device_put(tokens, batch_sharding(mesh))
        step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
        losses = []
        for _ in range(3):
            state, (_, loss) = step(state, tokens, tokens)
            losses.append(float(loss))
        return losses

    from tpusystem.parallel import DataParallel, single_device_mesh
    single = run(single_device_mesh(), DataParallel())
    sharded = run(MeshSpec(data=2, fsdp=2, model=2).build(),
                  TensorParallel(gpt2_tiny().partition_rules(), fsdp=True, fsdp_min_size=64))
    np.testing.assert_allclose(single, sharded, rtol=2e-4)


def _stack_block_params(params, prefix, layers, stacked_key):
    """Transplant unrolled per-layer params into the scanned layout."""
    per_layer = [params[f'{prefix}{i}'] for i in range(layers)]
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_layer)
    rest = {k: v for k, v in params.items() if not k.startswith(prefix)}
    rest[stacked_key] = stacked
    return rest


def test_gpt2_scan_layers_matches_unrolled():
    """scan_layers compiles one block body over stacked params — identical
    logits to the unrolled stack given transplanted weights."""
    unrolled = gpt2_tiny(layers=4, dtype='float32')
    scanned = gpt2_tiny(layers=4, scan_layers=True, dtype='float32')
    tokens = jnp.asarray(np.random.default_rng(5).integers(0, 256, (2, 32)),
                         jnp.int32)
    params = unrolled.init(jax.random.PRNGKey(0), tokens)['params']
    stacked = _stack_block_params(params, 'h_', 4, 'hs')
    # structural check against a fresh scanned init
    fresh = scanned.init(jax.random.PRNGKey(0), tokens)['params']
    assert jax.tree.structure(fresh) == jax.tree.structure(stacked)
    logits_u = unrolled.apply({'params': params}, tokens)
    logits_s = scanned.apply({'params': stacked}, tokens)
    np.testing.assert_allclose(np.asarray(logits_u), np.asarray(logits_s),
                               atol=2e-5)


def test_llama_scan_layers_matches_unrolled():
    from tpusystem.models import llama_tiny
    unrolled = llama_tiny(layers=4, dtype='float32')
    scanned = llama_tiny(layers=4, scan_layers=True, dtype='float32')
    tokens = jnp.asarray(np.random.default_rng(6).integers(0, 256, (2, 32)),
                         jnp.int32)
    params = unrolled.init(jax.random.PRNGKey(0), tokens)['params']
    stacked = _stack_block_params(params, 'layer_', 4, 'blocks')
    logits_u = unrolled.apply({'params': params}, tokens)
    logits_s = scanned.apply({'params': stacked}, tokens)
    np.testing.assert_allclose(np.asarray(logits_u), np.asarray(logits_s),
                               atol=2e-5)


@pytest.mark.slow
def test_gpt2_scan_layers_tensor_parallel_trains():
    """The stacked-stack partition rules ('hs/' with the leading layer dim)
    shard under TP+FSDP and the model trains to the same loss as the
    unrolled variant."""
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()
    tokens = jnp.asarray(np.random.default_rng(7).integers(0, 256, (8, 32)),
                         jnp.int32)

    def one_loss(scan):
        module = gpt2_tiny(layers=4, scan_layers=scan, dtype='float32')
        optimizer = AdamW(lr=1e-3)
        state = init_state(module, optimizer, tokens[:1], rng=0)
        if scan:
            # same weights as the unrolled run, transplanted
            reference = gpt2_tiny(layers=4, dtype='float32')
            ref_state = init_state(reference, optimizer, tokens[:1], rng=0)
            state = state.replace(params=_stack_block_params(
                ref_state.params, 'h_', 4, 'hs'))
        policy = TensorParallel(module.partition_rules(), fsdp=True,
                                fsdp_min_size=64)
        state = policy.place(state, mesh)
        if scan:
            spec = state.params['hs']['attn']['qkv']['kernel'].sharding.spec
            assert spec[-1] == 'model', spec
        placed = jax.device_put(tokens, batch_sharding(mesh))
        step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
        _, (_, loss) = step(state, placed, placed)
        return float(loss)

    np.testing.assert_allclose(one_loss(True), one_loss(False), rtol=2e-4)


def test_llama_forward_shape_and_dtype():
    from tpusystem.models import llama_tiny
    module = llama_tiny()
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)['params']
    logits = module.apply({'params': params}, tokens)
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32


def test_llama_causality():
    from tpusystem.models import llama_tiny
    module = llama_tiny()
    tokens = jnp.asarray(np.arange(16)[None, :] % 256, jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)['params']
    logits_a = module.apply({'params': params}, tokens)
    perturbed = tokens.at[0, 10].set(99)
    logits_b = module.apply({'params': params}, perturbed)
    np.testing.assert_allclose(np.asarray(logits_a[0, :10]),
                               np.asarray(logits_b[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(logits_a[0, 10:]),
                           np.asarray(logits_b[0, 10:]))


def test_rotary_properties():
    """RoPE preserves norms, and <rot(q,i), rot(k,j)> depends only on i-j."""
    from tpusystem.models.llama import apply_rotary, rotary_embedding
    rng = np.random.default_rng(0)
    head_dim = 16
    vectors = jnp.asarray(rng.normal(size=(1, 8, 2, head_dim)), jnp.float32)
    cos, sin = rotary_embedding(jnp.arange(8), head_dim)
    rotated = apply_rotary(vectors, cos, sin)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(rotated), axis=-1),
                               np.linalg.norm(np.asarray(vectors), axis=-1),
                               rtol=1e-5)
    # relative-position invariance: shift both positions by 3
    query = jnp.asarray(rng.normal(size=(head_dim,)), jnp.float32)
    key = jnp.asarray(rng.normal(size=(head_dim,)), jnp.float32)

    def score(q_pos, k_pos):
        cos, sin = rotary_embedding(jnp.arange(12), head_dim)
        rot = lambda vec, pos: apply_rotary(
            jnp.broadcast_to(vec, (1, 12, 1, head_dim)), cos, sin)[0, pos, 0]
        return float(jnp.dot(rot(query, q_pos), rot(key, k_pos)))

    assert abs(score(5, 2) - score(8, 5)) < 1e-4


def test_llama_gqa_matches_repeated_kv():
    """GQA through the xla kernel == manually repeating KV to full heads."""
    from tpusystem.ops.attention import dot_product_attention
    rng = np.random.default_rng(0)
    query = jnp.asarray(rng.normal(size=(2, 8, 4, 16)), jnp.float32)
    key = jnp.asarray(rng.normal(size=(2, 8, 2, 16)), jnp.float32)
    value = jnp.asarray(rng.normal(size=(2, 8, 2, 16)), jnp.float32)
    grouped = dot_product_attention(query, key, value, causal=True)
    full = dot_product_attention(query, jnp.repeat(key, 2, axis=2),
                                 jnp.repeat(value, 2, axis=2), causal=True)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(full), atol=1e-6)


@pytest.mark.slow
def test_llama_memorizes_one_batch():
    from tpusystem.models import llama_tiny
    module = llama_tiny(dtype='float32')
    optimizer = AdamW(lr=1e-3)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32)), jnp.int32)
    state = init_state(module, optimizer, tokens)
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    first = None
    for _ in range(30):
        state, (_, loss) = step(state, tokens, tokens)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.2


@pytest.mark.slow
def test_llama_tensor_parallel_shards_and_trains():
    from tpusystem.models import llama_tiny
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()
    module = llama_tiny()
    optimizer = AdamW(lr=1e-3)
    policy = TensorParallel(module.partition_rules(), fsdp=True, fsdp_min_size=64)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 256, (8, 32)), jnp.int32)
    state = init_state(module, optimizer, tokens[:1])
    state = policy.place(state, mesh)
    gate = state.params['layer_0']['gate']['kernel']
    assert gate.sharding.spec == P('fsdp', 'model')
    tokens = jax.device_put(tokens, batch_sharding(mesh))
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)
    state, (_, loss) = step(state, tokens, tokens)
    assert np.isfinite(float(loss))


def test_llama3_8b_preset_shape():
    from tpusystem.models import llama3_8b
    module = llama3_8b()
    assert (module.layers, module.dim, module.heads, module.kv_heads,
            module.ffn_dim, module.vocab_size) == (32, 4096, 32, 8, 14336, 128256)
    assert module.remat  # 8B needs rematerialization


@pytest.mark.slow
def test_resnet_forward_shape():
    from tpusystem.models import resnet_tiny
    module = resnet_tiny()
    images = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = module.init(jax.random.PRNGKey(0), images)['params']
    logits = module.apply({'params': params}, images)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_resnet50_parameter_count():
    """ResNet-50 shape sanity: ~25.6M params like the canonical model."""
    from tpusystem.models import resnet50
    module = resnet50()
    shapes = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 224, 224, 3), jnp.float32)))
    count = sum(int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(shapes))
    assert 25e6 < count < 26.5e6, count


@pytest.mark.slow
def test_resnet_learns_one_batch():
    from tpusystem.models import resnet_tiny
    from tpusystem.train import CrossEntropyLoss
    module = resnet_tiny()
    optimizer = AdamW(lr=3e-3)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(8, 16, 16, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
    state = init_state(module, optimizer, images[:1])
    step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer)
    first = None
    for _ in range(25):
        state, (_, loss) = step(state, images, labels)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.3


def test_resnet_data_parallel():
    from tpusystem.models import resnet_tiny
    from tpusystem.parallel import DataParallel
    from tpusystem.train import CrossEntropyLoss
    mesh = MeshSpec(data=8).build()
    module = resnet_tiny()
    optimizer = AdamW(lr=1e-3)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(16, 16, 16, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, (16,)), jnp.int32)
    state = init_state(module, optimizer, images[:1])
    state = DataParallel().place(state, mesh)
    images = jax.device_put(images, batch_sharding(mesh))
    labels = jax.device_put(labels, batch_sharding(mesh))
    step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer)
    state, (_, loss) = step(state, images, labels)
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_graft_entry_dryrun():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_gpt2_scanned_moe_matches_unrolled():
    """MoE-every-k stacks ride nn.scan over (dense*, moe) SPANS
    (BlockSpan): logits and router aux must match the unrolled
    heterogeneous stack given transplanted weights."""
    from tpusystem.models import GPT2
    cfg = dict(vocab_size=64, layers=4, dim=32, heads=4, max_seq=32,
               dropout=0.0, dtype='float32', moe_experts=4, moe_every=2)
    tokens = jnp.asarray(np.random.default_rng(9).integers(0, 64, (2, 16)),
                         jnp.int32)
    unrolled = GPT2(**cfg)
    scanned = GPT2(**cfg, scan_layers=True)
    params = unrolled.init(jax.random.PRNGKey(0), tokens)['params']
    # span i = {d_0: h_{2i} (dense), moe_1: h_{2i+1} (moe)}
    spans = [{'d_0': params['h_0'], 'moe_1': params['h_1']},
             {'d_0': params['h_2'], 'moe_1': params['h_3']}]
    stacked = {k: v for k, v in params.items() if not k.startswith('h_')}
    stacked['hs'] = jax.tree.map(lambda *leaves: jnp.stack(leaves), *spans)
    fresh = scanned.init(jax.random.PRNGKey(0), tokens)['params']
    assert jax.tree.structure(fresh) == jax.tree.structure(stacked)
    logits_u, aux_u = unrolled.apply({'params': params}, tokens)
    logits_s, aux_s = scanned.apply({'params': stacked}, tokens)
    np.testing.assert_allclose(np.asarray(logits_u), np.asarray(logits_s),
                               atol=2e-5)
    np.testing.assert_allclose(float(aux_u), float(aux_s), rtol=1e-5)


def test_gpt2_scan_layers_moe_needs_divisible_layers():
    from tpusystem.models import GPT2
    module = GPT2(vocab_size=64, layers=3, dim=32, heads=4, max_seq=32,
                  moe_experts=4, moe_every=2, scan_layers=True)
    with pytest.raises(ValueError, match='divisible by the span'):
        module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    bad_unit = GPT2(vocab_size=64, layers=4, dim=32, heads=4, max_seq=32,
                    moe_experts=4, moe_every=2, scan_layers=True,
                    scan_unit=3)
    with pytest.raises(ValueError, match='multiple of moe_every'):
        bad_unit.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


@pytest.mark.slow
def test_gpt2_scanned_moe_with_scan_unit_matches_unrolled():
    """scan_unit composes with MoE: one span of scan_unit=4 layers carries
    two (dense, moe) groups — parity with the unrolled stack."""
    from tpusystem.models import GPT2
    cfg = dict(vocab_size=64, layers=4, dim=32, heads=4, max_seq=32,
               dropout=0.0, dtype='float32', moe_experts=4, moe_every=2)
    tokens = jnp.asarray(np.random.default_rng(10).integers(0, 64, (2, 16)),
                         jnp.int32)
    unrolled = GPT2(**cfg)
    scanned = GPT2(**cfg, scan_layers=True, scan_unit=4)
    params = unrolled.init(jax.random.PRNGKey(1), tokens)['params']
    span = {'d_0': params['h_0'], 'moe_1': params['h_1'],
            'd_2': params['h_2'], 'moe_3': params['h_3']}
    stacked = {k: v for k, v in params.items() if not k.startswith('h_')}
    stacked['hs'] = jax.tree.map(lambda leaf: leaf[None], span)
    fresh = scanned.init(jax.random.PRNGKey(1), tokens)['params']
    assert jax.tree.structure(fresh) == jax.tree.structure(stacked)
    logits_u, aux_u = unrolled.apply({'params': params}, tokens)
    logits_s, aux_s = scanned.apply({'params': stacked}, tokens)
    np.testing.assert_allclose(np.asarray(logits_u), np.asarray(logits_s),
                               atol=2e-5)
    np.testing.assert_allclose(float(aux_u), float(aux_s), rtol=1e-5)


@pytest.mark.parametrize('family', ['gpt2', 'llama'])
def test_scan_unit_groups_match_unrolled(family):
    """scan_unit=2 groups two blocks per scan step (the workaround for the
    TPU backend's nested-loop compile cliff): logits must match the
    unrolled stack given transplanted weights (span i = layers 2i, 2i+1
    under d_0/d_1)."""
    from tpusystem.models import gpt2_tiny, llama_tiny
    if family == 'gpt2':
        unrolled = gpt2_tiny(layers=4, dtype='float32')
        scanned = gpt2_tiny(layers=4, scan_layers=True, scan_unit=2,
                            dtype='float32')
        prefix, stacked_key = 'h_', 'hs'
    else:
        unrolled = llama_tiny(layers=4, dtype='float32')
        scanned = llama_tiny(layers=4, scan_layers=True, scan_unit=2,
                             dtype='float32')
        prefix, stacked_key = 'layer_', 'blocks'
    tokens = jnp.asarray(np.random.default_rng(15).integers(0, 256, (2, 16)),
                         jnp.int32)
    params = unrolled.init(jax.random.PRNGKey(4), tokens)['params']
    spans = [{'d_0': params[f'{prefix}{2 * i}'],
              'd_1': params[f'{prefix}{2 * i + 1}']} for i in range(2)]
    stacked = {k: v for k, v in params.items() if not k.startswith(prefix)}
    stacked[stacked_key] = jax.tree.map(
        lambda *leaves: jnp.stack(leaves), *spans)
    fresh = scanned.init(jax.random.PRNGKey(4), tokens)['params']
    assert jax.tree.structure(fresh) == jax.tree.structure(stacked)
    logits_u = unrolled.apply({'params': params}, tokens)
    logits_s = scanned.apply({'params': stacked}, tokens)
    np.testing.assert_allclose(np.asarray(logits_u), np.asarray(logits_s),
                               atol=2e-5)
