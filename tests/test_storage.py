"""Storage subsystem: document store + port adapters.

Mirrors the reference's DAO tests (``examples/tinysys/tests/test_daos.py``):
CRUD of every adapter, the latest-hash upsert dedupe of ``Modules.put``
(``adapters/modules.py:33-41``) and the phase-keyed upsert of
``Iterations.put`` (``adapters/iterations.py:22-29``).
"""

import pytest

from tpusystem.storage import (
    DocumentExperiments, DocumentIterations, DocumentMetrics, DocumentModels,
    DocumentModules, DocumentStore, Experiment, Iteration, Metric, Model,
    Module,
)
from tpusystem.storage.documents import where


@pytest.fixture()
def store(tmp_path):
    return DocumentStore(tmp_path / 'db.json')


def test_documents_crud_and_persistence(tmp_path):
    store = DocumentStore(tmp_path / 'db.json')
    table = store.table('things')
    first = table.insert({'name': 'a', 'value': 1})
    table.insert({'name': 'b', 'value': 2})
    assert first == 1
    assert len(table) == 2
    assert table.get(where(name='a'))['value'] == 1
    table.update({'value': 10}, where(name='a'))
    assert table.get(where(name='a'))['value'] == 10
    table.remove(where(name='b'))
    assert len(table) == 1

    # reopen from disk: contents and id counters survive
    reopened = DocumentStore(tmp_path / 'db.json')
    assert reopened.table('things').get(where(name='a'))['value'] == 10
    assert reopened.table('things').insert({'name': 'c'}) == 3


def test_experiments_create_is_idempotent(store):
    experiments = DocumentExperiments(store)
    first = experiments.create(Experiment(name='mnist'))
    again = experiments.create(Experiment(name='mnist'))
    assert first == again
    assert [e.name for e in experiments.list()] == ['mnist']
    experiments.remove('mnist')
    assert experiments.get('mnist') is None


def test_models_crud(store):
    models = DocumentModels(store)
    models.create(Model(hash='abc', experiment='mnist', epoch=0))
    models.create(Model(hash='abc', experiment='mnist', epoch=0))  # no dup
    assert len(models.list('mnist')) == 1
    models.update(Model(hash='abc', experiment='mnist', epoch=5))
    assert models.read('abc', 'mnist').epoch == 5
    # same hash, different experiment = different row
    models.update(Model(hash='abc', experiment='other', epoch=1))
    assert models.read('abc', 'other').epoch == 1
    models.delete('abc', 'mnist')
    assert models.read('abc', 'mnist') is None


def test_modules_put_dedupes_by_latest_hash(store):
    modules = DocumentModules(store)
    modules.put(Module(model='m', kind='nn', hash='h1', name='MLP', epoch=0))
    modules.put(Module(model='m', kind='nn', hash='h1', name='MLP', epoch=3))
    rows = modules.list('m')
    assert len(rows) == 1 and rows[0].epoch == 3

    # hash changed (hyperparameters edited) -> new row records the change
    modules.put(Module(model='m', kind='nn', hash='h2', name='MLP', epoch=4))
    assert len(modules.list('m')) == 2
    # a different kind under the same model is independent
    modules.put(Module(model='m', kind='optimizer', hash='h1', name='Adam', epoch=4))
    assert len(modules.list('m')) == 3


def test_iterations_put_upserts_per_phase(store):
    iterations = DocumentIterations(store)
    iterations.put(Iteration(model='m', phase='train', hash='l1', name='Loader', epoch=0))
    iterations.put(Iteration(model='m', phase='train', hash='l1', name='Loader', epoch=2))
    iterations.put(Iteration(model='m', phase='evaluation', hash='l1', name='Loader', epoch=2))
    rows = iterations.list('m')
    assert len(rows) == 2
    train_rows = [r for r in rows if r.phase == 'train']
    assert train_rows[0].epoch == 2
    iterations.put(Iteration(model='m', phase='train', hash='l2', name='Loader', epoch=3))
    assert len(iterations.list('m')) == 3


def test_metrics_stream(store):
    metrics = DocumentMetrics(store)
    for epoch in range(3):
        metrics.add(Metric(model='m', name='loss', value=1.0 / (epoch + 1),
                           epoch=epoch, phase='train'))
    metrics.add(Metric(model='other', name='loss', value=9.9, epoch=0, phase='train'))
    series = metrics.list('m')
    assert [point.epoch for point in series] == [0, 1, 2]
    metrics.clear('m')
    assert metrics.list('m') == [] and len(metrics.list('other')) == 1
