"""Mesh + sharding-policy tests on 8 simulated devices — the multi-device
coverage the reference lacks entirely (SURVEY.md §4 implications)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpusystem.models import MLP
from tpusystem.parallel import (
    DATA, FSDP, MODEL, DataParallel, FullyShardedDataParallel, MeshSpec,
    ShardingPolicy, TensorParallel, batch_sharding, single_device_mesh,
)
from tpusystem.train import Adam, CrossEntropyLoss, build_train_step, flax_apply, init_state


def test_mesh_spec_wildcard_resolution():
    spec = MeshSpec(data=-1, model=2)
    sizes = spec.resolved_sizes(8)
    assert sizes['data'] == 4 and sizes['model'] == 2
    mesh = spec.build()
    assert mesh.shape['data'] == 4 and mesh.shape['model'] == 2
    assert mesh.shape['fsdp'] == 1


def test_mesh_spec_errors():
    with pytest.raises(ValueError, match='only one axis'):
        MeshSpec(data=-1, model=-1).resolved_sizes(8)
    with pytest.raises(ValueError, match='not divisible'):
        MeshSpec(data=-1, model=3).resolved_sizes(8)
    with pytest.raises(ValueError, match='wants'):
        MeshSpec(data=4).build()


def test_mesh_spec_identity_distinguishes_layouts():
    from tpusystem.registry import gethash
    assert gethash(MeshSpec(data=4, model=2)) != gethash(MeshSpec(data=2, model=4))


def test_single_device_mesh_works():
    mesh = single_device_mesh()
    assert mesh.devices.size == 1


def test_fsdp_policy_shards_largest_divisible_dim():
    mesh = MeshSpec(fsdp=-1).build()  # fsdp=8
    policy = FullyShardedDataParallel(min_size=16)
    params = {'dense': {'kernel': jnp.zeros((24, 64)), 'bias': jnp.zeros((64,))},
              'tiny': jnp.zeros((2, 2))}
    specs = policy.tree_specs(params, mesh)
    assert specs['dense']['kernel'] == P(None, 'fsdp')  # 64 > 24
    assert specs['dense']['bias'] == P('fsdp')
    assert specs['tiny'] == P()  # below min_size


def test_tensor_parallel_rules_with_fsdp_fallback():
    mesh = MeshSpec(fsdp=2, model=4).build()
    policy = TensorParallel(
        rules=[(r'attention/query/kernel$', P(None, 'model')),
               (r'mlp/out/kernel$', P('model', None))],
        fsdp=True, fsdp_min_size=16)
    params = {
        'attention': {'query': {'kernel': jnp.zeros((16, 32))}},
        'mlp': {'out': {'kernel': jnp.zeros((32, 16))}},
        'embed': {'kernel': jnp.zeros((64, 8))},
    }
    specs = policy.tree_specs(params, mesh)
    assert specs['attention']['query']['kernel'] == P('fsdp', 'model')
    assert specs['mlp']['out']['kernel'] == P('model', 'fsdp')
    assert specs['embed']['kernel'] == P('fsdp')


def test_rule_axis_dropped_when_not_divisible():
    mesh = MeshSpec(model=8).build()
    policy = ShardingPolicy(rules=[(r'kernel$', P(None, 'model'))])
    specs = policy.tree_specs({'kernel': jnp.zeros((4, 6))}, mesh)  # 6 % 8 != 0
    assert specs['kernel'] == P()


def test_optimizer_state_inherits_param_rules():
    """Adam mu/nu paths end with the parameter path, so TP rules cover them."""
    mesh = MeshSpec(data=-1, model=2).build()
    policy = TensorParallel(rules=[(r'Dense_\d+/kernel$', P(None, 'model'))])
    module = MLP(features=(32,), classes=8)
    optimizer = Adam()
    state = init_state(module, optimizer, jnp.zeros((4, 28, 28)))
    specs = policy.tree_specs(state, mesh)
    kernel_spec = specs.params['Dense_0']['kernel']
    # all Dense kernels match the rule
    assert kernel_spec == P(None, 'model')
    mu_specs = jax.tree.leaves(
        specs.opt_state, is_leaf=lambda leaf: isinstance(leaf, P))
    assert P(None, 'model') in mu_specs


@pytest.fixture(scope='module')
def digits_batch():
    rng = np.random.default_rng(0)
    inputs = rng.normal(size=(64, 28, 28)).astype(np.float32)
    targets = rng.integers(0, 10, size=(64,)).astype(np.int32)
    return jnp.asarray(inputs), jnp.asarray(targets)


def _train_losses(mesh, policy, batch, steps=4):
    module = MLP(features=(64,), classes=10, dropout=0.0)
    optimizer = Adam(lr=1e-2)
    state = init_state(module, optimizer, jnp.zeros((8, 28, 28)), rng=0)
    state = policy.place(state, mesh)
    inputs = jax.device_put(batch[0], batch_sharding(mesh))
    targets = jax.device_put(batch[1], batch_sharding(mesh))
    step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer)
    losses = []
    for _ in range(steps):
        state, (_, loss) = step(state, inputs, targets)
        losses.append(float(loss))
    return losses, state


@pytest.mark.slow
def test_dp_matches_single_device_numerics(digits_batch):
    single_losses, _ = _train_losses(single_device_mesh(), DataParallel(), digits_batch)
    mesh = MeshSpec(data=-1).build()
    dp_losses, state = _train_losses(mesh, DataParallel(), digits_batch)
    np.testing.assert_allclose(single_losses, dp_losses, rtol=2e-5)


def test_fsdp_matches_single_device_and_actually_shards(digits_batch):
    single_losses, _ = _train_losses(single_device_mesh(), DataParallel(), digits_batch)
    mesh = MeshSpec(fsdp=-1).build()
    fsdp_losses, state = _train_losses(mesh, FullyShardedDataParallel(min_size=64), digits_batch)
    np.testing.assert_allclose(single_losses, fsdp_losses, rtol=2e-5)
    kernel = state.params['Dense_0']['kernel']  # (784, 64) -> sharded on dim 0
    shard_shape = kernel.addressable_shards[0].data.shape
    assert shard_shape[0] == kernel.shape[0] // 8, shard_shape


def test_tp_matches_single_device_and_shards_kernels(digits_batch):
    single_losses, _ = _train_losses(single_device_mesh(), DataParallel(), digits_batch)
    mesh = MeshSpec(model=-1).build()
    policy = TensorParallel(rules=[
        (r'Dense_0/kernel$', P(None, 'model')),
        (r'Dense_1/kernel$', P('model', None)),
    ])
    tp_losses, state = _train_losses(mesh, policy, digits_batch)
    np.testing.assert_allclose(single_losses, tp_losses, rtol=2e-5)
    kernel = state.params['Dense_0']['kernel']
    assert kernel.addressable_shards[0].data.shape[1] == kernel.shape[1] // 8


def test_combined_dp_fsdp_tp_mesh(digits_batch):
    """2-axis data x 2 fsdp x 2 model — the full combined layout compiles
    and trains with identical numerics."""
    single_losses, _ = _train_losses(single_device_mesh(), DataParallel(), digits_batch)
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()
    policy = TensorParallel(
        rules=[(r'Dense_0/kernel$', P(None, 'model'))], fsdp=True, fsdp_min_size=64)
    combined_losses, _ = _train_losses(mesh, policy, digits_batch)
    np.testing.assert_allclose(single_losses, combined_losses, rtol=2e-5)


class TestCollectiveVocabulary:
    """The shard_map collective wrappers — the data-plane vocabulary every
    explicit kernel (ring attention, pipeline, MoE) builds on."""

    def _mapped(self, fn, n=4, out_spec=None):
        import jax
        from jax.sharding import PartitionSpec as P
        from tpusystem.parallel import MeshSpec
        from tpusystem.parallel.mesh import shard_map
        mesh = MeshSpec(data=n).build(jax.devices()[:n])
        return shard_map(fn, mesh=mesh, in_specs=P('data'),
                         out_specs=P('data') if out_spec is None else out_spec)

    def test_reductions_and_gather(self):
        import jax.numpy as jnp
        import numpy as np
        from tpusystem.parallel import (all_gather, all_reduce_mean,
                                        all_reduce_sum)
        values = jnp.arange(4.0)

        total = self._mapped(lambda x: all_reduce_sum(x, 'data'))(values)
        np.testing.assert_array_equal(np.asarray(total), [6.0] * 4)
        mean = self._mapped(lambda x: all_reduce_mean(x, 'data'))(values)
        np.testing.assert_array_equal(np.asarray(mean), [1.5] * 4)
        gathered = self._mapped(lambda x: all_gather(x, 'data'))(values)
        # every shard holds the full gathered array
        np.testing.assert_array_equal(np.asarray(gathered),
                                      list(range(4)) * 4)

    def test_reduce_scatter_and_ring_shift(self):
        import jax.numpy as jnp
        import numpy as np
        from tpusystem.parallel import reduce_scatter, ring_shift

        scattered = self._mapped(
            lambda x: reduce_scatter(x[0], 'data'))(jnp.ones((4, 4)))
        np.testing.assert_array_equal(np.asarray(scattered), [4.0] * 4)

        shifted = self._mapped(lambda x: ring_shift(x, 'data'))(jnp.arange(4.0))
        np.testing.assert_array_equal(np.asarray(shifted), [3, 0, 1, 2])
        back = self._mapped(
            lambda x: ring_shift(x, 'data', reverse=True))(jnp.arange(4.0))
        np.testing.assert_array_equal(np.asarray(back), [1, 2, 3, 0])

    def test_all_to_all_shard_transpose(self):
        import jax.numpy as jnp
        import numpy as np
        from tpusystem.parallel import all_to_all

        data = jnp.arange(8.0).reshape(2, 4)   # each shard [1, 4]
        swapped = self._mapped(
            lambda x: all_to_all(x, 'data', split_dimension=1,
                                 concat_dimension=0), n=2)(data)
        # shard 0 keeps its first half and receives shard 1's first half
        np.testing.assert_array_equal(
            np.asarray(swapped), [[0, 1], [4, 5], [2, 3], [6, 7]])
