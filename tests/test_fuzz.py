"""Property-based parity fuzzing (hypothesis).

Two components whose whole value is exact agreement with a reference
implementation get randomized coverage beyond the hand-picked cases:
the fused chunked LM loss vs the materialized-logits loss, and the native
batch gather vs numpy fancy indexing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# a collection ERROR on a box without hypothesis would mask the whole
# file; a clean skip keeps the rest of the tier honest
hypothesis = pytest.importorskip('hypothesis')
from hypothesis import given, settings, strategies as st  # noqa: E402

from tpusystem.data import native
from tpusystem.train import ChunkedNextTokenLoss, NextTokenLoss


@settings(max_examples=25, deadline=None)
@given(
    batch=st.integers(1, 3),
    seq=st.integers(2, 17),
    vocab=st.integers(3, 40),
    dim=st.integers(2, 24),
    chunks=st.integers(1, 7),
    tied=st.booleans(),
    z_loss=st.sampled_from([0.0, 1e-3]),
    mask_tail=st.integers(0, 3),
    seed=st.integers(0, 2**16),
)
@pytest.mark.slow
def test_chunked_loss_matches_dense_loss(batch, seq, vocab, dim, chunks,
                                         tied, z_loss, mask_tail, seed):
    rng = np.random.default_rng(seed)
    features = jnp.asarray(rng.normal(size=(batch, seq, dim)), jnp.float32)
    table_shape = (vocab, dim) if tied else (dim, vocab)
    table = jnp.asarray(rng.normal(size=table_shape), jnp.float32)
    tokens = rng.integers(0, vocab, size=(batch, seq))
    if mask_tail:
        tokens[:, -min(mask_tail, seq - 1):] = -1
    tokens = jnp.asarray(tokens, jnp.int32)

    contract = ((2,), (1,)) if tied else ((2,), (0,))
    logits = jax.lax.dot_general(features, table, (contract, ((), ())))
    dense = NextTokenLoss(z_loss=z_loss)(logits, tokens)
    chunked = ChunkedNextTokenLoss(chunks=chunks, z_loss=z_loss, tied=tied)(
        (features, table), tokens)
    np.testing.assert_allclose(float(dense), float(chunked),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.skipif(not native.available(), reason='no C++ toolchain')
@settings(max_examples=40, deadline=None)
@given(
    rows=st.integers(1, 50),
    trailing=st.sampled_from([(), (3,), (5, 2), (2, 3, 4)]),
    picks=st.integers(0, 80),
    dtype=st.sampled_from([np.float32, np.float64, np.int32, np.int16,
                           np.uint8, np.bool_]),
    threads=st.sampled_from([0, 1, 3]),
    seed=st.integers(0, 2**16),
)
def test_native_gather_matches_numpy(rows, trailing, picks, dtype, threads, seed):
    rng = np.random.default_rng(seed)
    array = rng.integers(0, 2, size=(rows,) + trailing).astype(dtype)
    indices = rng.integers(0, rows, size=picks)
    np.testing.assert_array_equal(
        native.gather(array, indices, threads=threads), array[indices])
