"""Aux subsystems: event ledger (race detection), profiling, typed config.

The reference lacks all three (SURVEY.md §5); these tests pin the mechanisms
the TPU build supplies instead.
"""

from __future__ import annotations

import pytest

from tpusystem import config
from tpusystem.observe import EventLedger, LedgerDivergence, StepTimer
from tpusystem.observe.events import StepTimed
from tpusystem.parallel.multihost import Loopback
from tpusystem.registry import Registry, gethash
from tpusystem.services.prodcon import Consumer, Producer, event


@event
class EpochDone:
    epoch: int
    loss: float


class TestEventLedger:
    def test_identical_streams_identical_digests(self):
        ledgers = [EventLedger(), EventLedger()]
        for ledger in ledgers:
            ledger.record(EpochDone(epoch=0, loss=0.5))
            ledger.record(EpochDone(epoch=1, loss=0.4))
        assert ledgers[0].digest == ledgers[1].digest
        assert ledgers[0].count == 2

    def test_order_divergence_changes_digest(self):
        forward, backward = EventLedger(), EventLedger()
        first, second = EpochDone(0, 0.5), EpochDone(1, 0.4)
        forward.record(first), forward.record(second)
        backward.record(second), backward.record(first)
        assert forward.digest != backward.digest

    def test_float_noise_is_ignored_unless_strict(self):
        lenient = [EventLedger(), EventLedger()]
        lenient[0].record(EpochDone(epoch=0, loss=0.5))
        lenient[1].record(EpochDone(epoch=0, loss=0.500001))
        assert lenient[0].digest == lenient[1].digest

        strict = [EventLedger(strict=True), EventLedger(strict=True)]
        strict[0].record(EpochDone(epoch=0, loss=0.5))
        strict[1].record(EpochDone(epoch=0, loss=0.75))
        assert strict[0].digest != strict[1].digest

    def test_tap_records_every_dispatch(self):
        producer = Producer()
        producer.register(Consumer())
        ledger = EventLedger().tap(producer)
        producer.dispatch(EpochDone(epoch=0, loss=0.1))
        producer.dispatch(EpochDone(epoch=1, loss=0.2))
        assert ledger.count == 2

    def test_verify_unanimous_on_loopback(self):
        ledger = EventLedger()
        ledger.record(EpochDone(epoch=0, loss=0.1))
        assert ledger.verify(Loopback()) == ledger.digest

    def test_verify_raises_on_divergence(self):
        class SplitBrain:
            rank = 0

            def gather(self, value):
                return [value, (1, 99, 'deadbeef' * 8)]

        ledger = EventLedger()
        ledger.record(EpochDone(epoch=0, loss=0.1))
        with pytest.raises(LedgerDivergence, match='diverged'):
            ledger.verify(SplitBrain())


class TestStepTimer:
    def test_emits_step_timed_event(self):
        producer = Producer()
        seen = []
        consumer = Consumer()
        consumer.register(StepTimed, seen.append)
        producer.register(consumer)

        timer = StepTimer(producer).start()
        timed = timer.stop(model=object(), phase='train', steps=100)
        assert seen == [timed]
        assert timed.steps == 100 and timed.seconds >= 0
        assert timed.steps_per_second > 0

    def test_stop_without_start_raises(self):
        with pytest.raises(RuntimeError):
            StepTimer().stop(model=None, phase='train', steps=1)


class TestConfig:
    def setup_method(self):
        self.registry = Registry()

        @self.registry.register
        class Tokenizer:
            def __init__(self, vocab: int = 256):
                self.vocab = vocab

        @self.registry.register
        class Normalizer:
            def __init__(self):
                pass

        @self.registry.register
        class Model:
            def __init__(self, dim: int, tokenizer=None, normalizer=None, tags=None):
                self.dim = dim
                self.tokenizer = tokenizer
                self.normalizer = normalizer
                self.tags = tags

        self.Tokenizer, self.Normalizer, self.Model = Tokenizer, Normalizer, Model

    def test_build_resolves_nested_specs(self):
        model = config.build({
            'name': 'Model',
            'arguments': {
                'dim': 64,
                'tokenizer': {'name': 'Tokenizer', 'arguments': {'vocab': 512}},
                'normalizer': 'Normalizer',  # collapsed argless form
                'tags': ['a', 'b'],
            },
        }, self.registry)
        assert isinstance(model, self.Model) and model.dim == 64
        assert isinstance(model.tokenizer, self.Tokenizer)
        assert model.tokenizer.vocab == 512
        assert isinstance(model.normalizer, self.Normalizer)
        assert model.tags == ['a', 'b']

    def test_unknown_type_fails_loudly(self):
        with pytest.raises(KeyError, match='Mystery'):
            config.build({'name': 'Mystery', 'arguments': {}}, self.registry)

    def test_snapshot_build_roundtrip_preserves_identity(self):
        model = self.Model(dim=32, tokenizer=self.Tokenizer(vocab=128))
        spec = config.snapshot(model)
        rebuilt = config.build(spec, self.registry)
        assert gethash(rebuilt) == gethash(model)
        assert rebuilt.tokenizer.vocab == 128

    def test_plain_strings_pass_through(self):
        model = config.build(
            {'name': 'Model', 'arguments': {'dim': 8, 'tags': 'not-a-type'}},
            self.registry)
        assert model.tags == 'not-a-type'

    def test_load_json_and_toml(self, tmp_path):
        json_path = tmp_path / 'model.json'
        json_path.write_text('{"name": "Model", "arguments": {"dim": 4}}')
        assert config.load(json_path)['arguments']['dim'] == 4

        toml_path = tmp_path / 'model.toml'
        toml_path.write_text('name = "Model"\n[arguments]\ndim = 4\n')
        spec = config.load(toml_path)
        assert config.build(spec, self.registry).dim == 4
