"""Multi-tenant gang orchestration: blast-radius isolation, journaled
capacity arbitration, cross-tenant chaos certification
(tpusystem/orchestrator/* + parallel/chaos.pick_tenant_chaos).

Same two-tier discipline as the serve drills:

* **Wire + policy** — scoped consumers and tenant buses on one shared
  Producer (the ``evaluation_consumer(subject=)`` guard generalized),
  the carve planner, JobSpec validation, the orchestrator journal's
  corrupt-reads-as-absent framing. Zero sleeps, zero processes.
* **Arbitration** — a burst shrinks the lowest-priority elastic tenant
  through its resize seam and the ebb pays the debt back; the decision
  is journaled two-phase, so an orchestrator SIGKILL mid-arbitration
  recovers and COMPLETES the in-flight resize instead of re-deciding.
* **Cross-tenant chaos** — :func:`certify_tenants` over fixed seeds:
  a seeded (tenant × component × kill-tick) draw dies and every
  non-victim tenant's outputs stay bitwise-identical to an undisturbed
  twin while the victim recovers or degrades typed.
"""

import pytest

from tests.test_serve_fleet import witness
from tpusystem.checkpoint.memstore import MemStore
from tpusystem.observe.events import (AnomalyDetected, CapacityArbitrated,
                                      JobAdmitted, JobHalted, JobPreempted,
                                      RequestAdmitted, RequestCompleted,
                                      Trained)
from tpusystem.orchestrator import (CapacityError, JobSpec, LeakAudit,
                                    NamespacedWriter, Orchestrator,
                                    OrchestratorJournal, Submesh, TenantBus,
                                    TenantHarness, carve, certify_tenants,
                                    orchestrator_identity,
                                    recover_orchestrator_journal, scoped,
                                    subject_of)
from tpusystem.parallel.chaos import pick_tenant_chaos
from tpusystem.parallel.recovery import (CRASH_LOOP_EXIT, DIVERGED_EXIT,
                                         LOST_WORKER_EXIT, RESIZED_EXIT)
from tpusystem.serve.failover import JournalCorrupt
from tpusystem.services.prodcon import Consumer, Producer


class FakeRunner:
    """The orchestrator's runner seam, scripted: ``code`` is what poll
    reports; every resize records the new device tuple."""

    def __init__(self, code=None):
        self.code = code
        self.resizes = []

    def poll(self):
        return self.code

    def resize(self, devices):
        self.resizes.append(tuple(devices))


class RecordingBoard:
    """SummaryWriter stand-in collecting (tag, value, step) rows."""

    def __init__(self):
        self.rows = []

    def add_scalar(self, tag, value, step):
        self.rows.append((tag, value, step))

    def add_scalars(self, tag, values, step):
        for name, value in values.items():
            self.rows.append((f'{tag}/{name}', value, step))

    def flush(self):
        pass

    close = flush


class Model:
    def __init__(self, identity, epoch=1):
        self.id = identity
        self.epoch = epoch


# ---------------------------------------------------------------------------
# namespaces: the blast-radius isolation wire
# ---------------------------------------------------------------------------


class TestNamespace:

    def test_subject_resolution_order(self):
        event = Trained(Model('m1'), {'loss': 0.5})
        assert subject_of(event) == 'm1'             # model.id convention
        event.tenant = 'job-a'
        assert subject_of(event) == 'job-a'          # the stamp wins
        bare = RequestCompleted(id='r', produced=3, reason='length',
                                seconds=0.1)
        assert subject_of(bare) is None              # unattributed

    def test_scoped_consumer_drops_foreign_and_unattributed(self):
        seen = []
        inner = Consumer('probe')
        inner.register(RequestCompleted, seen.append)
        consumer = scoped(inner, 'job-a')
        mine = RequestCompleted(id='a', produced=1, reason='length',
                                seconds=0.1)
        mine.tenant = 'job-a'
        theirs = RequestCompleted(id='b', produced=1, reason='length',
                                  seconds=0.1)
        theirs.tenant = 'job-b'
        bare = RequestCompleted(id='c', produced=1, reason='length',
                                seconds=0.1)
        for event in (mine, theirs, bare):
            consumer.consume(event)
        assert [event.id for event in seen] == ['a']

    def test_tenant_bus_stamps_and_isolates_two_jobs(self):
        """Two jobs on ONE shared Producer: each bus stamps its tenant
        at dispatch and scopes its consumers, so neither job's events
        ever fire the other's handlers — while an unscoped tap on the
        shared producer still witnesses the whole stream."""
        producer = Producer()
        tap = witness(producer, RequestCompleted)
        buses = {name: TenantBus(producer, name) for name in ('a', 'b')}
        seen = {name: [] for name in ('a', 'b')}
        for name, bus in buses.items():
            consumer = Consumer(f'job-{name}')
            consumer.register(RequestCompleted, seen[name].append)
            bus.register(consumer)
        buses['a'].dispatch(RequestCompleted(id='a1', produced=1,
                                             reason='length', seconds=0.1))
        buses['b'].dispatch(RequestCompleted(id='b1', produced=1,
                                             reason='length', seconds=0.1))
        assert [event.id for event in seen['a']] == ['a1']
        assert [event.id for event in seen['b']] == ['b1']
        assert [event.id for event in tap] == ['a1', 'b1']

    def test_tenant_bus_refuses_to_restamp_a_foreign_event(self):
        producer = Producer()
        event = RequestCompleted(id='x', produced=1, reason='length',
                                 seconds=0.1)
        TenantBus(producer, 'a').dispatch(event)
        with pytest.raises(ValueError, match='refusing to re-stamp'):
            TenantBus(producer, 'b').dispatch(event)
        with pytest.raises(ValueError, match='non-None tenant'):
            TenantBus(producer, None)

    def test_leak_audit_records_foreign_deliveries(self):
        audit = LeakAudit('a')
        mine = RequestCompleted(id='m', produced=1, reason='length',
                                seconds=0.1)
        mine.tenant = 'a'
        theirs = RequestCompleted(id='t', produced=1, reason='length',
                                  seconds=0.1)
        theirs.tenant = 'b'
        audit.consume(mine)
        audit.consume(theirs)
        assert audit.seen == 2
        assert audit.leaks == [('a', 'b', 'RequestCompleted')]

    def test_namespaced_writer_prefixes_every_tag(self):
        board = RecordingBoard()
        writer = NamespacedWriter(board, 'job-a')
        writer.add_scalar('serve/tok_s', 3.0, 7)
        writer.add_scalars('loss', {'train': 0.5}, 2)
        assert board.rows == [('job-a/serve/tok_s', 3.0, 7),
                              ('job-a/loss/train', 0.5, 2)]
        with pytest.raises(ValueError):
            NamespacedWriter(board, '')


# ---------------------------------------------------------------------------
# the satellite regression: cross-job leakage through REAL consumers
# (the evaluation_consumer subject-scope guard, generalized)
# ---------------------------------------------------------------------------


class TestCrossJobLeakage:

    def test_serve_metrics_never_ingest_a_foreign_completion(self):
        """Two models' serving stacks share one Producer: each job's
        serve-metrics consumer (scoped through its TenantBus) must
        never fold a foreign request's latency into its histograms."""
        from tpusystem.observe.metrics import ServeLatency
        from tpusystem.observe.metrics import serve_metrics_consumer
        from tpusystem.observe import tensorboard as tensorboard_module
        producer = Producer()
        states = {name: ServeLatency() for name in ('a', 'b')}
        for name, state in states.items():
            consumer = serve_metrics_consumer(latency=state)
            board = RecordingBoard()
            consumer.dependency_overrides[tensorboard_module.writer] = (
                lambda board=board: board)
            TenantBus(producer, name).register(consumer)
        for name, count in (('a', 3), ('b', 1)):
            bus = TenantBus(producer, name)
            for index in range(count):
                bus.dispatch(RequestAdmitted(
                    id=f'{name}{index}', row=0, prompt_tokens=4,
                    ttft=0.1, queue_depth=1))
                bus.dispatch(RequestCompleted(
                    id=f'{name}{index}', produced=5, reason='length',
                    seconds=0.5))
        assert states['a'].ttft.count == 3
        assert states['b'].ttft.count == 1

    def test_sentinel_and_training_charts_never_cross_models(self):
        """The tensorboard consumer (training + sentinel charts) scoped
        per model id — the exact evaluation_consumer regression, lifted
        to the chart consumers: model B's divergence must not land on
        model A's board, and vice versa. No stamping here: the scope
        resolves through the events' own ``model.id``, so pre-existing
        events isolate without a TenantBus."""
        from tpusystem.observe import tensorboard as tensorboard_module
        from tpusystem.observe import tensorboard_consumer
        producer = Producer()
        boards = {}
        for identity in ('m-a', 'm-b'):
            consumer = tensorboard_consumer()
            board = boards[identity] = RecordingBoard()
            consumer.dependency_overrides[tensorboard_module.writer] = (
                lambda board=board: board)
            producer.register(scoped(consumer, identity))
        producer.dispatch(Trained(Model('m-a', epoch=2), {'loss': 0.5}))
        producer.dispatch(AnomalyDetected(Model('m-b', epoch=1), step=9,
                                          kind='spike', loss=9.0,
                                          gnorm=100.0, zscore=8.0))
        tags_a = {tag for tag, _, _ in boards['m-a'].rows}
        tags_b = {tag for tag, _, _ in boards['m-b'].rows}
        assert any(tag.startswith('m-a/') for tag in tags_a)
        assert not any('m-b' in tag for tag in tags_a)
        assert any('anomal' in tag or 'sentinel' in tag or 'm-b' in tag
                   for tag in tags_b)
        assert not any('m-a' in tag for tag in tags_b)


# ---------------------------------------------------------------------------
# specs and the carve planner
# ---------------------------------------------------------------------------


class TestCarve:

    def test_jobspec_validation_and_elasticity(self):
        spec = JobSpec('train', 'train', priority=1, chips=4, min_chips=2)
        assert spec.elastic
        pinned = JobSpec('serve', 'serve', priority=2, chips=2)
        assert pinned.min_chips == 2 and not pinned.elastic
        with pytest.raises(ValueError):
            JobSpec('', 'train', priority=1, chips=2)
        with pytest.raises(ValueError):
            JobSpec('x', 'train', priority=1, chips=0)
        with pytest.raises(ValueError):
            JobSpec('x', 'train', priority=1, chips=2, min_chips=3)

    def test_carve_is_contiguous_deterministic_priority_ordered(self):
        specs = [JobSpec('train', 'train', priority=1, chips=4, min_chips=2),
                 JobSpec('serve', 'serve', priority=3, chips=2),
                 JobSpec('eval', 'eval', priority=0, chips=1)]
        placements = carve(range(8), specs)
        assert placements['serve'].devices == (0, 1)      # highest first
        assert placements['train'].devices == (2, 3, 4, 5)
        assert placements['eval'].devices == (6,)
        assert carve(range(8), specs) == placements       # deterministic

    def test_carve_refuses_oversubscription_typed(self):
        with pytest.raises(CapacityError, match='9 chips'):
            carve(range(8), [
                JobSpec('a', 'train', priority=1, chips=5),
                JobSpec('b', 'serve', priority=2, chips=4)])
        with pytest.raises(ValueError, match='duplicate job names'):
            carve(range(8), [JobSpec('a', 'train', priority=1, chips=1),
                             JobSpec('a', 'serve', priority=2, chips=1)])
        with pytest.raises(ValueError):
            Submesh((0, 0, 1))


# ---------------------------------------------------------------------------
# the orchestrator: lifecycle, blast radius, arbitration, recovery
# ---------------------------------------------------------------------------


def gang(client=None, producer=None):
    """The standing three-tenant drill: elastic low-priority training,
    pinned high-priority serving, a pinned background eval."""
    orchestrator = Orchestrator(range(8), client=client, producer=producer)
    tenants = {
        'train': orchestrator.admit(
            JobSpec('train', 'train', priority=1, chips=4, min_chips=2),
            FakeRunner()),
        'serve': orchestrator.admit(
            JobSpec('serve', 'serve', priority=2, chips=2), FakeRunner()),
        'eval': orchestrator.admit(
            JobSpec('eval', 'eval', priority=0, chips=1), FakeRunner()),
    }
    return orchestrator, tenants


class TestOrchestrator:

    def test_admission_seats_and_narrates(self):
        producer = Producer()
        admitted = witness(producer, JobAdmitted)
        orchestrator, tenants = gang(producer=producer)
        assert [event.job for event in admitted] == ['train', 'serve',
                                                     'eval']
        assert len(orchestrator.free) == 1
        assert tenants['train'].submesh.devices == (0, 1, 2, 3)
        with pytest.raises(ValueError, match='already admitted'):
            orchestrator.admit(JobSpec('train', 'train', priority=1,
                                       chips=1), FakeRunner())
        with pytest.raises(CapacityError, match='only 1 are free'):
            orchestrator.admit(JobSpec('big', 'train', priority=1,
                                       chips=4), FakeRunner())

    @pytest.mark.parametrize('code,reason', [
        (DIVERGED_EXIT, 'diverged'), (CRASH_LOOP_EXIT, 'crash-loop'),
        (1, 'failure')])
    def test_halt_isolates_the_blast_radius(self, code, reason):
        """A non-restartable exit halts ONLY its tenant: devices return
        to the pool, JobHalted carries the typed verdict, and no other
        tenant's runner, submesh, or state is touched."""
        producer = Producer()
        halted = witness(producer, JobHalted)
        orchestrator, tenants = gang(producer=producer)
        before = {name: tenant.submesh.devices
                  for name, tenant in tenants.items()}
        tenants['eval'].runner.code = code
        changed = orchestrator.step()
        assert [tenant.name for tenant in changed] == ['eval']
        assert tenants['eval'].state == 'halted'
        assert (halted[0].job, halted[0].code,
                halted[0].reason) == ('eval', code, reason)
        for name in ('train', 'serve'):
            assert tenants[name].state == 'running'
            assert tenants[name].submesh.devices == before[name]
            assert tenants[name].runner.resizes == []
        assert set(orchestrator.free) == {7} | set(before['eval'])

    def test_restartable_exits_are_the_supervisor_trees_business(self):
        orchestrator, tenants = gang()
        for code in (LOST_WORKER_EXIT, RESIZED_EXIT, 43):
            tenants['train'].runner.code = code
            assert orchestrator.step() == []
            assert tenants['train'].state == 'running'

    def test_clean_exit_retires_and_frees(self):
        orchestrator, tenants = gang()
        tenants['eval'].runner.code = 0
        (retired,) = orchestrator.step()
        assert retired.state == 'done' and retired.exit_code == 0
        assert 6 in orchestrator.free

    def test_burst_shrinks_lowest_priority_elastic_donor(self):
        producer = Producer()
        preempted = witness(producer, JobPreempted)
        arbitrated = witness(producer, CapacityArbitrated)
        orchestrator, tenants = gang(producer=producer)
        granted = orchestrator.request_capacity('serve', 3)
        # 1 chip from the free pool + 2 preempted from training
        assert len(granted) == 3
        assert tenants['train'].submesh.devices == (0, 1)
        assert tenants['train'].runner.resizes == [(0, 1)]
        assert len(tenants['serve'].submesh) == 5
        assert orchestrator.free == []
        assert (preempted[0].job, preempted[0].chips,
                preempted[0].to) == ('train', 2, 'serve')
        assert arbitrated[0].kind == 'grant' and arbitrated[0].chips == 3
        assert orchestrator.debts == [{'from': 'serve', 'to': 'train',
                                       'devices': (2, 3)}]
        # the ebb pays the debt back and training grows again
        returned = orchestrator.release_capacity('serve')
        assert returned == 2
        assert tenants['train'].submesh.devices == (0, 1, 2, 3)
        assert tenants['train'].runner.resizes[-1] == (0, 1, 2, 3)
        assert orchestrator.debts == []
        assert arbitrated[1].kind == 'release'
        assert orchestrator.release_capacity('serve') == 0   # no debt left

    def test_burst_never_shrinks_equal_or_higher_priority(self):
        orchestrator, tenants = gang()
        # eval (priority 0, pinned) asks: train outranks nobody below it
        with pytest.raises(CapacityError, match='no donor'):
            orchestrator.request_capacity('eval', 2)
        # and a refused burst is never partially applied
        assert len(orchestrator.free) == 1
        assert tenants['train'].submesh.devices == (0, 1, 2, 3)
        assert tenants['train'].runner.resizes == []

    def test_donor_floor_is_its_min_chips(self):
        orchestrator, tenants = gang()
        orchestrator.request_capacity('serve', 3)    # train at its floor
        with pytest.raises(CapacityError, match='no donor'):
            orchestrator.request_capacity('serve', 1)


class TestOrchestratorRecovery:

    def test_snapshot_journals_and_recovers_placements(self):
        store = MemStore()
        orchestrator, tenants = gang(client=store)
        tenants['eval'].runner.code = DIVERGED_EXIT
        orchestrator.step()
        runners = {name: FakeRunner() for name in tenants}
        fresh = Orchestrator(range(8), client=store)
        assert fresh.recover([store], runners)
        assert fresh.journal.term == orchestrator.journal.term + 1
        assert fresh.tenants['eval'].state == 'halted'
        assert fresh.tenants['eval'].exit_code == DIVERGED_EXIT
        assert (fresh.tenants['train'].submesh.devices
                == tenants['train'].submesh.devices)
        assert fresh.tenants['train'].spec.elastic
        with pytest.raises(RuntimeError, match='fresh orchestrator'):
            fresh.recover([store], runners)

    def test_corrupt_journal_reads_as_absent(self):
        store = MemStore()
        orchestrator, _ = gang(client=store)
        journal = OrchestratorJournal()
        with pytest.raises(JournalCorrupt):
            journal.unpack(b'x:not a journal')
        torn = MemStore()
        torn.put(orchestrator_identity('orchestrator'), 1, b'x:torn')
        assert recover_orchestrator_journal('orchestrator', [torn]) is None
        # ...and the preference chain falls through to the intact copy
        tick, state = recover_orchestrator_journal('orchestrator',
                                                   [torn, store])
        assert state['placements']['train'] == (0, 1, 2, 3)

    def test_sigkill_mid_arbitration_completes_without_redeciding(self):
        """The headline recovery drill: the orchestrator dies BETWEEN
        journaling 'decided' and finishing the resize. A fresh
        orchestrator recovers the in-flight decision and executes the
        RECORDED plan — same donor, same devices — instead of
        re-deriving one, then journals 'done'."""
        store = MemStore()
        orchestrator, tenants = gang(client=store)

        class DiesMidResize:
            def poll(self):
                return None

            def resize(self, devices):
                raise RuntimeError('orchestrator SIGKILLed mid-resize')

        tenants['train'].runner = DiesMidResize()
        with pytest.raises(RuntimeError, match='SIGKILLed'):
            orchestrator.request_capacity('serve', 3)
        # the plane holds the 'decided' record the dead process pushed
        tick, state = recover_orchestrator_journal('orchestrator', [store])
        assert state['inflight'] is not None
        assert state['inflight']['requester'] == 'serve'
        assert state['inflight']['donor'] == 'train'

        runners = {name: FakeRunner() for name in tenants}
        fresh = Orchestrator(range(8), client=store)
        assert fresh.recover([store], runners)
        # the in-flight grant COMPLETED from the journal: training shrunk
        # to the recorded remainder, serving holds the recorded grant
        assert fresh.inflight is None
        assert fresh.tenants['train'].submesh.devices == (0, 1)
        assert runners['train'].resizes == [(0, 1)]
        assert len(fresh.tenants['serve'].submesh) == 5
        assert fresh.free == []
        assert fresh.debts == [{'from': 'serve', 'to': 'train',
                                'devices': (2, 3)}]
        # and the 'done' record is on the plane: a SECOND recovery finds
        # nothing in flight
        again = Orchestrator(range(8), client=store)
        assert again.recover([store], {name: FakeRunner()
                                       for name in tenants})
        assert again.inflight is None
        assert again.tenants['train'].submesh.devices == (0, 1)

    def test_recovered_term_fences_the_predecessors_pushes(self):
        store = MemStore()
        orchestrator, _ = gang(client=store)
        fresh = Orchestrator(range(8), client=store)
        assert fresh.recover([store], {})
        # the successor stamped its bumped term; the predecessor's next
        # push lands at a LOWER store step and the plane keeps the
        # successor's copy (term * 1_000_000 + tick monotonic-step rule)
        orchestrator.journal.tick += 1
        orchestrator.journal.replicate(orchestrator.snapshot())
        tick, state = recover_orchestrator_journal('orchestrator', [store])
        assert state['term'] == fresh.journal.term


# ---------------------------------------------------------------------------
# the seeded tenant chaos picker
# ---------------------------------------------------------------------------


class TestTenantChaosPick:

    def test_deterministic_and_in_range(self):
        tenants, components = ('a', 'b', 'c'), ('worker', 'plane')
        first = pick_tenant_chaos(5, tenants, components, lo=1, hi=8)
        assert pick_tenant_chaos(5, tenants, components, lo=1, hi=8) == first
        assert first.tenant in tenants and first.component in components
        assert 1 <= first.step <= 8
        picked = {pick_tenant_chaos(seed, tenants, components).tenant
                  for seed in range(32)}
        assert picked == set(tenants)       # every tenant is reachable

    def test_validation(self):
        with pytest.raises(ValueError):
            pick_tenant_chaos(0, (), ('x',))
        with pytest.raises(ValueError):
            pick_tenant_chaos(0, ('a',), ())
        with pytest.raises(ValueError):
            pick_tenant_chaos(0, ('a',), ('x',), lo=5, hi=2)


# ---------------------------------------------------------------------------
# cross-tenant chaos certification over fixed seeds
# ---------------------------------------------------------------------------


def job_token(name, position):
    """Deterministic per-job token stream — pure function of (job,
    position), so a replayed job recovers bitwise by construction."""
    return (sum(map(ord, name)) * 37 + position * 13) % 991


class ScriptedJob:
    """A certifiable job driver: emits its deterministic token stream
    one step at a time, narrating each emission on its tenant bus. The
    two scripted kills mirror the real failure modes: ``lose`` drops
    the last two tokens and replays them (the journal-replay shape —
    recovers bitwise), ``halt`` is a typed terminal verdict (the
    exit-44/45 shape — degrades, never corrupts)."""

    def __init__(self, name, length=6, bus=None):
        self.name = name
        self.length = length
        self.bus = bus
        self.tokens = []
        self.done = False
        self.verdict = None
        self.duplicates = []

    @property
    def idle(self):
        return self.done or self.verdict is not None

    def step(self):
        if self.idle:
            return
        position = len(self.tokens)
        self.tokens.append(job_token(self.name, position))
        if self.bus is not None:
            self.bus.dispatch(RequestCompleted(
                id=f'{self.name}-{position}', produced=1, reason='length',
                seconds=0.01))
        if len(self.tokens) >= self.length:
            self.done = True

    def outputs(self):
        reason = self.verdict or ('done' if self.done else 'running')
        return {'stream': (reason, tuple(self.tokens))}

    def lose(self):
        if self.verdict is None:
            self.tokens = self.tokens[:-2]
            self.done = False

    def halt(self):
        self.verdict = 'halted'


def tenant_harness(sabotage=None, unscoped_audit=False):
    """Three scripted tenants on one shared Producer, each behind its
    TenantBus with a LeakAudit registered through the tenant's own
    wiring path; ``sabotage`` lets a kill reach ACROSS tenants (the bug
    the certifier must catch), ``unscoped_audit`` wires one audit
    without its scope (the leak the certifier must catch)."""
    def build():
        producer = Producer()
        audits = []
        jobs, kills = {}, {}
        names = ('train', 'serve', 'eval')
        for name in names:
            bus = TenantBus(producer, name)
            audit = LeakAudit(name)
            if unscoped_audit and name == 'eval':
                producer.register(audit)     # the leak: no scope
            else:
                bus.register(audit)
            audits.append(audit)
            jobs[name] = ScriptedJob(name, length=6, bus=bus)
        for name in names:
            job = jobs[name]

            def corrupt(job=job, name=name):
                job.halt()
                if sabotage is not None:
                    other = jobs[sabotage(name)]
                    other.tokens.append(-1)   # a cross-tenant write

            kills[name] = {'worker': job.lose, 'plane': corrupt}
        return TenantHarness(
            jobs=jobs, kills=kills,
            leaks=lambda: [leak for audit in audits
                           for leak in audit.leaks])
    return build


class TestCertifyTenants:

    @pytest.mark.parametrize('seed', range(10))
    def test_non_victims_stay_bitwise_across_seeds(self, seed):
        """The acceptance drill: for every seeded (tenant × component ×
        kill-tick) draw, the two non-victim tenants finish bitwise-
        identical to the undisturbed reference, the victim recovers
        bitwise (worker kill) or degrades typed (plane kill), nothing
        hangs, nothing leaks across a namespace."""
        report = certify_tenants(tenant_harness(), seed=seed)
        assert report.ok, report.summary()
        assert report.exact == 2             # both non-victims, bitwise
        assert not report.leaked and not report.hung
        if report.component == 'worker':
            assert report.victim_exact       # replay recovered bitwise
        else:
            assert report.victim_verdict == 'halted'

    def test_cross_tenant_corruption_is_caught(self):
        """A kill that writes into ANOTHER tenant's stream must turn
        the report red — the whole point of the bitwise non-victim
        check."""
        names = ('train', 'serve', 'eval')

        def neighbor(name):
            return names[(names.index(name) + 1) % len(names)]

        reports = [certify_tenants(tenant_harness(sabotage=neighbor),
                                   seed=seed) for seed in range(10)]
        corrupted = [report for report in reports
                     if report.component == 'plane']
        assert corrupted, 'no seed in range drew the corrupting kill'
        assert all(not report.ok and report.mismatches
                   for report in corrupted)

    def test_cross_namespace_delivery_is_caught(self):
        """An audit wired WITHOUT its scope witnesses foreign events —
        certification reports the leak even when every token stream is
        intact."""
        report = certify_tenants(tenant_harness(unscoped_audit=True),
                                 seed=0)
        assert report.leaked and not report.ok
        assert any(tenant == 'eval' for tenant, _, _ in report.leaked)

    def test_reference_must_drain(self):
        def build():
            harness = tenant_harness()()
            harness.jobs['train'].length = 10 ** 9   # never idles
            return harness
        with pytest.raises(RuntimeError, match='fix the harness'):
            certify_tenants(build, seed=0, max_steps=50)

    def test_component_sets_must_match_across_tenants(self):
        def build():
            harness = tenant_harness()()
            del harness.kills['eval']['plane']
            return harness
        with pytest.raises(ValueError, match='SAME component set'):
            certify_tenants(build, seed=0)

    def test_lo_floor_keeps_the_kill_after_startup(self):
        with pytest.raises(ValueError, match='lo must be >= 1'):
            certify_tenants(tenant_harness(), seed=0, lo=0)
