"""Minimal TensorBoard event-file *reader* for tests.

The writer (``tpusystem/observe/tensorboard.py``) hand-rolls the
TFRecord + Event-proto format; this is its mirror — a varint/field
parser just big enough to read scalar summaries back, so TB-handler
tests assert **parsed tags and values** instead of poking at raw bytes
or file sizes. Not a test module: shared via ``from tests.tb import``.
"""

import io
import struct


def read_records(path):
    """Raw TFRecord payloads from one event file (CRCs skipped — the
    writer's own format test verifies them once)."""
    records = []
    with open(path, 'rb') as handle:
        while header := handle.read(8):
            (length,) = struct.unpack('<Q', header)
            handle.read(4)                      # length crc
            records.append(handle.read(length))
            handle.read(4)                      # payload crc
    return records


def _varint(stream):
    shift = result = 0
    while True:
        byte = stream.read(1)[0]
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result
        shift += 7


def _walk(data):
    """One level of proto fields: {field_number: value-or-[bytes, ...]}."""
    stream = io.BytesIO(data)
    fields = {}
    while stream.tell() < len(data):
        key = _varint(stream)
        field, wire = key >> 3, key & 7
        if wire == 0:
            fields[field] = _varint(stream)
        elif wire == 1:
            fields[field] = struct.unpack('<d', stream.read(8))[0]
        elif wire == 5:
            fields[field] = struct.unpack('<f', stream.read(4))[0]
        elif wire == 2:
            fields.setdefault(field, []).append(stream.read(_varint(stream)))
    return fields


def parse_scalars(record):
    """{tag: (value, step)} from one serialized Event proto record."""
    scalars = {}
    top = _walk(record)
    step = top.get(2, 0)
    for summary in top.get(5, []):
        for value in _walk(summary).get(1, []):
            fields = _walk(value)
            scalars[fields[1][0].decode()] = (fields[2], step)
    return scalars


def read_scalars(logdir, history=False):
    """Every scalar from every event file under ``logdir``.

    ``history=False`` (default): {tag: (value, step)} with the LAST
    write winning — the one-shot assertion shape. ``history=True``:
    {tag: [(value, step), ...]} in write order — for charts written at
    several steps.
    """
    out = {}
    for event_file in sorted(logdir.glob('events.out.tfevents.*')):
        for record in read_records(event_file)[1:]:    # [0] = version
            for tag, pair in parse_scalars(record).items():
                if history:
                    out.setdefault(tag, []).append(pair)
                else:
                    out[tag] = pair
    return out
