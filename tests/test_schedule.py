"""Unified overlap scheduler: FSDP param-prefetch / grad-scatter hiding
composed with the TP rings (``tpusystem/parallel/schedule.py``).

Parity harness on the virtual CPU mesh, mirroring ``test_overlap.py``:
the scheduled FFN must match the GSPMD reference in forward AND
gradients — and the FSDP-prefetch-only forward must match **bitwise**
(the ring gather is a copy, so every matmul sees identical operands).
Plan helpers pin exactly which path each leaf takes; the tie-break of
the placement policy's FSDP dimension choice is a regression contract
(a silent reshard would invalidate every checkpoint); model-level, the
``schedule=`` knob never changes a param tree, and a checkpoint written
before the knob existed restores under it unchanged. The compile guard
pins that a scheduled train step traces and compiles exactly once
across steps (the pipeline.py per-step-retrace bug class from PR 1).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.checkpoint import Checkpointer
from tpusystem.models import GPT2
from tpusystem.models.llama import llama_tiny
from tpusystem.parallel import (MeshSpec, OverlapSchedule, ShardingPolicy,
                                batch_sharding, fsdp_plan, resolve_schedule,
                                schedule_applicable, scheduled_ffn)
from tpusystem.parallel.collectives import (ring_allgather,
                                            ring_reducescatter)
from tpusystem.parallel.mesh import FSDP, MODEL, shard_map
from tpusystem.parallel.sharding import fsdp_shard_dim

RING = 4           # >= 4-device virtual mesh (conftest forces 8 devices)


def fsdp_mesh():
    return MeshSpec(fsdp=RING).build(jax.devices()[:RING])


def composed_mesh():
    return MeshSpec(fsdp=2, model=2).build(jax.devices()[:4])


# ---------------------------------------------------------------------------
# the ring collectives the prefetch custom_vjp is built from
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('dimension,chunks', [(0, 1), (0, 2), (1, 1)])
def test_ring_allgather_is_bitwise_identical_to_lax(dimension, chunks):
    """The decomposed gather is a pure copy: every row-block lands
    exactly where ``lax.all_gather(tiled=True)`` puts it, bit for bit."""
    mesh = fsdp_mesh()
    value = jnp.asarray(
        np.random.default_rng(0).normal(size=(16, 24)), jnp.float32)
    in_spec = P(FSDP, None) if dimension == 0 else P(None, FSDP)

    @functools.partial(shard_map, mesh=mesh, check_vma=False,
                       in_specs=in_spec, out_specs=P(None, None))
    def ring(shard):
        return ring_allgather(shard, FSDP, dimension=dimension,
                              chunks=chunks)

    @functools.partial(shard_map, mesh=mesh, check_vma=False,
                       in_specs=in_spec, out_specs=P(None, None))
    def monolithic(shard):
        return lax.all_gather(shard, FSDP, axis=dimension, tiled=True)

    np.testing.assert_array_equal(np.asarray(jax.jit(ring)(value)),
                                  np.asarray(jax.jit(monolithic)(value)))


@pytest.mark.parametrize('dimension,chunks', [(0, 1), (0, 2), (1, 1)])
def test_ring_reducescatter_matches_psum_scatter(dimension, chunks):
    """The decomposed scatter sums all ring contributions into the home
    block — ``lax.psum_scatter`` semantics, f32 carry, tight tolerance
    (only the summation order differs)."""
    mesh = fsdp_mesh()
    # distinct full-size value per device, stacked on the fsdp axis
    values = jnp.asarray(
        np.random.default_rng(1).normal(size=(RING, 16, 24)), jnp.float32)
    out_spec = P(FSDP, None) if dimension == 0 else P(None, FSDP)

    @functools.partial(shard_map, mesh=mesh, check_vma=False,
                       in_specs=P(FSDP, None, None), out_specs=out_spec)
    def ring(stacked):
        return ring_reducescatter(stacked[0], FSDP, dimension=dimension,
                                  chunks=chunks)

    out = jax.jit(ring)(values)
    reference = values.sum(axis=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               rtol=2e-6, atol=2e-6)


# ---------------------------------------------------------------------------
# plan pinning: every leaf's path is decided by the pure helper
# ---------------------------------------------------------------------------


def test_fsdp_plan_pins_skip_paths():
    # trivial axis: the leaf was never sharded
    plan = fsdp_plan((256, 1024), 1)
    assert plan.path == 'skip' and 'axis_size' in plan.reason
    # tiny leaf below fsdp_min_size stays replicated by the policy
    plan = fsdp_plan((8, 8), RING, min_size=4096)
    assert plan.path == 'skip' and 'fsdp_min_size' in plan.reason
    # no dimension divides the fsdp axis -> policy left it unsharded
    plan = fsdp_plan((5001, 3), RING, min_size=64)
    assert plan.path == 'skip' and 'divisible' in plan.reason
    # dimensions claimed by rule axes are not FSDP candidates
    plan = fsdp_plan((256, 1024), RING, taken=(0, 1))
    assert plan.path == 'skip'


def test_fsdp_plan_pins_one_shot_when_chunks_cannot_tile():
    plan = fsdp_plan((256, 1024), RING, chunks=3)
    assert plan.path == 'one-shot' and 'chunks' in plan.reason
    assert plan.dim == 1                   # the gather dim is still chosen
    plan = fsdp_plan((256, 1024), RING, chunks=2)
    assert plan.path == 'ring' and plan.chunks == 2


def test_fsdp_plan_dim_agrees_with_the_placement_policy():
    """The plan's gather dim IS fsdp_shard_dim's choice — the manual
    collectives and the placement policy can never disagree."""
    for shape, taken in [((256, 1024), ()), ((256, 1024), (1,)),
                         ((64, 64), ()), ((4, 256, 256), (0,))]:
        plan = fsdp_plan(shape, RING, taken=taken, min_size=64)
        assert plan.dim == fsdp_shard_dim(shape, RING, taken)


# ---------------------------------------------------------------------------
# satellite: deterministic FSDP dimension tie-breaking
# ---------------------------------------------------------------------------


def test_fsdp_shard_dim_tie_breaks_to_the_lowest_index():
    """Several equally-largest divisible dims: the LOWEST index wins,
    deterministically — a checkpoint placed under this choice must
    never silently reshard across jax/python versions."""
    assert fsdp_shard_dim((64, 64), 4) == 0
    assert fsdp_shard_dim((4, 64, 64), 4) == 1          # dim 0 smaller
    assert fsdp_shard_dim((64, 64, 64), 4, taken=(0,)) == 1
    # largest still wins over lower index when sizes differ
    assert fsdp_shard_dim((64, 128), 4) == 1
    # non-divisible largest dim loses to a smaller divisible one
    assert fsdp_shard_dim((129, 64), 4) == 1
    assert fsdp_shard_dim((5, 3), 4) is None


def test_policy_fsdp_placement_tie_break_is_deterministic():
    """Policy-level regression: a square kernel's FSDP axis lands on
    dim 0 (the tie-break), not wherever enumeration order wandered."""
    mesh = fsdp_mesh()
    policy = ShardingPolicy(rules=(), fsdp=True, fsdp_min_size=64)
    assert policy.spec('dense/kernel', (64, 64), mesh) == P(FSDP)
    # a rule-claimed dim 0 pushes the tie-winner to dim 1
    ruled = ShardingPolicy(rules=((r'kernel', P(MODEL)),), fsdp=True,
                           fsdp_min_size=64)
    assert ruled.spec('dense/kernel', (64, 64), mesh) == P(MODEL, FSDP)


# ---------------------------------------------------------------------------
# the schedule object and the legacy-knob seam
# ---------------------------------------------------------------------------


def test_resolve_schedule_folds_legacy_knobs():
    schedule = resolve_schedule(None, 'overlap', 2)
    assert schedule == OverlapSchedule(tp='overlap', fsdp='gspmd', chunks=2)
    assert resolve_schedule(None) == OverlapSchedule()
    passed = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=4)
    assert resolve_schedule(passed) is passed


def test_resolve_schedule_rejects_conflicting_knobs():
    with pytest.raises(ValueError, match='not both'):
        resolve_schedule(OverlapSchedule(), 'overlap', 1)
    with pytest.raises(ValueError, match='not both'):
        resolve_schedule(OverlapSchedule(), 'gspmd', 2)
    with pytest.raises(ValueError, match='tp_impl'):
        resolve_schedule(None, 'magic', 1)
    with pytest.raises(TypeError, match='OverlapSchedule'):
        resolve_schedule('overlap')


def test_overlap_schedule_validates_knobs():
    with pytest.raises(ValueError, match='tp'):
        OverlapSchedule(tp='magic')
    with pytest.raises(ValueError, match='fsdp'):
        OverlapSchedule(fsdp='magic')
    with pytest.raises(ValueError, match='chunks'):
        OverlapSchedule(chunks=0)


def test_for_policy_matches_the_policy_min_size():
    """The schedule's fsdp_min_size must equal the placement policy's or
    jit reshards at the manual boundary — for_policy pins the pairing."""
    policy = ShardingPolicy(rules=(), fsdp=True, fsdp_min_size=64)
    schedule = OverlapSchedule.for_policy(policy, tp='overlap', chunks=2)
    assert schedule.fsdp_min_size == 64
    assert (schedule.tp, schedule.fsdp) == ('overlap', 'prefetch')


def test_schedule_applicable_gates_per_shape():
    mesh = composed_mesh()
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=1)
    # seq 16 shards over model=2; batch 4 over fsdp=2
    assert schedule_applicable(schedule, mesh, (4, 16, 64), 256)
    # odd sequence cannot ride the TP ring nor shard rows
    assert not schedule_applicable(schedule, mesh, (4, 15, 64), 256)
    # no mesh -> GSPMD path
    assert not schedule_applicable(schedule, None, (4, 16, 64), 256)
    # all-gspmd schedule never takes the manual path
    assert not schedule_applicable(OverlapSchedule(), mesh, (4, 16, 64), 256)
    # prefetch-only schedule applies without a model axis
    pure = MeshSpec(fsdp=RING).build(jax.devices()[:RING])
    assert schedule_applicable(
        OverlapSchedule(fsdp='prefetch'), pure, (4, 16, 64), 256)
    # ... but not when the batch cannot shard over (data, fsdp): the
    # manual gradient scatter assumes distinct batch slices per device
    assert not schedule_applicable(
        OverlapSchedule(fsdp='prefetch'), pure, (3, 16, 64), 256)


# ---------------------------------------------------------------------------
# scheduled FFN vs the GSPMD reference
# ---------------------------------------------------------------------------


def _ffn_operands(dtype, batch=4, seq=16, dim=64, grown=256, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(batch, seq, dim)) * 0.5, dtype)
    w_up = jnp.asarray(rng.normal(size=(dim, grown)) * 0.1, dtype)
    b_up = jnp.asarray(rng.normal(size=(grown,)) * 0.1, dtype)
    w_down = jnp.asarray(rng.normal(size=(grown, dim)) * 0.1, dtype)
    b_down = jnp.asarray(rng.normal(size=(dim,)) * 0.1, dtype)
    return x, w_up, b_up, w_down, b_down


def _reference_ffn(x, w_up, b_up, w_down, b_down):
    grown = jax.nn.gelu(jnp.matmul(x, w_up) + b_up)
    return jnp.matmul(grown, w_down) + b_down


def _loss(fn):
    def loss(*operands):
        out = fn(*operands)
        return jnp.sum(jnp.square(out.astype(jnp.float32))) * 1e-3
    return loss


@pytest.mark.parametrize('chunks', [1, 2])
def test_prefetch_forward_is_bitwise_vs_gspmd_f32(chunks):
    """fsdp='prefetch' alone (tp left to GSPMD on a model-free mesh):
    the ring gather is a copy, so every device's matmuls see identical
    operands — the scheduled forward is BITWISE-equal in f32 to the
    same FFN with every collective left monolithic (the all-gspmd
    schedule), and tight against the unsharded reference (only
    operand-shape-dependent fusion differs there)."""
    mesh = fsdp_mesh()
    operands = _ffn_operands(jnp.float32)
    schedule = OverlapSchedule(fsdp='prefetch', chunks=chunks,
                               fsdp_min_size=64)
    out = jax.jit(lambda *a: scheduled_ffn(
        *a, mesh, schedule=schedule))(*operands)
    monolithic = OverlapSchedule(fsdp_min_size=64)
    baseline = jax.jit(lambda *a: scheduled_ffn(
        *a, mesh, schedule=monolithic))(*operands)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(baseline))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference_ffn(*operands)),
                               rtol=2e-6, atol=2e-6)


def test_prefetch_grads_match_gspmd_f32():
    """The backward's deferred grad reduce-scatter reproduces the
    reference cotangents (tight f32: only the ring sum's order
    differs from the partitioner's reduction)."""
    mesh = fsdp_mesh()
    operands = _ffn_operands(jnp.float32)
    schedule = OverlapSchedule(fsdp='prefetch', chunks=2, fsdp_min_size=64)
    scheduled = lambda *a: scheduled_ffn(*a, mesh, schedule=schedule)
    grads = jax.jit(jax.grad(_loss(scheduled), argnums=(0, 1, 2, 3, 4)))(
        *operands)
    reference = jax.grad(_loss(_reference_ffn), argnums=(0, 1, 2, 3, 4))(
        *operands)
    for got, want in zip(grads, reference):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('chunks', [1, 2])
def test_composed_tp_ring_plus_prefetch_matches_gspmd_f32(chunks):
    """The composition the three-knob world could not express: TP rings
    AND FSDP prefetch in ONE manual region, on a fsdp=2 x model=2 mesh,
    matching the reference in forward and all gradients."""
    mesh = composed_mesh()
    operands = _ffn_operands(jnp.float32)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=chunks,
                               fsdp_min_size=64)
    scheduled = lambda *a: scheduled_ffn(*a, mesh, schedule=schedule)
    out = jax.jit(scheduled)(*operands)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference_ffn(*operands)),
                               rtol=2e-5, atol=2e-5)
    grads = jax.jit(jax.grad(_loss(scheduled), argnums=(0, 1, 2, 3, 4)))(
        *operands)
    reference = jax.grad(_loss(_reference_ffn), argnums=(0, 1, 2, 3, 4))(
        *operands)
    for got, want in zip(grads, reference):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


def test_scheduled_ffn_bf16_bounded():
    """bf16 operands with f32 accumulation: bounded tolerance against
    the reference computed the GSPMD way (bf16 matmuls), the
    test_overlap bf16 discipline."""
    mesh = composed_mesh()
    operands = _ffn_operands(jnp.bfloat16)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=1,
                               fsdp_min_size=64)
    scheduled = lambda *a: scheduled_ffn(*a, mesh, schedule=schedule)
    out = jax.jit(scheduled)(*operands)
    reference = _reference_ffn(*operands)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(reference, np.float32),
                               rtol=0.05, atol=0.1)
    grads = jax.jit(jax.grad(_loss(scheduled), argnums=(0, 1)))(*operands)
    want = jax.grad(_loss(_reference_ffn), argnums=(0, 1))(*operands)
    for got, ref in zip(grads, want):
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=0.1, atol=0.5)


def test_one_shot_fallback_still_matches_reference():
    """chunks=3 cannot tile the per-device kernel shards (pinned by the
    plan) -> the monolithic lax.all_gather path runs and stays correct,
    grads (its native psum_scatter transpose) included."""
    mesh = fsdp_mesh()
    operands = _ffn_operands(jnp.float32)
    assert fsdp_plan((64, 256), RING, chunks=3, min_size=64).path == 'one-shot'
    schedule = OverlapSchedule(fsdp='prefetch', chunks=3, fsdp_min_size=64)
    scheduled = lambda *a: scheduled_ffn(*a, mesh, schedule=schedule)
    out = jax.jit(scheduled)(*operands)
    monolithic = OverlapSchedule(fsdp_min_size=64)
    baseline = jax.jit(lambda *a: scheduled_ffn(
        *a, mesh, schedule=monolithic))(*operands)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(baseline))
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference_ffn(*operands)),
                               rtol=2e-6, atol=2e-6)
    grads = jax.jit(jax.grad(_loss(scheduled), argnums=(1, 3)))(*operands)
    reference = jax.grad(_loss(_reference_ffn), argnums=(1, 3))(*operands)
    for got, want in zip(grads, reference):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_down_kernel_row_split_falls_back_to_one_shot():
    """Regression: the down kernel's rows are TP-sharded INSIDE the
    manual region, so the plan's chunk-tiling check must see the LOCAL
    row count — chunks=32 tiles the full 96 rows but not the 48 a
    model=2 shard holds, and without ``row_split`` the plan said
    ``'ring'`` for a shard ``ring_shift_chunked`` then refused to split
    at trace time. It must fall back to one-shot and stay correct."""
    plan = fsdp_plan((96, 64), 2, taken=(0,), chunks=32, row_split=2,
                     min_size=64)
    assert plan.path == 'one-shot' and 'chunks' in plan.reason
    # the bug's exact shape: without the row split the leaf planned 'ring'
    assert fsdp_plan((96, 64), 2, taken=(0,), chunks=32,
                     min_size=64).path == 'ring'
    mesh = composed_mesh()
    operands = _ffn_operands(jnp.float32, grown=96)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=32,
                               fsdp_min_size=64)
    scheduled = lambda *a: scheduled_ffn(*a, mesh, schedule=schedule)
    out = jax.jit(scheduled)(*operands)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_reference_ffn(*operands)),
                               rtol=2e-5, atol=2e-5)
    grads = jax.jit(jax.grad(_loss(scheduled), argnums=(1, 3)))(*operands)
    reference = jax.grad(_loss(_reference_ffn), argnums=(1, 3))(*operands)
    for got, want in zip(grads, reference):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# model-level: the schedule= knob on GPT-2 and Llama
# ---------------------------------------------------------------------------


def _run_model(model, rules, tokens, mesh, min_size=64):
    variables = model.init(jax.random.PRNGKey(0), tokens[:1, :8])
    params = ShardingPolicy(rules=rules, fsdp=True,
                            fsdp_min_size=min_size).place(
        variables['params'], mesh)
    placed_tokens = jax.device_put(tokens, batch_sharding(mesh))
    out = jax.jit(lambda p, t: model.apply({'params': p}, t))(
        params, placed_tokens)

    def loss(p):
        logits = model.apply({'params': p}, placed_tokens)
        return jnp.sum(jnp.square(logits.astype(jnp.float32))) * 1e-3

    grads = jax.jit(jax.grad(loss))(params)
    return variables, out, grads


@pytest.mark.parametrize('family', ['gpt2', 'llama'])
def test_schedule_knob_matches_gspmd_model_level(family):
    """schedule=OverlapSchedule(tp='overlap', fsdp='prefetch') is purely
    an implementation schedule: identical param trees (bitwise — the
    checkpoint contract), matching logits and grads, on the composed
    fsdp=2 x model=2 mesh with FSDP-placed params."""
    mesh = composed_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 16)), jnp.int32)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=2,
                               fsdp_min_size=64)

    def build(schedule):
        if family == 'gpt2':
            model = GPT2(vocab_size=256, layers=2, dim=64, heads=4,
                         max_seq=128, dropout=0.0, dtype='float32',
                         mesh=mesh, schedule=schedule)
            return model, GPT2.partition_rules()
        model = llama_tiny(dtype='float32', mesh=mesh, schedule=schedule)
        return model, type(model).partition_rules()

    v_ref, out_ref, grads_ref = _run_model(*build(None),
                                           tokens=tokens, mesh=mesh)
    v_sch, out_sch, grads_sch = _run_model(*build(schedule),
                                           tokens=tokens, mesh=mesh)
    # the knob never changes the checkpoint: identical trees, identical init
    assert (jax.tree_util.tree_structure(v_ref)
            == jax.tree_util.tree_structure(v_sch))
    for ref, sch in zip(jax.tree.leaves(v_ref), jax.tree.leaves(v_sch)):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(sch))
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_sch),
                               rtol=2e-5, atol=2e-5)
    for ref, sch in zip(jax.tree.leaves(grads_ref),
                        jax.tree.leaves(grads_sch)):
        np.testing.assert_allclose(np.asarray(ref), np.asarray(sch),
                                   rtol=2e-4, atol=3e-5)


def test_scan_path_accepts_the_schedule():
    """The BlockSpan scan path (scan_layers=True) threads the schedule
    through the scanned block and still matches the GSPMD scan —
    including BITWISE-identical init draws. Regression: the legacy
    threefry's bits depend on the sharding the manual region imposes
    inside the scanned init program, so on a composed fsdp x model mesh
    a schedule-on init that ran the scheduled branch drew different
    kernels than schedule-off (PR-2's tp_impl knob had the same latent
    bug); init must always take the nn.Dense path."""
    mesh = composed_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 256, (4, 16)), jnp.int32)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=2,
                               fsdp_min_size=64)
    common = dict(vocab_size=256, layers=2, dim=64, heads=4, max_seq=128,
                  dropout=0.0, dtype='float32', mesh=mesh, scan_layers=True)
    v_ref, out_ref, _ = _run_model(GPT2(**common), GPT2.partition_rules(),
                                   tokens, mesh)
    v_sch, out_sch, _ = _run_model(GPT2(**common, schedule=schedule),
                                   GPT2.partition_rules(), tokens, mesh)
    assert (jax.tree_util.tree_structure(v_ref)
            == jax.tree_util.tree_structure(v_sch))
    for ref, sch in zip(jax.tree.leaves(v_ref), jax.tree.leaves(v_sch)):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(sch))
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_sch),
                               rtol=2e-5, atol=2e-5)


def test_schedule_rejects_unknown_values_at_model_level():
    with pytest.raises(ValueError, match='schedule fsdp'):
        OverlapSchedule(fsdp='sometimes')
    model = GPT2(vocab_size=64, layers=1, dim=32, heads=4, max_seq=32,
                 dropout=0.0, dtype='float32', schedule='overlap')
    tokens = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(TypeError, match='OverlapSchedule'):
        model.init(jax.random.PRNGKey(0), tokens)


def test_schedule_with_legacy_knobs_raises_at_model_level():
    model = GPT2(vocab_size=64, layers=1, dim=32, heads=4, max_seq=32,
                 dropout=0.0, dtype='float32', tp_impl='overlap',
                 schedule=OverlapSchedule())
    tokens = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match='not both'):
        model.init(jax.random.PRNGKey(0), tokens)


# ---------------------------------------------------------------------------
# checkpoint invariance: a pre-schedule-era checkpoint restores unchanged
# ---------------------------------------------------------------------------


def test_pre_schedule_checkpoint_restores_under_the_new_knob(tmp_path):
    """Regression for the PR-5-era fleet: a checkpoint written by a
    model with NO schedule knob (the old tree) restores bitwise into a
    schedule-on run and produces matching logits — the knob is invisible
    to every existing checkpoint."""
    from tpusystem.train import AdamW, init_state

    mesh = composed_mesh()
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 256, (4, 16)), jnp.int32)
    common = dict(vocab_size=256, layers=2, dim=64, heads=4, max_seq=128,
                  dropout=0.0, dtype='float32', mesh=mesh)
    old_era = GPT2(**common)                        # exactly the PR-5 model
    state = init_state(old_era, AdamW(lr=1e-3), tokens[:1, :8], rng=0)
    with Checkpointer(tmp_path, async_save=False) as checkpointer:
        checkpointer.save('pre-schedule', 0, state)
        blank = jax.tree.map(jnp.zeros_like, state)
        restored = checkpointer.restore('pre-schedule', blank)
    for original, loaded in zip(jax.tree.leaves(state),
                                jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(original),
                                      np.asarray(loaded))
    scheduled = GPT2(**common, schedule=OverlapSchedule(
        tp='overlap', fsdp='prefetch', chunks=2, fsdp_min_size=64))
    placed = ShardingPolicy(rules=GPT2.partition_rules(), fsdp=True,
                            fsdp_min_size=64).place(restored.params, mesh)
    placed_tokens = jax.device_put(tokens, batch_sharding(mesh))
    out_old = jax.jit(lambda p, t: old_era.apply({'params': p}, t))(
        placed, placed_tokens)
    out_new = jax.jit(lambda p, t: scheduled.apply({'params': p}, t))(
        placed, placed_tokens)
    np.testing.assert_allclose(np.asarray(out_old), np.asarray(out_new),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# compile guard: schedule-on traces and compiles ONCE across steps
# ---------------------------------------------------------------------------


def test_compile_guard_scheduled_step_never_retraces():
    """The pipeline.py bug class from PR 1, guarded permanently: a
    scheduled train step must trace exactly once and hit the jit cache
    on every subsequent step — a per-step retrace/recompile would eat
    the overlap win thousands of times over."""
    from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                                 flax_apply, init_state)

    mesh = composed_mesh()
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', chunks=2,
                               fsdp_min_size=64)
    module = GPT2(vocab_size=256, layers=2, dim=64, heads=4, max_seq=128,
                  dropout=0.0, dtype='float32', mesh=mesh,
                  scan_layers=True, schedule=schedule)
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, 256, (4, 16)), jnp.int32)
    optimizer = AdamW(lr=1e-3)
    state = init_state(module, optimizer, tokens[:1, :8])
    state = ShardingPolicy(rules=GPT2.partition_rules(), fsdp=True,
                           fsdp_min_size=64).place(state, mesh)
    placed = jax.device_put(tokens, batch_sharding(mesh))
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer,
                            jit=False)

    traces = []

    def counting_step(state, inputs, targets):
        traces.append(1)          # runs at trace time only
        return step(state, inputs, targets)

    runner = jax.jit(counting_step)
    for _ in range(3):
        state, _ = runner(state, placed, placed)
    assert len(traces) == 1, (
        f'scheduled train step retraced: {len(traces)} traces for 3 steps')
    if hasattr(runner, '_cache_size'):    # recompile guard, where exposed
        assert runner._cache_size() == 1


# ---------------------------------------------------------------------------
# the pp= and moe= arms: pipeline p2p + expert all-to-all under the schedule
# ---------------------------------------------------------------------------


from tpusystem.models import GPT2Pipelined, gpt2_tiny  # noqa: E402
from tpusystem.parallel import (PipelineParallel, moe_plan,  # noqa: E402
                                pipeline_apply, pp_plan)
from tpusystem.parallel.mesh import partial_manual_skip_reason  # noqa: E402
from tpusystem.train import (AdamW, NextTokenLoss, WithAuxLoss,  # noqa: E402
                             build_train_step, flax_apply, init_state)

_PARTIAL_MANUAL_REASON = partial_manual_skip_reason()
needs_partial_manual = pytest.mark.skipif(
    _PARTIAL_MANUAL_REASON is not None,
    reason=_PARTIAL_MANUAL_REASON or 'partial-manual shard_map supported')


def test_overlap_schedule_validates_the_new_arms():
    with pytest.raises(ValueError, match='schedule pp'):
        OverlapSchedule(pp='sometimes')
    with pytest.raises(ValueError, match='schedule moe'):
        OverlapSchedule(moe='magic')
    # the new arms participate in identity and equality like the old ones
    a = OverlapSchedule(pp='overlap', moe='overlap')
    assert a != OverlapSchedule() and hash(a) != hash(OverlapSchedule())
    assert 'pp=' in repr(a) and 'moe=' in repr(a)
    # for_policy threads them through the policy pairing
    policy = ShardingPolicy(rules=(), fsdp=True, fsdp_min_size=64)
    paired = OverlapSchedule.for_policy(policy, tp='overlap', pp='overlap',
                                        moe='overlap')
    assert (paired.pp, paired.moe) == ('overlap', 'overlap')
    assert paired.fsdp_min_size == 64
    # the legacy-knob fold keeps both new arms on gspmd (old behavior)
    legacy = resolve_schedule(None, 'overlap', 2)
    assert (legacy.pp, legacy.moe) == ('gspmd', 'gspmd')


def test_pp_plan_pins_paths():
    # no stage axis: nothing to hide
    plan = pp_plan(4, 1)
    assert plan.path == 'skip' and 'axis_size' in plan.reason
    # chunks that cannot tile the microbatch rows: classic ticks
    plan = pp_plan(3, 4, chunks=2)
    assert plan.path == 'one-shot' and 'chunks' in plan.reason
    # the interleaved schedule owns its ticks
    plan = pp_plan(4, 4, chunks=1, interleave=2)
    assert plan.path == 'one-shot' and 'interleaved' in plan.reason
    # plain GPipe with tiling rows: the skewed overlap schedule
    plan = pp_plan(4, 4, chunks=2)
    assert plan == pp_plan(4, 4, chunks=2)
    assert plan.path == 'overlap' and plan.chunks == 2


def test_moe_plan_pins_paths():
    plan = moe_plan(8, 1)
    assert plan.path == 'skip' and 'axis_size' in plan.reason
    # ragged exchanges seat at the receiver: not pipelined today
    for exchange in ('ragged', 'ragged-emulated'):
        plan = moe_plan(8, 2, exchange=exchange)
        assert plan.path == 'one-shot' and 'receiver' in plan.reason
    # rows that won't split into pieces
    plan = moe_plan(5, 2)
    assert plan.path == 'one-shot' and 'split' in plan.reason
    plan = moe_plan(8, 2)
    assert plan.path == 'overlap' and plan.pieces == 2


def _pp_stack():
    layers, batch, dim = 8, 8, 16
    keys = jax.random.split(jax.random.PRNGKey(0), layers)
    weights = jax.vmap(lambda key: jax.random.normal(key, (dim, dim)) / dim)(
        keys)
    inputs = jax.random.normal(jax.random.PRNGKey(1), (batch, dim))
    block_fn = lambda lp, x: jnp.tanh(x @ lp['w'])
    return weights, inputs, block_fn


@pytest.mark.parametrize('chunks', [1, 2])
def test_pp_overlap_gpipe_is_bitwise_vs_classic(chunks):
    """The skewed schedule computes identical math on identical operands
    (the hops are pure copies), so outputs AND gradients are bitwise-
    equal to the classic GPipe tick — in any dtype, the strongest form
    of the f32-bitwise parity contract."""
    mesh = MeshSpec(stage=4, data=2).build()
    weights, inputs, block_fn = _pp_stack()
    schedule = OverlapSchedule(pp='overlap', chunks=chunks)
    assert pp_plan(2, 4, chunks=chunks).path == 'overlap'

    classic = pipeline_apply(block_fn, {'w': weights}, inputs, mesh,
                             microbatches=2)
    skewed = pipeline_apply(block_fn, {'w': weights}, inputs, mesh,
                            microbatches=2, schedule=schedule)
    np.testing.assert_array_equal(np.asarray(classic), np.asarray(skewed))

    def loss(sched):
        def inner(w):
            out = pipeline_apply(block_fn, {'w': w}, inputs, mesh,
                                 microbatches=2, schedule=sched)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return inner

    g_classic = jax.jit(jax.grad(loss(None)))(weights)
    g_skewed = jax.jit(jax.grad(loss(schedule)))(weights)
    np.testing.assert_array_equal(np.asarray(g_classic),
                                  np.asarray(g_skewed))


def test_pp_overlap_fallback_when_chunks_cannot_tile():
    """Microbatch rows that won't split into the requested chunks pin the
    classic schedule (pp_plan) — and the run stays correct."""
    mesh = MeshSpec(stage=4, data=2).build()
    weights, inputs, block_fn = _pp_stack()
    # local batch 4 over 2 microbatches = 2 rows; chunks=3 cannot tile
    assert pp_plan(2, 4, chunks=3).path == 'one-shot'
    schedule = OverlapSchedule(pp='overlap', chunks=3)
    out = pipeline_apply(block_fn, {'w': weights}, inputs, mesh,
                         microbatches=2, schedule=schedule)
    reference = pipeline_apply(block_fn, {'w': weights}, inputs, mesh,
                               microbatches=2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(reference))


def _moe_mesh():
    return MeshSpec(data=2, expert=2).build(jax.devices()[:4])


def _moe_tokens(seed=0, batch=8, seq=32):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 256, (batch, seq)), jnp.int32)


def _moe_loss_and_grads(schedule, mesh, tokens, **overrides):
    config = dict(dim=64, heads=4, mesh=mesh, moe_experts=2, moe_every=2,
                  moe_capacity_factor=2.0, dtype='float32',
                  schedule=schedule)
    config.update(overrides)
    module = gpt2_tiny(**config)
    optimizer = AdamW(lr=1e-3)
    state = init_state(module, optimizer, tokens[:1], rng=0)
    state = ShardingPolicy(rules=module.partition_rules()).place(state, mesh)
    placed = jax.device_put(tokens, batch_sharding(mesh))
    criterion = WithAuxLoss(NextTokenLoss())
    apply_fn = flax_apply(module)

    def loss(params):
        return criterion(apply_fn(params, placed, None, True), placed)

    value, grads = jax.jit(jax.value_and_grad(loss))(state.params)
    return state.params, float(value), grads


def test_moe_overlap_dispatch_matches_gspmd_model_level():
    """moe='overlap' on the sharded quota path: the pipelined dispatch
    (piece k+1's all_to_all under the expert matmuls of k) reproduces
    the one-shot exchange — loss BITWISE in f32 at ample capacity
    (routing runs unsplit; the FFN and combine are row-independent),
    grads f32-tight (only backward summation order differs), identical
    param trees."""
    mesh = _moe_mesh()
    tokens = _moe_tokens()
    p_ref, l_ref, g_ref = _moe_loss_and_grads(None, mesh, tokens)
    p_ovl, l_ovl, g_ovl = _moe_loss_and_grads(
        OverlapSchedule(moe='overlap'), mesh, tokens)
    assert (jax.tree_util.tree_structure(p_ref)
            == jax.tree_util.tree_structure(p_ovl))
    for ref, ovl in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_ovl)):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(ovl))
    assert l_ref == l_ovl, (l_ref, l_ovl)
    for ref, ovl in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_ovl)):
        np.testing.assert_allclose(np.asarray(ref), np.asarray(ovl),
                                   rtol=1e-4, atol=1e-6)


def test_moe_overlap_ragged_exchange_falls_back_one_shot():
    """The ragged exchange keeps its single whole-batch exchange under
    moe='overlap' (pinned by moe_plan) — the knob degrades to the
    documented fallback instead of changing semantics or crashing."""
    assert moe_plan(8, 2, exchange='ragged-emulated').path == 'one-shot'
    mesh = _moe_mesh()
    tokens = _moe_tokens(seed=1)
    _, l_ref, _ = _moe_loss_and_grads(None, mesh, tokens,
                                      moe_exchange='ragged-emulated')
    _, l_ovl, _ = _moe_loss_and_grads(OverlapSchedule(moe='overlap'), mesh,
                                      tokens,
                                      moe_exchange='ragged-emulated')
    assert l_ref == l_ovl, (l_ref, l_ovl)


def _pipelined_moe_losses(schedule, mesh, tokens, steps=3, **overrides):
    config = dict(vocab_size=256, layers=4, dim=48, heads=4, max_seq=64,
                  dtype='float32', microbatches=2, mesh=mesh,
                  moe_experts=2, moe_every=2, moe_capacity_factor=2.0,
                  schedule=schedule)
    config.update(overrides)
    model = GPT2Pipelined(**config)
    optimizer = AdamW(lr=1e-3)
    state = init_state(model, optimizer, tokens[:1], rng=0)
    state = PipelineParallel(
        stacked_rules=GPT2Pipelined.block_partition_rules(),
        fsdp=True, fsdp_min_size=64).place(state, mesh)
    placed = jax.device_put(tokens, batch_sharding(mesh))
    step = build_train_step(flax_apply(model), WithAuxLoss(NextTokenLoss()),
                            optimizer)
    losses = []
    for _ in range(steps):
        state, (_, loss) = step(state, placed, placed)
        losses.append(float(loss))
    return state, losses


def test_composed_pp_fsdp_moe_pipelined_step_is_bitwise_vs_gspmd():
    """The composed arms on a dp x fsdp x stage mesh (fully-manual
    pipeline — runs on every jaxlib): a pipelined MoE GPT-2 under
    OverlapSchedule(pp='overlap', fsdp='prefetch', moe='overlap') trains
    BITWISE-equal losses and params to the all-GSPMD reference across 3
    steps — pp reschedules pure copies; fsdp/moe arms degrade per their
    plans inside the pipe (the blocks see mesh=None) and bite on the
    non-pipelined meshes their own tests cover."""
    mesh = MeshSpec(data=2, fsdp=2, stage=2).build()
    tokens = _moe_tokens(seed=2, batch=16)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', pp='overlap',
                               moe='overlap', fsdp_min_size=64)
    s_ref, l_ref = _pipelined_moe_losses(None, mesh, tokens)
    s_ovl, l_ovl = _pipelined_moe_losses(schedule, mesh, tokens)
    assert l_ref == l_ovl, (l_ref, l_ovl)
    assert (jax.tree_util.tree_structure(s_ref.params)
            == jax.tree_util.tree_structure(s_ovl.params))
    for ref, ovl in zip(jax.tree.leaves(s_ref.params),
                        jax.tree.leaves(s_ovl.params)):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(ovl))


@needs_partial_manual
def test_composed_pp_tp_fsdp_moe_pipelined_step_matches_gspmd():
    """The full four-axis composition (dp-free fsdp x model x stage mesh,
    partial-manual pipeline: GSPMD partitions the stage bodies over
    `model`): losses bitwise vs the all-GSPMD reference."""
    mesh = MeshSpec(fsdp=2, model=2, stage=2).build()
    tokens = _moe_tokens(seed=3, batch=16)
    schedule = OverlapSchedule(tp='overlap', fsdp='prefetch', pp='overlap',
                               moe='overlap', fsdp_min_size=64)
    s_ref, l_ref = _pipelined_moe_losses(None, mesh, tokens)
    s_ovl, l_ovl = _pipelined_moe_losses(schedule, mesh, tokens)
    assert l_ref == l_ovl, (l_ref, l_ovl)
    for ref, ovl in zip(jax.tree.leaves(s_ref.params),
                        jax.tree.leaves(s_ovl.params)):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(ovl))


def test_compile_guard_composed_pipelined_step_never_retraces():
    """The PR-1 pipeline retrace bug class, guarded for the new arms: the
    composed pp/moe-scheduled train step traces exactly once across
    steps."""
    mesh = MeshSpec(data=2, fsdp=2, stage=2).build()
    tokens = _moe_tokens(seed=4, batch=16)
    schedule = OverlapSchedule(pp='overlap', moe='overlap',
                               fsdp='prefetch', fsdp_min_size=64)
    model = GPT2Pipelined(vocab_size=256, layers=4, dim=48, heads=4,
                          max_seq=64, dtype='float32', microbatches=2,
                          mesh=mesh, moe_experts=2, moe_every=2,
                          moe_capacity_factor=2.0, schedule=schedule)
    optimizer = AdamW(lr=1e-3)
    state = init_state(model, optimizer, tokens[:1], rng=0)
    state = PipelineParallel(fsdp=True, fsdp_min_size=64).place(state, mesh)
    placed = jax.device_put(tokens, batch_sharding(mesh))
    raw = build_train_step(flax_apply(model), WithAuxLoss(NextTokenLoss()),
                           optimizer, jit=False)

    traces = []

    def counting_step(state, inputs, targets):
        traces.append(1)          # runs at trace time only
        return raw(state, inputs, targets)

    runner = jax.jit(counting_step)
    loss = None
    for _ in range(3):
        state, (_, loss) = runner(state, placed, placed)
    assert np.isfinite(float(loss)), float(loss)
    assert len(traces) == 1, (
        f'composed pipelined step retraced: {len(traces)} traces for 3 steps')


def test_pipelined_moe_rejects_1f1b_and_interleave():
    from tpusystem.train import build_1f1b_train_step
    mesh = MeshSpec(data=2, stage=2).build(jax.devices()[:4])
    with pytest.raises(ValueError, match='interleave'):
        GPT2Pipelined(vocab_size=64, layers=4, dim=32, heads=2, max_seq=32,
                      mesh=mesh, moe_experts=2, interleave=2)
    model = GPT2Pipelined(vocab_size=64, layers=4, dim=32, heads=2,
                          max_seq=32, dtype='float32', microbatches=2,
                          mesh=mesh, moe_experts=2)
    with pytest.raises(ValueError, match='MoE spans'):
        build_1f1b_train_step(model, NextTokenLoss(), AdamW(lr=1e-3))
