"""Observability consumers: logging, TensorBoard event files, tracking.

Test pattern is the reference's canonical one
(``examples/tinysys/tests/test_storage.py:33-66``): forge events directly,
point DI overrides at test fixtures, assert the stored state — the
framework itself is never mocked.
"""

import logging
import struct

import pytest

from tpusystem.checkpoint import Repository
from tpusystem.observe import (
    Iterated, StepTimed, SummaryWriter, Trained, Validated,
    checkpoint_consumer, logging_consumer, tensorboard_consumer,
    tracking_consumer,
)
from tpusystem.observe import tensorboard as tensorboard_module
from tpusystem.observe import tracking
from tpusystem.storage import (
    DocumentIterations, DocumentMetrics, DocumentModels, DocumentModules,
    DocumentStore,
)


class Model:
    """Host-side stand-in satisfying the aggregate surface consumers use."""

    def __init__(self, identity='hash-1', epoch=3):
        self.id = identity
        self.epoch = epoch
        self.state = {'w': [1.0, 2.0]}
        self._parts = {}

    def modules(self):
        return self._parts


def test_logging_consumer_reports_each_event(caplog):
    consumer = logging_consumer()
    model = Model()
    with caplog.at_level(logging.INFO, logger='tpusystem'):
        consumer.consume(Trained(model, {'loss': 0.5}))
        consumer.consume(Validated(model, {'accuracy': 0.9}))
        consumer.consume(Iterated(model))
        consumer.consume(StepTimed(model, 'train', steps=100, seconds=2.0))
    text = caplog.text
    assert 'loss: 0.5000' in text and 'accuracy: 0.9000' in text
    assert 'hash-1' in text and '50.0 steps/s' in text


# --- minimal TFRecord/Event readers to verify the on-disk format ---------

def read_records(path):
    records = []
    with open(path, 'rb') as handle:
        while header := handle.read(8):
            (length,) = struct.unpack('<Q', header)
            handle.read(4)                      # length crc
            records.append(handle.read(length))
            handle.read(4)                      # payload crc
    return records


def parse_scalars(record):
    """Extract {tag: (value, step)} from a serialized Event proto."""
    import io
    scalars = {}

    def varint(stream):
        shift = result = 0
        while True:
            byte = stream.read(1)[0]
            result |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return result
            shift += 7

    def walk(data, step):
        stream = io.BytesIO(data)
        fields = {}
        while stream.tell() < len(data):
            key = varint(stream)
            field, wire = key >> 3, key & 7
            if wire == 0:
                fields[field] = varint(stream)
            elif wire == 1:
                fields[field] = struct.unpack('<d', stream.read(8))[0]
            elif wire == 5:
                fields[field] = struct.unpack('<f', stream.read(4))[0]
            elif wire == 2:
                fields.setdefault(field, []).append(stream.read(varint(stream)))
        return fields

    top = walk(record, 0)
    step = top.get(2, 0)
    for summary in top.get(5, []):
        for value in walk(summary, step).get(1, []):
            fields = walk(value, step)
            tag = fields[1][0].decode()
            scalars[tag] = (fields[2], step)
    return scalars


def test_summary_writer_emits_valid_tfrecord_events(tmp_path):
    writer = SummaryWriter(tmp_path / 'run')
    writer.add_scalar('loss', 0.25, step=7)
    writer.add_scalars('metrics', {'a': 1.0, 'b': 2.0}, step=8)
    writer.close()
    (event_file,) = list((tmp_path / 'run').iterdir())
    records = read_records(event_file)
    assert len(records) == 4                      # version + 3 scalars
    assert b'brain.Event:2' in records[0]
    scalars = {}
    for record in records[1:]:
        scalars.update(parse_scalars(record))
    assert scalars['loss'] == (0.25, 7)
    assert scalars['metrics/a'] == (1.0, 8) and scalars['metrics/b'] == (2.0, 8)


def test_tensorboard_consumer_charts_per_phase(tmp_path):
    consumer = tensorboard_consumer()
    writer = SummaryWriter(tmp_path / 'run')
    consumer.dependency_overrides[tensorboard_module.writer] = lambda: writer
    model = Model(identity='m1', epoch=2)
    consumer.consume(Trained(model, {'loss': 0.5}))
    consumer.consume(Validated(model, {'loss': 0.4}))
    writer.close()
    (event_file,) = list((tmp_path / 'run').iterdir())
    scalars = {}
    for record in read_records(event_file)[1:]:
        scalars.update(parse_scalars(record))
    assert scalars['m1/loss/train'] == (0.5, 2)
    value, step = scalars['m1/loss/evaluation']
    assert value == pytest.approx(0.4) and step == 2


@pytest.fixture()
def tracked(tmp_path):
    store = DocumentStore(tmp_path / 'db.json')
    consumer = tracking_consumer()
    saver = checkpoint_consumer()
    fixtures = {
        'metrics': DocumentMetrics(store),
        'models': DocumentModels(store),
        'modules': DocumentModules(store),
        'iterations': DocumentIterations(store),
        'repository': Repository(tmp_path / 'weights', async_save=False),
    }
    overrides = consumer.dependency_overrides
    overrides[tracking.metrics_store] = lambda: fixtures['metrics']
    overrides[tracking.models_store] = lambda: fixtures['models']
    overrides[tracking.modules_store] = lambda: fixtures['modules']
    overrides[tracking.iterations_store] = lambda: fixtures['iterations']
    overrides[tracking.repository] = lambda: fixtures['repository']
    overrides[tracking.experiment] = lambda: 'exp-test'
    saver.dependency_overrides[tracking.repository] = lambda: fixtures['repository']
    yield (consumer, saver), fixtures
    fixtures['repository'].close()


def test_tracking_consumer_persists_metrics_and_epoch(tracked):
    (consumer, _), fixtures = tracked
    model = Model(identity='m1', epoch=4)
    consumer.consume(Trained(model, {'loss': 0.33}))
    consumer.consume(Validated(model, {'loss': 0.44, 'accuracy': 0.9}))
    rows = fixtures['metrics'].list('m1')
    assert {(r.name, r.phase) for r in rows} == {
        ('loss', 'train'), ('loss', 'evaluation'), ('accuracy', 'evaluation')}
    assert all(r.epoch == 4 for r in rows)

    consumer.consume(Iterated(model))
    assert fixtures['models'].read('m1', 'exp-test').epoch == 4


def test_tracking_consumer_persists_module_metadata_and_weights(tracked):
    from tpusystem.models import MLP
    from tpusystem.data import Loader, SyntheticDigits

    (consumer, saver), fixtures = tracked
    model = Model(identity='m2', epoch=1)
    network = MLP(features=(8,), classes=4)
    model._parts = {'nn': network, 'criterion': object()}
    loader = Loader(SyntheticDigits(samples=16, seed=0), batch_size=4)
    event = Iterated(model, loaders={'train': loader})
    consumer.consume(event)
    saver.consume(event)   # all-hosts consumer: collective sharded save

    by_kind = {row.kind: row for row in fixtures['modules'].list('m2')}
    assert by_kind['nn'].name == 'MLP'
    assert by_kind['nn'].arguments == {'features': (8,), 'classes': 4}
    assert by_kind['criterion'].hash is None   # unregistered degrades to name

    (iteration,) = fixtures['iterations'].list('m2')
    assert iteration.phase == 'train' and iteration.name == 'Loader'

    # weights snapshotted under the aggregate id at its epoch
    assert fixtures['repository'].latest(model) == 1
