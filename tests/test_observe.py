"""Observability consumers: logging, TensorBoard event files, tracking.

Test pattern is the reference's canonical one
(``examples/tinysys/tests/test_storage.py:33-66``): forge events directly,
point DI overrides at test fixtures, assert the stored state — the
framework itself is never mocked.
"""

import dataclasses
import logging
import pathlib

import pytest

from tpusystem.checkpoint import Repository
from tpusystem.observe import (
    Iterated, StepTimed, SummaryWriter, Trained, Validated,
    checkpoint_consumer, logging_consumer, tensorboard_consumer,
    tracking_consumer,
)
from tpusystem.observe import tensorboard as tensorboard_module
from tpusystem.observe import tracking
from tpusystem.storage import (
    DocumentIterations, DocumentMetrics, DocumentModels, DocumentModules,
    DocumentStore,
)


class Model:
    """Host-side stand-in satisfying the aggregate surface consumers use."""

    def __init__(self, identity='hash-1', epoch=3):
        self.id = identity
        self.epoch = epoch
        self.state = {'w': [1.0, 2.0]}
        self._parts = {}

    def modules(self):
        return self._parts


def test_logging_consumer_reports_each_event(caplog):
    consumer = logging_consumer()
    model = Model()
    with caplog.at_level(logging.INFO, logger='tpusystem'):
        consumer.consume(Trained(model, {'loss': 0.5}))
        consumer.consume(Validated(model, {'accuracy': 0.9}))
        consumer.consume(Iterated(model))
        consumer.consume(StepTimed(model, 'train', steps=100, seconds=2.0))
    text = caplog.text
    assert 'loss: 0.5000' in text and 'accuracy: 0.9000' in text
    assert 'hash-1' in text and '50.0 steps/s' in text


# --- the minimal TFRecord/Event reader lives in tests/tb.py (shared by
# every TB-handler test, so assertions parse tags/values back instead of
# byte-poking); the format test below still exercises it end to end ------

from tests.tb import parse_scalars, read_records, read_scalars  # noqa: E402


def test_summary_writer_emits_valid_tfrecord_events(tmp_path):
    writer = SummaryWriter(tmp_path / 'run')
    writer.add_scalar('loss', 0.25, step=7)
    writer.add_scalars('metrics', {'a': 1.0, 'b': 2.0}, step=8)
    writer.close()
    (event_file,) = list((tmp_path / 'run').iterdir())
    records = read_records(event_file)
    assert len(records) == 4                      # version + 3 scalars
    assert b'brain.Event:2' in records[0]
    scalars = {}
    for record in records[1:]:
        scalars.update(parse_scalars(record))
    assert scalars['loss'] == (0.25, 7)
    assert scalars['metrics/a'] == (1.0, 8) and scalars['metrics/b'] == (2.0, 8)


def test_tensorboard_consumer_charts_per_phase(tmp_path):
    consumer = tensorboard_consumer()
    writer = SummaryWriter(tmp_path / 'run')
    consumer.dependency_overrides[tensorboard_module.writer] = lambda: writer
    model = Model(identity='m1', epoch=2)
    consumer.consume(Trained(model, {'loss': 0.5}))
    consumer.consume(Validated(model, {'loss': 0.4}))
    writer.close()
    (event_file,) = list((tmp_path / 'run').iterdir())
    scalars = {}
    for record in read_records(event_file)[1:]:
        scalars.update(parse_scalars(record))
    assert scalars['m1/loss/train'] == (0.5, 2)
    value, step = scalars['m1/loss/evaluation']
    assert value == pytest.approx(0.4) and step == 2


@pytest.fixture()
def tracked(tmp_path):
    store = DocumentStore(tmp_path / 'db.json')
    consumer = tracking_consumer()
    saver = checkpoint_consumer()
    fixtures = {
        'metrics': DocumentMetrics(store),
        'models': DocumentModels(store),
        'modules': DocumentModules(store),
        'iterations': DocumentIterations(store),
        'repository': Repository(tmp_path / 'weights', async_save=False),
    }
    overrides = consumer.dependency_overrides
    overrides[tracking.metrics_store] = lambda: fixtures['metrics']
    overrides[tracking.models_store] = lambda: fixtures['models']
    overrides[tracking.modules_store] = lambda: fixtures['modules']
    overrides[tracking.iterations_store] = lambda: fixtures['iterations']
    overrides[tracking.repository] = lambda: fixtures['repository']
    overrides[tracking.experiment] = lambda: 'exp-test'
    saver.dependency_overrides[tracking.repository] = lambda: fixtures['repository']
    yield (consumer, saver), fixtures
    fixtures['repository'].close()


def test_tracking_consumer_persists_metrics_and_epoch(tracked):
    (consumer, _), fixtures = tracked
    model = Model(identity='m1', epoch=4)
    consumer.consume(Trained(model, {'loss': 0.33}))
    consumer.consume(Validated(model, {'loss': 0.44, 'accuracy': 0.9}))
    rows = fixtures['metrics'].list('m1')
    assert {(r.name, r.phase) for r in rows} == {
        ('loss', 'train'), ('loss', 'evaluation'), ('accuracy', 'evaluation')}
    assert all(r.epoch == 4 for r in rows)

    consumer.consume(Iterated(model))
    assert fixtures['models'].read('m1', 'exp-test').epoch == 4


def test_tracking_consumer_persists_module_metadata_and_weights(tracked):
    from tpusystem.models import MLP
    from tpusystem.data import Loader, SyntheticDigits

    (consumer, saver), fixtures = tracked
    model = Model(identity='m2', epoch=1)
    network = MLP(features=(8,), classes=4)
    model._parts = {'nn': network, 'criterion': object()}
    loader = Loader(SyntheticDigits(samples=16, seed=0), batch_size=4)
    event = Iterated(model, loaders={'train': loader})
    consumer.consume(event)
    saver.consume(event)   # all-hosts consumer: collective sharded save

    by_kind = {row.kind: row for row in fixtures['modules'].list('m2')}
    assert by_kind['nn'].name == 'MLP'
    assert by_kind['nn'].arguments == {'features': (8,), 'classes': 4}
    assert by_kind['criterion'].hash is None   # unregistered degrades to name

    (iteration,) = fixtures['iterations'].list('m2')
    assert iteration.phase == 'train' and iteration.name == 'Loader'

    # weights snapshotted under the aggregate id at its epoch
    assert fixtures['repository'].latest(model) == 1


# --- the event-inventory drift guard -------------------------------------
# Every dataclass event must either have a TensorBoard handler or sit on
# the explicit exemption list below (with its reason), and every event
# name must appear in docs/observability.md — the inventory can no longer
# silently outgrow its charts or its docs.

# events that deliberately have NO TensorBoard chart; each entry names why
TB_EXEMPT = {
    'Iterated',             # an epoch edge — the checkpoint/tracking
                            # consumers' trigger, nothing scalar to chart
    'StepTimed',            # throughput is charted from Trained metrics;
                            # StepTimed feeds the logging consumer
    'RequestEvicted',       # a cancellation is caller intent, not system
                            # state; completions/expiries carry the charts
    'RequestReplayed',      # EngineRestarted charts replayed/resubmitted
                            # counts; per-row detail lives on the trace
    'TokenStreamed',        # per-token volume would swamp the board;
                            # TTFT and latency ride RequestAdmitted /
                            # RequestCompleted, throughput ServeStepped
    'RouterDeposed',        # the deposed zombie exits 47 before any board
                            # flush; the standby's RouterTakeover charts
                            # the takeover, WorkerExited the halt verdict
    'WorkerRelaunched',     # WorkerExited's per-rank exit chart already
                            # counts every relaunch verdict
    'WorldResizeProposed',  # proposals can outnumber commits under churn;
                            # WorldResized charts the committed epochs
}


def _event_classes():
    from tpusystem.observe import events as events_module
    return [value for value in vars(events_module).values()
            if dataclasses.is_dataclass(value) and isinstance(value, type)
            and value.__module__ == events_module.__name__]


def test_every_event_has_a_tb_handler_or_an_explicit_exemption():
    from tpusystem.observe.metrics import serve_metrics_consumer
    consumer = tensorboard_consumer()
    charted = {cls.__name__ for cls in consumer.types.values()}
    charted |= {cls.__name__
                for cls in serve_metrics_consumer().types.values()}
    classes = _event_classes()
    assert classes, 'found no event dataclasses'
    missing = [cls.__name__ for cls in classes
               if cls.__name__ not in charted
               and cls.__name__ not in TB_EXEMPT]
    assert not missing, (
        f'events with neither a TensorBoard handler nor an entry on the '
        f'TB_EXEMPT list (add a chart or an exemption WITH its reason): '
        f'{missing}')
    stale = sorted(TB_EXEMPT & charted)
    assert not stale, f'exempted events that ARE charted now: {stale}'


def test_every_event_is_documented_in_observability_md():
    docs = (pathlib.Path(__file__).parent.parent / 'docs'
            / 'observability.md').read_text()
    missing = [cls.__name__ for cls in _event_classes()
               if cls.__name__ not in docs]
    assert not missing, (
        f'events missing from docs/observability.md (add them to the '
        f'event table): {missing}')


# --- profile.trace: only stop what was started ---------------------------

def test_trace_refuses_double_start_with_typed_error(monkeypatch):
    """A failed start_trace (trace already active) must surface as the
    typed ProfilerBusy carrying the ORIGINAL error — and must NOT run
    stop_trace, which would kill the pre-existing trace and mask the
    real problem with a second 'no trace running' error."""
    import jax

    from tpusystem.observe import ProfilerBusy, trace

    calls = []
    monkeypatch.setattr(
        jax.profiler, 'start_trace',
        lambda logdir: (_ for _ in ()).throw(
            RuntimeError('Only one profile may be run at a time.')))
    monkeypatch.setattr(jax.profiler, 'stop_trace',
                        lambda: calls.append('stop'))
    with pytest.raises(ProfilerBusy, match='already active'):
        with trace('/tmp/unused'):
            raise AssertionError('body must not run on a failed start')
    assert calls == [], 'stop_trace ran for a trace that never started'


def test_trace_stops_what_it_started(monkeypatch):
    import jax

    from tpusystem.observe import trace

    calls = []
    monkeypatch.setattr(jax.profiler, 'start_trace',
                        lambda logdir: calls.append(('start', logdir)))
    monkeypatch.setattr(jax.profiler, 'stop_trace',
                        lambda: calls.append('stop'))
    with trace('/tmp/logs'):
        pass
    assert calls == [('start', '/tmp/logs'), 'stop']
    # the body's own exception still stops the trace it started
    calls.clear()
    with pytest.raises(ValueError):
        with trace('/tmp/logs'):
            raise ValueError('body failed')
    assert calls == [('start', '/tmp/logs'), 'stop']


# --- fleet/* charts, parsed back (the previously untested handlers) ------

def test_tensorboard_fleet_charts_parse_back(tmp_path):
    from tpusystem.observe.events import (FleetResized, ReplicaUnhealthy,
                                          RequestRerouted)

    consumer = tensorboard_consumer()
    writer = SummaryWriter(tmp_path / 'run')
    consumer.dependency_overrides[tensorboard_module.writer] = lambda: writer
    consumer.consume(ReplicaUnhealthy(name='rep0', cause='died mid-step',
                                      routed=3))
    consumer.consume(RequestRerouted(id='a', origin='rep0', target='rep1',
                                     where='hot', prefix=4,
                                     cause='failover'))
    consumer.consume(RequestRerouted(id='b', origin='rep0', target='rep2',
                                     where='cold', prefix=0,
                                     cause='failover'))
    consumer.consume(FleetResized(action='grow', replicas=4,
                                  cause='backpressure', name='rep3'))
    writer.close()
    scalars = read_scalars(tmp_path / 'run', history=True)
    assert scalars['fleet/unhealthy_total'] == [(1.0, 1)]
    assert scalars['fleet/rehomed_requests'] == [(3.0, 1)]
    # per reroute: a running total and the hot prefix carried over
    assert scalars['fleet/rerouted_total'] == [(1.0, 1), (2.0, 2)]
    assert scalars['fleet/reroute_prefix'] == [(4.0, 1), (0.0, 2)]
    assert scalars['fleet/replicas'] == [(4.0, 1)]
