"""Mixture-of-experts and expert parallelism over the ``expert`` mesh axis.

The reference has only a dense MLP (SURVEY.md §2.4: "EP/MoE | absent").
Coverage: routing algebra (capacity, drops, gate renormalization), the MoE
layer's dense-equivalence limit, and an expert-parallel GPT-2 train step on
a simulated (data x expert) mesh.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.models import GPT2
from tpusystem.ops import MoEMLP, expert_capacity, route_top_k
from tpusystem.parallel import MeshSpec, ShardingPolicy, batch_sharding
from tpusystem.train import (AdamW, NextTokenLoss, WithAuxLoss,
                             build_train_step, flax_apply, init_state)


def test_route_top_k_seats_every_token_with_ample_capacity():
    gates = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(0), (16, 4)))
    dispatch, combine, fraction = route_top_k(gates, k=2, capacity=16)
    # every token seated for both choices
    np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))), 2.0)
    # combine weights renormalize the chosen gates to sum to 1 per token
    np.testing.assert_allclose(np.asarray(combine.sum((1, 2))), 1.0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(fraction.sum()), 1.0, rtol=1e-6)


def test_route_top_k_respects_capacity_and_drops_overflow():
    # all 8 tokens want expert 0 first; capacity 2 seats only the first 2
    gates = jnp.tile(jnp.asarray([[0.7, 0.3, 0.0, 0.0]]), (8, 1))
    dispatch, combine, _ = route_top_k(gates, k=1, capacity=2)
    per_expert = np.asarray(dispatch.sum((0, 2)))
    assert per_expert[0] == 2.0, per_expert
    assert per_expert[1:].sum() == 0.0, per_expert
    seated_tokens = np.asarray(dispatch.sum((1, 2)))
    np.testing.assert_array_equal(seated_tokens[:2], 1.0)
    np.testing.assert_array_equal(seated_tokens[2:], 0.0)


def test_first_choices_seat_before_second_choices():
    # token 0's first choice and token 1's second choice collide on expert 0
    # with capacity 1: the first choice must win regardless of token order
    gates = jnp.asarray([[0.9, 0.1, 0.0, 0.0]] * 1 + [[0.1, 0.9, 0.0, 0.0]] * 1)
    gates = jnp.concatenate([gates[1:], gates[:1]])  # token 0 prefers e1, token 1 prefers e0
    dispatch, _, _ = route_top_k(gates, k=2, capacity=1)
    # expert 0: token 1 (first choice) seated; token 0's second choice dropped
    expert0 = np.asarray(dispatch[:, 0].sum(-1))
    np.testing.assert_array_equal(expert0, [0.0, 1.0])


@pytest.mark.slow
def test_moe_single_expert_matches_dense_ffn():
    """experts=1, k=1, ample capacity reduces to a plain FFN."""
    layer = MoEMLP(experts=1, k=1, capacity_factor=4.0, dtype=jnp.float32)
    hidden = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    variables = layer.init(jax.random.PRNGKey(1), hidden)
    output, aux = layer.apply(variables, hidden)
    params = variables['params']
    dense = jax.nn.gelu(hidden.reshape(-1, 16) @ params['w1'][0] + params['b1'][0])
    dense = dense @ params['w2'][0] + params['b2'][0]
    np.testing.assert_allclose(np.asarray(output.reshape(-1, 16)),
                               np.asarray(dense), rtol=1e-4, atol=1e-5)
    assert float(aux) > 0.0


def test_expert_capacity_bounds():
    assert expert_capacity(128, 8, 2, 1.0) == 32
    assert expert_capacity(4, 8, 1, 1.0) == 1       # floor of 1
    assert expert_capacity(8, 2, 2, 100.0) == 8     # ceiling of all tokens


@pytest.mark.slow
def test_moe_gpt2_expert_parallel_train_step():
    mesh = MeshSpec(data=2, expert=4).build()
    model = GPT2(vocab_size=64, layers=2, dim=32, heads=4, max_seq=32,
                 dropout=0.0, dtype='float32', moe_experts=4, moe_every=2,
                 moe_k=2, mesh=mesh)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 64, (8, 16)))
    optimizer = AdamW(lr=1e-2)
    state = init_state(model, optimizer, tokens[:2])
    policy = ShardingPolicy(rules=GPT2.partition_rules())
    state = policy.place(state, mesh)
    # stacked expert weights actually sharded over the expert axis
    spec = state.params['h_1']['moe']['w1'].sharding.spec
    assert spec[0] == 'expert', spec
    tokens = jax.device_put(tokens, batch_sharding(mesh))

    step = build_train_step(flax_apply(model), WithAuxLoss(NextTokenLoss()),
                            optimizer)
    losses = []
    for _ in range(4):
        state, (outputs, loss) = step(state, tokens, tokens)
        losses.append(float(loss))
    logits, aux = outputs
    assert logits.shape == (8, 16, 64)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_sparse_routing_matches_dense_routing():
    """route_top_k_sparse seats exactly the tokens route_top_k seats, in the
    same slots with the same combine weights (choice-major priority)."""
    from tpusystem.ops.moe import route_top_k_sparse
    gates = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(3), (32, 4)) * 2)
    for k, capacity in [(1, 4), (2, 6), (2, 32)]:
        dispatch, combine, fraction = route_top_k(gates, k=k, capacity=capacity)
        token_ids, slots, weights, sparse_fraction = route_top_k_sparse(
            gates, k=k, capacity=capacity)
        experts = gates.shape[1]
        dense_from_sparse = np.zeros((32, experts, capacity), np.float32)
        combine_from_sparse = np.zeros_like(dense_from_sparse)
        for token, slot, weight in zip(np.asarray(token_ids),
                                       np.asarray(slots),
                                       np.asarray(weights)):
            if slot < experts * capacity:     # seated
                expert, position = divmod(int(slot), capacity)
                dense_from_sparse[token, expert, position] = 1.0
                combine_from_sparse[token, expert, position] = weight
        np.testing.assert_array_equal(dense_from_sparse, np.asarray(dispatch))
        np.testing.assert_allclose(combine_from_sparse, np.asarray(combine),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(sparse_fraction),
                                   np.asarray(fraction), atol=1e-6)


@pytest.mark.slow
def test_sparse_dispatch_layer_matches_dense_dispatch_layer():
    """The full MoE layer produces the same output and aux loss through the
    sort/scatter path as through the one-hot einsum path, including drops
    (tight capacity) — forward and gradients."""
    rng = jax.random.PRNGKey(5)
    hidden = jax.random.normal(rng, (4, 16, 32), jnp.float32)

    def build(dispatch):
        module = MoEMLP(experts=4, k=2, capacity_factor=0.75,
                        dtype=jnp.float32, dispatch=dispatch)
        params = module.init(jax.random.PRNGKey(0), hidden)['params']
        return module, params

    dense_module, params = build('dense')
    sparse_module, sparse_params = build('sparse')
    chex_equal = jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, sparse_params)
    del chex_equal

    dense_out, dense_aux = dense_module.apply({'params': params}, hidden)
    sparse_out, sparse_aux = sparse_module.apply({'params': params}, hidden)
    np.testing.assert_allclose(np.asarray(dense_out), np.asarray(sparse_out),
                               atol=2e-5)
    np.testing.assert_allclose(float(dense_aux), float(sparse_aux), rtol=1e-6)

    def loss(module):
        def fn(p):
            out, aux = module.apply({'params': p}, hidden)
            return jnp.mean(out ** 2) + aux
        return fn

    dense_grads = jax.grad(loss(dense_module))(params)
    sparse_grads = jax.grad(loss(sparse_module))(params)
    for a, b in zip(jax.tree.leaves(dense_grads), jax.tree.leaves(sparse_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.slow
def test_sharded_sparse_matches_dense_on_expert_mesh():
    """Expert-parallel sparse dispatch (shard_map + all_to_all over the
    expert axis, SURVEY §2.4's ragged-style exchange with fixed quotas):
    with ample capacity (no drops) the output, aux loss, and gradients
    match the dense one-hot path on the same mesh exactly."""
    mesh = MeshSpec(data=2, expert=2).build(jax.devices()[:4])
    hidden = jax.random.normal(jax.random.PRNGKey(2), (8, 16, 32), jnp.float32)

    def build(dispatch):
        module = MoEMLP(experts=4, k=2, capacity_factor=4.0,
                        dtype=jnp.float32, mesh=mesh, dispatch=dispatch)
        params = module.init(jax.random.PRNGKey(0), hidden)['params']
        return module, params

    dense_module, params = build('dense')
    sparse_module, sparse_params = build('sparse')
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(sparse_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    dense_out, dense_aux = dense_module.apply({'params': params}, hidden)
    sparse_out, sparse_aux = sparse_module.apply({'params': params}, hidden)
    np.testing.assert_allclose(np.asarray(dense_out), np.asarray(sparse_out),
                               atol=2e-5)
    np.testing.assert_allclose(float(dense_aux), float(sparse_aux), rtol=1e-5)

    def loss(module):
        def fn(p):
            out, aux = module.apply({'params': p}, hidden)
            return jnp.mean(out ** 2) + aux
        return fn

    dense_grads = jax.grad(loss(dense_module))(params)
    sparse_grads = jax.grad(loss(sparse_module))(sparse_params)
    for a, b in zip(jax.tree.leaves(dense_grads),
                    jax.tree.leaves(sparse_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.slow
def test_ragged_exchange_matches_dense_on_expert_mesh():
    """The ragged exchange path (actual-size sends + receiver-side global
    seating; 'ragged-emulated' = identical seating over an all_gather
    transport, since XLA:CPU cannot lower ragged-all-to-all) matches the
    dense one-hot path with ample capacity — forward, aux, and gradients
    (the custom_vjp reverse exchange)."""
    mesh = MeshSpec(data=2, expert=2).build(jax.devices()[:4])
    hidden = jax.random.normal(jax.random.PRNGKey(2), (8, 16, 32), jnp.float32)

    def build(dispatch, exchange='quota'):
        module = MoEMLP(experts=4, k=2, capacity_factor=4.0,
                        dtype=jnp.float32, mesh=mesh, dispatch=dispatch,
                        exchange=exchange)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), hidden)['params']
        return module, params

    dense_module, params = build('dense')
    ragged_module, _ = build('sparse', 'ragged-emulated')

    dense_out, dense_aux = jax.jit(dense_module.apply)({'params': params},
                                                       hidden)
    ragged_out, ragged_aux = jax.jit(ragged_module.apply)({'params': params},
                                                          hidden)
    np.testing.assert_allclose(np.asarray(dense_out), np.asarray(ragged_out),
                               atol=2e-5)
    np.testing.assert_allclose(float(dense_aux), float(ragged_aux), rtol=1e-5)

    def loss(module):
        def fn(p):
            out, aux = module.apply({'params': p}, hidden)
            return jnp.mean(out ** 2) + aux
        return fn

    dense_grads = jax.jit(jax.grad(loss(dense_module)))(params)
    ragged_grads = jax.jit(jax.grad(loss(ragged_module)))(params)
    for a, b in zip(jax.tree.leaves(dense_grads),
                    jax.tree.leaves(ragged_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


@pytest.mark.slow
def test_ragged_matches_dense_even_under_drops():
    """With the whole batch in one expert-axis group, receiver-side seating
    reproduces the dense path's global choice-major drop order exactly —
    parity holds even at tight capacity, where the quota path diverges."""
    mesh = MeshSpec(expert=2).build(jax.devices()[:2])
    hidden = jax.random.normal(jax.random.PRNGKey(7), (4, 16, 32), jnp.float32)

    def build(dispatch, exchange='quota'):
        module = MoEMLP(experts=4, k=2, capacity_factor=0.75,
                        dtype=jnp.float32, mesh=mesh, dispatch=dispatch,
                        exchange=exchange)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), hidden)['params']
        return module, params

    dense_module, params = build('dense')
    ragged_module, _ = build('sparse', 'ragged-emulated')
    dense_out, dense_aux = jax.jit(dense_module.apply)({'params': params},
                                                       hidden)
    ragged_out, ragged_aux = jax.jit(ragged_module.apply)({'params': params},
                                                          hidden)
    np.testing.assert_allclose(np.asarray(dense_out), np.asarray(ragged_out),
                               atol=2e-5)
    np.testing.assert_allclose(float(dense_aux), float(ragged_aux), rtol=1e-5)


def test_ragged_seats_tokens_the_quota_path_drops():
    """Skewed routing: every token on shard 0 wants the expert shard 1
    owns (and vice versa). The quota path caps each sender at its
    1/experts share and drops the rest; the ragged path seats everything
    (global capacity allows it) and matches the dense reference."""
    mesh = MeshSpec(expert=2).build(jax.devices()[:2])
    dim, rows = 8, 16
    # shard 0 = first 8 rows -> expert 1; shard 1 -> expert 0
    features = np.zeros((rows, dim), np.float32)
    features[:rows // 2, 1] = 1.0
    features[rows // 2:, 0] = 1.0
    features += 0.01 * np.random.default_rng(0).normal(size=features.shape)
    hidden = jnp.asarray(features.reshape(2, rows // 2, dim))

    def run(dispatch, exchange):
        module = MoEMLP(experts=2, k=1, capacity_factor=1.0,
                        dtype=jnp.float32, mesh=mesh, dispatch=dispatch,
                        exchange=exchange)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), hidden)['params']
        # pin the router so shard 0's tokens route to expert 1 and
        # shard 1's to expert 0 (feature f -> expert f, scaled hard)
        router = np.zeros((dim, 2), np.float32)
        router[0, 0] = router[1, 1] = 20.0
        params = dict(params, router=jnp.asarray(router))
        out, _ = jax.jit(module.apply)({'params': params}, hidden)
        return np.asarray(out)

    dense = run('dense', 'quota')
    quota = run('sparse', 'quota')
    ragged = run('sparse', 'ragged-emulated')
    seated = lambda out: int((np.abs(out).sum(-1) > 1e-6).sum())
    # dense/ragged seat all 16 tokens; the quota path drops half of each
    # shard's sends (its per-expert quota is rows/2/experts = 4)
    assert seated(dense) == rows, seated(dense)
    assert seated(ragged) == rows, seated(ragged)
    assert seated(quota) < rows, seated(quota)
    np.testing.assert_allclose(ragged, dense, atol=2e-5)


def test_sharded_sparse_guards():
    """Explicit dispatch='sparse' on a mesh it cannot serve raises with the
    reason; 'auto' silently falls back to dense there. sparse_impl is
    validated up front (a typo cannot ride the sharded path unnoticed)
    and 'fused' raises on a multi-device mesh instead of silently running
    a different implementation."""
    mesh = MeshSpec(data=2, expert=2, model=2).build()
    hidden = jnp.zeros((8, 16, 32), jnp.float32)
    module = MoEMLP(experts=4, dtype=jnp.float32, mesh=mesh,
                    dispatch='sparse')
    with pytest.raises(ValueError, match='dense-only'):
        module.init(jax.random.PRNGKey(0), hidden)
    auto = MoEMLP(experts=4, dtype=jnp.float32, mesh=mesh, dispatch='auto')
    auto.init(jax.random.PRNGKey(0), hidden)   # falls back, no raise

    typo = MoEMLP(experts=4, dtype=jnp.float32, mesh=mesh,
                  dispatch='auto', sparse_impl='fussed')
    with pytest.raises(ValueError, match='unknown sparse_impl'):
        typo.init(jax.random.PRNGKey(0), hidden)
    fused_sharded = MoEMLP(experts=4, dtype=jnp.float32,
                           mesh=MeshSpec(data=2, expert=2).build(
                               jax.devices()[:4]),
                           dispatch='sparse', sparse_impl='fused')
    with pytest.raises(ValueError, match='single-shard only'):
        fused_sharded.init(jax.random.PRNGKey(0), hidden)


def test_gather_impl_matches_scatter_impl_exactly():
    """The scatter-free gather dispatch/combine (custom_vjp pair) must
    reproduce the row-scatter formulation bit-for-bit — same seating,
    same drops (tight capacity), same forward and same gradients."""
    rng = jax.random.PRNGKey(11)
    hidden = jax.random.normal(rng, (4, 16, 32), jnp.float32)

    def build(sparse_impl):
        module = MoEMLP(experts=4, k=2, capacity_factor=0.75,
                        dtype=jnp.float32, dispatch='sparse',
                        sparse_impl=sparse_impl)
        params = module.init(jax.random.PRNGKey(0), hidden)['params']
        return module, params

    gather_module, params = build('gather')
    scatter_module, _ = build('scatter')

    out_g, aux_g = gather_module.apply({'params': params}, hidden)
    out_s, aux_s = scatter_module.apply({'params': params}, hidden)
    np.testing.assert_array_equal(np.asarray(out_g), np.asarray(out_s))
    assert float(aux_g) == float(aux_s)

    def loss(module):
        def fn(p, hidden):
            out, aux = module.apply({'params': p}, hidden)
            return jnp.mean(out ** 2) + aux
        return fn

    grads_g = jax.grad(loss(gather_module), argnums=(0, 1))(params, hidden)
    grads_s = jax.grad(loss(scatter_module), argnums=(0, 1))(params, hidden)
    for a, b in zip(jax.tree.leaves(grads_g), jax.tree.leaves(grads_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_bf16_gradient_parity_across_sparse_impls():
    """bfloat16-compute gradient parity — the dtype models actually train
    in. Pins the f32 ``d_weights``/``d_buffer`` accumulation of
    ``_gather_combine_bwd`` against the scatter formulation, and the
    fused kernels' f32 MXU accumulation against both, with tolerances
    sized to bf16 rounding (summation orders legitimately differ)."""
    hidden = jax.random.normal(jax.random.PRNGKey(13), (4, 16, 32),
                               jnp.float32)

    def build(sparse_impl):
        module = MoEMLP(experts=4, k=2, capacity_factor=1.25,
                        dtype=jnp.bfloat16, dispatch='sparse',
                        sparse_impl=sparse_impl)
        params = module.init(jax.random.PRNGKey(0), hidden)['params']
        return module, params

    def loss(module):
        def fn(p, hidden):
            out, aux = module.apply({'params': p}, hidden)
            return jnp.mean(out.astype(jnp.float32) ** 2) + aux
        return fn

    reference_module, params = build('scatter')
    reference = jax.grad(loss(reference_module), argnums=(0, 1))(
        params, hidden)
    # gather's f32 d_weights/combine accumulation vs scatter's bf16
    # scatter-add differ by summation order and rounding point, so even
    # the gather comparison carries a (tight) tolerance in bf16; the
    # fused kernels additionally accumulate matmuls in f32 on the MXU
    # and get a looser bound
    tolerance = {'gather': dict(rtol=0.05, atol=1e-4),
                 'fused': dict(rtol=0.05, atol=2e-2)}
    for impl in ('gather', 'fused'):
        module, impl_params = build(impl)
        for a, b in zip(jax.tree.leaves(params),
                        jax.tree.leaves(impl_params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        grads = jax.grad(loss(module), argnums=(0, 1))(params, hidden)
        for a, b in zip(jax.tree.leaves(reference), jax.tree.leaves(grads)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                err_msg=f'sparse_impl={impl}', **tolerance[impl])


def test_fused_impl_matches_gather_impl():
    """The fused grouped gather-matmul path (Pallas kernels under
    ``interpret=True`` on CPU) reproduces the gather impl within float32
    tolerance — forward, aux loss, and every gradient (params AND
    hidden), including drop behavior at tight capacity, where the
    sentinel row-skip paths of both kernels are exercised."""
    hidden = jax.random.normal(jax.random.PRNGKey(17), (4, 16, 32),
                               jnp.float32)

    def build(sparse_impl, capacity_factor):
        module = MoEMLP(experts=4, k=2, capacity_factor=capacity_factor,
                        dtype=jnp.float32, dispatch='sparse',
                        sparse_impl=sparse_impl)
        params = module.init(jax.random.PRNGKey(0), hidden)['params']
        return module, params

    for capacity_factor in (0.75, 4.0):   # with drops / ample
        gather_module, params = build('gather', capacity_factor)
        fused_module, _ = build('fused', capacity_factor)

        out_g, aux_g = gather_module.apply({'params': params}, hidden)
        out_f, aux_f = fused_module.apply({'params': params}, hidden)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_g),
                                   atol=1e-5)
        np.testing.assert_allclose(float(aux_f), float(aux_g), rtol=1e-6)

        def loss(module):
            def fn(p, hidden):
                out, aux = module.apply({'params': p}, hidden)
                return jnp.mean(out ** 2) + aux
            return fn

        grads_g = jax.grad(loss(gather_module), argnums=(0, 1))(params,
                                                                hidden)
        grads_f = jax.grad(loss(fused_module), argnums=(0, 1))(params,
                                                               hidden)
        for a, b in zip(jax.tree.leaves(grads_g), jax.tree.leaves(grads_f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-6, rtol=1e-4,
                                       err_msg=f'cf={capacity_factor}')


def test_grouped_matmul_kernels_match_einsum_reference():
    """The Pallas kernels against plain einsum references, both operand
    orientations (``transpose_rhs`` is what the backward's operand swap
    uses), sentinel handling included."""
    from tpusystem.ops.pallas.grouped_matmul import (gather_rows_matmul,
                                                     matmul_scatter_rows)
    rng = np.random.default_rng(3)
    tokens, dim, hidden_dim, experts, capacity = 48, 16, 24, 4, 12
    rows = experts * capacity
    group_of = np.arange(rows) // capacity
    src = rng.normal(size=(tokens, dim)).astype(np.float32)
    w1 = rng.normal(size=(experts, dim, hidden_dim)).astype(np.float32)
    ids = rng.integers(0, tokens + 1, rows).astype(np.int32)  # incl sentinel
    clamped = np.minimum(ids, tokens - 1)
    scale = (ids < tokens).astype(np.float32) * rng.random(rows).astype(
        np.float32)

    up = gather_rows_matmul(jnp.asarray(src), jnp.asarray(w1),
                            jnp.asarray(clamped), jnp.asarray(scale),
                            rows_per_group=capacity)
    reference = np.einsum('rd,rdh->rh', src[clamped] * scale[:, None],
                          w1[group_of])
    np.testing.assert_allclose(np.asarray(up), reference, atol=1e-5)

    up_t = gather_rows_matmul(jnp.asarray(src),
                              jnp.asarray(w1.transpose(0, 2, 1)),
                              jnp.asarray(clamped), jnp.asarray(scale),
                              rows_per_group=capacity, transpose_rhs=True)
    np.testing.assert_allclose(np.asarray(up_t), reference, atol=1e-5)

    # scatter-combine: each expert seats a token at most once (the MoE
    # seating invariant the RMW epilogue relies on)
    lhs = rng.normal(size=(rows, hidden_dim)).astype(np.float32)
    w2 = rng.normal(size=(experts, hidden_dim, dim)).astype(np.float32)
    b2 = rng.normal(size=(experts, dim)).astype(np.float32)
    toks = np.concatenate([rng.choice(tokens, capacity, replace=False)
                           for _ in range(experts)]).astype(np.int32)
    toks[::7] = tokens                              # sentinel slots
    weights = rng.random(rows).astype(np.float32)
    weights[toks >= tokens] = 0.0

    out, buffer_rows = matmul_scatter_rows(
        jnp.asarray(lhs), jnp.asarray(w2), jnp.asarray(b2),
        jnp.asarray(toks), jnp.asarray(weights), tokens,
        rows_per_group=capacity)
    reference_rows = (np.einsum('rh,rhd->rd', lhs, w2[group_of])
                      + b2[group_of])
    reference_out = np.zeros((tokens, dim), np.float32)
    for row in range(rows):
        if toks[row] < tokens:
            reference_out[toks[row]] += weights[row] * reference_rows[row]
    np.testing.assert_allclose(np.asarray(buffer_rows), reference_rows,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out), reference_out, atol=1e-5)

    # backward orientation: transposed rhs, no bias, rows not saved
    out_t, no_rows = matmul_scatter_rows(
        jnp.asarray(lhs), jnp.asarray(w2.transpose(0, 2, 1)), None,
        jnp.asarray(toks), jnp.asarray(weights), tokens,
        rows_per_group=capacity, transpose_rhs=True, save_rows=False)
    reference_nb = np.zeros((tokens, dim), np.float32)
    for row in range(rows):
        if toks[row] < tokens:
            reference_nb[toks[row]] += (weights[row]
                                        * (reference_rows
                                           - b2[group_of])[row])
    assert no_rows is None
    np.testing.assert_allclose(np.asarray(out_t), reference_nb, atol=1e-5)
