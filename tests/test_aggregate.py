"""Aggregate contracts (reference parity: tests/test_aggregate.py:14-17)."""

from unittest.mock import Mock

import pytest

from tpusystem import Aggregate


class Model(Aggregate):
    def __init__(self):
        super().__init__()
        self.epoch = 0
        self.phase_witness = Mock()
        self.epoch_witness = Mock()

    @property
    def id(self):
        return 'model-under-test'

    def onphase(self):
        self.phase_witness(self.phase)

    def onepoch(self):
        self.epoch_witness(self.epoch)


def test_epoch_assignment_fires_hook_once_and_preserves_value():
    model = Model()
    assert model.epoch == 0
    model.epoch_witness.assert_not_called()  # __init__ assignment is silent
    model.epoch += 1
    assert model.epoch == 1
    model.epoch_witness.assert_called_once_with(1)


def test_phase_state_machine():
    model = Model()
    assert model.phase == 'train'
    model.phase = 'evaluation'
    assert model.phase == 'evaluation'
    model.phase_witness.assert_called_once_with('evaluation')
    model.phase = 'train'
    assert model.phase == 'train'


def test_events_queue_available_for_early_stopping():
    model = Model()
    model.events.enqueue(StopIteration)
    with pytest.raises(StopIteration):
        model.events.commit()


def test_id_is_abstract():
    class NoId(Aggregate):
        ...
    with pytest.raises(TypeError):
        NoId()
