"""Docs-site consistency: mkdocs.yml nav targets exist and every
mkdocstrings directive names an importable module — so the CI docs job
(`mkdocs build --strict`) cannot fail on references this environment
can't check (mkdocs itself is not installed here)."""

import importlib
import os
import pathlib
import re
import subprocess
import sys

import pytest
import yaml

REPO = pathlib.Path(__file__).parent.parent
DOCS = REPO / 'docs'


def _nav_files(node):
    if isinstance(node, str):
        yield node
    elif isinstance(node, list):
        for item in node:
            yield from _nav_files(item)
    elif isinstance(node, dict):
        for value in node.values():
            yield from _nav_files(value)


def test_mkdocs_nav_targets_exist():
    config = yaml.safe_load((REPO / 'mkdocs.yml').read_text())
    missing = [path for path in _nav_files(config['nav'])
               if not (DOCS / path).exists()]
    assert not missing, f'mkdocs.yml nav references missing pages: {missing}'


def test_api_pages_cover_every_module_and_import():
    directives = set()
    for page in (DOCS / 'api').glob('*.md'):
        directives.update(re.findall(r'^::: (\S+)$', page.read_text(), re.M))
    for module in sorted(directives):
        importlib.import_module(module)   # raises on a stale reference
    # every package module appears on exactly one API page
    modules = {
        str(p.relative_to(REPO)).removesuffix('.py').removesuffix('/__init__')
        .replace('/', '.')
        for p in (REPO / 'tpusystem').rglob('*.py')}
    assert modules == directives, (
        f'API pages out of sync: missing {modules - directives}, '
        f'stale {directives - modules}')


@pytest.mark.slow
def test_coverage_md_test_count_matches_collection():
    """COVERAGE.md's "Totals: N tests" line must equal what pytest
    actually collects — the count drifted in two consecutive rounds when
    maintained by hand, so it is now pinned by construction."""
    out = subprocess.run(
        [sys.executable, '-m', 'pytest', 'tests/', '--collect-only', '-q',
         '-m', '', '-p', 'no:cacheprovider'],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'}).stdout
    collected = int(re.search(r'(\d+) tests collected', out).group(1))
    written = int(re.search(r'Totals: (\d+) tests',
                            (REPO / 'COVERAGE.md').read_text()).group(1))
    assert written == collected, (
        f'COVERAGE.md says {written} tests but collection finds '
        f'{collected} — update the Totals line')
