"""Fused chunked LM loss: parity with the materialized-logits path.

``ChunkedNextTokenLoss`` + ``return_features=True`` must reproduce
``NextTokenLoss`` over full logits exactly (same math, different
scheduling): value parity, gradient parity, padding-mask parity, and both
table orientations (tied ``[vocab, dim]`` GPT-2 table, untied
``[dim, vocab]`` Llama head kernel).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.models import GPT2, Llama, gpt2_tiny, llama_tiny
from tpusystem.train import ChunkedNextTokenLoss, NextTokenLoss, flax_apply


@pytest.fixture(scope='module')
def tokens():
    return jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 33)), jnp.int32)


def _pair(module_logits, module_features, tokens):
    params = module_logits.init(jax.random.PRNGKey(0), tokens)['params']
    logits = module_logits.apply({'params': params}, tokens)
    features = module_features.apply({'params': params}, tokens)
    return params, logits, features


@pytest.mark.slow
def test_gpt2_value_and_grad_parity(tokens):
    reference, fused = gpt2_tiny(), gpt2_tiny(return_features=True)
    params, logits, features = _pair(reference, fused, tokens)
    baseline = NextTokenLoss()(logits, tokens)
    chunked = ChunkedNextTokenLoss(chunks=4)(features, tokens)
    np.testing.assert_allclose(float(baseline), float(chunked), rtol=2e-5)

    apply_ref = flax_apply(reference)
    apply_fused = flax_apply(fused)
    grad_ref = jax.grad(
        lambda p: NextTokenLoss()(apply_ref(p, tokens, None, False), tokens))(params)
    grad_fused = jax.grad(
        lambda p: ChunkedNextTokenLoss(chunks=4)(
            apply_fused(p, tokens, None, False), tokens))(params)
    flat_ref = jax.tree.leaves(grad_ref)
    flat_fused = jax.tree.leaves(grad_fused)
    for a, b in zip(flat_ref, flat_fused):
        # bf16 operands + different summation order (per-chunk vs whole
        # matrix): agreement is bounded by bf16 ulps, not exact
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-2, atol=4e-4)


def test_llama_value_parity(tokens):
    reference = llama_tiny()
    fused = llama_tiny(return_features=True)
    params, logits, features = _pair(reference, fused, tokens)
    # untied head: table arrives [dim, vocab]
    assert features[1].shape[0] == features[0].shape[-1]
    baseline = NextTokenLoss()(logits, tokens)
    chunked = ChunkedNextTokenLoss(chunks=3)(features, tokens)
    np.testing.assert_allclose(float(baseline), float(chunked), rtol=2e-5)


def test_padding_rows_and_masked_targets_excluded(tokens):
    """Row count not divisible by chunks forces internal padding; explicit
    pad ids (< 0) must also drop out, matching NextTokenLoss."""
    fused = gpt2_tiny(return_features=True)
    reference = gpt2_tiny()
    masked = tokens.at[:, -5:].set(-1)
    params, logits, features = _pair(reference, fused, masked)
    baseline = NextTokenLoss()(logits, masked)
    for chunks in (1, 4, 7):
        chunked = ChunkedNextTokenLoss(chunks=chunks)(features, masked)
        np.testing.assert_allclose(float(baseline), float(chunked), rtol=2e-5)


def test_z_loss_parity(tokens):
    reference, fused = gpt2_tiny(), gpt2_tiny(return_features=True)
    params, logits, features = _pair(reference, fused, tokens)
    baseline = NextTokenLoss(z_loss=1e-3)(logits, tokens)
    chunked = ChunkedNextTokenLoss(chunks=4, z_loss=1e-3)(features, tokens)
    np.testing.assert_allclose(float(baseline), float(chunked), rtol=2e-5)


def test_square_table_requires_explicit_orientation():
    """vocab == dim makes the table orientation ambiguous: head_logits must
    refuse to guess (a wrong guess silently transposes the head)."""
    from tpusystem.ops.precision import head_logits
    features = jnp.ones((2, 3, 8), jnp.float32)
    square = jnp.ones((8, 8), jnp.float32)
    with pytest.raises(ValueError):
        head_logits(features, square)
    assert head_logits(features, square, tied=True).shape == (2, 3, 8)
    loss = ChunkedNextTokenLoss(chunks=2, tied=True)
    tokens = jnp.zeros((2, 3), jnp.int32)
    assert float(loss((features, square), tokens)) > 0


def test_llama_head_param_path_unchanged():
    """The fused-head refactor must not move 'lm_head/kernel' — partition
    rules and existing checkpoints key on that path."""
    module = llama_tiny()
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)['params']
    assert 'lm_head' in params and 'kernel' in params['lm_head']
    dim = params['lm_head']['kernel'].shape
    assert dim == (module.dim, module.vocab_size)


@pytest.mark.slow
def test_pipelined_gpt2_fused_loss_matches_logits_path():
    """return_features on the pipelined variant: same loss as the full
    logits path on the same stacked parameters (2-stage virtual mesh)."""
    from tpusystem.models import GPT2Pipelined
    from tpusystem.parallel import MeshSpec

    mesh = MeshSpec(stage=2).build(jax.devices()[:2])
    common = dict(vocab_size=256, layers=4, dim=32, heads=4, max_seq=64,
                  dtype='float32', microbatches=2, remat=False, mesh=mesh)
    logits_model = GPT2Pipelined(**common)
    fused_model = GPT2Pipelined(**common, return_features=True)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 256, (2, 16)), jnp.int32)
    variables = logits_model.init(jax.random.PRNGKey(0), tokens)

    logits = logits_model.apply(variables, tokens)
    features = fused_model.apply(variables, tokens)
    assert features[1].shape == (256, 32)            # tied [vocab, dim] table
    baseline = NextTokenLoss()(logits, tokens)
    chunked = ChunkedNextTokenLoss(chunks=4, tied=True)(features, tokens)
    np.testing.assert_allclose(float(baseline), float(chunked), rtol=2e-5)
