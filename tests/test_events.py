"""Domain event contracts (reference parity: tests/test_events.py:12-76)."""

from unittest.mock import Mock

import pytest

from tpusystem.domain.events import Event, Events


class Occurred(Event):
    def __init__(self, payload):
        self.payload = payload


class Marker(Event):
    ...


def test_unhandled_exception_raises_at_commit():
    events = Events()
    events.enqueue(StopIteration)
    with pytest.raises(StopIteration):
        events.commit()


def test_unhandled_exception_instance_raises():
    events = Events()
    events.enqueue(ValueError('epoch regression'))
    with pytest.raises(ValueError, match='epoch regression'):
        events.commit()


def test_handled_exception_is_suppressed():
    events = Events()
    witness = Mock()
    events.handlers[StopIteration] = lambda: witness()
    events.enqueue(StopIteration)
    events.commit()
    witness.assert_called_once()


def test_unhandled_plain_event_is_dropped():
    events = Events()
    events.enqueue(Marker)
    events.enqueue(Occurred('x'))
    events.commit()  # no raise
    assert not events.queue


def test_class_event_dispatch_without_argument():
    events = Events()
    witness = Mock()
    events.handlers[Marker] = lambda: witness('no-arg')
    events.enqueue(Marker)
    events.commit()
    witness.assert_called_once_with('no-arg')


def test_instance_event_delivers_payload():
    events = Events()
    seen = []
    events.handlers[Occurred] = lambda event: seen.append(event.payload)
    events.enqueue(Occurred('value'))
    events.commit()
    assert seen == ['value']


def test_queue_drains_in_fifo_order():
    events = Events()
    order = []
    events.handlers[Occurred] = lambda e: order.append(e.payload)
    events.handlers[Marker] = lambda: order.append('marker')
    events.enqueue(Occurred(1))
    events.enqueue(Marker)
    events.enqueue(Occurred(2))
    events.commit()
    assert order == [1, 'marker', 2]
    assert events.dequeue() is None


def test_handler_sequence_all_called():
    events = Events()
    first, second = Mock(), Mock()
    events.handlers[Marker] = [lambda: first(), lambda: second()]
    events.enqueue(Marker)
    events.commit()
    first.assert_called_once()
    second.assert_called_once()
