"""Seconds-scale recovery: the Supervisor control loop + hot memstore.

The restart exit-code contract used to exist only as launcher prose; these
tests pin the subsystem that now enforces it:

* exit-code verdicts (relaunch 42/43/signal, halt 44/unknown, done 0) and
  backoff/crash-loop policy — driven entirely by an injectable fake clock
  and fake processes, so tier-1 has **no real sleeps**;
* SIGTERM forwarding with a grace window: the worker's preemption handler
  gets to drain (exit 43), SIGKILL only after the grace expires;
* the worker ⇄ supervisor memstore wire (chunked, digest-verified) and the
  :func:`hot_resume` decision: hot wins only when its step ≥ the newest
  committed disk step AND its digest verifies, restores bitwise-identical
  to the disk path, and every failure rung falls back to disk;
* buddy cross-replication over the control plane (a replaced host pulls
  its hot state back from its buddy's supervisor);
* the full SIGKILL-mid-epoch drill over real processes (slow): relaunch,
  hot-restore, losses bitwise-identical to an uninterrupted reference —
  and identical again with the memstore disabled or its copy corrupted.
"""

from __future__ import annotations

import json
import os
import signal as signal_module
import subprocess
import sys
import threading
import time

import pytest

from tpusystem.checkpoint.memstore import (MemStore, MemStoreClient,
                                           MemStoreServer, blob_digest,
                                           pack_hot, serialize_state,
                                           supervisor_client)
from tpusystem.observe.events import (RecoveryTimeline, WorkerExited,
                                      WorkerRelaunched)
from tpusystem.parallel.multihost import Hub, TcpTransport
from tpusystem.parallel.recovery import (CRASH_LOOP_EXIT, DIVERGED_EXIT,
                                         FAILURE_EXIT, LOST_WORKER_EXIT,
                                         PREEMPTED_EXIT, RESIZED_EXIT,
                                         DivergenceError, Preempted,
                                         WorkerLostError, WorldResizedError,
                                         exit_for_restart)
from tpusystem.parallel.supervisor import Supervisor
from tpusystem.services.prodcon import Consumer, Producer

IDENTITY = 'drill-mlp'


# ---------------------------------------------------------------------------
# satellite: exit_for_restart maps ONLY the recovery exceptions


class TestExitContract:

    @pytest.mark.parametrize('reason, code', [
        (WorkerLostError(1, 2.0), LOST_WORKER_EXIT),
        (Preempted(signal_module.SIGTERM), PREEMPTED_EXIT),
        (WorldResizedError(1, (0, 2)), RESIZED_EXIT),
        (DivergenceError('gave up', step=7), DIVERGED_EXIT),
        (ValueError('a plain bug'), FAILURE_EXIT),
        (KeyboardInterrupt(), FAILURE_EXIT),
        (RuntimeError('not a recovery type'), FAILURE_EXIT),
    ])
    def test_exit_code_table(self, reason, code):
        """The fixed bug: an unrecognized exception used to map to the
        restartable 42 — a plain ValueError (or a ^C) would have been
        relaunched forever. Only the three recovery exceptions get
        contract codes; everything else is a non-restart failure."""
        assert exit_for_restart(reason).code == code

    def test_worker_lost_error_carries_reason(self):
        assert 'socket death' in str(WorkerLostError(2, 1.0))
        assert 'heartbeat stall' in str(WorkerLostError(2, 1.0, 'heartbeat'))
        assert WorkerLostError(2, 1.0, 'heartbeat').reason == 'heartbeat'


# ---------------------------------------------------------------------------
# fake process harness: policy tests with zero subprocesses and zero sleeps


class FakeClock:
    def __init__(self):
        self.time = 0.0
        self.slept: list[float] = []

    def __call__(self) -> float:
        return self.time

    def sleep(self, seconds: float) -> None:
        self.slept.append(seconds)
        self.time += seconds


class FakeWorker:
    """Exits with ``code`` after ``polls`` poll cycles; ``on_poll`` can
    inject timeline marks / lifetime exactly like a real worker would."""

    pid = 4242

    def __init__(self, code, polls=1, on_poll=None):
        self.code = code
        self.polls = polls
        self.on_poll = on_poll
        self.count = 0
        self.signals: list[int] = []

    def poll(self):
        self.count += 1
        if self.on_poll is not None:
            self.on_poll(self)
        return self.code if self.count > self.polls else None

    def send_signal(self, signum):
        self.signals.append(signum)

    def kill(self):
        self.signals.append(signal_module.SIGKILL)


def scripted(*workers):
    """A fake popen yielding each FakeWorker in turn."""
    launched = []

    def popen(argv, env=None):
        launched.append(env)
        return workers[len(launched) - 1]
    popen.launched = launched
    return popen


def capture_events(supervisor):
    producer = Producer()
    seen = []
    consumer = Consumer()
    for kind in (WorkerExited, WorkerRelaunched, RecoveryTimeline):
        consumer.register(kind, seen.append)
    producer.register(consumer)
    supervisor.producer = producer
    return seen


def policy_supervisor(popen, clock, **kwargs):
    kwargs.setdefault('memstore', False)
    kwargs.setdefault('backoff_jitter', 0.0)
    return Supervisor(['worker'], popen=popen, clock=clock,
                      sleep=clock.sleep, **kwargs)


class TestSupervisorPolicy:

    def test_clean_exit_is_not_relaunched(self):
        clock = FakeClock()
        popen = scripted(FakeWorker(0))
        supervisor = policy_supervisor(popen, clock)
        seen = capture_events(supervisor)
        assert supervisor.run() == 0
        assert len(popen.launched) == 1
        assert [event.action for event in seen
                if isinstance(event, WorkerExited)] == ['done']

    @pytest.mark.parametrize('code', [DIVERGED_EXIT, 1, 7])
    def test_non_restart_codes_halt_for_triage(self, code):
        """Exit 44 (diverged) and unknown codes are NEVER relaunched — a
        blind relaunch of a deterministic failure replays it."""
        clock = FakeClock()
        popen = scripted(FakeWorker(code))
        supervisor = policy_supervisor(popen, clock)
        seen = capture_events(supervisor)
        assert supervisor.run() == code
        assert len(popen.launched) == 1
        assert clock.slept.count(0.05) >= 1     # polled, never backed off
        assert [event.action for event in seen
                if isinstance(event, WorkerExited)] == ['halt']

    @pytest.mark.parametrize('code', [LOST_WORKER_EXIT, PREEMPTED_EXIT,
                                      RESIZED_EXIT, -9])
    def test_restartable_codes_relaunch(self, code):
        """42, 43, 46 and signal deaths (a SIGKILLed worker IS the
        worker-lost case) relaunch; the run ends when the worker
        completes."""
        clock = FakeClock()
        popen = scripted(FakeWorker(code), FakeWorker(0))
        supervisor = policy_supervisor(popen, clock, crash_loop_k=5)
        assert supervisor.run() == 0
        assert len(popen.launched) == 2
        assert supervisor.restarts == 1

    @pytest.mark.parametrize('signum, outcome', [
        (signal_module.SIGKILL, 'relaunch'),   # OOM-killer / SIGKILLed pod
        (signal_module.SIGTERM, 'relaunch'),   # eviction the worker missed
        (signal_module.SIGSEGV, 'relaunch'),   # process failed as a unit
        (signal_module.SIGBUS, 'relaunch'),
        (signal_module.SIGINT, 'halt'),        # ^C is operator intent
        (signal_module.SIGQUIT, 'halt'),       # ^\ likewise
    ])
    def test_signal_death_verdict_table(self, signum, outcome):
        """The fixed gap: every ``code < 0`` used to relaunch — a worker
        dying to the operator's own ^C/^\\ would be respawned forever,
        fighting the human. SIGINT/SIGQUIT now halt for triage like exit
        1; genuine process deaths still relaunch."""
        clock = FakeClock()
        popen = scripted(FakeWorker(-signum), FakeWorker(0))
        supervisor = policy_supervisor(popen, clock, crash_loop_k=5)
        seen = capture_events(supervisor)
        code = supervisor.run()
        actions = [e.action for e in seen if isinstance(e, WorkerExited)]
        if outcome == 'relaunch':
            assert code == 0 and len(popen.launched) == 2
            assert actions == ['relaunch', 'done']
        else:
            assert code == FAILURE_EXIT and len(popen.launched) == 1
            assert actions == ['halt']
            assert seen[0].code == -signum     # the event keeps the truth

    def test_resize_relaunches_under_the_new_spec_without_backoff(self):
        """The elastic commit hook: resize() drains the worker (SIGTERM),
        merges the new world spec into its env, re-points the buddy, and
        relaunches immediately — no backoff, no crash-loop sample."""
        clock = FakeClock()
        box = {}

        def trigger(worker):
            if worker.count == 1:
                box['sup'].resize({'TPUSYSTEM_ELASTIC': 'new-spec'}, buddy=2)

        first = FakeWorker(PREEMPTED_EXIT, polls=3, on_poll=trigger)
        popen = scripted(first, FakeWorker(0))
        supervisor = policy_supervisor(popen, clock)
        box['sup'] = supervisor
        seen = capture_events(supervisor)
        assert supervisor.run() == 0
        assert len(popen.launched) == 2
        assert signal_module.SIGTERM in first.signals       # the drain
        assert popen.launched[0].get('TPUSYSTEM_ELASTIC') is None
        assert popen.launched[1]['TPUSYSTEM_ELASTIC'] == 'new-spec'
        assert supervisor.buddy == 2                        # re-paired
        assert [s for s in clock.slept if s >= 1.0] == []   # no backoff
        actions = [e.action for e in seen if isinstance(e, WorkerExited)]
        assert actions == ['resize', 'done']

    def test_resize_during_backoff_applies_before_the_relaunch(self):
        """A resize committed while the supervisor sleeps out a backoff
        must fold into the environment BEFORE the relaunch — spawning a
        worker under the stale world spec just to SIGTERM it would waste
        a whole worker start."""
        clock = FakeClock()
        box = {}

        def sleep_then_resize(seconds):
            clock.sleep(seconds)
            if seconds >= 1.0:            # the backoff sleep, not a poll
                box['sup'].resize({'TPUSYSTEM_ELASTIC': 'spec'}, buddy=2)

        relaunched = FakeWorker(0)
        popen = scripted(FakeWorker(LOST_WORKER_EXIT), relaunched)
        supervisor = Supervisor(['worker'], memstore=False, popen=popen,
                                clock=clock, sleep=sleep_then_resize,
                                backoff_base=1.0, backoff_jitter=0.0)
        box['sup'] = supervisor
        assert supervisor.run() == 0
        assert len(popen.launched) == 2
        assert popen.launched[1]['TPUSYSTEM_ELASTIC'] == 'spec'
        assert supervisor.buddy == 2
        assert relaunched.signals == []   # fresh worker never SIGTERMed

    def test_operator_sigint_outranks_a_pending_resize(self):
        """^C while a resize drain is in flight still halts: the pending
        resize must not dress an operator interrupt as a relaunch."""
        clock = FakeClock()
        box = {}

        def trigger(worker):
            if worker.count == 1:
                box['sup'].resize({'TPUSYSTEM_ELASTIC': 'spec'})

        popen = scripted(FakeWorker(-signal_module.SIGINT, polls=3,
                                    on_poll=trigger))
        supervisor = policy_supervisor(popen, clock)
        box['sup'] = supervisor
        assert supervisor.run() == FAILURE_EXIT
        assert len(popen.launched) == 1

    def test_backoff_grows_exponentially_and_caps(self):
        """Relaunch delays follow min(cap, base * 2**attempt): measured on
        the fake clock, no real time passes."""
        clock = FakeClock()
        workers = [FakeWorker(42) for _ in range(6)] + [FakeWorker(0)]
        popen = scripted(*workers)
        supervisor = policy_supervisor(popen, clock, backoff_base=1.0,
                                       backoff_cap=8.0, crash_loop_k=100)
        assert supervisor.run() == 0
        backoffs = [s for s in clock.slept if s >= 1.0]
        assert backoffs == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]

    def test_backoff_jitter_is_bounded_and_seeded(self):
        clock = FakeClock()
        popen = scripted(*([FakeWorker(42) for _ in range(4)]
                           + [FakeWorker(0)]))
        supervisor = policy_supervisor(popen, clock, backoff_base=2.0,
                                       backoff_jitter=0.5, seed=11,
                                       crash_loop_k=100)
        supervisor.run()
        backoffs = [s for s in clock.slept if s >= 2.0]
        for index, backoff in enumerate(backoffs):
            nominal = 2.0 * 2 ** index
            assert nominal <= backoff <= nominal * 1.5
        # deterministic: same seed, same jitter
        clock2 = FakeClock()
        popen2 = scripted(*([FakeWorker(42) for _ in range(4)]
                            + [FakeWorker(0)]))
        supervisor2 = policy_supervisor(popen2, clock2, backoff_base=2.0,
                                        backoff_jitter=0.5, seed=11,
                                        crash_loop_k=100)
        supervisor2.run()
        assert [s for s in clock2.slept if s >= 2.0] == backoffs

    def test_crash_loop_gives_up_with_distinct_exit(self):
        """K consecutive restartable exits within the window -> the
        supervisor stops relaunching and exits CRASH_LOOP_EXIT (45), a
        code deliberately outside RESTART_EXITS."""
        clock = FakeClock()
        popen = scripted(*[FakeWorker(42) for _ in range(10)])
        supervisor = policy_supervisor(popen, clock, crash_loop_k=3,
                                       crash_loop_window=60.0)
        seen = capture_events(supervisor)
        assert supervisor.run() == CRASH_LOOP_EXIT
        assert len(popen.launched) == 3
        actions = [e.action for e in seen if isinstance(e, WorkerExited)]
        assert actions == ['relaunch', 'relaunch', 'crash-loop']

    def test_productive_run_resets_crash_loop_and_backoff(self):
        """A worker that lives past the window (here: its polls advance the
        fake clock beyond it) clears the rapid-death counter AND the
        backoff ladder — only *consecutive* rapid deaths count."""
        clock = FakeClock()

        def long_lived(worker):
            clock.time += 30.0        # each poll cycle ages the run

        workers = [FakeWorker(42), FakeWorker(42),
                   FakeWorker(42, polls=3, on_poll=long_lived),
                   FakeWorker(42), FakeWorker(42), FakeWorker(0)]
        popen = scripted(*workers)
        supervisor = policy_supervisor(popen, clock, crash_loop_k=3,
                                       crash_loop_window=60.0,
                                       backoff_base=1.0, backoff_cap=64.0)
        assert supervisor.run() == 0
        assert len(popen.launched) == 6
        backoffs = [s for s in clock.slept if s >= 1.0]
        # 1, 2 (two rapid deaths), then the productive run resets the
        # ladder: 1 again, and the following rapid deaths climb afresh
        assert backoffs == [1.0, 2.0, 1.0, 2.0, 4.0]

    def test_first_step_mark_anchors_the_crash_window(self):
        """The window measures from the worker's first-step mark, not from
        launch: a worker that spends ages compiling, steps once, then dies
        immediately IS a crash-loop sample."""
        clock = FakeClock()

        def mark_first_step(worker):
            if worker.count == 1:
                clock.time += 100.0           # long compile, no step yet
            elif worker.count == 2:
                worker.supervisor._on_mark('first-step', {})

        workers = []
        for _ in range(3):
            worker = FakeWorker(42, polls=2, on_poll=mark_first_step)
            workers.append(worker)
        popen = scripted(*workers)
        supervisor = policy_supervisor(popen, clock, crash_loop_k=3,
                                       crash_loop_window=60.0)
        for worker in workers:
            worker.supervisor = supervisor
        assert supervisor.run() == CRASH_LOOP_EXIT
        assert len(popen.launched) == 3

    def test_max_restarts_caps_the_loop(self):
        clock = FakeClock()

        def long_lived(worker):
            clock.time += 30.0

        popen = scripted(*[FakeWorker(42, polls=3, on_poll=long_lived)
                           for _ in range(10)])
        supervisor = policy_supervisor(popen, clock, crash_loop_k=100,
                                       crash_loop_window=60.0,
                                       max_restarts=4)
        assert supervisor.run() == CRASH_LOOP_EXIT
        assert len(popen.launched) == 5            # 1 launch + 4 relaunches

    def test_terminate_during_backoff_skips_the_relaunch(self):
        """Review regression: eviction arriving while the supervisor
        sleeps out a backoff must NOT spawn a fresh worker just to
        SIGTERM it (likely before its handler is even installed) — the
        loop exits with the preemption code instead."""
        clock = FakeClock()
        supervisor_box = {}

        def sleep_then_terminate(seconds):
            clock.sleep(seconds)
            if seconds >= 1.0:            # the backoff sleep, not a poll
                supervisor_box['sup'].terminate()

        popen = scripted(FakeWorker(42), FakeWorker(0))
        supervisor = Supervisor(['worker'], memstore=False, popen=popen,
                                clock=clock, sleep=sleep_then_terminate,
                                backoff_base=1.0, backoff_jitter=0.0)
        supervisor_box['sup'] = supervisor
        assert supervisor.run() == PREEMPTED_EXIT
        assert len(popen.launched) == 1   # the doomed relaunch never ran

    def test_recovery_timeline_event_from_marks(self):
        """detect -> relaunch -> restore -> first-step, stamped on the fake
        clock, emitted as ONE RecoveryTimeline event with stage offsets
        relative to detection."""
        clock = FakeClock()
        supervisor_box = {}

        def resumed(worker):
            if worker.count == 1:
                sup = supervisor_box['sup']
                sup._on_mark('restore', {'source': 'hot', 'step': 6})
                clock.time += 0.5
                sup._on_mark('first-step', {'step': 7})

        popen = scripted(FakeWorker(42), FakeWorker(0, polls=2,
                                                    on_poll=resumed))
        supervisor = policy_supervisor(popen, clock, backoff_base=1.0)
        supervisor_box['sup'] = supervisor
        seen = capture_events(supervisor)
        assert supervisor.run() == 0
        timelines = [e for e in seen if isinstance(e, RecoveryTimeline)]
        assert len(timelines) == 1
        timeline = timelines[0]
        assert timeline.source == 'hot' and timeline.step == 6
        assert set(timeline.stages) >= {'relaunch', 'restore', 'first-step'}
        assert timeline.stages['relaunch'] <= timeline.stages['restore']
        assert timeline.stages['restore'] < timeline.stages['first-step']
        assert timeline.seconds == timeline.stages['first-step'] > 0
        assert supervisor.timelines == [timeline]


# ---------------------------------------------------------------------------
# SIGTERM forwarding: real processes, stub (jax-free) workers


STUB_DRAINS = ('import pathlib, signal, sys, time\n'
               'signal.signal(signal.SIGTERM, lambda *a: sys.exit(43))\n'
               'pathlib.Path(sys.argv[1]).touch()   # handler armed\n'
               'time.sleep(120)\n')

STUB_IGNORES = ('import pathlib, signal, sys, time\n'
                'signal.signal(signal.SIGTERM, signal.SIG_IGN)\n'
                'pathlib.Path(sys.argv[1]).touch()\n'
                'time.sleep(120)\n')


class TestSigtermForwarding:

    def stub(self, tmp_path, source):
        path = tmp_path / 'stub.py'
        path.write_text(source)
        self.ready = tmp_path / 'ready'
        return [sys.executable, str(path), str(self.ready)]

    def when_ready(self, action):
        """Fire ``action`` once the stub's handler is armed — terminating
        before that would hit the default SIGTERM disposition instead of
        the handler under test."""

        def wait_then_act():
            deadline = time.monotonic() + 30
            while not self.ready.exists() and time.monotonic() < deadline:
                time.sleep(0.01)
            action()

        threading.Thread(target=wait_then_act, daemon=True).start()

    def test_sigterm_reaches_the_preemption_handler(self, tmp_path):
        """Regression: the forwarded SIGTERM must land in the worker's own
        handler — exit 43 (the preemption drain), NOT a SIGKILL — and the
        supervisor passes that code through without relaunching."""
        supervisor = Supervisor(self.stub(tmp_path, STUB_DRAINS),
                                memstore=False, grace=10.0)
        self.when_ready(supervisor.terminate)
        start = time.monotonic()
        assert supervisor.run() == PREEMPTED_EXIT
        assert time.monotonic() - start < 8.0      # drained, no grace burn

    def test_sigterm_via_installed_signal_handler(self, tmp_path):
        """The launcher wiring: the scheduler SIGTERMs the *supervisor*
        process; the installed handler forwards to the worker."""
        previous = signal_module.getsignal(signal_module.SIGTERM)
        supervisor = Supervisor(self.stub(tmp_path, STUB_DRAINS),
                                memstore=False, grace=10.0)
        supervisor.install_signal_handler()
        try:
            self.when_ready(
                lambda: os.kill(os.getpid(), signal_module.SIGTERM))
            assert supervisor.run() == PREEMPTED_EXIT
        finally:
            signal_module.signal(signal_module.SIGTERM, previous)

    def test_grace_expiry_escalates_to_sigkill(self, tmp_path, caplog):
        """A worker that ignores SIGTERM is SIGKILLed once the grace
        window closes. The supervisor still exits with the preemption
        code: a raw negative waitpid code through SystemExit would
        surface as a meaningless 128+ shell status."""
        import logging
        supervisor = Supervisor(self.stub(tmp_path, STUB_IGNORES),
                                memstore=False, grace=0.5)
        self.when_ready(supervisor.terminate)
        with caplog.at_level(logging.WARNING, 'tpusystem.supervisor'):
            assert supervisor.run() == PREEMPTED_EXIT
        assert 'grace expired' in caplog.text
        assert 'without draining' in caplog.text


# ---------------------------------------------------------------------------
# the memstore wire (no jax: blobs are plain bytes here)


class TestMemStoreWire:

    def test_push_fetch_roundtrip_chunked(self):
        store = MemStore()
        server = MemStoreServer(store, chunk_size=1024)
        client = MemStoreClient(server.address, chunk_size=1024)
        try:
            blob = os.urandom(10_000)              # ~10 chunks each way
            client.push(IDENTITY, 4, blob,
                        extras={'cursor': {'epoch': 0, 'batch': 4}})
            held = store.newest(IDENTITY)
            assert held.step == 4 and held.blob == blob
            fetched = client.fetch(IDENTITY)
            assert fetched.step == 4 and fetched.blob == blob
            assert fetched.extras == {'cursor': {'epoch': 0, 'batch': 4}}
            assert client.fetch('unknown-identity') is None
        finally:
            client.close()
            server.close()

    def test_stale_push_never_replaces_newer(self):
        store = MemStore()
        store.put(IDENTITY, 9, b'newer')
        store.put(IDENTITY, 3, b'older')
        assert store.newest(IDENTITY).blob == b'newer'

    def test_corrupted_slot_reads_as_absent(self, caplog):
        """RAM corruption (or a torn replication) must cost only the hot
        tier: the digest check turns the slot into a miss, never state."""
        import logging
        store = MemStore()
        entry = store.put(IDENTITY, 5, b'good bytes')
        entry.blob = b'bad  bytes'
        with caplog.at_level(logging.WARNING, 'tpusystem.memstore'):
            assert store.newest(IDENTITY) is None
        assert 'digest' in caplog.text

    def test_put_verifies_caller_digest(self):
        store = MemStore()
        with pytest.raises(ValueError, match='digest'):
            store.put(IDENTITY, 5, b'payload', digest=blob_digest(b'other'))

    def test_marks_reach_the_supervisor(self):
        marks = []
        server = MemStoreServer(on_mark=lambda s, i: marks.append((s, i)))
        client = MemStoreClient(server.address)
        try:
            client.mark('restore', source='hot', step=6)
            client.mark('first-step', step=7)
            deadline = time.monotonic() + 5
            while len(marks) < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert marks == [('restore', {'source': 'hot', 'step': 6}),
                             ('first-step', {'step': 7})]
        finally:
            client.close()
            server.close()

    def test_dead_supervisor_degrades_push_and_fetch(self, caplog):
        """Review regression: a supervisor that dies mid-run must cost
        only the hot tier — push returns False and fetch returns None
        (logged once), never an exception that would kill the worker with
        a non-restartable exit while disk checkpoints still stand."""
        import logging
        server = MemStoreServer()
        client = MemStoreClient(server.address)
        assert client.push(IDENTITY, 1, b'while alive') is True
        server.close()                    # the supervisor is OOM-killed
        with caplog.at_level(logging.WARNING, 'tpusystem.memstore'):
            assert client.push(IDENTITY, 2, b'after death') is False
            assert client.push(IDENTITY, 3, b'again') is False
            assert client.fetch(IDENTITY) is None
        assert caplog.text.count('supervisor unreachable') == 1  # logged once
        client.close()

    @staticmethod
    def _rebind(store, host, port):
        """A restarted supervisor re-listens at its old address; the
        kernel frees the port as soon as the dead client socket's FIN
        lands (the client's redial machinery closed it), which can race
        an immediate rebind by a few ms — retry like a real relaunch."""
        deadline = time.monotonic() + 5
        while True:
            try:
                return MemStoreServer(store, host=host, port=port)
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.02)

    def test_bounced_supervisor_redial_resumes_pushes(self):
        """The permanent-degradation regression: a supervisor that
        RESTARTS listens at the same address again, and the client must
        redial it — journal/hot-state pushes resume instead of silently
        freezing durability for the rest of the run."""
        store = MemStore()
        server = MemStoreServer(store)
        host, port = server.address
        client = MemStoreClient(server.address, redial_backoff=0.0)
        try:
            assert client.push(IDENTITY, 1, b'before the bounce') is True
            server.close()               # the supervisor dies...
            assert client.push(IDENTITY, 2, b'into the void') is False
            store = MemStore()           # ... and is relaunched fresh
            server = self._rebind(store, host, port)
            # the next call redials (backoff 0) and durability resumes
            assert client.push(IDENTITY, 3, b'after the bounce') is True
            assert store.newest(IDENTITY).step == 3
            fetched = client.fetch(IDENTITY)
            assert fetched.step == 3 and fetched.blob == b'after the bounce'
        finally:
            client.close()
            server.close()

    def test_redial_budget_is_bounded(self):
        """The redial ladder is capped per outage: once the budget is
        spent the client degrades permanently (the old contract) —
        even a healthy supervisor at the address is not re-dialed."""
        server = MemStoreServer()
        host, port = server.address
        client = MemStoreClient(server.address, redials=2,
                                redial_backoff=0.0)
        assert client.push(IDENTITY, 1, b'x') is True
        server.close()
        for step in range(2, 6):         # dead-socket push + 2 failed
            assert client.push(IDENTITY, step, b'y') is False   # redials
        server = self._rebind(MemStore(), host, port)
        try:                             # budget spent: stays degraded
            assert client.push(IDENTITY, 9, b'z') is False
        finally:
            client.close()
            server.close()

    def test_sharded_leaf_round_trip_is_bitwise(self):
        """The multi-host wire format: a sharded array serialized as its
        per-shard pieces reassembles bitwise onto the same sharding, and
        a layout the shards cannot cover is a typed failure (-> disk)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec
        from tpusystem.checkpoint.memstore import ShardedLeaf
        from tpusystem.parallel import MeshSpec
        mesh = MeshSpec(data=4).build(jax.devices('cpu')[:4])
        values = jnp.arange(64, dtype=jnp.float32).reshape(8, 8) * 0.37
        sharded = jax.device_put(
            values, NamedSharding(mesh, PartitionSpec('data')))
        leaf = ShardedLeaf.from_array(sharded)
        assert len(leaf.shards) == 4                  # one piece per slice
        assert all(piece.shape == (2, 8) for piece in leaf.shards.values())
        rebuilt = leaf.place(sharded)
        np.testing.assert_array_equal(np.asarray(rebuilt),
                                      np.asarray(sharded))
        assert rebuilt.sharding == sharded.sharding
        # a different layout wants slices this host never held
        other = jax.device_put(
            values, NamedSharding(mesh, PartitionSpec(None, 'data')))
        with pytest.raises(ValueError, match='do not cover'):
            leaf.place(other)

    def test_supervisor_client_env_plumbing(self):
        server = MemStoreServer()
        try:
            client = supervisor_client(server.env)
            assert client is not None
            client.push(IDENTITY, 1, b'via-env')
            assert server.store.newest(IDENTITY).blob == b'via-env'
            client.close()
            assert supervisor_client({}) is None           # unsupervised
            # unreachable supervisor: hot tier off, never an exception
            assert supervisor_client(
                {'TPUSYSTEM_SUPERVISOR': '127.0.0.1:1'}) is None
        finally:
            server.close()


# ---------------------------------------------------------------------------
# buddy replication over the control plane (supervisor pod)


class TestBuddyReplication:

    def pod(self, faults=None):
        from tpusystem.parallel.chaos import ChaosTransport
        hub = Hub(2)
        make = (lambda r: ChaosTransport(hub.address, r, 2, faults=faults[r])
                if faults else TcpTransport(hub.address, r, 2))
        transports = [make(rank) for rank in range(2)]
        deadline = time.monotonic() + 5
        while len(hub._clients) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        return hub, transports

    def test_push_is_replicated_to_the_buddy(self):
        hub, transports = self.pod()
        supervisors = [Supervisor(['w'], rank=rank,
                                  transport=transports[rank], buddy=1 - rank)
                       for rank in range(2)]
        try:
            client = MemStoreClient(supervisors[0].server.address)
            client.push(IDENTITY, 7, b'hot state bytes', extras={'b': 7})
            client.close()
            deadline = time.monotonic() + 5
            while (supervisors[1].store.newest(IDENTITY, replica=True) is None
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            replica = supervisors[1].store.newest(IDENTITY, replica=True)
            assert replica is not None
            assert replica.blob == b'hot state bytes'
            assert replica.step == 7 and replica.extras == {'b': 7}
            # the buddy's LOCAL namespace is untouched — replicas cannot
            # shadow the buddy host's own state
            assert supervisors[1].store.newest(IDENTITY) is None
        finally:
            for supervisor in supervisors:
                supervisor.close()
            for transport in transports:
                transport.close()
            hub.close()

    def test_replaced_host_pulls_from_its_buddy(self):
        """The replaced-host path: a FRESH supervisor (empty RAM) serving
        its worker's `get` pulls the hot state back from the buddy's
        replica slot over the control plane, digest-verified end to end."""
        hub, transports = self.pod()
        original = Supervisor(['w'], rank=0, transport=transports[0], buddy=1)
        buddy = Supervisor(['w'], rank=1, transport=transports[1], buddy=0)
        try:
            client = MemStoreClient(original.server.address)
            client.push(IDENTITY, 9, b'replicate me', extras={'b': 9})
            client.close()
            deadline = time.monotonic() + 5
            while (buddy.store.newest(IDENTITY, replica=True) is None
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            # host 0 is replaced: its supervisor restarts with empty RAM
            original.close()
            transports[0].close()
            replacement_transport = TcpTransport(hub.address, 0, 2)
            replacement = Supervisor(['w'], rank=0,
                                     transport=replacement_transport, buddy=1)
            try:
                client = MemStoreClient(replacement.server.address)
                pulled = client.fetch(IDENTITY)
                client.close()
                assert pulled is not None
                assert pulled.step == 9 and pulled.blob == b'replicate me'
                # and it is now cached locally for the next get
                assert replacement.store.newest(IDENTITY).step == 9
            finally:
                replacement.close()
                replacement_transport.close()
        finally:
            buddy.close()
            original.close()
            for transport in transports:
                transport.close()
            hub.close()

    def test_concurrent_buddy_push_cannot_satisfy_a_pull(self):
        """Review regression: while a replaced host pulls its state back
        (key 'hot:{id}'), the buddy's own concurrent replication push of
        ITS state (key 'replica:{id}') must never be mistaken for the
        pull's answer — with symmetric shard shapes that would silently
        restore the wrong host's bytes."""
        hub, transports = self.pod()
        try:
            # rank 1 actively replicates its own state toward rank 0
            transports[1].send_blob(0, f'replica:{IDENTITY}', b'rank1 OWN')
            # rank 0's pull must NOT see it: rank 1 has no replica slot
            # for rank 0 yet, so the honest answer is a NAK
            from tpusystem.parallel.multihost import BlobError
            transports[0].on_blob = lambda *a: None   # swallow the push
            with pytest.raises(BlobError, match='no blob'):
                transports[0].fetch_blob(1, f'hot:{IDENTITY}', timeout=5)
        finally:
            for transport in transports:
                transport.close()
            hub.close()

    def test_truncated_replication_keeps_the_previous_copy(self, caplog):
        """Chaos: the replication transfer loses half a chunk — the
        transfer digest catches it at the receiving transport and the
        buddy keeps its previous (older) replica instead of a torn one."""
        import logging
        from tpusystem.parallel.chaos import Faults
        faults = [Faults(seed=1, truncate=1.0, kinds=('blob',)),
                  Faults(seed=2)]
        hub, transports = self.pod(faults=faults)
        supervisors = [Supervisor(['w'], rank=rank,
                                  transport=transports[rank], buddy=1 - rank)
                       for rank in range(2)]
        try:
            # seed the buddy with a good older replica, fault-free
            from tpusystem.checkpoint.memstore import HotState
            good = pack_hot(HotState(step=3, digest=blob_digest(b'v3'),
                                     blob=b'v3', extras=None))
            supervisors[1]._accept_replica(0, f'replica:{IDENTITY}', good)
            assert supervisors[1].store.newest(IDENTITY, replica=True).step == 3
            # now the live replication path, with every blob chunk truncated
            client = MemStoreClient(supervisors[0].server.address)
            with caplog.at_level(logging.WARNING, 'tpusystem.multihost'):
                client.push(IDENTITY, 8, b'v8 fresh state')
                deadline = time.monotonic() + 3
                while ('digest' not in caplog.text
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
            client.close()
            assert faults[0].truncated       # the fault really fired
            held = supervisors[1].store.newest(IDENTITY, replica=True)
            assert held is not None and held.step == 3   # old copy stands
        finally:
            for supervisor in supervisors:
                supervisor.close()
            for transport in transports:
                transport.close()
            hub.close()


# ---------------------------------------------------------------------------
# hot_resume: the restart decision, bitwise (in-process, real jax state)


class TestHotResume:

    def parts(self):
        import jax.numpy as jnp
        import numpy as np
        from tpusystem.models import MLP
        from tpusystem.train import (Adam, CrossEntropyLoss, build_train_step,
                                     flax_apply, init_state)
        module = MLP(features=(16,), classes=10, dropout=0.2)
        optimizer = Adam(lr=1e-2)
        state = init_state(module, optimizer, jnp.zeros((1, 28, 28)), rng=7)
        step = build_train_step(flax_apply(module), CrossEntropyLoss(),
                                optimizer)
        rng = np.random.default_rng(0)
        inputs = jnp.asarray(rng.normal(size=(8, 28, 28)), jnp.float32)
        targets = jnp.asarray(np.arange(8) % 10)
        return state, step, inputs, targets

    def trained(self, steps=3):
        state, step, inputs, targets = self.parts()
        for _ in range(steps):
            state, _ = step(state, inputs, targets)
        return state

    def assert_bitwise(self, left, right):
        import jax
        import numpy as np
        for a, b in zip(jax.tree.leaves(left), jax.tree.leaves(right)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_hot_restore_is_bitwise_identical_to_disk(self, tmp_path):
        """The headline property: restoring from RAM and restoring the
        disk checkpoint of the same step produce the same bits — hot is a
        faster path to the SAME state, never a different one."""
        import jax
        from tpusystem.checkpoint import Checkpointer, hot_resume
        state = self.trained()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            checkpointer.save(IDENTITY, 3, state, extras={'k': 3})
            store = MemStore()
            store.put(IDENTITY, 3, serialize_state(state), extras={'k': 3})
            blank, _, _, _ = self.parts()
            restored, step, extras, source = hot_resume(
                checkpointer, IDENTITY, blank, store)
            assert source == 'hot' and step == 3 and extras == {'k': 3}
            disk = checkpointer.restore(IDENTITY, blank, epoch=3)
            self.assert_bitwise(restored, disk)
            # shardings land like a disk restore would
            for leaf in jax.tree.leaves(restored):
                assert leaf.sharding is not None

    def test_hot_ahead_of_disk_is_preferred(self, tmp_path):
        """Pushes run at step cadence, disk saves can lag: a hot step
        NEWER than the last commit must win (that is the whole point)."""
        from tpusystem.checkpoint import Checkpointer, hot_resume
        older = self.trained(steps=2)
        newer = self.trained(steps=4)
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            checkpointer.save(IDENTITY, 2, older)
            store = MemStore()
            store.put(IDENTITY, 4, serialize_state(newer))
            blank, _, _, _ = self.parts()
            restored, step, _, source = hot_resume(checkpointer, IDENTITY,
                                                   blank, store)
            assert (source, step) == ('hot', 4)
            self.assert_bitwise(restored, newer)

    def test_stale_hot_state_falls_back_to_disk(self, tmp_path, caplog):
        """Chaos scenario 'stale-hot-state': pushes stopped while disk
        saves continued — RAM must NOT silently rewind training."""
        import logging
        from tpusystem.checkpoint import Checkpointer, hot_resume
        older = self.trained(steps=2)
        newer = self.trained(steps=4)
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            checkpointer.save(IDENTITY, 4, newer)
            store = MemStore()
            store.put(IDENTITY, 2, serialize_state(older))
            blank, _, _, _ = self.parts()
            with caplog.at_level(logging.WARNING, 'tpusystem.memstore'):
                restored, step, _, source = hot_resume(
                    checkpointer, IDENTITY, blank, store)
            assert (source, step) == ('disk', 4)
            assert 'stale' in caplog.text
            self.assert_bitwise(restored, newer)

    def test_corrupted_hot_state_falls_back_to_disk(self, tmp_path):
        from tpusystem.checkpoint import Checkpointer, hot_resume
        state = self.trained()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            checkpointer.save(IDENTITY, 3, state)
            store = MemStore()
            entry = store.put(IDENTITY, 5, serialize_state(state))
            entry.blob = entry.blob[:-1] + bytes([entry.blob[-1] ^ 1])
            blank, _, _, _ = self.parts()
            restored, step, _, source = hot_resume(checkpointer, IDENTITY,
                                                   blank, store)
            assert (source, step) == ('disk', 3)
            self.assert_bitwise(restored, state)

    def test_unsupervised_resume_is_plain_disk(self, tmp_path):
        from tpusystem.checkpoint import Checkpointer, hot_resume
        state = self.trained()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            checkpointer.save(IDENTITY, 3, state)
            blank, _, _, _ = self.parts()
            _, step, _, source = hot_resume(checkpointer, IDENTITY, blank,
                                            client=None)
            assert (source, step) == ('disk', 3)

    def test_restore_mark_rides_the_timeline(self, tmp_path):
        from tpusystem.checkpoint import Checkpointer, hot_resume
        state = self.trained()

        class Marked(MemStore):
            def __init__(self):
                super().__init__()
                self.marks = []

            def mark(self, stage, **info):
                self.marks.append((stage, info))

        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            checkpointer.save(IDENTITY, 3, state)
            store = Marked()
            store.put(IDENTITY, 3, serialize_state(state))
            blank, _, _, _ = self.parts()
            hot_resume(checkpointer, IDENTITY, blank, store)
            assert store.marks == [('restore', {'source': 'hot', 'step': 3})]


# ---------------------------------------------------------------------------
# the end-to-end drill: SIGKILL mid-epoch under the Supervisor, over REAL
# processes — relaunch, hot-restore, bitwise-identical continuation


DRILL_WORKER = r'''
import json, os, signal, sys
out_path, ckpt_root = sys.argv[1], sys.argv[2]
die_at, total = int(sys.argv[3]), int(sys.argv[4])
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
import jax
import jax.numpy as jnp
import numpy as np
from tpusystem.checkpoint import (Checkpointer, hot_resume, serialize_state,
                                  supervisor_client)
from tpusystem.data import Loader, SyntheticDigits
from tpusystem.models import MLP
from tpusystem.train import (Adam, CrossEntropyLoss, build_train_step,
                             flax_apply, init_state, resume_extras)

IDENTITY = 'drill-mlp'

def out(record):
    with open(out_path, 'a') as handle:
        handle.write(json.dumps(record) + '\n')
        handle.flush()
        os.fsync(handle.fileno())

dataset = SyntheticDigits(samples=40, seed=4)
loader = Loader(dataset, batch_size=8, shuffle=True, seed=3)   # 5 per epoch
module = MLP(features=(16,), classes=10, dropout=0.2)
optimizer = Adam(lr=1e-2)
state = init_state(module, optimizer, jnp.zeros((1, 28, 28)), rng=7)
step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer)

client = supervisor_client()
checkpointer = Checkpointer(ckpt_root, async_save=False)
try:
    state, at, extras, source = hot_resume(checkpointer, IDENTITY, state,
                                           client)
except FileNotFoundError:
    pass            # fresh start: nothing hot, nothing committed
else:
    # the acceptance proof: the restored state is bitwise-equal to the
    # disk checkpoint of the SAME step, whichever path produced it
    same = None
    if checkpointer.verify(IDENTITY, at):
        disk = checkpointer.restore(IDENTITY, state, epoch=at)
        same = all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(jax.tree.leaves(state),
                                   jax.tree.leaves(disk)))
    out({'resumed': at, 'source': source, 'bitwise_disk_equal': same})
    loader.seek(extras['cursor'])

first = True
done = False
while not done:
    for inputs, targets in loader:
        state, (_, loss) = step(state, inputs, targets)
        at = int(state.step)
        extras = resume_extras(state, loader)
        checkpointer.save(IDENTITY, at, state, extras=extras)
        if client is not None:
            client.push(IDENTITY, at, serialize_state(state), extras=extras)
        if first:
            first = False
            if client is not None:
                client.mark('first-step', step=at)
        out({'step': at, 'loss': float(loss)})
        if at == die_at:
            os.kill(os.getpid(), signal.SIGKILL)    # mid-epoch, no cleanup
        if at >= total:
            done = True
            break
checkpointer.close()
out({'done': True})
'''


@pytest.mark.slow
class TestEndToEndDrill:

    DIE_AT, TOTAL = 6, 10          # dies mid-epoch 2 (5 batches per epoch)

    def launch(self, tmp_path, name, *, die_at, memstore, popen=None):
        run_dir = tmp_path / name
        run_dir.mkdir()
        worker = run_dir / 'worker.py'
        worker.write_text(DRILL_WORKER)
        out_path = run_dir / 'out.jsonl'
        argv = [sys.executable, str(worker), str(out_path),
                str(tmp_path / 'ckpt' / name), str(die_at), str(self.TOTAL)]
        env = {'PYTHONPATH': str(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), 'JAX_PLATFORMS': 'cpu'}
        kwargs = {}
        if popen is not None:
            kwargs['popen'] = popen
        supervisor = Supervisor(argv, memstore=memstore, env=env,
                                backoff_base=0.05, backoff_cap=0.2,
                                crash_loop_window=0.0, **kwargs)
        code = supervisor.run()
        records = [json.loads(line)
                   for line in out_path.read_text().splitlines()]
        losses = {r['step']: r['loss'] for r in records if 'step' in r}
        resumes = [r for r in records if 'resumed' in r]
        return code, losses, resumes, supervisor

    def test_sigkill_hot_restore_bitwise(self, tmp_path):
        """The acceptance drill: SIGKILL mid-epoch under the Supervisor ->
        relaunch -> restore from the memstore (source 'hot', bitwise-equal
        to the disk checkpoint of the same step) -> losses from the resume
        on are bitwise-identical to an uninterrupted reference. Then the
        same drill with the memstore disabled (disk path) and with the hot
        copy corrupted between runs (chaos: SDC in supervisor RAM) — both
        fall back to disk and still converge identically."""
        code, reference, resumes, _ = self.launch(
            tmp_path, 'ref', die_at=0, memstore=False)
        assert code == 0 and not resumes
        assert sorted(reference) == list(range(1, self.TOTAL + 1))

        # --- hot path -------------------------------------------------
        code, losses, resumes, supervisor = self.launch(
            tmp_path, 'hot', die_at=self.DIE_AT, memstore=True)
        assert code == 0
        assert supervisor.restarts == 1
        assert len(resumes) == 1
        assert resumes[0]['source'] == 'hot'
        assert resumes[0]['resumed'] == self.DIE_AT
        assert resumes[0]['bitwise_disk_equal'] is True
        assert sorted(losses) == list(range(1, self.TOTAL + 1))
        for at in range(1, self.TOTAL + 1):
            assert losses[at] == reference[at], (at, losses[at],
                                                 reference[at])
        # the recovery timeline covered detect -> first-step
        assert len(supervisor.timelines) == 1
        timeline = supervisor.timelines[0]
        assert timeline.source == 'hot'
        assert set(timeline.stages) >= {'relaunch', 'restore', 'first-step'}

        # --- memstore disabled: the disk fallback ---------------------
        code, losses, resumes, _ = self.launch(
            tmp_path, 'disk', die_at=self.DIE_AT, memstore=False)
        assert code == 0
        assert resumes[0]['source'] == 'disk'
        for at in range(1, self.TOTAL + 1):
            assert losses[at] == reference[at]

        # --- hot copy corrupted between runs: digest -> disk ----------
        launches = []

        def corrupting_popen(argv, env=None):
            launches.append(argv)
            if len(launches) == 2:     # the relaunch: flip one RAM bit
                slot = corrupting_popen.supervisor.store._slots[
                    (IDENTITY, False)]
                slot.blob = slot.blob[:-1] + bytes([slot.blob[-1] ^ 1])
            return subprocess.Popen(argv, env=env)

        run_dir = tmp_path / 'corrupt'
        run_dir.mkdir()
        worker = run_dir / 'worker.py'
        worker.write_text(DRILL_WORKER)
        out_path = run_dir / 'out.jsonl'
        argv = [sys.executable, str(worker), str(out_path),
                str(tmp_path / 'ckpt' / 'corrupt'), str(self.DIE_AT),
                str(self.TOTAL)]
        env = {'PYTHONPATH': str(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), 'JAX_PLATFORMS': 'cpu'}
        supervisor = Supervisor(argv, memstore=True, env=env,
                                backoff_base=0.05, backoff_cap=0.2,
                                crash_loop_window=0.0,
                                popen=corrupting_popen)
        corrupting_popen.supervisor = supervisor
        assert supervisor.run() == 0
        records = [json.loads(line)
                   for line in out_path.read_text().splitlines()]
        losses = {r['step']: r['loss'] for r in records if 'step' in r}
        resumes = [r for r in records if 'resumed' in r]
        assert resumes[0]['source'] == 'disk'      # digest failed -> disk
        assert resumes[0]['bitwise_disk_equal'] is True
        for at in range(1, self.TOTAL + 1):
            assert losses[at] == reference[at]
