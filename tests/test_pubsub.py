"""Publisher/Subscriber contracts (reference parity: tests/test_pubsub.py:12-36)."""

import pytest

from tpusystem.services import Publisher, Subscriber
from tpusystem.depends import Depends


def test_multi_topic_subscription_with_di():
    subscriber = Subscriber()
    stored = []

    def metrics():
        raise NotImplementedError

    @subscriber.subscribe('loss', 'accuracy')
    def store(metric, metrics: list = Depends(metrics)):
        metrics.append(metric)

    subscriber.dependency_overrides[metrics] = lambda: stored

    publisher = Publisher()
    publisher.register(subscriber)
    publisher.publish(0.1, 'loss')
    publisher.publish(0.9, 'accuracy')
    publisher.publish('ignored', 'other-topic')
    assert stored == [0.1, 0.9]


def test_handler_exception_propagates_to_publisher():
    subscriber = Subscriber()

    @subscriber.subscribe('accuracy')
    def early_stop(metric):
        if metric > 0.99:
            raise StopIteration

    publisher = Publisher()
    publisher.register(subscriber)
    publisher.publish(0.5, 'accuracy')  # fine
    with pytest.raises(StopIteration):
        publisher.publish(1.0, 'accuracy')


def test_reentrant_receive_reroutes_between_handlers():
    subscriber = Subscriber()
    seen = []

    @subscriber.subscribe('raw')
    def reroute(message):
        subscriber.receive(message * 2, 'derived')

    @subscriber.subscribe('derived')
    def collect(message):
        seen.append(message)

    subscriber.receive(21, 'raw')
    assert seen == [42]
