"""Divergence-proof training drills: guard, escalation ladder, SDC parity.

Every rung of the sentinel's ladder (tpusystem.train.sentinel) is exercised
with the chaos harness's *internal* fault kinds — deterministic, seeded,
replayable — the same discipline test_chaos.py applies to external faults:

* in-graph guard: NaN/Inf gradients and EMA z-score spikes suppress the
  optimizer update bitwise (params AND moments untouched), inside the one
  fused jitted program;
* policy ladder: skip events → LR backoff (and recovery) → rollback to the
  last committed checkpoint *before* the anomaly with a PaLM-style
  skip-window (post-rollback losses bitwise-match a fault-free reference
  that trained on the same surviving batches) → bounded give-up
  (DivergenceError, exit code 44);
* SDC parity: a FlipParamBit on one DP replica is caught by the
  cross-replica checksum gather before the next checkpoint commits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpusystem.checkpoint import Checkpointer
from tpusystem.data import Loader, SyntheticDigits
from tpusystem.models import MLP
from tpusystem.observe.events import (AnomalyDetected, BackoffApplied,
                                      ReplicaDiverged, RolledBack)
from tpusystem.parallel import MeshSpec, replicated
from tpusystem.parallel.chaos import CorruptBatch, CorruptGrads, FlipParamBit
from tpusystem.parallel.collectives import replica_checksums
from tpusystem.parallel.recovery import (DIVERGED_EXIT, RESTART_EXITS,
                                         DivergenceError, exit_for_restart)
from tpusystem.services.prodcon import Consumer, Producer
from tpusystem.train import (Adam, CrossEntropyLoss, Guard, Sentinel,
                             build_multi_step, build_train_step, flax_apply,
                             grouped_batches, init_state, resume_extras)
from tpusystem.train.sentinel import (HEALTH_GNORM, HEALTH_LOSS, HEALTH_OK,
                                      HEALTH_Z)

IDENTITY = 'sentinel-mlp'


def make_parts(*, guard=None, fault=None, seed=3, dropout=0.2):
    """One training cell: deterministic loader + model + jitted step."""
    dataset = SyntheticDigits(samples=40, seed=4)
    loader = Loader(dataset, batch_size=8, shuffle=True, seed=seed)  # 5/epoch
    module = MLP(features=(16,), classes=10, dropout=dropout)
    optimizer = Adam(lr=1e-2)
    state = init_state(module, optimizer, jnp.zeros((1, 28, 28)), rng=7)
    if guard is not None:
        state = guard.arm(state)
    step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer,
                            guard=guard, fault=fault)
    return loader, state, step


def snapshot(tree):
    """Host copies of every leaf, taken BEFORE the buffers are donated."""
    return jax.tree.map(lambda leaf: np.asarray(leaf), tree)


def capture(*event_types):
    """(producer, seen) with every dispatched event of the types recorded."""
    producer = Producer()
    consumer = Consumer()
    seen = []
    for event_type in event_types:
        consumer.register(event_type, seen.append)
    producer.register(consumer)
    return producer, seen


class TestGuardedStep:
    """The in-graph rung: detection + suppression inside the jitted step."""

    def test_healthy_run_matches_unguarded_bitwise(self):
        """guard= must be a bitwise no-op on a healthy trajectory (the
        update path multiplies by lr_scale=1.0 and selects the new branch
        — both exact), so flipping it on mid-project never forks a run."""
        guard = Guard()
        loader, plain_state, plain_step = make_parts()
        loader2, guarded_state, guarded_step = make_parts(guard=guard)
        for (inputs, targets), (inputs2, targets2) in zip(loader, loader2):
            plain_state, (_, plain_loss) = plain_step(plain_state, inputs,
                                                      targets)
            guarded_state, (_, guarded_loss) = guarded_step(guarded_state,
                                                            inputs2, targets2)
            assert float(plain_loss) == float(guarded_loss)
        for a, b in zip(jax.tree.leaves(plain_state.params),
                        jax.tree.leaves(guarded_state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(guarded_state.health.bad_steps) == 0
        assert int(guarded_state.health.count) == 5

    def test_nan_grads_suppress_update_bitwise(self):
        """CorruptGrads NaN at step 3: params and optimizer moments after
        the bad step are bitwise the step-2 values, the step counter still
        advances (the batch was consumed), and training continues finite."""
        guard = Guard()
        loader, state, step = make_parts(guard=guard,
                                         fault=CorruptGrads(step=3))
        frozen = None
        for inputs, targets in loader:
            before_params = snapshot(state.params)
            before_opt = snapshot(state.opt_state)
            before_ema = float(state.health.ema_norm)
            state, (_, loss) = step(state, inputs, targets)
            if int(state.step) == 3:
                frozen = (before_params, before_opt, before_ema)
                row = np.asarray(state.health.last)
                assert row[HEALTH_OK] == 0.0
                assert not np.isfinite(row[HEALTH_GNORM])
                assert int(state.health.bad_steps) == 1
                for before, after in zip(jax.tree.leaves(before_params),
                                         jax.tree.leaves(state.params)):
                    np.testing.assert_array_equal(before, np.asarray(after))
                for before, after in zip(jax.tree.leaves(before_opt),
                                         jax.tree.leaves(state.opt_state)):
                    np.testing.assert_array_equal(before, np.asarray(after))
                # the anomaly must not fold into the EMA it is judged by
                assert float(state.health.ema_norm) == before_ema
            else:
                assert np.isfinite(float(loss))
        assert frozen is not None
        assert int(state.step) == 5 and int(state.health.bad_steps) == 1

    def test_finite_spike_flagged_by_zscore(self):
        """A finite 200x grad spike passes every isfinite check — only the
        EMA z-score rung catches it (armed after warmup)."""
        guard = Guard(warmup=4, zmax=6.0)
        _, state, step = make_parts(
            guard=guard, fault=CorruptGrads(step=8, mode='spike', scale=200.0),
            dropout=0.0)
        rng = np.random.default_rng(0)
        inputs = jnp.asarray(rng.standard_normal((8, 28, 28)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
        for _ in range(7):
            state, _ = step(state, inputs, targets)
        before = snapshot(state.params)
        state, _ = step(state, inputs, targets)
        row = np.asarray(state.health.last)
        assert row[HEALTH_OK] == 0.0 and np.isfinite(row[HEALTH_GNORM])
        assert row[HEALTH_Z] > 6.0
        for a, b in zip(jax.tree.leaves(before),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(a, np.asarray(b))

    def test_spike_detector_respects_warmup(self):
        """Before ``warmup`` healthy steps the variance estimate is noise:
        the same spike must pass (finite!) instead of tripping a phantom."""
        guard = Guard(warmup=100)
        _, state, step = make_parts(
            guard=guard, fault=CorruptGrads(step=3, mode='spike', scale=200.0),
            dropout=0.0)
        rng = np.random.default_rng(0)
        inputs = jnp.asarray(rng.standard_normal((8, 28, 28)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
        for _ in range(3):
            state, _ = step(state, inputs, targets)
        assert int(state.health.bad_steps) == 0
        assert np.asarray(state.health.last)[HEALTH_OK] == 1.0

    def test_lr_scale_scales_the_update_exactly(self):
        """HealthStats.lr_scale = 0.5 halves the applied update (the scale
        multiplies the optax update directly, so for Adam/AdamW/SGD it IS a
        learning-rate change) — the backoff lever needs no recompilation.
        Deltas are compared through a params-sized add/subtract, hence
        allclose rather than bitwise."""
        guard = Guard()
        _, state_full, step = make_parts(guard=guard, dropout=0.0)
        _, state_half, _ = make_parts(guard=guard, dropout=0.0)
        state_half = state_half.replace(health=state_half.health.replace(
            lr_scale=jnp.float32(0.5)))
        rng = np.random.default_rng(1)
        inputs = jnp.asarray(rng.standard_normal((8, 28, 28)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
        before = snapshot(state_full.params)
        state_full, _ = step(state_full, inputs, targets)
        state_half, _ = step(state_half, inputs, targets)
        for initial, full, half in zip(jax.tree.leaves(before),
                                       jax.tree.leaves(state_full.params),
                                       jax.tree.leaves(state_half.params)):
            np.testing.assert_allclose(
                (np.asarray(full) - initial) * 0.5,
                np.asarray(half) - initial, rtol=1e-4, atol=1e-7)

    def test_multi_step_stacks_per_step_health(self):
        """build_multi_step(guard=True): the N-step dispatch returns the
        [N, 4] health matrix alongside the loss vector, so the Sentinel
        reviews every step of the group at one sync."""
        guard = Guard()
        loader, state, _ = make_parts(guard=guard)
        module = MLP(features=(16,), classes=10, dropout=0.2)
        optimizer = Adam(lr=1e-2)
        inner = build_train_step(flax_apply(module), CrossEntropyLoss(),
                                 optimizer, guard=guard,
                                 fault=CorruptGrads(step=2), jit=False)
        multi = build_multi_step(inner, guard=True)
        (inputs, targets), = grouped_batches(loader, 5)
        state, (losses, health) = multi(state, inputs, targets)
        losses, health = np.asarray(losses), np.asarray(health)
        assert losses.shape == (5,) and health.shape == (5, 4)
        assert health[1, HEALTH_OK] == 0.0          # step 2 was the bad one
        assert health[[0, 2, 3, 4], HEALTH_OK].tolist() == [1.0] * 4
        assert int(state.health.bad_steps) == 1

    def test_guard_requires_armed_state(self):
        guard = Guard()
        module = MLP(features=(16,), classes=10)
        optimizer = Adam(lr=1e-2)
        step = build_train_step(flax_apply(module), CrossEntropyLoss(),
                                optimizer, guard=guard)
        state = init_state(module, optimizer, jnp.zeros((1, 28, 28)))  # unarmed
        with pytest.raises(AssertionError, match='arm'):
            step(state, jnp.zeros((8, 28, 28)),
                 jnp.zeros((8,), jnp.int32))


class TestSentinelPolicy:
    """The host-side ladder over the health vector, at review cadence."""

    def drive(self, loader, state, step, sentinel, *, until,
              corrupt=None, checkpointer=None):
        """Epoch loop: step, checkpoint, review — losses recorded in
        arrival order (a rollback revisits step numbers). Terminates once
        step ``until`` completes HEALTHILY: a suppressed step at the target
        must still reach its review (that's where the rollback lives)."""
        losses = []
        while True:
            for inputs, targets in loader:
                if corrupt is not None:
                    inputs = corrupt(inputs)
                state, (_, loss) = step(state, inputs, targets)
                losses.append((int(state.step), float(loss)))
                if checkpointer is not None:
                    checkpointer.save(IDENTITY, int(state.step), state,
                                      extras=resume_extras(state, loader))
                state = sentinel.review(state)
                healthy = bool(
                    np.asarray(state.health.last)[HEALTH_OK] >= 0.5)
                if int(state.step) >= until and healthy:
                    return state, losses

    def test_anomaly_events_emitted_at_review(self):
        producer, seen = capture(AnomalyDetected)
        guard = Guard()
        loader, state, step = make_parts(guard=guard,
                                         fault=CorruptGrads(step=2))
        sentinel = Sentinel(producer=producer, model='drill')
        state, _ = self.drive(loader, state, step, sentinel, until=4)
        assert [event.step for event in seen] == [2]
        assert seen[0].kind == 'nonfinite' and seen[0].model == 'drill'
        assert not np.isfinite(seen[0].gnorm)

    def test_backoff_then_recovery(self):
        """One bad step at backoff_after=1 halves lr_scale (event + hook);
        a healthy streak of recover_after restores full rate."""
        producer, seen = capture(BackoffApplied)
        hook_calls = []
        guard = Guard()
        loader, state, step = make_parts(guard=guard,
                                         fault=CorruptGrads(step=2))
        sentinel = Sentinel(producer=producer, backoff_after=1,
                            recover_after=2, window=8,
                            on_backoff=lambda level, scale:
                            hook_calls.append((level, scale)))
        state, _ = self.drive(loader, state, step, sentinel, until=5)
        assert [(event.level, event.scale) for event in seen] == [
            (1, 0.5), (0, 1.0)]
        assert hook_calls == [(1, 0.5)]          # recovery is not a backoff
        assert float(state.health.lr_scale) == 1.0

    def test_rollback_skip_window_matches_fault_free_reference(self, tmp_path):
        """The acceptance drill: batches feeding steps 6-9 are poisoned
        (CorruptBatch — data-borne, so the skip-window genuinely escapes
        it). The guard suppresses all four updates, the sentinel rolls back
        to the last committed step before the anomaly (5) and keeps the
        loader cursor (the skip-window). From there the trajectory must be
        BITWISE identical to a fault-free reference that trained to step 5
        and skipped the same four batches."""
        guard = Guard()
        producer, seen = capture(RolledBack)

        # fault-free reference: 5 steps, skip the window, 3 more steps
        loader, state, step = make_parts(guard=guard)
        reference = {}
        consumed = 0
        iterator = iter(loader)
        while int(state.step) < 5:
            inputs, targets = next(iterator)
            consumed += 1
            state, (_, loss) = step(state, inputs, targets)
        iterator.close()
        loader.seek({'epoch': 1, 'batch': 4})    # past the 4 poisoned batches
        while int(state.step) < 8:
            for inputs, targets in loader:
                state, (_, loss) = step(state, inputs, targets)
                reference[int(state.step)] = float(loss)
                if int(state.step) >= 8:
                    break

        # chaos run: same seeds, poisoned window, checkpoint every step
        loader, state, step = make_parts(guard=guard)
        with Checkpointer(tmp_path, async_save=False,
                          max_to_keep=None) as checkpointer:
            sentinel = Sentinel(checkpointer=checkpointer, identity=IDENTITY,
                                loader=loader, producer=producer,
                                rollback_after=4, window=8)
            state, losses = self.drive(
                loader, state, step, sentinel, until=8,
                corrupt=CorruptBatch(batch=6, steps=4),
                checkpointer=checkpointer)
            # the rollback happened: steps 6..9 ran suppressed, then the
            # counter rewound to 5 and steps 6..8 reran on fresh batches
            assert [event.to_step for event in seen] == [5]
            assert seen[0].step == 9
            assert seen[0].window['to'] == {'epoch': 1, 'batch': 4}
            assert checkpointer.latest(IDENTITY) == 8   # dead branch pruned
            # rollback resets the backoff ladder: host level and the
            # restored (checkpointed, pre-burst) lr_scale stay in sync
            assert sentinel.level == 0
            assert float(state.health.lr_scale) == 1.0
        resumed = dict(losses[-3:])
        assert sorted(resumed) == [6, 7, 8]
        for at in (6, 7, 8):
            assert resumed[at] == reference[at], (at, resumed, reference)

    def test_persistent_divergence_bounded_giveup(self, tmp_path):
        """CorruptGrads is keyed on the STEP COUNTER, so a rollback rewinds
        straight back into the fault window — the model of a divergence
        that rollback cannot fix. The second rollback attempt must give up
        with DivergenceError -> exit code 44 (not a restart code)."""
        guard = Guard()
        loader, state, step = make_parts(guard=guard,
                                         fault=CorruptGrads(step=6, steps=4))
        with Checkpointer(tmp_path, async_save=False,
                          max_to_keep=None) as checkpointer:
            sentinel = Sentinel(checkpointer=checkpointer, identity=IDENTITY,
                                loader=loader, rollback_after=4, window=8,
                                max_rollbacks=1)
            with pytest.raises(DivergenceError) as excinfo:
                self.drive(loader, state, step, sentinel, until=20,
                           checkpointer=checkpointer)
        assert sentinel.rollbacks == 1
        assert exit_for_restart(excinfo.value).code == DIVERGED_EXIT
        assert DIVERGED_EXIT not in RESTART_EXITS

    def test_rollback_without_predating_checkpoint_gives_up(self, tmp_path):
        """An anomaly on the very first step has nothing committed before
        it: the ladder must give up typed, not restore a bad branch."""
        guard = Guard()
        loader, state, step = make_parts(guard=guard,
                                         fault=CorruptGrads(step=1))
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            sentinel = Sentinel(checkpointer=checkpointer, identity=IDENTITY,
                                loader=loader, rollback_after=1)
            with pytest.raises(DivergenceError, match='predates'):
                self.drive(loader, state, step, sentinel, until=3,
                           checkpointer=checkpointer)


class TestParity:
    """SDC detection: cross-replica checksums over the mesh data axis."""

    def placed_state(self, mesh):
        module = MLP(features=(16,), classes=10, dropout=0.0)
        optimizer = Adam(lr=1e-2)
        state = init_state(module, optimizer, jnp.zeros((1, 28, 28)), rng=1)
        return jax.tree.map(lambda leaf: jax.device_put(leaf,
                                                        replicated(mesh)),
                            state)

    def test_replicas_agree_and_flip_is_attributed(self):
        mesh = MeshSpec(data=4, model=2).build(jax.devices('cpu')[:8])
        state = self.placed_state(mesh)
        matrix, paths = replica_checksums(state.params, mesh)
        assert matrix.shape[0] == 4 and matrix.shape[1] == len(paths)
        assert bool(np.all(matrix == matrix[0]))
        # one bit, one leaf, one replica — the minority vote names it
        flip = FlipParamBit(replica=2, leaf=1, index=5, bit=12)
        corrupted = flip(state.params, mesh)
        matrix2, _ = replica_checksums(corrupted, mesh)
        assert not bool(np.all(matrix2 == matrix2[0]))
        sentinel = Sentinel()
        replicas, leaves = sentinel.check_parity(
            state.replace(params=corrupted), mesh, raise_on_mismatch=False)
        assert replicas == [2] and len(leaves) == 1

    def test_two_replica_tie_reports_both_sides(self):
        """With two replicas there is no majority: blaming one side of the
        tie arbitrarily would send the operator to swap the healthy host —
        every replica of the disagreeing column must be reported."""
        mesh = MeshSpec(data=2, model=2).build(jax.devices('cpu')[:4])
        state = self.placed_state(mesh)
        corrupted = FlipParamBit(replica=0, leaf=1, index=3, bit=9)(
            state.params, mesh)
        replicas, leaves = Sentinel().check_parity(
            state.replace(params=corrupted), mesh, raise_on_mismatch=False)
        assert replicas == [0, 1] and len(leaves) == 1

    def test_sentinel_checkpointer_requires_identity(self, tmp_path):
        """Satellite of the rollback rung: a misconfigured pair must fail
        at construction, not crash the recovery path hours in."""
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            with pytest.raises(ValueError, match='identity'):
                Sentinel(checkpointer=checkpointer)

    def test_flip_detected_before_next_checkpoint_commits(self, tmp_path):
        """The acceptance scenario: the parity check sits between the step
        and the save — a corrupted replica raises DivergenceError, so the
        poisoned state never becomes the checkpoint a restart trusts."""
        mesh = MeshSpec(data=4, model=2).build(jax.devices('cpu')[:8])
        state = self.placed_state(mesh)
        producer, seen = capture(ReplicaDiverged)
        sentinel = Sentinel(producer=producer, model='sdc-drill')
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            assert sentinel.check_parity(state, mesh) is None
            checkpointer.save(IDENTITY, 1, state)      # clean step commits
            state = state.replace(
                params=FlipParamBit(replica=1, leaf=0, index=0, bit=30)(
                    state.params, mesh))
            with pytest.raises(DivergenceError, match='replica'):
                sentinel.check_parity(state, mesh)     # BEFORE save(2)
            assert checkpointer.latest(IDENTITY) == 1  # nothing contaminated
        assert seen and seen[0].replicas == [1]
        assert exit_for_restart(DivergenceError('sdc')).code == DIVERGED_EXIT


def test_debug_nans_env_knob(monkeypatch):
    """TPUSYSTEM_DEBUG_NANS=1 arms jax_debug_nans (the post-mortem sibling
    of the guard's in-graph masking), documented next to
    TPUSYSTEM_DEBUG_CACHE."""
    import __graft_entry__
    previous = jax.config.jax_debug_nans
    try:
        monkeypatch.setenv('TPUSYSTEM_DEBUG_NANS', '1')
        __graft_entry__.configure_debug_nans()
        assert jax.config.jax_debug_nans is True
        # absent (or != '1') the knob must not clobber an existing setting
        jax.config.update('jax_debug_nans', False)
        monkeypatch.delenv('TPUSYSTEM_DEBUG_NANS')
        __graft_entry__.configure_debug_nans()
        assert jax.config.jax_debug_nans is False
    finally:
        jax.config.update('jax_debug_nans', previous)
