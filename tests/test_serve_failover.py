"""Serving failover: journaled requests, token-prefix replay, supervised
engine relaunch (tpusystem/serve/failover.py).

The contract under drill: a serving replica killed (SIGKILL /
kill-at-tick-k), hung (stalled-step watchdog), or overloaded (watermark
shedding) survives without corrupting a single completion — greedy
decode is deterministic, so a replayed request's final output is
TOKEN-EXACT against an uninterrupted reference, whether it replays hot
from its journaled prefix or cold from scratch. The journal is digest-
verified at every hop (a corrupt copy reads as absent, falls to the
buddy replica, then to cold re-submit — never to wrong tokens), and all
of it runs on injectable clocks with zero real sleeps in tier-1.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.checkpoint.memstore import MemStore, blob_digest
from tpusystem.models import gpt2_tiny
from tpusystem.parallel.chaos import DieAtStep, StalledStep, WorkerKilled
from tpusystem.serve import (Engine, EngineStalled, InferenceService,
                             JournalCorrupt, QueueFull, Request,
                             RequestJournal, Scheduler, ServingReplica,
                             StepWatchdog, Watermarks, journal_identity,
                             recover_journal, replay)
from tpusystem.train import generate


class FakeClock:
    """Injectable monotonic clock — the Supervisor test discipline."""

    def __init__(self, now: float = 1000.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


@pytest.fixture(scope='module')
def served():
    module = gpt2_tiny(dtype='float32')
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (1, 8)), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    return module, params


def reference(module, params, prompt, steps):
    out = generate(module, params, jnp.asarray(prompt, jnp.int32)[None],
                   steps=steps)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


def build_for(module, params, **kwargs):
    knobs = dict(rows=2, block_size=8)
    knobs.update(kwargs)
    engine_knobs = {k: knobs.pop(k)
                    for k in ('rows', 'block_size', 'blocks', 'share_prefix',
                              'decode_impl', 'stream_dtype')
                    if k in knobs}
    return lambda: Scheduler(Engine(module, params, **engine_knobs), **knobs)


def workload(seed=5, lengths=(5, 9, 7), budgets=(12, 10, 8)):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, 256, (n,)).tolist() for n in lengths]
    return prompts, list(budgets)


# ---------------------------------------------------------------------------
# the journal: pack/unpack, digest, lifecycle, replication cadence
# ---------------------------------------------------------------------------


class TestJournal:

    def test_pack_unpack_round_trip_with_digest(self):
        clock = FakeClock()
        journal = RequestJournal('j', clock=clock)
        journal.record(Request('a', [1, 2, 3], 8), clock())
        clock.advance(2.0)
        journal.seated('a', 7)
        journal.append('a', 9)
        journal.record(Request('b', [4], 4, deadline=30.0), clock())
        journal.tick = 11
        tick, rows = RequestJournal.unpack(journal.pack())
        assert tick == 11
        assert [(r.id, waited, emitted)
                for r, waited, emitted in rows] == [
                    ('a', 2.0, [7, 9]), ('b', 0.0, [])]
        assert rows[1][0].deadline == 30.0

    @pytest.mark.parametrize('mangle', [
        lambda data: data[:len(data) // 2],                  # truncated
        lambda data: data[:-3] + b'???',                     # flipped tail
        lambda data: b'deadbeef' + data,                     # bad digest
    ])
    def test_corrupt_bytes_raise_journal_corrupt(self, mangle):
        journal = RequestJournal('j')
        journal.record(Request('a', [1, 2], 4), 0.0)
        with pytest.raises(JournalCorrupt):
            RequestJournal.unpack(mangle(journal.pack()))

    def test_lifecycle_leaves_no_rows(self, served):
        """Every terminal transition (length completion, queued cancel,
        active cancel) removes the row — a drained replica's journal is
        empty, so a relaunch replays nothing."""
        module, params = served
        prompts, budgets = workload()
        scheduler = build_for(module, params)()
        scheduler.journal = journal = RequestJournal('j')
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            scheduler.submit(Request(f'r{index}', prompt, budget))
        assert set(journal.rows) == {'r0', 'r1', 'r2'}
        scheduler.step()
        assert len(journal.rows['r0'].emitted) >= 1   # seated: admission
        assert not journal.rows['r2'].emitted         # still queued
        scheduler.cancel('r2')                      # queued cancel
        assert 'r2' not in journal.rows
        scheduler.cancel('r1')                      # active cancel
        assert 'r1' not in journal.rows
        scheduler.run()
        assert journal.rows == {}

    def test_cadence_and_monotonic_tick(self):
        store = MemStore()
        journal = RequestJournal('cad', client=store, cadence=3)
        for _ in range(7):
            journal.observe_tick()
        assert journal.pushes == 2                  # ticks 3 and 6
        entry = store.fetch(journal_identity('cad'))
        assert entry.step == 6
        # a relaunch seeds the tick from the recovered journal, so the
        # store's monotonic slot discipline keeps accepting pushes
        tick, _ = RequestJournal.unpack(entry.blob)
        relaunched = RequestJournal('cad', client=store, cadence=3)
        relaunched.tick = tick
        for _ in range(3):
            relaunched.observe_tick()
        assert store.fetch(journal_identity('cad')).step == 9

    def test_push_failure_degrades_and_logs_once(self, caplog):
        class DeadClient:
            def push(self, *args, **kwargs):
                raise OSError('supervisor gone')

            def fetch(self, identity):
                return None

        journal = RequestJournal('dead', client=DeadClient(), cadence=1)
        with caplog.at_level(logging.WARNING, 'tpusystem.serve.failover'):
            journal.observe_tick()
            journal.observe_tick()
        assert not journal.pushes
        assert caplog.text.count('journal replication') == 1

    def test_recover_journal_skips_corrupt_and_missing(self, caplog):
        good = MemStore()
        journal = RequestJournal('rec', client=good, cadence=1)
        journal.record(Request('a', [1, 2], 4), 0.0)
        journal.observe_tick()
        corrupt = MemStore()
        corrupt.put(journal_identity('rec'), 5, b'garbage-bytes')
        with caplog.at_level(logging.WARNING, 'tpusystem.serve.failover'):
            recovered = recover_journal('rec', (None, MemStore(), corrupt,
                                                good))
        assert recovered is not None
        tick, rows = recovered
        assert tick == 1 and rows[0][0].id == 'a'
        assert 'rejected' in caplog.text
        assert recover_journal('rec', (MemStore(),)) is None


# ---------------------------------------------------------------------------
# the chaos drill: kill at tick k -> relaunch -> replay -> token-exact
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_kill_at_tick_k_replay_is_token_exact(served):
    """The headline: journal pushed every tick; the replica dies at tick
    4 mid-decode (objects abandoned, journal lives in the supervisor-side
    store); a fresh replica recovers, replays seated rows hot from their
    emitted prefixes and the queued row cold, and EVERY completion is
    token-exact vs the uninterrupted reference."""
    module, params = served
    prompts, budgets = workload()
    refs = [reference(module, params, p, b)
            for p, b in zip(prompts, budgets)]
    store = MemStore()
    build = build_for(module, params)
    replica = ServingReplica(build, identity='drill', client=store,
                             cadence=1)
    assert not replica.recovered
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        replica.submit(Request(f'r{index}', prompt, budget))
    for _ in range(4):
        replica.step()
    # SIGKILL stand-in: nothing flushed, nothing drained — only the
    # journal already replicated out of "the process" survives
    relaunched = ServingReplica(build, identity='drill', client=store,
                                cadence=1)
    assert relaunched.recovered
    assert set(relaunched.report.replayed) == {'r0', 'r1'}
    assert relaunched.report.resubmitted == ['r2']
    results = relaunched.run_until_idle()
    for index in range(3):
        got = results[f'r{index}']
        assert got.tokens == refs[index], f'r{index} diverged after replay'
        assert got.reason == 'length'
    assert relaunched.scheduler.engine.trace_count == 1


@pytest.mark.slow
def test_kill_via_chaos_fault_mid_step(served):
    """The same drill through the chaos seam: DieAtStep fires at tick 3
    (the in-process WorkerKilled form); the journal already holds tick
    2's deltas, so the relaunch replays and finishes token-exact."""
    module, params = served
    prompts, budgets = workload(seed=11, lengths=(6, 4), budgets=(9, 7))
    refs = [reference(module, params, p, b)
            for p, b in zip(prompts, budgets)]
    store = MemStore()
    build = build_for(module, params)
    replica = ServingReplica(build, identity='chaos', client=store,
                             cadence=1, fault=DieAtStep(step=3))
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        replica.submit(Request(f'r{index}', prompt, budget))
    with pytest.raises(WorkerKilled):
        replica.run_until_idle()
    relaunched = ServingReplica(build, identity='chaos', client=store,
                                cadence=1)
    assert relaunched.recovered
    results = relaunched.run_until_idle()
    for index in range(2):
        assert results[f'r{index}'].tokens == refs[index]


class _Replicating:
    """The supervisor's buddy-replication discipline, in miniature: every
    verified local push mirrors to the buddy's replica namespace."""

    def __init__(self, local, buddy):
        self.local, self.buddy = local, buddy

    def push(self, identity, step, blob, extras=None):
        self.local.put(identity, step, blob, extras=extras)
        self.buddy.put(identity, step, blob, extras=extras, replica=True)
        return True

    def fetch(self, identity):
        return self.local.fetch(identity)


class _ReplicaView:
    """Read a buddy store's replica namespace — the serving side of the
    replaced-host pull (`hot:{identity}` answers from replica slots)."""

    def __init__(self, store):
        self.store = store

    def fetch(self, identity):
        return self.store.newest(identity, replica=True)


@pytest.mark.slow
def test_corrupt_local_journal_recovers_from_buddy(served, caplog):
    """Acceptance: the local journal slot is corrupted in RAM after the
    kill — the digest check reads it as ABSENT (never as requests) and
    recovery falls through to the buddy's replica copy; completions stay
    token-exact."""
    module, params = served
    prompts, budgets = workload(seed=13, lengths=(5, 7), budgets=(10, 6))
    refs = [reference(module, params, p, b)
            for p, b in zip(prompts, budgets)]
    local, buddy = MemStore(), MemStore()
    build = build_for(module, params)
    replica = ServingReplica(build, identity='pair',
                             client=_Replicating(local, buddy), cadence=1)
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        replica.submit(Request(f'r{index}', prompt, budget))
    for _ in range(3):
        replica.step()
    # the kill, then RAM corruption of the local slot: flip payload bytes
    entry = local.fetch(journal_identity('pair'))
    entry.blob = entry.blob[:-4] + b'!!!!'
    with caplog.at_level(logging.WARNING):
        relaunched = ServingReplica(
            build, identity='pair', client=local,
            fallbacks=(_ReplicaView(buddy),), cadence=1)
    assert 'digest' in caplog.text            # the corrupt slot was seen
    assert relaunched.recovered               # ... and the buddy answered
    results = relaunched.run_until_idle()
    for index in range(2):
        assert results[f'r{index}'].tokens == refs[index]


class _TornPushes:
    """MemStoreClient semantics under a torn wire: the sender digests the
    FULL payload, the receiving store rejects the truncated bytes and
    keeps its previous verified copy (push returns False)."""

    def __init__(self, store, good: int):
        self.store, self.good, self.count = store, good, 0

    def push(self, identity, step, blob, extras=None):
        self.count += 1
        digest = blob_digest(bytes(blob))
        if self.count > self.good:
            blob = blob[:len(blob) // 2]
        try:
            self.store.put(identity, step, blob, digest=digest)
            return True
        except ValueError:
            return False

    def fetch(self, identity):
        return self.store.fetch(identity)


@pytest.mark.slow
def test_truncated_replication_degrades_to_cold_resubmit(served, caplog):
    """Acceptance: replication is torn from tick 3 on, so the store's
    newest verified journal is OLDER than the kill point. Recovery
    replays the seated row hot from its shorter prefix (more re-decode,
    same tokens) and the row that journal only knew as queued re-submits
    cold — no crash, every completion token-exact."""
    module, params = served
    prompts, budgets = workload(seed=17, lengths=(6, 5), budgets=(12, 5))
    refs = [reference(module, params, p, b)
            for p, b in zip(prompts, budgets)]
    store = MemStore()
    build = build_for(module, params, rows=1)     # r1 must queue
    replica = ServingReplica(build, identity='torn',
                             client=_TornPushes(store, good=2), cadence=1)
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        replica.submit(Request(f'r{index}', prompt, budget))
    with caplog.at_level(logging.WARNING, 'tpusystem.serve.failover'):
        for _ in range(6):                        # kill at tick 6
            replica.step()
    assert 'journal replication' in caplog.text   # degraded, not crashed
    held = store.fetch(journal_identity('torn'))
    assert held.step == 2                         # old verified copy stands
    relaunched = ServingReplica(build, identity='torn', client=store,
                                cadence=1)
    assert relaunched.recovered
    assert relaunched.report.replayed == ['r0']
    assert relaunched.report.resubmitted == ['r1']
    # the tick-2 prefix (admission token + 2 decode tokens) is shorter
    # than the 7 tokens r0 had emitted by tick 6 — replay just re-decodes
    # the lost tail, landing on the same greedy tokens
    assert len(relaunched.scheduler.journal.rows['r0'].emitted) == 3
    results = relaunched.run_until_idle()
    for index in range(2):
        assert results[f'r{index}'].tokens == refs[index]


def test_unrecoverable_journal_serves_fresh_traffic(served):
    """No journal anywhere (or journaling off): the replica starts
    empty and serves — losing the backlog degrades service, it never
    crashes it."""
    module, params = served
    build = build_for(module, params)
    replica = ServingReplica(build, identity='fresh', client=MemStore())
    assert not replica.recovered and replica.report.replayed == []
    prompts, budgets = workload(seed=19, lengths=(4,), budgets=(5,))
    replica.submit(Request('only', prompts[0], budgets[0]))
    results = replica.run_until_idle()
    assert results['only'].tokens == reference(module, params, prompts[0],
                                               budgets[0])


def test_replica_b_adopts_replica_a_journal_token_exact(served):
    """Cross-host handoff onto a DIFFERENT identity: replica A dies for
    good and replica B — its own identity, its own engine, mid-serving
    its own traffic — adopts ``journal:{A}`` through the recovery chain
    (A's supervisor RAM here; the buddy's replica slot on a real pod)
    and replays A's rows. Greedy decode is deterministic, so A's seated
    rows resume from their prefixes TOKEN-EXACT on B's engine — the
    fleet router's redistribution move, drilled at the failover layer."""
    module, params = served
    prompts, budgets = workload(seed=23)
    refs = [reference(module, params, p, b)
            for p, b in zip(prompts, budgets)]
    store_a = MemStore()
    build = build_for(module, params)
    replica_a = ServingReplica(build, identity='A', client=store_a,
                               cadence=1)
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        replica_a.submit(Request(f'a{index}', prompt, budget))
    for _ in range(3):
        replica_a.step()             # seats a0/a1, emits prefixes
    # A is SIGKILLed (objects abandoned); B is a different replica with
    # its own identity and journal, already serving its own request
    replica_b = ServingReplica(build, identity='B', client=MemStore(),
                               cadence=1)
    assert not replica_b.recovered   # B's OWN journal has nothing
    b_prompt, b_budget = prompts[0][::-1], 6
    replica_b.submit(Request('b0', b_prompt, b_budget))
    replica_b.step()
    recovered = recover_journal('A', (store_a,))
    assert recovered is not None
    tick, rows = recovered
    report = replay(replica_b.scheduler, rows)
    assert set(report.replayed) == {'a0', 'a1'}   # hot, from A's prefixes
    assert report.resubmitted == ['a2']           # queued-only: cold
    results = replica_b.run_until_idle()
    for index in range(3):
        got = results[f'a{index}']
        assert got.tokens == refs[index], (
            f'a{index} diverged replaying on a different identity')
        assert got.reason == 'length'
    # B's own traffic is untouched by the adoption
    assert results['b0'].tokens == reference(module, params, b_prompt,
                                             b_budget)
    # and the adopted rows now journal under B, so a LATER death of B
    # hands them on again (the chain composes)
    assert set(replica_b.scheduler.journal.rows) == set()   # all done


def test_restore_rejects_finished_rows(served):
    module, params = served
    scheduler = build_for(module, params)()
    with pytest.raises(ValueError, match='no business in the journal'):
        scheduler.restore(Request('done', [1, 2], 3), prefix=[5, 6, 7])


def test_replayed_request_past_deadline_expires_truthfully(served):
    """A journaled request whose deadline passed before (or during) the
    outage is NOT silently dropped by replay: it re-queues with its
    original submission backdated, and the scheduler's ordinary expiry
    retires it with the typed 'expired' verdict on the next step."""
    module, params = served
    clock = FakeClock()
    scheduler = build_for(module, params, clock=clock)()
    report = replay(scheduler,
                    [(Request('late', [1, 2, 3], 6, deadline=5.0), 9.0,
                      [7, 7])])
    assert report.replayed == ['late']
    tick = scheduler.step()
    assert [(completion.request.id, where)
            for completion, where in tick.expired] == [('late', 'queued')]
    late = scheduler.results['late']
    assert late.reason == 'expired'
    assert late.tokens == [7, 7]                  # partial output survives
    assert late.seconds >= 9.0


# ---------------------------------------------------------------------------
# the step watchdog: hung/slow decode becomes a typed verdict + relaunch
# ---------------------------------------------------------------------------


class TestWatchdog:

    def test_absolute_stall_threshold(self):
        dog = StepWatchdog(stall_after=2.0, slow_factor=None)
        dog.observe(1.99)
        with pytest.raises(EngineStalled, match='stall') as caught:
            dog.observe(2.0)
        assert caught.value.seconds == 2.0

    def test_ema_slow_verdict_is_warmup_gated_and_unpolluted(self):
        dog = StepWatchdog(slow_factor=4.0, warmup=3, floor=0.0)
        dog.observe(8.0)              # would be 'slow' later; warmup passes
        for _ in range(5):
            dog.observe(1.0)
        with pytest.raises(EngineStalled, match='slow'):
            dog.observe(dog.ema * 4.0 + 0.01)
        # the anomalous sample did NOT fold into the EMA that caught it
        healthy = dog.ema
        dog.observe(1.0)
        assert dog.ema <= healthy + 1e-9

    def test_unarmed_watchdog_is_refused(self):
        with pytest.raises(ValueError, match='unarmed'):
            StepWatchdog(stall_after=None, slow_factor=None)

    def test_deadman_guard_arms_and_cancels(self):
        fired = []

        class FakeTimer:
            instances = []

            def __init__(self, interval, function):
                self.interval, self.function = interval, function
                self.cancelled = False
                FakeTimer.instances.append(self)

            def start(self):
                pass

            def cancel(self):
                self.cancelled = True

        dog = StepWatchdog(stall_after=1.5, slow_factor=None,
                           on_stall=lambda: fired.append(True),
                           timer=FakeTimer)
        with dog.guard():
            pass                      # the step returned in time
        (timer,) = FakeTimer.instances
        assert timer.interval == 1.5 and timer.cancelled and not fired
        timer.function()              # what a real expiry would run
        assert fired == [True]
        with pytest.raises(ValueError, match='stall_after'):
            StepWatchdog(slow_factor=2.0).guard()

    @pytest.mark.slow
    def test_stalled_step_fires_relaunch_and_replay_token_exact(
            self, served):
        """Acceptance: a stalled decode step at tick 3 (chaos
        StalledStep advancing the fake clock 10s) trips the watchdog ->
        typed EngineStalled -> in-process relaunch -> journal replay;
        the affected requests' completions are token-exact vs the
        uninterrupted reference. Zero real sleeps."""
        module, params = served
        prompts, budgets = workload(seed=23, lengths=(5, 8), budgets=(9, 6))
        refs = [reference(module, params, p, b)
                for p, b in zip(prompts, budgets)]
        clock = FakeClock()
        witnessed = []
        from tpusystem.observe.events import EngineRestarted, RequestReplayed
        from tpusystem.services.prodcon import Consumer, Producer
        consumer = Consumer('probe')
        consumer.register(EngineRestarted, witnessed.append)
        consumer.register(RequestReplayed, witnessed.append)
        producer = Producer()
        producer.register(consumer)
        replica = ServingReplica(
            build_for(module, params, clock=clock),
            identity='stall', client=MemStore(), cadence=1,
            watchdog=StepWatchdog(stall_after=5.0, slow_factor=None),
            producer=producer, clock=clock,
            fault=StalledStep(tick=3, action=lambda: clock.advance(10.0)))
        for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
            replica.submit(Request(f'r{index}', prompt, budget))
        results = replica.run_until_idle()
        assert replica.relaunches == 1
        for index in range(2):
            assert results[f'r{index}'].tokens == refs[index], (
                f'r{index} diverged across the stall relaunch')
        restarts = [e for e in witnessed
                    if isinstance(e, EngineRestarted)]
        assert [e.cause for e in restarts] == ['stalled']
        assert restarts[0].replayed == 2
        replayed = {e.id: e for e in witnessed
                    if isinstance(e, RequestReplayed)}
        assert set(replayed) == {'r0', 'r1'}
        assert all(e.where == 'hot' and e.prefix > 0
                   for e in replayed.values())


def test_replica_deadman_arms_each_watched_tick(served):
    """deadman=True wraps every watched tick in StepWatchdog.guard (the
    defense for a step that NEVER returns — post-hoc observe can't see
    it): one timer armed and cancelled per tick, with the first tick
    after the build exempt (a decode compile must not read as a hang).
    Opt-in, because the default expiry action exits the process."""
    module, params = served

    class FakeTimer:
        instances = []

        def __init__(self, interval, function):
            self.interval, self.function = interval, function
            self.cancelled = False
            FakeTimer.instances.append(self)

        def start(self):
            pass

        def cancel(self):
            self.cancelled = True

    replica = ServingReplica(
        build_for(module, params, rows=1), identity='deadman',
        watchdog=StepWatchdog(stall_after=30.0, slow_factor=None,
                              timer=FakeTimer),
        deadman=True)
    replica.submit(Request('only', [1, 2, 3, 4], 3))
    replica.run_until_idle()
    ticks = replica.scheduler.steps
    assert len(FakeTimer.instances) == ticks - 1    # build tick exempt
    assert all(timer.cancelled and timer.interval == 30.0
               for timer in FakeTimer.instances)
    with pytest.raises(ValueError, match='deadman'):
        ServingReplica(build_for(module, params), deadman=True)


def test_replica_refuses_a_mismatched_scheduler_clock(served):
    """The journal subtracts scheduler timestamps from the replica
    clock; a build() that forgets to thread the same clock through
    Scheduler(clock=) would backdate every replay by garbage — refused
    at construction, not discovered as corrupt deadlines after a
    relaunch."""
    module, params = served
    clock = FakeClock()
    with pytest.raises(ValueError, match='share one clock'):
        ServingReplica(build_for(module, params), clock=clock)
    with pytest.raises(ValueError, match='share one clock'):
        ServingReplica(build_for(module, params, clock=clock))


def test_clientless_relaunch_replays_from_the_live_journal(served):
    """Review regression: a replica journaling only in RAM (no client —
    the constructor default) must not lose its queued and in-flight
    requests to a watchdog relaunch. In-process, the live journal is
    strictly fresher than any replicated copy and replays directly."""
    module, params = served
    prompts, budgets = workload(seed=37, lengths=(5, 4), budgets=(8, 5))
    refs = [reference(module, params, p, b)
            for p, b in zip(prompts, budgets)]
    clock = FakeClock()
    replica = ServingReplica(
        build_for(module, params, rows=1, clock=clock),
        identity='ramonly', clock=clock,
        watchdog=StepWatchdog(stall_after=5.0, slow_factor=None),
        fault=StalledStep(tick=3, action=lambda: clock.advance(10.0)))
    for index, (prompt, budget) in enumerate(zip(prompts, budgets)):
        replica.submit(Request(f'r{index}', prompt, budget))
    results = replica.run_until_idle()
    assert replica.relaunches == 1
    assert set(replica.report.replayed + replica.report.resubmitted) \
        == {'r0', 'r1'}
    for index in range(2):
        assert results[f'r{index}'].tokens == refs[index], (
            f'r{index} lost or diverged across the client-less relaunch')


def test_engine_exposes_decode_step_wall_seconds(served):
    module, params = served
    engine = Engine(module, params, rows=1, block_size=8)
    assert engine.last_step_seconds == 0.0
    engine.admit(np.arange(1, 5), max_new=3)
    engine.step()
    assert engine.last_step_seconds > 0.0


# ---------------------------------------------------------------------------
# admission control: bounded backlog + watermark shedding by deadline slack
# ---------------------------------------------------------------------------


class TestAdmissionControl:

    def test_max_queued_typed_rejection(self, served):
        module, params = served
        engine = Engine(module, params, rows=1, block_size=8)
        scheduler = Scheduler(engine, max_queued=2)
        for index in range(2):
            scheduler.submit(Request(f'q{index}', [1, 2, 3], 4))
        with pytest.raises(QueueFull, match='max_queued=2'):
            scheduler.submit(Request('q2', [1, 2, 3], 4))
        # default stays unbounded; and the bound must be sane
        assert Scheduler(engine).max_queued is None
        with pytest.raises(ValueError, match='max_queued'):
            Scheduler(engine, max_queued=0)

    def test_watermark_validation(self):
        with pytest.raises(ValueError, match='watermarks'):
            Watermarks(high=2, low=3)
        with pytest.raises(ValueError, match='watermarks'):
            Watermarks(high=0, low=0)
        assert Watermarks(high=4, low=2).excess(7) == 5
        assert Watermarks(high=4, low=2).excess(4) == 0

    def test_shed_by_deadline_slack_spares_active_rows(self, served):
        """Past the high watermark the queue sheds down to the low one:
        victims by ascending deadline slack (the request that will
        expire anyway goes first); the ACTIVE row is never shed and
        stays token-exact."""
        module, params = served
        clock = FakeClock()
        prompts, _ = workload(seed=29, lengths=(5,), budgets=(10,))
        expected = reference(module, params, prompts[0], 10)
        engine = Engine(module, params, rows=1, block_size=8)
        scheduler = Scheduler(engine, clock=clock,
                              watermarks=Watermarks(high=2, low=1))
        scheduler.submit(Request('active', prompts[0], 10,
                                 deadline=0.5))    # seats first: never shed
        scheduler.step()
        scheduler.submit(Request('soon', [1, 2, 3], 4, deadline=1.0))
        scheduler.submit(Request('later', [1, 2, 3], 4, deadline=60.0))
        scheduler.submit(Request('forever', [1, 2, 3], 4))
        tick = scheduler.step()                    # depth 3 > high 2
        shed = [(completion.request.id, slack)
                for completion, slack in tick.shed]
        assert shed == [('soon', 1.0), ('later', 60.0)]
        assert scheduler.results['soon'].reason == 'shed'
        assert scheduler.backpressure
        assert scheduler.queue_depth == 1          # 'forever' survived
        tick = scheduler.step()                    # depth 1 <= low
        assert not tick.shed and not scheduler.backpressure
        results = scheduler.run()
        assert results['active'].tokens == expected
        assert results['forever'].reason == 'length'

    def test_no_deadline_sheds_newest_first(self, served):
        """Among no-deadline requests the newest sheds first — the
        oldest waiters keep their FIFO claim."""
        module, params = served
        clock = FakeClock()
        engine = Engine(module, params, rows=1, block_size=8)
        scheduler = Scheduler(engine, clock=clock,
                              watermarks=Watermarks(high=2, low=2))
        scheduler.submit(Request('seated', [1, 2, 3], 20))
        scheduler.step()
        for name in ('old', 'mid', 'new'):
            scheduler.submit(Request(name, [1, 2, 3], 4))
            clock.advance(1.0)
        tick = scheduler.step()
        assert [completion.request.id
                for completion, _ in tick.shed] == ['new']
        assert tick.shed[0][1] is None             # no deadline, no slack
        assert {'old', 'mid'} <= {p.request.id for p in scheduler._queue}

    def test_service_narrates_loadshed_and_backpressure(self, served):
        from tpusystem.observe.events import Backpressure, LoadShed
        from tpusystem.services.prodcon import Consumer, Producer

        module, params = served
        clock = FakeClock()
        witnessed = []
        consumer = Consumer('probe')
        consumer.register(LoadShed, witnessed.append)
        consumer.register(Backpressure, witnessed.append)
        producer = Producer()
        producer.register(consumer)
        service = InferenceService(module, params, producer=producer,
                                   rows=1, block_size=8, clock=clock,
                                   watermarks=Watermarks(high=1, low=0))
        service.submit(Request('seated', [1, 2, 3], 30))
        service.step()
        service.submit(Request('q1', [1, 2, 3], 4, deadline=2.0))
        service.submit(Request('q2', [1, 2, 3], 4))
        service.step()                             # sheds both, engages
        sheds = [e for e in witnessed if isinstance(e, LoadShed)]
        assert [e.id for e in sheds] == ['q1', 'q2']
        assert sheds[0].slack == 2.0 and sheds[1].slack is None
        # events carry the depth that TRIGGERED the shed (2 > high 1),
        # not the post-admission depth (0 — would read as no overload)
        assert all(e.queue_depth == 2 for e in sheds)
        flags = [e for e in witnessed if isinstance(e, Backpressure)]
        assert [e.engaged for e in flags] == [True]
        assert flags[0].queue_depth == 2
        service.step()                             # empty queue: releases
        flags = [e for e in witnessed if isinstance(e, Backpressure)]
        assert [e.engaged for e in flags] == [True, False]
        service.cancel('seated')


# ---------------------------------------------------------------------------
# the injectable clock: deadline/expiry edges with zero real sleeps
# ---------------------------------------------------------------------------


def test_deadline_expiry_runs_on_the_fake_clock(served):
    """The satellite: wall time enters the scheduler ONLY through
    clock=, so deadline starvation drills advance a number instead of
    sleeping."""
    module, params = served
    clock = FakeClock()
    engine = Engine(module, params, rows=1, block_size=8)
    scheduler = Scheduler(engine, clock=clock)
    scheduler.submit(Request('hog', [1, 2, 3, 4], 10))
    scheduler.submit(Request('starved', [1, 2, 3, 4], 4, deadline=5.0))
    tick = scheduler.step()
    assert tick.queue_depth == 1 and not tick.expired
    clock.advance(5.0)
    tick = scheduler.step()
    assert [(completion.request.id, where)
            for completion, where in tick.expired] == [('starved', 'queued')]
    assert scheduler.results['starved'].seconds == 5.0


def test_cancel_landing_the_same_tick_as_completion(served):
    """Edge: the cancel arrives on the tick the request completes —
    cancel() must answer None (already done), the 'length' completion
    stands, and the row is already free for the queue."""
    module, params = served
    engine = Engine(module, params, rows=1, block_size=8)
    scheduler = Scheduler(engine)
    scheduler.submit(Request('a', [1, 2, 3, 4], 3))
    scheduler.step()                   # admit emits token 1, decode token 2
    tick = scheduler.step()            # token 3: completes
    assert [completion.request.id
            for completion in tick.completed] == ['a']
    assert scheduler.cancel('a') is None
    assert scheduler.results['a'].reason == 'length'
    assert engine.free_rows == 1
    # and the degenerate flavor: completion at the ADMISSION tick
    scheduler.submit(Request('b', [1, 2, 3], 1))   # max_new=1: done at admit
    tick = scheduler.step()
    assert tick.completed[0].request.id == 'b'
    assert scheduler.cancel('b') is None
    assert scheduler.results['b'].reason == 'length'


def test_deadline_expiring_exactly_at_the_admission_tick(served):
    """Edge: the deadline lands exactly on the tick that would have
    admitted the request — expiry (>=) wins before admission, the
    request retires 'expired' with zero tokens even though a row was
    free."""
    module, params = served
    clock = FakeClock()
    engine = Engine(module, params, rows=2, block_size=8)
    scheduler = Scheduler(engine, clock=clock)
    scheduler.submit(Request('edge', [1, 2, 3], 4, deadline=1.0))
    clock.advance(1.0)                             # exactly the deadline
    tick = scheduler.step()
    assert [(completion.request.id, where)
            for completion, where in tick.expired] == [('edge', 'queued')]
    assert scheduler.results['edge'].tokens == []
    assert not tick.admitted and engine.free_rows == 2


def test_expiry_of_a_request_whose_row_is_mid_prefill(served):
    """Edge: the deadline passes while the row is being seated (the
    prefill consumed the remaining slack) — the admission emits its
    first token, then the NEXT tick's expiry evicts the row 'active'
    with that partial output kept and the neighbor token-exact."""
    module, params = served
    clock = FakeClock()
    prompts, _ = workload(seed=31, lengths=(6,), budgets=(8,))
    expected = reference(module, params, prompts[0], 8)
    engine = Engine(module, params, rows=2, block_size=8)
    scheduler = Scheduler(engine, clock=clock)
    scheduler.submit(Request('keep', prompts[0], 8))
    scheduler.submit(Request('doomed', [1, 2, 3, 4], 20, deadline=2.0))

    original_admit = engine.admit

    def slow_admit(prompt, max_new, **kwargs):
        if kwargs.get('tag') == 'doomed':          # prefill eats the slack
            clock.advance(2.0)
        return original_admit(prompt, max_new, **kwargs)

    engine.admit = slow_admit
    tick = scheduler.step()                        # both seated
    assert len(tick.admitted) == 2 and not tick.expired
    tick = scheduler.step()
    assert [(completion.request.id, where)
            for completion, where in tick.expired] == [('doomed', 'active')]
    doomed = scheduler.results['doomed']
    assert doomed.reason == 'expired'
    assert 1 <= len(doomed.tokens) < 20            # the admission token(s)
    engine.admit = original_admit
    results = scheduler.run()
    assert results['keep'].tokens == expected


# ---------------------------------------------------------------------------
# observability: the failover events chart like everything else
# ---------------------------------------------------------------------------


def test_tensorboard_failover_handlers_chart_the_events(tmp_path):
    from tests.tb import read_scalars
    from tpusystem.observe.events import (Backpressure, EngineRestarted,
                                          LoadShed)
    from tpusystem.observe.tensorboard import (SummaryWriter,
                                               tensorboard_consumer, writer)

    consumer = tensorboard_consumer()
    board = SummaryWriter(tmp_path)
    consumer.dependency_overrides[writer] = lambda: board
    consumer.consume(EngineRestarted(cause='stalled', replayed=2,
                                     resubmitted=1, seconds=0.8))
    consumer.consume(LoadShed(id='r9', produced=0, queue_depth=7,
                              slack=-0.5))
    consumer.consume(Backpressure(engaged=True, queue_depth=7))
    board.flush()
    scalars = read_scalars(tmp_path)        # parsed back, not byte-poked
    value, step = scalars['serve/recovery_seconds']
    assert value == pytest.approx(0.8) and step == 1    # restart counter
    assert scalars['serve/replayed'] == (2.0, 1)
    assert scalars['serve/resubmitted'] == (1.0, 1)
    assert scalars['serve/shed'] == (7.0, 1)            # triggering depth
    value, _ = scalars['serve/shed_slack']
    assert value == pytest.approx(-0.5)
    assert scalars['serve/backpressure'] == (1.0, 1)


# ---------------------------------------------------------------------------
# the real thing: SIGKILL under the Supervisor (subprocess drill)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sigkill_subprocess_drill_under_supervisor():
    """The dryrun stage as a test: a serving worker subprocess SIGKILLs
    itself mid-decode, the Supervisor relaunches it (signal death =
    worker-lost), the relaunch recovers the journal from the
    supervisor's memstore and finishes — completions token-exact vs an
    uninterrupted run of the same worker, decode compiled once, and the
    worker's flight-recorder post-mortem (write-ahead ring, read back by
    the supervisor onto WorkerExited) reconstructs exactly the emitted
    prefixes the journal replay re-prefilled."""
    from __graft_entry__ import _dryrun_serve_failover
    _dryrun_serve_failover(2)
