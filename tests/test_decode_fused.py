"""Serving-path decode: quantized weight streaming + the fused Pallas
decode chain.

Three layers of parity, mirroring the test_moe kernel discipline:
the quantize/dequantize pair's error bounds and leaf rule
(`ops/precision.py`), the Pallas kernels directly against their einsum
references in interpret mode (`ops/pallas/decode_matmul.py`), and the
whole fused decode loop token-for-token against the flax reference path
(`train/decode_fused.py`).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.models import gpt2_tiny, llama_tiny
from tpusystem.ops.pallas.decode_matmul import (decode_ffn, decode_matmul,
                                                decode_plan)
from tpusystem.ops.precision import (QuantizedLeaf, dequantize_leaf,
                                     dequantize_streamed,
                                     fp8_unsupported_reason, qdot,
                                     quantize_leaf, quantize_streamed)

fp8_reason = fp8_unsupported_reason()
needs_fp8 = pytest.mark.skipif(fp8_reason is not None, reason=fp8_reason or '')


# --- quantize/dequantize pair --------------------------------------------

def test_quantize_leaf_int8_roundtrip_error_bound():
    """Per-output-channel symmetric int8: the dequantized matrix is within
    half a quantization step of the original, column by column."""
    leaf = jnp.asarray(np.random.default_rng(0).normal(size=(32, 48)) * 0.3,
                       jnp.float32)
    quantized = quantize_leaf(leaf, 'int8')
    assert quantized.values.dtype == jnp.int8
    assert quantized.scales.shape == (1, 48)
    roundtrip = dequantize_leaf(quantized)
    error = np.abs(np.asarray(roundtrip) - np.asarray(leaf))
    bound = np.asarray(quantized.scales)[0] / 2 + 1e-7
    assert (error <= bound[None, :]).all()


def test_quantize_leaf_all_zero_column_stays_finite():
    leaf = jnp.zeros((8, 4), jnp.float32)
    quantized = quantize_leaf(leaf, 'int8')
    roundtrip = np.asarray(dequantize_leaf(quantized))
    assert np.isfinite(roundtrip).all() and (roundtrip == 0).all()


def test_quantize_streamed_applies_the_decode_caster_leaf_rule():
    """Matrices quantize; embedding tables, MoE routers, and vector leaves
    (biases, layernorms) pass through untouched — exactly the exclusion
    set of generate's bf16 caster."""
    params = {
        'wte': {'embedding': jnp.ones((16, 8), jnp.float32)},
        'h_0': {'attn': {'qkv': {'kernel': jnp.ones((8, 24), jnp.float32),
                                 'bias': jnp.zeros((24,), jnp.float32)}},
                'ln_1': {'scale': jnp.ones((8,), jnp.float32)},
                'moe': {'router': {'kernel': jnp.ones((8, 4), jnp.float32)}}},
    }
    quantized = quantize_streamed(params, 'int8')
    assert isinstance(quantized['h_0']['attn']['qkv']['kernel'],
                      QuantizedLeaf)
    for untouched in (quantized['wte']['embedding'],
                      quantized['h_0']['attn']['qkv']['bias'],
                      quantized['h_0']['ln_1']['scale'],
                      quantized['h_0']['moe']['router']['kernel']):
        assert not isinstance(untouched, QuantizedLeaf)
        assert untouched.dtype == jnp.float32
    with pytest.raises(ValueError, match='int8'):
        quantize_streamed(params, 'int3')


def test_quantized_leaf_rides_pytrees_and_jit():
    leaf = quantize_leaf(jnp.ones((4, 8), jnp.float32) * 0.5, 'int8')
    doubled = jax.jit(lambda q: jax.tree.map(lambda a: a, q))(leaf)
    assert isinstance(doubled, QuantizedLeaf)
    np.testing.assert_array_equal(np.asarray(doubled.values),
                                  np.asarray(leaf.values))
    assert leaf.shape == (4, 8)
    assert leaf.nbytes == leaf.values.nbytes + leaf.scales.nbytes


def test_dequantize_streamed_is_identity_for_plain_trees():
    params = {'a': jnp.ones((4, 4)), 'b': jnp.zeros((3,))}
    assert dequantize_streamed(params) is params


def test_fp8_capability_probe_is_cached_and_stable():
    assert fp8_unsupported_reason() == fp8_unsupported_reason()


@needs_fp8
def test_quantize_leaf_fp8_roundtrip_is_bounded():
    leaf = jnp.asarray(np.random.default_rng(1).normal(size=(16, 16)) * 0.2,
                       jnp.float32)
    quantized = quantize_leaf(leaf, 'fp8')
    roundtrip = np.asarray(dequantize_leaf(quantized))
    assert np.isfinite(roundtrip).all()
    # e4m3 keeps ~2 mantissa-digit relative accuracy after per-channel
    # rescaling into its range
    np.testing.assert_allclose(roundtrip, np.asarray(leaf), atol=0.05)


# --- decode_plan: pinned tiling decisions --------------------------------

def test_decode_plan_pins_which_shapes_run_fused():
    # TPU mode: out-column blocks are the largest <=want lane multiple
    # dividing the width; non-lane-tileable shapes refuse (einsum path)
    assert decode_plan(256, 768, interpret=False) == 384
    assert decode_plan(256, 512, interpret=False) == 512
    assert decode_plan(256, 2304, interpret=False, want=512) == 384
    assert decode_plan(100, 768, interpret=False) is None   # inner % 128
    assert decode_plan(256, 130, interpret=False) is None   # no 128-divisor
    # interpret mode has no tiling constraint: any divisor works
    assert decode_plan(5, 7, interpret=True) == 7
    assert decode_plan(5, 6, interpret=True, want=4) == 3


# --- kernels vs einsum references (interpret mode on CPU) ----------------

@pytest.fixture(scope='module')
def operands():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 64)) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    return x, w, bias


def test_decode_matmul_matches_qdot_reference(operands):
    x, w, bias = operands
    np.testing.assert_allclose(np.asarray(decode_matmul(x, w)),
                               np.asarray(qdot(x, w)), atol=1e-5)
    fused = decode_matmul(x, w, bias, activation=jax.nn.gelu, block_cols=16)
    reference = jax.nn.gelu(qdot(x, w) + bias).astype(x.dtype)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(reference),
                               atol=1e-5)


def test_decode_matmul_dequantizes_int8_tiles_in_kernel(operands):
    x, w, bias = operands
    quantized = quantize_leaf(w, 'int8')
    fused = decode_matmul(x, quantized, bias, block_cols=16)
    reference = (qdot(x, quantized) + bias).astype(x.dtype)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(reference),
                               atol=1e-5)


@needs_fp8
def test_decode_matmul_dequantizes_fp8_tiles_in_kernel(operands):
    x, w, bias = operands
    quantized = quantize_leaf(w, 'fp8')
    fused = decode_matmul(x, quantized, bias, block_cols=16)
    reference = (qdot(x, quantized) + bias).astype(x.dtype)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(reference),
                               atol=1e-5)


def test_decode_ffn_matches_the_two_matmul_chain(operands):
    """The fc->gelu->proj chain in one kernel, multi-tile grid (the
    scratch accumulator crosses 4 grid steps), plain and quantized."""
    x, w1, b1 = operands
    rng = np.random.default_rng(1)
    w2 = jnp.asarray(rng.normal(size=(64, 16)) * 0.3, jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(16,)), jnp.float32)

    fused = decode_ffn(x, w1, b1, w2, b2, block_hidden=16)
    reference = (jax.nn.gelu(qdot(x, w1) + b1).astype(x.dtype) @ w2
                 + b2)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(reference),
                               atol=1e-4)

    q1, q2 = quantize_leaf(w1, 'int8'), quantize_leaf(w2, 'int8')
    fused = decode_ffn(x, q1, b1, q2, b2, block_hidden=16)
    mid = jax.nn.gelu(qdot(x, q1) + b1).astype(x.dtype)
    reference = qdot(mid, q2) + b2
    np.testing.assert_allclose(np.asarray(fused), np.asarray(reference),
                               atol=1e-4)


def test_untileable_shapes_take_the_einsum_fallback():
    """interpret=False with non-lane shapes must never reach pallas_call
    (it would fail on CPU): decode_plan refuses and the einsum path
    answers — same math."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 10)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(10, 6)), jnp.float32)
    bias = jnp.zeros((6,), jnp.float32)
    out = decode_matmul(x, w, bias, interpret=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), atol=1e-6)
    w2 = jnp.asarray(rng.normal(size=(6, 10)), jnp.float32)
    out = decode_ffn(x, w, bias, w2, jnp.zeros((10,)), interpret=False)
    reference = jax.nn.gelu(x @ w) @ w2
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               atol=1e-5)


def test_decode_matmul_rejects_mismatched_shapes():
    with pytest.raises(ValueError, match='cols'):
        decode_matmul(jnp.ones((2, 4)), jnp.ones((5, 8)))
    with pytest.raises(ValueError, match='compose'):
        decode_ffn(jnp.ones((2, 4)), jnp.ones((4, 8)), jnp.zeros(8),
                   jnp.ones((9, 4)), jnp.zeros(4))


# --- the fused decode loop vs the flax reference -------------------------

@pytest.fixture(scope='module')
def prompt():
    return jnp.asarray(
        np.random.default_rng(7).integers(0, 256, (2, 8)), jnp.int32)


def test_fused_decode_matches_flax_token_exact(prompt):
    from tpusystem.train import generate
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    flax = generate(module, params, prompt, steps=12, decode_impl='flax')
    fused = generate(module, params, prompt, steps=12, decode_impl='fused')
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(flax))


@pytest.mark.slow
def test_fused_decode_matches_flax_under_quantized_streaming(prompt):
    """stream_dtype='int8' composes with decode_impl='fused': the
    in-kernel dequantize must reproduce the flax loop's
    dequantize-then-apply math token for token."""
    from tpusystem.train import generate
    module = gpt2_tiny(dtype='float32')
    params = module.init(jax.random.PRNGKey(0), prompt)['params']
    flax = generate(module, params, prompt, steps=10, stream_dtype='int8')
    fused = generate(module, params, prompt, steps=10, stream_dtype='int8',
                     decode_impl='fused')
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(flax))


def test_fused_decode_impl_names_its_scope(prompt):
    from tpusystem.train import generate
    from tpusystem.train.decode_fused import fused_unsupported_reason

    llama = llama_tiny(dtype='float32')
    params = llama.init(jax.random.PRNGKey(0), prompt)['params']
    with pytest.raises(ValueError, match='GPT2'):
        generate(llama, params, prompt, steps=2, decode_impl='fused')
    # 'auto' silently falls back to the flax loop for the same module
    out = generate(llama, params, prompt, steps=2, decode_impl='auto')
    assert out.shape == (2, 10)

    scanned = dataclasses.replace(gpt2_tiny(dtype='float32'),
                                  decode=True, scan_layers=True)
    assert 'scan_layers' in fused_unsupported_reason(scanned)
    moe = dataclasses.replace(gpt2_tiny(dtype='float32'), decode=True,
                              moe_experts=2)
    assert 'MoE' in fused_unsupported_reason(moe)

    with pytest.raises(ValueError, match='decode_impl'):
        generate(llama, params, prompt, steps=2, decode_impl='vectorized')
