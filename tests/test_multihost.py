"""Control-plane tests: a 3-host pod simulated with in-process transports.

The reference has no distributed machinery at all (SURVEY.md §2.4) — its
buses are in-process calls. These tests pin the distributed generalization:
wired events reach every host, collectives complete in rank-uniform order,
stop decisions are collectively agreed, and a silent host surfaces as a
``WorkerLost`` domain event.
"""

from __future__ import annotations

import socket
import time

import pytest

from tpusystem.parallel.multihost import (
    BlobError, DistributedProducer, DistributedPublisher, Hub, Loopback,
    TcpTransport, WorkerLost, agree,
)
from tpusystem.services.prodcon import Consumer, event
from tpusystem.services.pubsub import Subscriber


@event
class Synced:
    epoch: int
    loss: float


def pod(size, **kwargs):
    hub = Hub(size, **kwargs)
    transports = [TcpTransport(hub.address, rank, size) for rank in range(size)]
    deadline = time.monotonic() + 5
    while len(hub._clients) < size and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(hub._clients) == size
    return hub, transports


def shutdown(hub, transports):
    for transport in transports:
        transport.close()
    hub.close()


def wait_until(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


class TestTransport:
    def test_wired_event_reaches_every_other_host(self):
        hub, transports = pod(3)
        try:
            seen = {rank: [] for rank in range(3)}
            for rank, transport in enumerate(transports):
                transport.subscribe('test', seen[rank].append)
            transports[1].send_event('test', {'from': 1})
            assert wait_until(lambda: seen[0] and seen[2])
            assert seen[0] == [{'from': 1}] and seen[2] == [{'from': 1}]
            assert seen[1] == []  # sender does not hear itself
        finally:
            shutdown(hub, transports)

    def test_channels_do_not_crosstalk(self):
        hub, transports = pod(2)
        try:
            alpha, beta = [], []
            transports[1].subscribe('alpha', alpha.append)
            transports[1].subscribe('beta', beta.append)
            transports[0].send_event('alpha', 'a')
            transports[0].send_event('gamma', 'dropped')  # no subscriber
            transports[0].send_event('beta', 'b')
            assert wait_until(lambda: alpha and beta)
            assert alpha == ['a'] and beta == ['b']
        finally:
            shutdown(hub, transports)

    def test_allreduce_ops(self):
        hub, transports = pod(3)
        try:
            import threading
            results = {}

            def run(rank, transport):
                results[('or', rank)] = transport.allreduce(rank == 2, op='or')
                results[('and', rank)] = transport.allreduce(True, op='and')
                results[('sum', rank)] = transport.allreduce(rank, op='sum')
                results[('gather', rank)] = sorted(transport.gather(rank))

            threads = [threading.Thread(target=run, args=(rank, transport))
                       for rank, transport in enumerate(transports)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            for rank in range(3):
                assert results[('or', rank)] is True
                assert results[('and', rank)] is True
                assert results[('sum', rank)] == 3
                assert results[('gather', rank)] == [0, 1, 2]
        finally:
            shutdown(hub, transports)

    def test_loopback_degenerate_case(self):
        transport = Loopback()
        assert transport.allreduce(True, op='and') is True
        assert transport.allreduce(False, op='or') is False
        assert transport.gather(7) == [7]
        transport.barrier()
        transport.send_event('any', 'dropped')  # nowhere to go, no error

    def test_connect_retries_until_hub_listens(self):
        import socket as socket_module
        import threading
        placeholder = socket_module.socket()
        placeholder.bind(('127.0.0.1', 0))
        address = placeholder.getsockname()
        placeholder.close()                       # port free, nothing listening
        box = {}

        def dial():
            box['transport'] = TcpTransport(address, 0, 1, connect_timeout=10)

        dialer = threading.Thread(target=dial, daemon=True)
        dialer.start()                            # client starts BEFORE the hub
        time.sleep(0.3)
        hub = Hub(1, host=address[0], port=address[1])
        try:
            dialer.join(timeout=10)
            assert 'transport' in box
            assert wait_until(lambda: len(hub._clients) == 1)
        finally:
            box['transport'].close()
            hub.close()


class TestDistributedProducer:
    def test_wired_events_cross_hosts_on_drain(self):
        hub, transports = pod(2)
        try:
            producers = [DistributedProducer(transport) for transport in transports]
            logs = {0: [], 1: []}
            for rank, producer in enumerate(producers):
                consumer = Consumer()

                def make(rank):
                    def on_synced(message: Synced):
                        logs[rank].append(message)
                    return on_synced
                consumer.register(Synced, make(rank))
                producer.register(consumer)
                producer.wire(Synced)

            producers[0].dispatch(Synced(epoch=1, loss=0.5))
            assert logs[0] == [Synced(1, 0.5)]  # local, synchronous
            assert wait_until(lambda: not producers[1]._inbox.empty())
            assert logs[1] == []  # remote events wait for a safe point
            assert producers[1].drain() == 1
            assert logs[1] == [Synced(1, 0.5)]
        finally:
            shutdown(hub, transports)

    def test_unwired_events_stay_local(self):
        hub, transports = pod(2)
        try:
            producers = [DistributedProducer(transport) for transport in transports]
            producers[0].dispatch(Synced(epoch=1, loss=0.5))
            time.sleep(0.1)
            assert producers[1].drain() == 0
        finally:
            shutdown(hub, transports)

    def test_primary_only_consumer_skipped_off_primary(self):
        hub, transports = pod(2)
        try:
            producers = [DistributedProducer(transport) for transport in transports]
            for producer in producers:
                producer.register(Consumer(), primary_only=True)
            assert len(producers[0].consumers) == 1
            assert len(producers[1].consumers) == 0
        finally:
            shutdown(hub, transports)

    def test_loopback_producer_is_plain_producer(self):
        producer = DistributedProducer()
        seen = []
        consumer = Consumer()
        consumer.register(Synced, seen.append)
        producer.register(consumer, primary_only=True)  # rank 0 -> registered
        producer.wire(Synced)
        producer.dispatch(Synced(epoch=0, loss=1.0))
        assert seen == [Synced(0, 1.0)]
        assert producer.drain() == 0


class TestDistributedPublisher:
    def test_wired_topic_crosses_hosts(self):
        hub, transports = pod(2)
        try:
            publishers = [DistributedPublisher(transport) for transport in transports]
            received = []
            subscriber = Subscriber()
            subscriber.register('loss', received.append)
            publishers[1].register(subscriber)
            publishers[1].wire('loss')
            publishers[0].wire('loss')

            publishers[0].publish(0.25, 'loss')
            assert wait_until(lambda: not publishers[1]._inbox.empty())
            publishers[1].drain()
            assert received == [0.25]
        finally:
            shutdown(hub, transports)


class TestAgreement:
    def test_any_host_stops_all(self):
        hub, transports = pod(3)
        try:
            import threading
            verdicts = {}

            def run(rank, transport):
                verdicts[rank] = agree(transport, rank == 1)  # host 1 wants out

            threads = [threading.Thread(target=run, args=(rank, transport))
                       for rank, transport in enumerate(transports)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert verdicts == {0: True, 1: True, 2: True}
        finally:
            shutdown(hub, transports)

    def test_unanimous_op_requires_all(self):
        assert agree(Loopback(), False, op='and') is False
        assert agree(Loopback(), True, op='and') is True


class TestSharedTransport:
    def test_producer_and_publisher_share_one_transport(self):
        """The Runtime wiring: both buses on the same TcpTransport, each
        draining only its own channel's traffic."""
        hub, transports = pod(2)
        try:
            producers = [DistributedProducer(transport) for transport in transports]
            publishers = [DistributedPublisher(transport) for transport in transports]
            for producer in producers:
                producer.wire(Synced)
            for publisher in publishers:
                publisher.wire('loss')
            events, topics = [], []
            consumer = Consumer()
            consumer.register(Synced, events.append)
            producers[1].register(consumer)
            subscriber = Subscriber()
            subscriber.register('loss', topics.append)
            publishers[1].register(subscriber)

            producers[0].dispatch(Synced(epoch=7, loss=0.1))
            publishers[0].publish(0.25, 'loss')
            assert wait_until(lambda: not producers[1]._inbox.empty()
                              and not publishers[1]._inbox.empty())
            assert producers[1].drain() == 1
            assert publishers[1].drain() == 1
            assert events == [Synced(7, 0.1)]
            assert topics == [0.25]
        finally:
            shutdown(hub, transports)


class TestFailureDetection:
    def test_crashed_worker_surfaces_immediately(self):
        """A dead connection (no 'bye') is a crash: lost is broadcast at
        once, without waiting for the heartbeat monitor."""
        hub, transports = pod(2)
        try:
            producer = DistributedProducer(transports[0])
            lost = []
            consumer = Consumer()
            consumer.register(WorkerLost, lost.append)
            producer.register(consumer)
            # Crash: the connection dies with no 'bye'. shutdown() (not just
            # close()) is needed in-process: the transport's own recv thread
            # keeps the open file description alive, so a bare close() never
            # sends the FIN a real process death would.
            transports[1]._sock.shutdown(socket.SHUT_RDWR)
            transports[1]._sock.close()
            # keep draining until the loss surfaces — a late 'joined'
            # control frame may land in the inbox first (same race the
            # silent-host test below guards against)
            assert wait_until(lambda: (producer.drain(), bool(lost))[1])
            assert lost[0].rank == 1
            assert lost[0].reason == 'socket'    # EOF, not a stall
        finally:
            transports[0].close()
            hub.close()

    def test_silent_host_surfaces_as_worker_lost_event(self):
        hub = Hub(2, heartbeat_timeout=0.3)
        transports = [
            TcpTransport(hub.address, 0, 2, heartbeat_interval=0.05),
            TcpTransport(hub.address, 1, 2),  # never heartbeats
        ]
        try:
            assert wait_until(lambda: len(hub._clients) == 2)
            producer = DistributedProducer(transports[0])
            lost = []
            consumer = Consumer()
            consumer.register(WorkerLost, lost.append)
            producer.register(consumer)
            # rank 1 stays silent past the timeout; keep draining until the
            # loss surfaces (a 'joined' frame may land in the inbox first)
            assert wait_until(lambda: (producer.drain(), bool(lost))[1],
                              timeout=5)
            assert lost[0].rank == 1
            assert lost[0].reason == 'heartbeat'   # a stall, not a crash
        finally:
            shutdown(hub, transports)


class TestRecovery:
    def test_crash_unwinds_as_worker_lost_error_at_drain(self):
        """detect (control plane) -> decide (recovery consumer) -> the
        error surfaces on the host loop thread at the drain point, never
        on a transport thread."""
        from tpusystem.parallel.recovery import WorkerLostError, recovery_consumer
        hub, transports = pod(2)
        try:
            producer = DistributedProducer(transports[0])
            producer.register(recovery_consumer())
            transports[1]._sock.shutdown(socket.SHUT_RDWR)
            transports[1]._sock.close()
            # wait for the 'lost' broadcast specifically — a late 'joined'
            # control frame can land in the inbox first
            assert wait_until(lambda: 1 in hub._lost)
            assert wait_until(lambda: not producer._inbox.empty())
            with pytest.raises(WorkerLostError) as excinfo:
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline:   # drain until it surfaces
                    producer.drain()
                    time.sleep(0.01)
            assert excinfo.value.rank == 1
        finally:
            shutdown(hub, transports)

    def test_collectives_degrade_to_survivors_after_loss(self):
        """The 'observe' policy is only viable if collectives stop waiting
        for the dead rank: an allreduce started by the survivors completes
        with their contributions once the loss is detected."""
        import threading
        hub, transports = pod(3)
        try:
            transports[2]._sock.shutdown(socket.SHUT_RDWR)
            transports[2]._sock.close()
            assert wait_until(lambda: 2 in hub._lost)
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank == 0, op='or',
                                                           timeout=10)

            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in (0, 1)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: True, 1: True}
        finally:
            shutdown(hub, transports)

    def test_pending_collective_completes_when_holdout_dies(self):
        """Loss DURING a collective: the op was waiting on the dead rank
        and must complete with the survivors' values."""
        import threading
        hub, transports = pod(3)
        try:
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=10)

            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in (0, 1)]
            for thread in threads:
                thread.start()
            assert wait_until(lambda: len(hub._pending) == 1)
            transports[2]._sock.shutdown(socket.SHUT_RDWR)
            transports[2]._sock.close()
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: 1, 1: 1}   # sum of ranks 0 + 1
        finally:
            shutdown(hub, transports)

    def test_excluded_rank_collective_fails_fast(self):
        """A restarted (excluded) rank's collective raises immediately via
        the hub's 'rejected' frame instead of blocking until its timeout:
        its op counter restarted at 0, so its op_key can never match the
        survivors' (ADVICE r1 #2)."""
        hub, transports = pod(3)
        try:
            transports[2]._sock.shutdown(socket.SHUT_RDWR)
            transports[2]._sock.close()
            assert wait_until(lambda: 2 in hub._excluded)
            revived = TcpTransport(hub.address, 2, 3)
            assert wait_until(lambda: 2 in hub._clients)
            start = time.monotonic()
            with pytest.raises(RuntimeError, match='excluded'):
                revived.allreduce(True, op='and', timeout=30)
            assert time.monotonic() - start < 5   # failed fast, not timeout
            revived.close()
        finally:
            shutdown(hub, transports)

    def test_vote_then_die_still_counts_and_survivor_vote_not_dropped(self):
        """A contribution received before the crash stays in the result;
        quota completion is keyed by rank, so the dead rank's early vote
        cannot displace a survivor's."""
        import threading
        hub, transports = pod(3)
        try:
            results = {}

            def contribute(rank, value):
                results[rank] = transports[rank].allreduce(value, op='sum',
                                                           timeout=10)

            # rank 2 votes first, then dies (its own call can never return —
            # swallow the timeout so the daemon thread exits quietly)
            def doomed_vote():
                try:
                    transports[2].allreduce(10, op='sum', timeout=2)
                except Exception:
                    pass

            doomed = threading.Thread(target=doomed_vote, daemon=True)
            doomed.start()
            assert wait_until(
                lambda: any(2 in values for values in hub._pending.values()))
            transports[2]._sock.shutdown(socket.SHUT_RDWR)
            transports[2]._sock.close()
            assert wait_until(lambda: 2 in hub._excluded)
            threads = [threading.Thread(target=contribute, args=(rank, rank))
                       for rank in (0, 1)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert results[0] == results[1] == 10 + 0 + 1
        finally:
            shutdown(hub, transports)

    def test_late_contribution_from_excluded_rank_dropped_without_leak(self):
        """A slow-but-alive rank marked lost by the heartbeat monitor: its
        late contribution must not resurrect a completed op (pending-entry
        leak) — and its call fails fast with 'rejected' rather than racing
        the survivors' result fanout (it is outside the quota; its vote was
        dropped, so handing it the result would let it believe it
        participated)."""
        import threading
        hub = Hub(3, heartbeat_timeout=0.3)
        transports = [
            TcpTransport(hub.address, 0, 3, heartbeat_interval=0.05),
            TcpTransport(hub.address, 1, 3, heartbeat_interval=0.05),
            TcpTransport(hub.address, 2, 3),   # never heartbeats -> 'lost'
        ]
        try:
            assert wait_until(lambda: len(hub._clients) == 3)
            assert wait_until(lambda: 2 in hub._excluded, timeout=5)
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=10)

            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in (0, 1)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: 1, 1: 1}
            # the excluded rank contributes late: dropped, no pending
            # leak, and the call fails fast instead of blocking to timeout
            start = time.monotonic()
            with pytest.raises(RuntimeError, match='excluded'):
                transports[2].allreduce(2, op='sum', timeout=30)
            assert time.monotonic() - start < 5
            assert wait_until(lambda: not hub._pending)
        finally:
            shutdown(hub, transports)

    def test_observe_policy_logs_and_continues(self, caplog):
        import logging
        from tpusystem.parallel.multihost import WorkerJoined, WorkerLost
        from tpusystem.parallel.recovery import recovery_consumer
        consumer = recovery_consumer('observe')
        with caplog.at_level(logging.INFO, logger='tpusystem.recovery'):
            consumer.consume(WorkerLost(rank=3, last_seen=1.0))
            consumer.consume(WorkerJoined(rank=3))
        assert 'worker 3 lost' in caplog.text
        assert 'worker 3 joined' in caplog.text

    def test_unknown_policy_rejected(self):
        from tpusystem.parallel.recovery import recovery_consumer
        with pytest.raises(ValueError):
            recovery_consumer('retry')


class TestScale:
    def test_sixteen_host_pod_events_and_collectives(self):
        """Control-plane stress: a 16-host pod running wired events and
        rank-uniform collectives concurrently — the hub must route both
        without cross-talk, loss, or deadlock."""
        import threading
        hub, transports = pod(16)
        try:
            producers = [DistributedProducer(transport) for transport in transports]
            logs = {rank: [] for rank in range(16)}
            for rank, producer in enumerate(producers):
                consumer = Consumer()

                def make(rank):
                    return lambda message: logs[rank].append(message)
                consumer.register(Synced, make(rank))
                producer.register(consumer)
                producer.wire(Synced)
            results = {}

            def worker(rank):
                producers[rank].dispatch(Synced(epoch=rank, loss=0.0))
                results[('sum', rank)] = transports[rank].allreduce(rank, op='sum', timeout=30)
                results[('max', rank)] = transports[rank].allreduce(rank, op='max', timeout=30)
                transports[rank].barrier(timeout=30)

            threads = [threading.Thread(target=worker, args=(rank,))
                       for rank in range(16)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=30)
            for rank in range(16):
                assert results[('sum', rank)] == sum(range(16))
                assert results[('max', rank)] == 15
            # every host drains 15 remote events (everyone else's dispatch)
            assert wait_until(
                lambda: all(not p._inbox.empty() for p in producers))
            for rank, producer in enumerate(producers):
                deadline = time.monotonic() + 5
                while len(logs[rank]) < 16 and time.monotonic() < deadline:
                    producer.drain()
                    time.sleep(0.01)
                # 1 local + 15 remote
                assert len(logs[rank]) == 16, (rank, len(logs[rank]))
        finally:
            shutdown(hub, transports)


class TestDeputy:
    """Hub redundancy: a standby deputy hub promotes when the primary dies
    (ROADMAP robustness item — the star's single point of failure)."""

    def _pod_with_deputy(self, size=3):
        from tpusystem.parallel.multihost import connect, World
        primary = Hub(size)
        deputy = Hub(size, standby_of=primary.address)
        transports = [
            TcpTransport([primary.address, deputy.address], rank, size)
            for rank in range(size)]
        assert wait_until(lambda: len(primary._clients) == size)
        return primary, deputy, transports

    def test_deputy_promotes_and_serves_after_primary_death(self):
        primary, deputy, transports = self._pod_with_deputy()
        try:
            assert deputy.is_standby
            # baseline: collectives work on the primary
            import threading
            results = {}

            def contribute(rank, value):
                results[rank] = transports[rank].allreduce(value, op='sum',
                                                           timeout=15)
            threads = [threading.Thread(target=contribute, args=(r, r + 1))
                       for r in range(3)]
            for t in threads: t.start()
            for t in threads: t.join(timeout=15)
            assert results == {0: 6, 1: 6, 2: 6}

            primary.close()                       # the star center dies
            assert wait_until(lambda: not deputy.is_standby, timeout=10)
            assert wait_until(lambda: len(deputy._clients) == 3, timeout=10)

            # post-failover collectives complete on the promoted deputy
            results.clear()
            threads = [threading.Thread(target=contribute, args=(r, 10 * (r + 1)))
                       for r in range(3)]
            for t in threads: t.start()
            for t in threads: t.join(timeout=15)
            assert results == {0: 60, 1: 60, 2: 60}

            # events flow through the deputy too
            received = []
            consumer = Consumer()

            @consumer.handler
            def on_synced(event: Synced):
                received.append(event.epoch)

            producer = DistributedProducer(transports[1])
            producer.register(consumer)
            sender = DistributedProducer(transports[0])
            sender.wire(Synced)
            sender.dispatch(Synced(epoch=7, loss=0.5))
            assert wait_until(lambda: not producer._inbox.empty(), timeout=10)
            producer.drain()
            assert received == [7]
        finally:
            for transport in transports:
                transport.close()
            deputy.close()

    def test_failover_mid_collective_raises_then_recovers(self):
        from tpusystem.parallel.multihost import ControlPlaneFailover
        primary, deputy, transports = self._pod_with_deputy()
        try:
            import threading
            outcomes = {}

            def contribute(rank):
                try:
                    outcomes[rank] = transports[rank].allreduce(
                        rank, op='sum', timeout=30)
                except ControlPlaneFailover:
                    outcomes[rank] = 'failover'

            # ranks 0 and 1 wait on rank 2, which never contributes
            threads = [threading.Thread(target=contribute, args=(r,))
                       for r in (0, 1)]
            for t in threads: t.start()
            assert wait_until(lambda: len(primary._pending) == 1)
            primary.close()
            for t in threads: t.join(timeout=30)
            assert outcomes == {0: 'failover', 1: 'failover'}

            # rank 2 burns its op-2 counter slot too so sequences realign
            # (its op never reached the primary; on the deputy it would
            # wait forever for ranks that already failed theirs)
            import queue as queue_module
            with pytest.raises((ControlPlaneFailover, queue_module.Empty)):
                transports[2].allreduce(2, op='sum', timeout=3)

            assert wait_until(lambda: not deputy.is_standby, timeout=10)
            results = {}

            def retry(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=20)
            threads = [threading.Thread(target=retry, args=(r,))
                       for r in range(3)]
            for t in threads: t.start()
            for t in threads: t.join(timeout=25)
            assert results == {0: 3, 1: 3, 2: 3}
        finally:
            for transport in transports:
                transport.close()
            deputy.close()

    def test_standby_deputy_bounces_flaked_client_back(self):
        """Split-brain guard: a client whose LINK to the live primary died
        is redirected back by the standby deputy instead of being served —
        the primary's exclusion policy then governs (it sees the crash)."""
        primary, deputy, transports = self._pod_with_deputy()
        try:
            # flake rank 2's link only; the primary itself stays up
            transports[2]._sock.shutdown(socket.SHUT_RDWR)
            assert wait_until(lambda: 2 in primary._excluded)
            assert deputy.is_standby
            # rank 2 failed over to the deputy; its first op gets bounced
            # ('standby'), it redials the primary (rejoins) and replays —
            # where the exclusion policy rejects it: fail-fast, no split
            with pytest.raises(RuntimeError, match='excluded|failover'):
                transports[2].allreduce(True, op='and', timeout=15)
            assert wait_until(lambda: 2 in primary._clients, timeout=10)
            # survivors still complete on the primary (degraded quota)
            import threading
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=15)
            threads = [threading.Thread(target=contribute, args=(r,))
                       for r in (0, 1)]
            for t in threads: t.start()
            for t in threads: t.join(timeout=15)
            assert results == {0: 1, 1: 1}
        finally:
            for transport in transports:
                transport.close()
            primary.close()
            deputy.close()

    def test_rank_lost_before_failover_degrades_on_deputy(self):
        """The primary's exclusion state dies with it; the promoted deputy
        seeds liveness for never-connected ranks so its heartbeat monitor
        excludes them and survivors' collectives degrade instead of
        deadlocking on a ghost."""
        import threading
        primary = Hub(3, heartbeat_timeout=0.4)
        deputy = Hub(3, standby_of=primary.address, heartbeat_timeout=0.4)
        transports = [
            TcpTransport([primary.address, deputy.address], rank, 3,
                         heartbeat_interval=0.05)
            for rank in range(3)]
        try:
            assert wait_until(lambda: len(primary._clients) == 3)
            # rank 2 crashes and is excluded on the primary
            transports[2]._sock.shutdown(socket.SHUT_RDWR)
            assert wait_until(lambda: 2 in primary._excluded)
            transports[2].close()     # it stays gone (no deputy dialing)
            primary.close()           # then the star center dies
            assert wait_until(lambda: not deputy.is_standby, timeout=10)

            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=20)
            threads = [threading.Thread(target=contribute, args=(r,))
                       for r in (0, 1)]
            for t in threads: t.start()
            for t in threads: t.join(timeout=25)
            assert results == {0: 1, 1: 1}     # degraded to the survivors
            assert 2 in deputy._excluded
        finally:
            for transport in transports:
                transport.close()
            deputy.close()


class TestBlobs:
    """The blob plane: chunked, digest-verified point-to-point transfers
    (what the supervisor's hot-state replication rides)."""

    def test_send_blob_is_point_to_point(self):
        """A blob reaches its addressee intact — reassembled across many
        bounded chunks — and NOBODY else sees any of it."""
        hub, transports = pod(3)
        try:
            delivered, stray = [], []
            transports[2].on_blob = lambda s, k, d: delivered.append((s, k, d))
            transports[1].on_blob = lambda s, k, d: stray.append(k)
            payload = bytes(range(256)) * 64          # 16 KiB, 16+ chunks
            transports[0].send_blob(2, 'shard', payload, chunk_size=1024)
            assert wait_until(lambda: delivered)
            assert delivered == [(0, 'shard', payload)]
            time.sleep(0.1)
            assert stray == []
        finally:
            shutdown(hub, transports)

    def test_fetch_blob_request_reply(self):
        """fetch_blob asks the peer's on_blob_request hook; a peer with
        nothing NAKs, and the requester gets a typed BlobError fast."""
        hub, transports = pod(2)
        try:
            transports[1].on_blob_request = (
                lambda key: b'served:' + key.encode() if key == 'have' else None)
            assert transports[0].fetch_blob(1, 'have',
                                            timeout=10) == b'served:have'
            start = time.monotonic()
            with pytest.raises(BlobError, match='no blob'):
                transports[0].fetch_blob(1, 'missing', timeout=10)
            assert time.monotonic() - start < 5       # NAK, not a timeout
        finally:
            shutdown(hub, transports)

    def test_unclaimed_push_is_held_for_a_later_fetch(self):
        hub, transports = pod(2)
        try:
            transports[0].send_blob(1, 'early', b'pushed before the fetch')
            assert wait_until(lambda: 'early' in transports[1]._blob_ready)
            assert transports[1].fetch_blob(0, 'early',
                                            timeout=5) == b'pushed before the fetch'
        finally:
            shutdown(hub, transports)

    def test_dropped_chunk_times_out_typed(self):
        """Chaos: lost chunks mean the blob never completes — the fetcher
        gets a typed BlobError at its own timeout, and the partial
        assembly is never delivered."""
        from tpusystem.parallel.chaos import ChaosTransport, Faults
        faults = Faults(seed=3, drop=0.5, kinds=('blob',))
        hub = Hub(2)
        responder = ChaosTransport(hub.address, 0, 2, faults=faults)
        requester = TcpTransport(hub.address, 1, 2)
        try:
            assert wait_until(lambda: len(hub._clients) == 2)
            received = []
            requester.on_blob = lambda s, k, d: received.append(k)
            responder.blob_chunk = 512                           # 16 chunks
            responder.on_blob_request = lambda key: bytes(8192)
            start = time.monotonic()
            with pytest.raises(BlobError, match='did not arrive'):
                requester.fetch_blob(0, 'torn', timeout=1.0)
            assert time.monotonic() - start < 5
            assert faults.dropped                # the fault really fired
            assert received == []                # no partial delivery
        finally:
            responder.close()
            requester.close()
            hub.close()

    def test_truncated_chunk_fails_digest_on_fetch(self, caplog):
        """Chaos: a truncated chunk arrives, the count completes, but the
        whole-blob digest fails — the waiting fetcher is failed typed and
        fast instead of receiving torn bytes."""
        import logging
        from tpusystem.parallel.chaos import ChaosTransport, Faults
        faults = Faults(seed=1, truncate=1.0, kinds=('blob',))
        hub = Hub(2)
        responder = ChaosTransport(hub.address, 0, 2, faults=faults)
        requester = TcpTransport(hub.address, 1, 2)
        try:
            assert wait_until(lambda: len(hub._clients) == 2)
            responder.on_blob_request = lambda key: bytes(4096)
            start = time.monotonic()
            with caplog.at_level(logging.WARNING, 'tpusystem.multihost'):
                with pytest.raises(BlobError, match='digest'):
                    requester.fetch_blob(0, 'torn', timeout=10)
            assert time.monotonic() - start < 5      # failed fast, typed
            assert faults.truncated == ['blob']
            assert 'digest' in caplog.text
        finally:
            responder.close()
            requester.close()
            hub.close()

    def test_fetch_is_pinned_to_the_requested_peer(self):
        """Review regression: a same-key blob pushed by a DIFFERENT rank
        while a fetch is in flight must not be mistaken for the answer —
        the waiter is pinned to the peer the request went to."""
        hub, transports = pod(3)
        try:
            unsolicited = []
            transports[0].on_blob = lambda s, k, d: unsolicited.append((s, d))
            transports[2].on_blob_request = (
                lambda key: time.sleep(0.5) or b'the real answer')
            import threading
            box = {}
            fetcher = threading.Thread(
                target=lambda: box.update(
                    got=transports[0].fetch_blob(2, 'shared-key', timeout=10)))
            fetcher.start()
            time.sleep(0.1)                 # fetch registered, reply pending
            transports[1].send_blob(0, 'shared-key', b'impostor bytes')
            fetcher.join(timeout=10)
            assert box['got'] == b'the real answer'
            assert wait_until(lambda: unsolicited)
            assert unsolicited == [(1, b'impostor bytes')]
        finally:
            shutdown(hub, transports)

    def test_transport_close_fails_inflight_fetch_typed(self):
        """Review regression: closing the transport with a fetch in
        flight must fail it typed and fast — the same no-hang-to-timeout
        discipline the collective waiters get — not leave it to ride out
        its full timeout."""
        import threading
        hub, transports = pod(2)
        try:
            transports[1].on_blob_request = (
                lambda key: time.sleep(30) or b'far too late')
            outcome = {}

            def fetch():
                start = time.monotonic()
                try:
                    transports[0].fetch_blob(1, 'slow', timeout=60)
                    outcome['verdict'] = 'completed'
                except BlobError as error:
                    outcome['verdict'] = str(error)
                outcome['elapsed'] = time.monotonic() - start

            fetcher = threading.Thread(target=fetch)
            fetcher.start()
            time.sleep(0.2)               # request sent, reply pending
            transports[0].close()
            fetcher.join(timeout=10)
            assert 'closed or failed over' in outcome['verdict']
            assert outcome['elapsed'] < 5
        finally:
            transports[1].close()
            hub.close()

    def test_concurrent_same_key_fetch_is_refused_typed(self):
        """Review regression: _blob_waiters holds one registration per
        key — a second concurrent fetch for the same key is refused typed
        instead of silently clobbering the first's."""
        import threading
        hub, transports = pod(2)
        try:
            transports[1].on_blob_request = (
                lambda key: time.sleep(0.5) or b'answer')
            box = {}
            first = threading.Thread(
                target=lambda: box.update(
                    got=transports[0].fetch_blob(1, 'dup', timeout=10)))
            first.start()
            time.sleep(0.1)
            with pytest.raises(BlobError, match='already in flight'):
                transports[0].fetch_blob(1, 'dup', timeout=10)
            first.join(timeout=10)
            assert box['got'] == b'answer'     # the first fetch unharmed
        finally:
            shutdown(hub, transports)

    def test_loopback_blob_parity(self):
        transport = Loopback()
        held = []
        transport.on_blob = lambda s, k, d: held.append((s, k, d))
        transport.send_blob(0, 'self', b'stay local')
        assert held == [(0, 'self', b'stay local')]
        transport.on_blob_request = (
            lambda key: b'mine' if key == 'x' else None)
        assert transport.fetch_blob(0, 'x') == b'mine'
        with pytest.raises(BlobError):
            transport.fetch_blob(0, 'absent')
