"""Integration test: the LM pretraining example end to end, twice (resume).

The BASELINE ladder-4 architecture — GPT-2 aggregate, FSDP policy on the
job mesh, fused chunked LM loss — driven through the full message stack:
compiler pipeline, service handlers, tracking/checkpoint consumers,
resume-by-identity.
"""

from __future__ import annotations

import importlib.util
import pathlib

import pytest

EXAMPLE = pathlib.Path(__file__).parent.parent / 'examples' / 'lm'


@pytest.fixture()
def lm_main(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location('lm_main', EXAMPLE / 'main.py')
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    monkeypatch.setattr(module, 'ROOT', tmp_path)
    return module


def test_pretrains_and_resumes(lm_main, capsys):
    lm_main.main(epochs=2)
    out = capsys.readouterr().out
    assert 'from epoch 0' in out

    from tpusystem.storage import DocumentMetrics, DocumentModels, DocumentStore
    store = DocumentStore(lm_main.ROOT / 'experiments.json')
    (model,) = DocumentModels(store).list('lm')
    assert model.epoch == 2
    rows = DocumentMetrics(store).list(model.hash)
    assert {row.name for row in rows} == {'loss', 'perplexity'}
    losses = [row.value for row in rows
              if row.name == 'loss' and row.phase == 'train']
    assert losses[-1] < losses[0]     # bigram structure is learnable
    evals = [row.value for row in rows
             if row.name == 'loss' and row.phase == 'evaluation']
    # holdout shares the bigram table (train=False): learning generalizes
    assert evals[-1] < evals[0]
    store.close()

    lm_main.main(epochs=3)
    out = capsys.readouterr().out
    assert 'from epoch 2' in out
    store = DocumentStore(lm_main.ROOT / 'experiments.json')
    (model,) = DocumentModels(store).list('lm')
    assert model.epoch == 3
    store.close()
