"""Integration test: the LM pretraining example end to end, twice (resume).

The BASELINE ladder-4 architecture — GPT-2 aggregate, FSDP policy on the
job mesh, fused chunked LM loss — driven through the full message stack:
compiler pipeline, service handlers, tracking/checkpoint consumers,
resume-by-identity.
"""

from __future__ import annotations

import importlib.util
import pathlib

import pytest

EXAMPLE = pathlib.Path(__file__).parent.parent / 'examples' / 'lm'


@pytest.fixture()
def lm_main(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location('lm_main', EXAMPLE / 'main.py')
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    monkeypatch.setattr(module, 'ROOT', tmp_path)
    return module


@pytest.mark.slow
def test_pretrains_and_resumes(lm_main, capsys):
    lm_main.main(epochs=2)
    out = capsys.readouterr().out
    assert 'from epoch 0' in out

    from tpusystem.storage import DocumentMetrics, DocumentModels, DocumentStore
    store = DocumentStore(lm_main.ROOT / 'experiments.json')
    (model,) = DocumentModels(store).list('lm')
    assert model.epoch == 2
    rows = DocumentMetrics(store).list(model.hash)
    assert {row.name for row in rows} == {'loss', 'perplexity'}
    losses = [row.value for row in rows
              if row.name == 'loss' and row.phase == 'train']
    assert losses[-1] < losses[0]     # bigram structure is learnable
    evals = [row.value for row in rows
             if row.name == 'loss' and row.phase == 'evaluation']
    # holdout shares the bigram table (train=False): learning generalizes
    assert evals[-1] < evals[0]
    store.close()

    lm_main.main(epochs=3)
    out = capsys.readouterr().out
    assert 'from epoch 2' in out
    store = DocumentStore(lm_main.ROOT / 'experiments.json')
    (model,) = DocumentModels(store).list('lm')
    assert model.epoch == 3
    store.close()


@pytest.mark.slow
def test_pretrains_from_generated_corpus_file(lm_main, tmp_path, capsys):
    """Real-data ingestion end to end (VERDICT r1 missing #3): write a
    binary token corpus to disk, train via --corpus/--holdout
    (MemmapTokens), verify learning on the held-out file."""
    import numpy as np

    def bigram_corpus(tokens, seed):
        # mostly-deterministic bigram chain (learnable), 10% noise
        rng = np.random.default_rng(seed)
        out = np.empty(tokens, np.uint16)
        out[0] = rng.integers(0, 96)
        jumps = rng.random(tokens) < 0.1
        noise = rng.integers(0, 96, tokens)
        for i in range(1, tokens):
            out[i] = noise[i] if jumps[i] else (out[i - 1] * 7 + 3) % 96
        return out

    corpus = tmp_path / 'train.bin'
    holdout = tmp_path / 'holdout.bin'
    corpus.write_bytes(bigram_corpus(8192, seed=1).tobytes())
    holdout.write_bytes(bigram_corpus(2176, seed=2).tobytes())

    lm_main.main(epochs=2, corpus=str(corpus), holdout_corpus=str(holdout))
    capsys.readouterr()

    from tpusystem.storage import DocumentMetrics, DocumentModels, DocumentStore
    store = DocumentStore(lm_main.ROOT / 'experiments.json')
    (model,) = DocumentModels(store).list('lm')
    assert model.epoch == 2
    rows = DocumentMetrics(store).list(model.hash)
    losses = [row.value for row in rows
              if row.name == 'loss' and row.phase == 'train']
    assert losses[-1] < losses[0]     # the on-disk chain is learnable
    evals = [row.value for row in rows
             if row.name == 'loss' and row.phase == 'evaluation']
    assert evals[-1] < evals[0]       # generalizes to the held-out file
    store.close()
