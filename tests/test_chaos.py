"""Chaos-tested fault tolerance: preemption, worker loss, corruption.

The ROADMAP's "handles as many scenarios as you can imagine" is enforced
here by *deterministic* fault injection over the real control-plane stack
(:mod:`tpusystem.parallel.chaos`), not by hand-crafted mocks:

* kill-at-step-k → restart → **step-granular resume**: the resumed run's
  losses are bitwise-identical to an uninterrupted reference run (same RNG
  stream, same batch order — the headline acceptance scenario);
* torn/corrupt checkpoint dirs are *skipped with a logged fallback* by
  ``latest``/``restore``, never crashed on;
* SIGTERM preemption surfaces as :class:`Preempted` at the ``sync()``
  drain, fences an emergency checkpoint, and maps to the restartable exit
  code;
* seeded frame drops/delays, heartbeat stalls, and mid-collective socket
  kills leave the collective machinery correct (or degraded exactly as
  documented).
"""

from __future__ import annotations

import json
import logging
import os
import signal as signal_module
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusystem.checkpoint import Checkpointer, Repository
from tpusystem.data import Loader, SyntheticDigits
from tpusystem.models import MLP
from tpusystem.parallel.chaos import (ChaosHub, ChaosTransport, DieAtStep,
                                      Faults, WorkerKilled)
from tpusystem.parallel.multihost import (ControlPlaneFailover,
                                          DistributedProducer, Hub,
                                          TcpTransport, WorkerJoined,
                                          WorkerLost)
from tpusystem.parallel.recovery import (LOST_WORKER_EXIT, PREEMPTED_EXIT,
                                         RESTART_EXITS, Preempted,
                                         WorkerLostError, exit_for_restart,
                                         recovery_consumer)
from tpusystem.runtime import Runtime
from tpusystem.services.prodcon import Consumer
from tpusystem.train import (Adam, CrossEntropyLoss, build_train_step,
                             flax_apply, init_state, resume_extras)

IDENTITY = 'chaos-mlp'


def wait_until(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


def make_parts():
    """One training cell: deterministic loader + model + jitted step."""
    dataset = SyntheticDigits(samples=40, seed=4)
    loader = Loader(dataset, batch_size=8, shuffle=True, seed=3)  # 5/epoch
    module = MLP(features=(16,), classes=10, dropout=0.2)
    optimizer = Adam(lr=1e-2)
    state = init_state(module, optimizer, jnp.zeros((1, 28, 28)), rng=7)
    step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer)
    return loader, state, step


def drive(loader, state, step, checkpointer, *, until, die=None):
    """Run the epoch loop to global step ``until``, checkpointing each step
    with the loader cursor; returns (state, {step: loss})."""
    losses = {}
    while int(state.step) < until:
        for inputs, targets in loader:
            state, (_, loss) = step(state, inputs, targets)
            at = int(state.step)
            losses[at] = float(loss)
            if checkpointer is not None:
                checkpointer.save(IDENTITY, at, state,
                                  extras=resume_extras(state, loader))
            if die is not None:
                die(at)
            if at == until:
                return state, losses
    return state, losses


class TestStepGranularResume:
    """The acceptance scenario: kill at step k, restart, resume bitwise."""

    def test_kill_at_step_restart_resumes_bitwise(self, tmp_path):
        # uninterrupted reference trajectory (no checkpointing at all)
        loader, state, step = make_parts()
        _, reference = drive(loader, state, step, None, until=10)
        assert sorted(reference) == list(range(1, 11))

        # chaos run: dies at step 6 — mid-epoch 2 (5 batches per epoch),
        # so resume must restart mid-epoch, not at an epoch edge
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            with pytest.raises(WorkerKilled):
                drive(loader, state, step, checkpointer,
                      until=10, die=DieAtStep(step=6))
            checkpointer.fence(IDENTITY)

            # restart: fresh process state — new loader, blank template
            loader, blank, step = make_parts()
            state, resumed_step, extras = checkpointer.resume(IDENTITY, blank)
            assert resumed_step == 6
            assert int(state.step) == 6          # device counter restored
            assert extras['step'] == 6
            assert extras['cursor'] == {'epoch': 1, 'batch': 1}
            loader.seek(extras['cursor'])
            _, resumed = drive(loader, state, step, checkpointer, until=10)

        # bitwise-identical continuation: same RNG key stream (carried in
        # TrainState), same batch order (cursor-seeked loader)
        assert sorted(resumed) == list(range(7, 11))
        for at in range(7, 11):
            assert resumed[at] == reference[at], (at, resumed[at], reference[at])

    def test_kill_over_live_control_plane_then_resume(self, tmp_path):
        """The same drill through the REAL multihost stack: a pod of TCP
        transports, a peer killed at step k (socket death, no 'bye'), the
        survivor's recovery consumer raising at the drain point, emergency
        fence, restart, bitwise resume."""
        hub = Hub(2)
        survivor = TcpTransport(hub.address, 0, 2)
        victim = ChaosTransport(hub.address, 1, 2)
        assert wait_until(lambda: len(hub._clients) == 2)
        producer = DistributedProducer(survivor)
        producer.register(recovery_consumer())
        try:
            loader, state, step = make_parts()
            _, reference = drive(loader, state, step, None, until=8)

            loader, state, step = make_parts()
            checkpointer = Checkpointer(tmp_path, async_save=False)
            die = DieAtStep(step=4, action=victim.kill)
            with pytest.raises(WorkerLostError) as excinfo:
                losses = {}
                while int(state.step) < 8:
                    for inputs, targets in loader:
                        state, (_, loss) = step(state, inputs, targets)
                        losses[int(state.step)] = float(loss)
                        checkpointer.save(IDENTITY, int(state.step), state,
                                          extras=resume_extras(state, loader))
                        die(int(state.step))
                        # drain point: worker loss surfaces HERE, on the
                        # host loop thread, never inside a collective
                        deadline = time.monotonic() + 5
                        while die.fired and time.monotonic() < deadline:
                            producer.drain()
                            time.sleep(0.01)
            assert excinfo.value.rank == 1
            assert excinfo.value.reason == 'socket'   # EOF, not a stall
            fenced = checkpointer.fence(IDENTITY)   # emergency durability
            assert fenced == 4
            assert exit_for_restart(excinfo.value).code == LOST_WORKER_EXIT

            # the scheduler restarts the job: fresh everything, same id
            loader, blank, step = make_parts()
            state, resumed_step, extras = checkpointer.resume(IDENTITY, blank)
            assert resumed_step == 4
            loader.seek(extras['cursor'])
            _, resumed = drive(loader, state, step, checkpointer, until=8)
            checkpointer.close()
            for at in range(5, 9):
                assert resumed[at] == reference[at]
        finally:
            survivor.close()
            hub.close()


class TestCorruptCheckpoints:
    """Torn step dirs are survivable: verify-probe, skip, logged fallback."""

    def plant_truncated(self, root, step):
        """A save torn by a kill: the dir exists, the commit marker and
        item manifests never landed."""
        torn = root / IDENTITY / str(step)
        (torn / 'default').mkdir(parents=True)
        (torn / 'default' / 'manifest.ocdbt').write_bytes(b'torn mid-write')

    def test_truncated_step_dir_skipped_with_logged_fallback(
            self, tmp_path, caplog):
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            state, _ = drive(loader, state, step, checkpointer, until=3)
        self.plant_truncated(tmp_path, 7)   # "newest" step is garbage

        # a fresh process must resume from 3, not crash on 7
        with Checkpointer(tmp_path, async_save=False) as fresh:
            with caplog.at_level(logging.WARNING, 'tpusystem.checkpoint'):
                assert fresh.latest(IDENTITY) == 3
                assert fresh.epochs(IDENTITY) == [1, 2, 3]
                assert not fresh.verify(IDENTITY, 7)
                assert fresh.verify(IDENTITY, 3)
                _, blank, _ = make_parts()
                restored, resumed_step, _ = fresh.resume(IDENTITY, blank)
            assert resumed_step == 3
            np.testing.assert_array_equal(
                np.asarray(restored.step), np.asarray(state.step))
            for expected, loaded in zip(jax.tree.leaves(state.params),
                                        jax.tree.leaves(restored.params)):
                np.testing.assert_array_equal(np.asarray(expected),
                                              np.asarray(loaded))
        assert 'incomplete or corrupt' in caplog.text
        assert '7' in caplog.text

    def test_explicit_missing_epoch_lists_available(self, tmp_path):
        """Satellite: an explicit epoch that is missing (or torn) names the
        committed epochs instead of an opaque Orbax error."""
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            drive(loader, state, step, checkpointer, until=2)
            _, blank, _ = make_parts()
            with pytest.raises(FileNotFoundError, match=r'epoch 9.*\[1, 2\]'):
                checkpointer.restore(IDENTITY, blank, epoch=9)
            self.plant_truncated(tmp_path, 5)
            with pytest.raises(FileNotFoundError, match=r'epoch 5.*\[1, 2\]'):
                checkpointer.restore(IDENTITY, blank, epoch=5)
            # the committed ones still restore explicitly
            restored = checkpointer.restore(IDENTITY, blank, epoch=1)
            assert int(restored.step) == 1

    def test_resume_falls_back_when_probe_passing_payload_is_torn(
            self, tmp_path, caplog):
        """A payload torn in a way the cheap probe cannot see (markers
        intact, array bytes gone) must still fall back, not crash the
        one-call resume path."""
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            state_two = None
            while int(state.step) < 3:
                for inputs, targets in loader:
                    state, _ = step(state, inputs, targets)
                    checkpointer.save(IDENTITY, int(state.step), state)
                    if int(state.step) == 2:
                        state_two = state
                    if int(state.step) == 3:
                        break
        # corrupt step 3's payload but keep every integrity marker
        (tmp_path / IDENTITY / '3' / 'default' /
         'manifest.ocdbt').write_bytes(b'probe-passing garbage')
        with Checkpointer(tmp_path, async_save=False) as fresh:
            assert fresh.verify(IDENTITY, 3)     # the probe cannot tell
            _, blank, _ = make_parts()
            with caplog.at_level(logging.WARNING, 'tpusystem.checkpoint'):
                restored, resumed_step, _ = fresh.resume(IDENTITY, blank)
            assert resumed_step == 2
            np.testing.assert_array_equal(
                np.asarray(restored.step), np.asarray(state_two.step))
        assert 'falling back' in caplog.text

    def test_repository_auto_version_respects_in_flight_async_save(
            self, tmp_path):
        """Regression: latest() only sees committed steps, so the auto
        increment must consult the in-flight async save too — reusing its
        step number would make Orbax raise StepAlreadyExists."""
        loader, state, step = make_parts()

        class Model:
            id = IDENTITY
        model = Model()
        model.state = state
        repository = Repository(tmp_path, async_save=True)
        try:
            repository.store(model)      # -> version 0, commits in background
            repository.store(model)      # must allocate 1, not 0 again
            repository.wait()
            assert repository.latest(model) == 1
        finally:
            repository.close()

    def test_fence_is_monotonic(self, tmp_path):
        import shutil
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            drive(loader, state, step, checkpointer, until=3)
            assert checkpointer.fenced(IDENTITY) is None
            assert checkpointer.fence(IDENTITY) == 3
            assert checkpointer.fenced(IDENTITY) == 3
            # losing the newest dir cannot move the fence backwards
            shutil.rmtree(tmp_path / IDENTITY / '3')
            assert checkpointer.fence(IDENTITY) == 3
            assert checkpointer.latest(IDENTITY) == 2

    def test_extras_sidecar_pruned_with_gc(self, tmp_path):
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False,
                          max_to_keep=2) as checkpointer:
            drive(loader, state, step, checkpointer, until=6)
            kept = checkpointer.epochs(IDENTITY)
            assert kept == [5, 6]        # window of 2
            sidecars = sorted(int(p.stem) for p in
                              (tmp_path / IDENTITY / '.extras').glob('*.json'))
            assert set(sidecars) <= {4, 5, 6}   # stale ones pruned
            assert checkpointer.extras(IDENTITY, 6)['step'] == 6


class TestChaosControlPlane:
    """Seeded frame faults over real sockets: the documented contracts
    hold under drops, delays, stalls, and kills."""

    def chaos_pod(self, size, faults, **hub_kwargs):
        hub = Hub(size, **hub_kwargs)
        transports = [
            ChaosTransport(hub.address, rank, size,
                           faults=faults[rank] if faults else None,
                           heartbeat_interval=hub_kwargs.get(
                               'heartbeat_timeout') and 0.05)
            for rank in range(size)]
        assert wait_until(lambda: len(hub._clients) == size)
        return hub, transports

    def shutdown(self, hub, transports):
        for transport in transports:
            transport.close()
        hub.close()

    def test_same_seed_same_fault_schedule(self):
        script = ['event', 'reduce', 'event', 'event', 'gather'] * 20
        first, second = Faults(seed=5, drop=0.3), Faults(seed=5, drop=0.3)
        decisions = [(first.decide(k), second.decide(k)) for k in script]
        assert all(a == b for a, b in decisions)
        assert first.dropped == second.dropped and first.dropped

    def test_explicit_kinds_override_default_spare(self):
        """Naming a kind in ``kinds`` is the opt-in that defeats the
        default spare list — else result/hb scenarios run fault-free and
        pass vacuously."""
        faults = Faults(seed=0, drop=1.0, kinds=('result',))
        assert faults.decide('result') is None       # spared by default, faulted on opt-in
        assert faults.decide('reduce') == 0.0        # outside kinds: passes
        assert Faults(seed=0, drop=1.0).decide('result') == 0.0  # default spare

    def test_dropped_events_leave_collectives_intact(self):
        """Events are at-most-once by contract; collectives are the
        agreement primitive and must survive a lossy event plane."""
        faults = [Faults(seed=rank, drop=1.0, kinds=('event',))
                  for rank in range(3)]
        hub, transports = self.chaos_pod(3, faults)
        try:
            seen = []
            transports[1].subscribe('test', seen.append)
            transports[0].send_event('test', 'vanishes')
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=10)
            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in range(3)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: 3, 1: 3, 2: 3}
            assert faults[0].dropped == ['event']
            assert seen == []                    # the event truly vanished
        finally:
            self.shutdown(hub, transports)

    def test_delayed_frames_do_not_corrupt_collectives(self):
        """Per-rank jitter reorders contributions across ranks; the hub's
        (kind, op, sequence) keying must still pair them correctly."""
        faults = [Faults(seed=rank, delay=0.7, delay_seconds=0.03,
                         kinds=('reduce', 'gather'))
                  for rank in range(3)]
        hub, transports = self.chaos_pod(3, faults)
        try:
            results = {}

            def contribute(rank):
                total = transports[rank].allreduce(rank, op='sum', timeout=10)
                gathered = transports[rank].gather(10 * rank, timeout=10)
                peak = transports[rank].allreduce(rank, op='max', timeout=10)
                results[rank] = (total, sorted(gathered), peak)
            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in range(3)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=15)
            assert all(results[rank] == (3, [0, 10, 20], 2)
                       for rank in range(3))
            assert any(faults[rank].delayed for rank in range(3))
        finally:
            self.shutdown(hub, transports)

    def test_heartbeat_stall_surfaces_worker_lost(self):
        """A host alive but unresponsive past the liveness timeout is a
        loss: excluded from the quota, broadcast as WorkerLost."""
        faults = Faults(seed=1)
        hub = Hub(3, heartbeat_timeout=0.3)
        transports = [
            TcpTransport(hub.address, 0, 3, heartbeat_interval=0.05),
            TcpTransport(hub.address, 1, 3, heartbeat_interval=0.05),
            ChaosTransport(hub.address, 2, 3, faults=faults,
                           heartbeat_interval=0.05),
        ]
        try:
            assert wait_until(lambda: len(hub._clients) == 3)
            producer = DistributedProducer(transports[0])
            lost = []
            consumer = Consumer()
            consumer.register(WorkerLost, lost.append)
            producer.register(consumer)
            faults.stall_heartbeats(30.0)
            assert wait_until(lambda: 2 in hub._excluded, timeout=5)
            assert wait_until(lambda: (producer.drain(), bool(lost))[1],
                              timeout=5)
            assert lost[0].rank == 2
            # satellite: a stall is detected by the liveness monitor, and
            # the event says so — different MTTR profile than socket death
            assert lost[0].reason == 'heartbeat'
            # the stalled rank is out of the quota: fail-fast, and the
            # survivors' collectives degrade to the live set
            with pytest.raises(RuntimeError, match='excluded'):
                transports[2].allreduce(True, op='and', timeout=15)
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=10)
            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in (0, 1)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: 1, 1: 1}
        finally:
            self.shutdown(hub, transports)

    def test_mid_collective_kill_completes_for_survivors(self):
        """DieAtStep(action=kill) mid-collective: the victim's socket dies
        with its contribution pending; survivors complete on the quota."""
        hub, transports = self.chaos_pod(3, None)
        try:
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=10)
            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in (0, 1)]
            for thread in threads:
                thread.start()
            assert wait_until(lambda: len(hub._pending) == 1)
            die = DieAtStep(step=3, action=transports[2].kill)
            die(3)                       # the scripted death fires
            assert die.fired
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: 1, 1: 1}
        finally:
            self.shutdown(hub, transports)

    def test_chaotic_hub_fanout_drops_are_at_most_once(self):
        """Faults on the router side: a dropped event fanout loses that
        delivery (at-most-once, documented) without wedging the hub."""
        faults = Faults(seed=3, drop=1.0, kinds=('event',))
        hub = ChaosHub(2, faults=faults)
        transports = [TcpTransport(hub.address, rank, 2) for rank in range(2)]
        try:
            assert wait_until(lambda: len(hub._clients) == 2)
            seen = []
            transports[1].subscribe('test', seen.append)
            transports[0].send_event('test', 'dropped-at-the-hub')
            time.sleep(0.2)
            assert seen == [] and faults.dropped == ['event']
            # collectives (not in kinds) still flow through the same hub
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank, op='sum',
                                                           timeout=10)
            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in range(2)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: 1, 1: 1}
        finally:
            self.shutdown(hub, transports)


class TestClose:
    """Satellite regression: teardown racing in-flight collectives must
    surface ControlPlaneFailover, not hang to the collective timeout."""

    def test_hub_close_mid_collective_fails_over_every_waiter(self):
        hub = Hub(3)
        transports = [TcpTransport(hub.address, rank, 3) for rank in range(3)]
        assert wait_until(lambda: len(hub._clients) == 3)
        try:
            outcomes = {}

            def contribute(rank):
                start = time.monotonic()
                try:
                    transports[rank].allreduce(rank, op='sum', timeout=60)
                    outcomes[rank] = 'completed'
                except ControlPlaneFailover:
                    outcomes[rank] = ('failover', time.monotonic() - start)
            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in (0, 1)]   # rank 2 withholds: op stays pending
            for thread in threads:
                thread.start()
            assert wait_until(lambda: len(hub._pending) == 1)
            hub.close()
            for thread in threads:
                thread.join(timeout=10)
            assert set(outcomes) == {0, 1}
            for rank in (0, 1):
                verdict, elapsed = outcomes[rank]
                assert verdict == 'failover' and elapsed < 5
        finally:
            for transport in transports:
                transport.close()
            hub.close()

    def test_transport_close_mid_collective_fails_typed_not_timeout(self):
        """The fixed hang: closing a transport with its own collective in
        flight used to leave the waiter for the full timeout and then
        raise a raw queue.Empty."""
        hub = Hub(2)
        transports = [TcpTransport(hub.address, rank, 2) for rank in range(2)]
        assert wait_until(lambda: len(hub._clients) == 2)
        try:
            outcome = {}

            def contribute():
                start = time.monotonic()
                try:
                    transports[0].allreduce(0, op='sum', timeout=60)
                    outcome['verdict'] = 'completed'
                except ControlPlaneFailover:
                    outcome['verdict'] = 'failover'
                except Exception as error:
                    outcome['verdict'] = type(error).__name__
                outcome['elapsed'] = time.monotonic() - start
            thread = threading.Thread(target=contribute)
            thread.start()
            assert wait_until(lambda: len(hub._pending) == 1)
            transports[0].close()
            thread.join(timeout=10)
            assert outcome['verdict'] == 'failover'
            assert outcome['elapsed'] < 5
        finally:
            transports[1].close()
            hub.close()


class TestPreemption:
    """SIGTERM → Preempted at the drain → emergency fence → restart code."""

    def test_sigterm_surfaces_at_sync_not_in_handler(self):
        previous = signal_module.getsignal(signal_module.SIGTERM)
        with Runtime() as runtime:
            runtime.install_preemption_handler()
            assert not runtime.preempted
            os.kill(os.getpid(), signal_module.SIGTERM)
            assert wait_until(lambda: runtime.preempted)
            with pytest.raises(Preempted) as excinfo:
                runtime.sync()
            assert excinfo.value.signum == signal_module.SIGTERM
            assert exit_for_restart(excinfo.value).code == PREEMPTED_EXIT
        # close() restored whatever disposition was there before
        assert signal_module.getsignal(signal_module.SIGTERM) is previous

    def test_reinstall_keeps_the_original_previous_handler(self):
        """Regression: a second install must not record the Runtime's own
        handler as 'previous', or close() would leave it armed forever."""
        previous = signal_module.getsignal(signal_module.SIGTERM)
        with Runtime(preemption=True) as runtime:
            runtime.install_preemption_handler()   # re-install
        assert signal_module.getsignal(signal_module.SIGTERM) is previous

    def test_queued_events_still_drain_before_the_raise(self):
        """The raise happens AFTER the drain: consumers see everything that
        arrived before the preemption unwinds the loop."""
        from tpusystem.services.prodcon import event

        @event
        class Tick:
            n: int

        with Runtime(preemption=True) as runtime:
            seen = []
            consumer = Consumer()
            consumer.register(Tick, seen.append)
            runtime.producer.register(consumer)
            runtime.producer._inbox.put(Tick(n=1))
            os.kill(os.getpid(), signal_module.SIGTERM)
            assert wait_until(lambda: runtime.preempted)
            with pytest.raises(Preempted):
                runtime.sync()
            assert seen == [Tick(1)]

    def test_preemption_mid_training_fences_and_resumes(self, tmp_path):
        """End to end: SIGTERM mid-epoch, Preempted at the next drain, the
        emergency checkpoint fences, the 'restarted' job resumes at the
        fenced step with bitwise-identical continuation."""
        loader, state, step = make_parts()
        _, reference = drive(loader, state, step, None, until=8)

        loader, state, step = make_parts()
        checkpointer = Checkpointer(tmp_path, async_save=True)
        with Runtime(preemption=True) as runtime:
            with pytest.raises(Preempted) as excinfo:
                while int(state.step) < 8:
                    for inputs, targets in loader:
                        state, (_, loss) = step(state, inputs, targets)
                        checkpointer.save(IDENTITY, int(state.step), state,
                                          extras=resume_extras(state, loader))
                        if int(state.step) == 5:   # the scheduler's notice
                            os.kill(os.getpid(), signal_module.SIGTERM)
                            assert wait_until(lambda: runtime.preempted)
                        runtime.sync()             # drain point raises
            # emergency path: fence the in-flight async save, then exit
            fenced = checkpointer.fence(IDENTITY)
            assert fenced == 5
            assert exit_for_restart(excinfo.value).code in RESTART_EXITS
        checkpointer.close()

        with Checkpointer(tmp_path, async_save=False) as fresh:
            loader, blank, step = make_parts()
            state, resumed_step, extras = fresh.resume(IDENTITY, blank)
            assert resumed_step == 5
            loader.seek(extras['cursor'])
            _, resumed = drive(loader, state, step, fresh, until=8)
        for at in range(6, 9):
            assert resumed[at] == reference[at]


class TestRecoveryPaths:
    """Satellite: the recovery consumer's untested decision paths."""

    def test_observe_policy_continues_in_live_pod(self, caplog):
        """policy='observe' over a real pod: the loss is logged, nothing
        raises at the drain, and the survivors keep agreeing stops."""
        hub = Hub(3)
        transports = [TcpTransport(hub.address, rank, 3) for rank in range(3)]
        assert wait_until(lambda: len(hub._clients) == 3)
        try:
            producer = DistributedProducer(transports[0])
            producer.register(recovery_consumer('observe'))
            transports[2]._sock.shutdown(socket.SHUT_RDWR)
            transports[2]._sock.close()
            assert wait_until(lambda: 2 in hub._lost)
            with caplog.at_level(logging.WARNING, 'tpusystem.recovery'):
                assert wait_until(
                    lambda: (producer.drain(),
                             'worker 2 lost' in caplog.text)[1])
            # no raise: the survivors still run the agreement machinery
            results = {}

            def contribute(rank):
                results[rank] = transports[rank].allreduce(rank == 0, op='or',
                                                           timeout=10)
            threads = [threading.Thread(target=contribute, args=(rank,))
                       for rank in (0, 1)]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=10)
            assert results == {0: True, 1: True}
        finally:
            for transport in transports[:2]:
                transport.close()
            hub.close()

    def test_worker_joined_surfaces_through_live_pod(self, caplog):
        """The WorkerJoined handler path, driven by a real (re)join: a new
        rank dialing the hub broadcasts 'joined' to every other host."""
        hub = Hub(3)
        transports = [TcpTransport(hub.address, rank, 3) for rank in range(2)]
        assert wait_until(lambda: len(hub._clients) == 2)
        try:
            producer = DistributedProducer(transports[0])
            joined = []
            consumer = Consumer()
            consumer.register(WorkerJoined, joined.append)
            producer.register(consumer)
            producer.register(recovery_consumer('observe'))
            late = TcpTransport(hub.address, 2, 3)
            transports.append(late)
            with caplog.at_level(logging.INFO, 'tpusystem.recovery'):
                # the broadcasts for the INITIAL joins may still be in
                # flight when on_control hooks up — wait for rank 2's
                assert wait_until(
                    lambda: (producer.drain(),
                             any(j.rank == 2 for j in joined))[1])
            assert 'worker 2 joined' in caplog.text
        finally:
            for transport in transports:
                transport.close()
            hub.close()

    def test_worker_lost_unwinds_with_pending_async_save(self, tmp_path):
        """Satellite: WorkerLostError through runtime.sync() with an async
        save still in flight — repository.wait() in the handler keeps the
        last good checkpoint restorable."""
        loader, state, step = make_parts()

        class Model:
            id = IDENTITY

        model = Model()
        model.state = state
        repository = Repository(tmp_path, async_save=True)
        with Runtime() as runtime:
            runtime.producer.register(recovery_consumer())
            for inputs, targets in loader:
                model.state, _ = step(model.state, inputs, targets)
                repository.store(model, int(model.state.step),
                                 extras=resume_extras(model.state, loader))
                break
            # the loss lands while the save may still be in flight
            runtime.producer._inbox.put(WorkerLost(rank=1, last_seen=2.0))
            with pytest.raises(WorkerLostError) as excinfo:
                runtime.sync()
            assert excinfo.value.rank == 1
            repository.wait()            # the docstring contract
            assert repository.fence(model) == 1
        # a fresh process restores the fenced checkpoint
        fresh = Repository(tmp_path, async_save=False)
        try:
            _, blank, _ = make_parts()
            clone = Model()
            clone.state = blank
            resumed_step, extras = fresh.resume(clone)
            assert resumed_step == 1 and int(clone.state.step) == 1
            assert extras['cursor'] == {'epoch': 0, 'batch': 1}
        finally:
            fresh.close()
            repository.close()


class TestFlakySaves:
    """Satellite: checkpoint durability under a flaky filesystem — bounded
    retry+backoff on the save, and background async failures surfacing at
    the NEXT save()/newest() instead of hiding until wait()/fence()."""

    def test_save_retries_transient_fs_errors(self, tmp_path, monkeypatch,
                                              caplog):
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False,
                          retry_backoff=0.01) as checkpointer:
            manager = checkpointer._manager(IDENTITY)
            real_save, calls = manager.save, []

            def flaky(*args, **kwargs):
                calls.append(1)
                if len(calls) <= 2:
                    raise OSError('EIO: flaky mount')
                return real_save(*args, **kwargs)

            monkeypatch.setattr(manager, 'save', flaky)
            with caplog.at_level(logging.WARNING, 'tpusystem.checkpoint'):
                checkpointer.save(IDENTITY, 1, state)
            assert len(calls) == 3
            assert checkpointer.verify(IDENTITY, 1)
        assert 'retry 1/2' in caplog.text and 'retry 2/2' in caplog.text

    def test_save_gives_up_after_bounded_retries(self, tmp_path, monkeypatch):
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False, save_retries=2,
                          retry_backoff=0.01) as checkpointer:
            manager = checkpointer._manager(IDENTITY)
            calls = []

            def dead(*args, **kwargs):
                calls.append(1)
                raise OSError('ENOSPC: disk full')

            monkeypatch.setattr(manager, 'save', dead)
            with pytest.raises(OSError, match='ENOSPC'):
                checkpointer.save(IDENTITY, 1, state)
            assert len(calls) == 3               # 1 try + save_retries

    def test_async_failure_surfaces_at_next_save_and_newest(
            self, tmp_path, monkeypatch):
        """The fixed gap: a background commit that failed used to stay
        silent until wait()/fence() — the training loop kept 'saving' into
        a void. It must raise at the very next save() or newest()."""
        loader, state, step = make_parts()
        checkpointer = Checkpointer(tmp_path, async_save=True)
        try:
            checkpointer.save(IDENTITY, 1, state)
            checkpointer.wait()
            manager = checkpointer._managers[IDENTITY]

            def boom():
                raise OSError('async commit failed: disk full')

            monkeypatch.setattr(manager, 'check_for_errors', boom,
                                raising=False)
            with pytest.raises(OSError, match='async commit failed'):
                checkpointer.save(IDENTITY, 2, state)
            with pytest.raises(OSError, match='async commit failed'):
                checkpointer.newest(IDENTITY)
        finally:
            monkeypatch.undo()
            checkpointer.close()

    def test_legacy_checkpoint_restores_into_grown_train_state(self,
                                                               tmp_path):
        """Regression (review finding): TrainState grew the optional
        ``health`` field — a checkpoint written before it existed must
        still restore/resume (the leafless field is pruned from the
        restore target and None grafted back), and only an ARMED target
        fails loudly."""
        from tpusystem.train import Guard
        loader, state, step = make_parts()
        state, _ = step(state, *next(iter(loader)))
        legacy = {'params': state.params, 'opt_state': state.opt_state,
                  'rng': state.rng, 'step': state.step}   # the PR-3 shape
        with Checkpointer(tmp_path, async_save=False) as checkpointer:
            checkpointer.save(IDENTITY, 1, legacy,
                              extras=resume_extras(state, loader))
            _, blank, _ = make_parts()
            restored, resumed_step, extras = checkpointer.resume(IDENTITY,
                                                                 blank)
            assert resumed_step == 1 and int(restored.step) == 1
            assert restored.health is None
            for expected, loaded in zip(jax.tree.leaves(state.params),
                                        jax.tree.leaves(restored.params)):
                np.testing.assert_array_equal(np.asarray(expected),
                                              np.asarray(loaded))
            # training continues from the grafted state, and arming works
            armed = Guard().arm(restored)
            assert armed.health is not None
            with pytest.raises(Exception):
                checkpointer.restore(IDENTITY, Guard().arm(blank), epoch=1)

    def test_discard_after_prunes_dead_branch_and_lowers_fence(
            self, tmp_path):
        """The rollback epilogue: steps beyond the target vanish (so the
        retrained steps cannot collide) and a fence pointing into the dead
        branch is lowered to the target."""
        loader, state, step = make_parts()
        with Checkpointer(tmp_path, async_save=False,
                          max_to_keep=None) as checkpointer:
            state, _ = drive(loader, state, step, checkpointer, until=6)
            assert checkpointer.fence(IDENTITY) == 6
            dead = checkpointer.discard_after(IDENTITY, 3)
            assert dead == [4, 5, 6]
            assert checkpointer.epochs(IDENTITY) == [1, 2, 3]
            assert checkpointer.fenced(IDENTITY) == 3
            # the retrained branch reuses the freed numbers without clashing
            checkpointer.save(IDENTITY, 4, state)
            assert checkpointer.latest(IDENTITY) == 4


class TestBuddyDoubleLoss:
    """Satellite: BOTH hosts of a replica pair die in one wave. Their
    pieces exist only in each other's replica slots, so the hot tier is
    unrecoverable for both — the elastic resize path must fall back to
    disk and still land bitwise on the last committed step (the drill's
    loss-equivalence), never deliver a partial hot state."""

    def test_double_buddy_loss_falls_back_to_disk(self, tmp_path, caplog):
        from tpusystem.checkpoint import Checkpointer, MemStoreClient
        from tpusystem.models import gpt2_tiny
        from tpusystem.parallel import (MeshSpec, Supervisor, TensorParallel,
                                        batch_sharding)
        from tpusystem.parallel.chaos import PreemptionWave
        from tpusystem.parallel.elastic import (ElasticCoordinator,
                                                ElasticPolicy, ResizeDecision,
                                                collect_pieces, elastic_resume,
                                                split_pieces)
        from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                                     flax_apply, init_state)
        identity = 'double-loss'
        devices = jax.devices('cpu')
        spec = MeshSpec(fsdp=4)          # every host holds UNIQUE shards
        mesh4 = spec.build(devices[:4])
        hub = Hub(4)
        transports = [ChaosTransport(hub.address, rank, 4,
                                     faults=Faults(seed=rank))
                      for rank in range(4)]
        assert wait_until(lambda: len(hub._clients) == 4)
        supervisors = [Supervisor(['w'], rank=rank,
                                  transport=transports[rank], buddy=rank ^ 1)
                       for rank in range(4)]
        policy = ElasticPolicy(settle_window=0.25, rebroadcast=0.1)
        coords = [ElasticCoordinator(transports[rank], rank, 4,
                                     policy=policy).start()
                  for rank in (0, 1)]
        clients = [MemStoreClient(supervisor.server.address)
                   for supervisor in supervisors]
        checkpointer = Checkpointer(tmp_path, async_save=False)
        try:
            module = gpt2_tiny(layers=2, dim=32, heads=2, max_seq=32)
            optimizer = AdamW(lr=1e-3)
            place = TensorParallel(module.partition_rules(), fsdp=True,
                                   fsdp_min_size=16)
            tokens = jnp.asarray(
                np.random.default_rng(1).integers(0, 256, (4, 16)), jnp.int32)
            state = place.place(init_state(module, optimizer, tokens[:1]),
                                mesh4)
            step = build_train_step(flax_apply(module), NextTokenLoss(),
                                    optimizer)
            placed = jax.device_put(tokens, batch_sharding(mesh4))
            die_at = 2
            # ranks 2 and 3 ARE a buddy pair: one wave takes both copies
            wave = PreemptionWave(step=die_at, kills=(transports[2].kill,
                                                      transports[3].kill))
            while int(state.step) < die_at:
                state, _ = step(state, placed, placed)
                at = int(state.step)
                checkpointer.save(identity, at, state, extras={'step': at})
                for rank, blob in enumerate(split_pieces(state, mesh4, 4)):
                    clients[rank].push(identity, at, blob,
                                       extras={'step': at})
                wave(at)
            assert wave.fired

            # the survivors agree the shrink — one epoch for the pair loss
            assert wait_until(lambda: all(coord.decisions
                                          for coord in coords))
            for coord in coords:
                assert coord.decisions == [
                    ResizeDecision(epoch=1, members=(0, 1))]

            # hot reshard CANNOT cover ranks 2/3's shards: typed fallback
            mesh2 = spec.resized(2).build(devices[:2])
            blank = place.place(init_state(module, optimizer, tokens[:1]),
                                mesh2)
            with caplog.at_level(logging.WARNING, 'tpusystem.elastic'):
                pieces = collect_pieces(
                    identity, rank=0, members=range(4), survivors=(0, 1),
                    store=supervisors[0].store, transport=transports[0],
                    buddy_of=lambda member: member ^ 1)
                assert len(pieces) == 2          # only the survivors' own
                restored, at, extras, source = elastic_resume(
                    checkpointer, identity, blank, pieces)
            assert 'no surviving buddy' in caplog.text
            assert 'restore from disk' in caplog.text
            assert source == 'disk' and at == die_at

            # loss-equivalence: the fallen-back state IS the disk restore
            # of the last committed step, and continues identically
            disk = checkpointer.restore(identity, blank, epoch=die_at)
            for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(disk)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            placed2 = jax.device_put(tokens, batch_sharding(mesh2))
            resumed, (_, loss_resumed) = step(restored, placed2, placed2)
            reference, (_, loss_reference) = step(disk, placed2, placed2)
            assert np.isfinite(float(loss_resumed))
            assert float(loss_resumed) == float(loss_reference)
        finally:
            for client in clients:
                client.close()
            for coord in coords:
                coord.close()
            for supervisor in supervisors:
                supervisor.close()
            checkpointer.close()
            for transport in transports:
                transport.close()
            hub.close()


class TestBarrierTimeout:
    """Satellite: a peer dead/hung between sync points must surface as a
    typed CollectiveTimeout instead of hanging the barrier forever."""

    def test_barrier_timeout_raises_typed(self):
        from tpusystem.parallel.multihost import CollectiveTimeout
        hub = Hub(2)
        transports = [TcpTransport(hub.address, rank, 2) for rank in range(2)]
        assert wait_until(lambda: len(hub._clients) == 2)
        try:
            start = time.monotonic()
            # rank 1 never contributes: it is alive (heartbeats would keep
            # it in the quota) but stuck between sync points
            with pytest.raises(CollectiveTimeout, match='timed out'):
                transports[0].barrier(timeout=1.0)
            assert time.monotonic() - start < 5
            assert isinstance(CollectiveTimeout('x'), ControlPlaneFailover)
            # the late straggler completes the op on the hub; its result
            # fanout must NOT leak a fresh never-read box into the timed-out
            # rank's _results (regression: setdefault in the recv loop)
            transports[1].barrier(timeout=5.0)
            assert wait_until(lambda: not transports[0]._results)
        finally:
            for transport in transports:
                transport.close()
            hub.close()

    def test_runtime_barrier_forwards_timeout(self):
        from tpusystem.parallel.multihost import CollectiveTimeout
        hub = Hub(2)
        transports = [TcpTransport(hub.address, rank, 2) for rank in range(2)]
        assert wait_until(lambda: len(hub._clients) == 2)
        runtime = Runtime()                      # Loopback: timeout is a no-op
        runtime.barrier(timeout=0.1)
        runtime.transport = transports[0]        # the pod-shaped wiring
        try:
            with pytest.raises(CollectiveTimeout):
                runtime.barrier(timeout=1.0)
        finally:
            for transport in transports:
                transport.close()
            hub.close()


# ---------------------------------------------------------------------------
# cross-process chaos: the real thing, over real processes

CHAOS_WORKER = r'''
import json, os, sys, time
rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
coordinator, out_path = sys.argv[3], sys.argv[4]
ckpt_root, die_at, total_steps = sys.argv[5], int(sys.argv[6]), int(sys.argv[7])

os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp
import numpy as np

from tpusystem.checkpoint import Checkpointer
from tpusystem.data import ArrayDataset, Loader
from tpusystem.models import gpt2_tiny
from tpusystem.parallel import MeshSpec, batch_sharding, replicated
from tpusystem.parallel.chaos import DieAtStep
from tpusystem.parallel.recovery import (WorkerLostError, exit_for_restart,
                                         recovery_consumer)
from tpusystem.registry import gethash
from tpusystem.runtime import Runtime
from tpusystem.train import (NextTokenLoss, SGD, build_train_step, flax_apply,
                             init_state, resume_extras)

victim = nprocs - 1               # never rank 0: the hub must survive
record = {'rank': rank, 'losses': {}}
runtime = Runtime(coordinator=coordinator, num_processes=nprocs,
                  process_id=rank, heartbeat=1.0)
runtime.producer.register(recovery_consumer())
mesh = MeshSpec(data=-1).build()
module = gpt2_tiny(attention='xla', dtype='float32')
identity = gethash(module)
optimizer = SGD(lr=0.1)
tokens = np.random.default_rng(0).integers(0, 256, (8 * nprocs, 32)).astype(np.int32)
loader = Loader(ArrayDataset(tokens), batch_size=2 * nprocs, shuffle=True,
                seed=5)           # 4 batches per epoch
state = init_state(module, optimizer, jnp.asarray(tokens[:1]))
state = jax.tree.map(
    lambda leaf: jax.make_array_from_process_local_data(
        replicated(mesh), np.asarray(leaf)), state)
ckpt = Checkpointer(ckpt_root, async_save=False)
sharding = batch_sharding(mesh)
step_fn = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)

record['start_step'] = ckpt.latest(identity) or 0
record['fenced_at_start'] = ckpt.fenced(identity)
if record['start_step']:
    state, _, extras = ckpt.resume(identity, state)
    loader.seek(extras['cursor'])

die = DieAtStep(step=die_at, action='exit') if rank == victim else None

def place(batch):
    host = np.asarray(jax.device_get(batch))
    per = host.shape[0] // nprocs
    return jax.make_array_from_process_local_data(
        sharding, host[rank * per:(rank + 1) * per])

try:
    done = False
    while not done:
        for (batch,) in loader:
            placed = place(batch)
            state, (_, loss) = step_fn(state, placed, placed)
            at = int(state.step)
            record['losses'][str(at)] = float(loss)
            ckpt.save(identity, at, state, extras=resume_extras(state, loader))
            if at >= total_steps:
                done = True
                break
            if die_at and at == die_at:
                # rendezvous: step k is committed on EVERY rank before the
                # death, so no collective save races a dead peer
                runtime.barrier()
                if die is not None:
                    die(at)                  # os._exit(1): no bye, no atexit
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    runtime.sync()           # WorkerLostError raises here
                    time.sleep(0.05)
                raise SystemExit('worker loss never surfaced at the drain')
except WorkerLostError as loss_error:
    ckpt.wait()
    record['fenced'] = ckpt.fence(identity)  # keep the last good checkpoint
    record['lost_rank'] = loss_error.rank
    with open(out_path, 'w') as handle:
        json.dump(record, handle)
        handle.flush()
        os.fsync(handle.fileno())
    if rank == 0:
        time.sleep(1)        # hub: let the lost fanout reach every survivor
    os._exit(exit_for_restart(loss_error).code)

record['fenced'] = ckpt.fence(identity)
ckpt.close()
runtime.barrier()
record['end_step'] = int(state.step)
with open(out_path, 'w') as handle:
    json.dump(record, handle)
runtime.close()
'''


@pytest.mark.slow
def test_multiprocess_kill_at_step_restart_resumes_bitwise(tmp_path):
    """The full acceptance drill over REAL processes: a 2-host DP job is
    killed at step 3 (rank 1 dies abruptly, mid-epoch), the survivor
    fences and exits with the restartable code, the relaunched job resumes
    at the checkpoint step and its losses from step 4 on are
    bitwise-identical to an uninterrupted reference run."""
    from tests.test_multiprocess import _launch_workers
    nprocs, die_at, total = 2, 3, 6

    def launch(run, root, die):
        run_dir = tmp_path / run
        run_dir.mkdir()
        procs, outputs = _launch_workers(run_dir, CHAOS_WORKER, nprocs,
                                         timeout=420,
                                         extra_args=(root, die, total))
        return procs, outputs, run_dir

    # uninterrupted reference trajectory
    procs, outputs, run_dir = launch('ref', tmp_path / 'ref-ckpt', 0)
    if any('Multiprocess computations aren\'t implemented' in output
           for output in outputs):
        # same jaxlib gap that fails tests/test_multiprocess.py's training
        # workers on this host: the CPU backend cannot execute
        # cross-process computations at all (probe precedent:
        # parallel/mesh.py partial_manual_skip_reason)
        pytest.skip('this jaxlib cannot run multiprocess computations '
                    'on the CPU backend')
    for proc, output in zip(procs, outputs):
        assert proc.returncode == 0, f'reference worker failed:\n{output[-3000:]}'
    reference = json.loads((run_dir / 'out0.json').read_text())
    assert sorted(map(int, reference['losses'])) == list(range(1, total + 1))

    # phase 1: the kill — victim dies at step 3, survivor fences and exits
    # with the restart contract's code
    root = tmp_path / 'ckpt'
    procs, outputs, run_dir = launch('run1', root, die_at)
    assert procs[1].returncode == 1              # the scripted death
    assert procs[0].returncode == LOST_WORKER_EXIT, outputs[0][-3000:]
    survivor = json.loads((run_dir / 'out0.json').read_text())
    assert survivor['lost_rank'] == 1
    assert survivor['fenced'] == die_at
    assert sorted(map(int, survivor['losses'])) == list(range(1, die_at + 1))

    # phase 2: the scheduler restarts the job — step-granular resume
    procs, outputs, run_dir = launch('run2', root, 0)
    for proc, output in zip(procs, outputs):
        assert proc.returncode == 0, f'resumed worker failed:\n{output[-3000:]}'
    resumed = json.loads((run_dir / 'out0.json').read_text())
    assert resumed['start_step'] == die_at
    assert resumed['fenced_at_start'] == die_at
    assert resumed['end_step'] == total
    assert sorted(map(int, resumed['losses'])) == list(range(die_at + 1,
                                                             total + 1))
    # bitwise-identical continuation: pre-kill steps match, post-resume
    # steps match the uninterrupted run exactly
    for at in range(1, die_at + 1):
        assert survivor['losses'][str(at)] == reference['losses'][str(at)]
    for at in range(die_at + 1, total + 1):
        assert resumed['losses'][str(at)] == reference['losses'][str(at)]
