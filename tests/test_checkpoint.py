"""Checkpoint/resume: async sharded saves, identity-keyed restore.

Mirrors the reference's repository semantics (store/restore by id,
resume-by-identity — ``examples/tinysys/tinysys/repository.py``,
``.../services/compilation.py:41-64``) plus what the reference lacks:
sharded restore onto a live device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpusystem.checkpoint import Checkpointer, Repository
from tpusystem.models import MLP
from tpusystem.registry import gethash
from tpusystem.train import Adam, init_state


@pytest.fixture()
def state():
    module = MLP(features=(16,), classes=10)
    return init_state(module, Adam(lr=1e-3), jnp.zeros((4, 28, 28)), rng=0)


def test_save_restore_roundtrip(tmp_path, state):
    with Checkpointer(tmp_path, async_save=False) as ckpt:
        ckpt.save('model-a', 0, state)
        blank = jax.tree.map(jnp.zeros_like, state)
        restored = ckpt.restore('model-a', blank)
    for original, loaded in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(original), np.asarray(loaded))


def test_latest_and_epochs_track_versions(tmp_path, state):
    with Checkpointer(tmp_path, async_save=False, max_to_keep=None) as ckpt:
        assert ckpt.latest('m') is None
        for epoch in (0, 1, 2):
            ckpt.save('m', epoch, state)
        assert ckpt.latest('m') == 2
        assert ckpt.epochs('m') == [0, 1, 2]
        # identities are isolated
        assert ckpt.latest('other') is None


def test_restore_missing_identity_raises(tmp_path, state):
    with Checkpointer(tmp_path, async_save=False) as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore('nope', state)


def test_async_save_commits_after_wait(tmp_path, state):
    with Checkpointer(tmp_path, async_save=True) as ckpt:
        ckpt.save('m', 0, state)
        ckpt.wait()
        assert ckpt.latest('m') == 0


def test_restore_onto_sharded_target(tmp_path, state):
    """Weights saved unsharded restore directly onto a mesh layout —
    checkpoint portability across topologies (SURVEY.md §5 checkpoint)."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ('data',))
    with Checkpointer(tmp_path, async_save=False) as ckpt:
        ckpt.save('m', 0, state)
        replicated = NamedSharding(mesh, P())
        target = jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=replicated),
            state)
        restored = ckpt.restore('m', target)
    leaf = jax.tree.leaves(restored)[0]
    assert leaf.sharding.is_equivalent_to(replicated, leaf.ndim)
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(restored)[0]), np.asarray(jax.tree.leaves(state)[0]))


class FakeAggregate:
    def __init__(self, state, identity='agg'):
        self.state = state
        self._id = identity

    @property
    def id(self):
        return self._id


def test_repository_store_restore_by_identity(tmp_path, state):
    aggregate = FakeAggregate(state, identity=gethash(MLP(features=(16,), classes=10)))
    repository = Repository(tmp_path, async_save=False)
    try:
        repository.store(aggregate, epoch=0)
        trained = jax.tree.map(lambda leaf: leaf + 1, state)
        aggregate.state = trained
        repository.store(aggregate, epoch=1)
        assert repository.latest(aggregate) == 1

        # fresh process: same hyperparameters -> same id -> same checkpoint
        clone = FakeAggregate(jax.tree.map(jnp.zeros_like, state),
                              identity=gethash(MLP(features=(16,), classes=10)))
        repository.restore(clone)
        for expected, loaded in zip(jax.tree.leaves(trained), jax.tree.leaves(clone.state)):
            np.testing.assert_array_equal(np.asarray(expected), np.asarray(loaded))

        repository.restore(clone, epoch=0)
        for expected, loaded in zip(jax.tree.leaves(state), jax.tree.leaves(clone.state)):
            np.testing.assert_array_equal(np.asarray(expected), np.asarray(loaded))
    finally:
        repository.close()


def test_repository_auto_epoch_increments(tmp_path, state):
    aggregate = FakeAggregate(state)
    repository = Repository(tmp_path, async_save=False)
    try:
        repository.store(aggregate)   # no epoch attr -> version 0
        repository.store(aggregate)   # -> version 1
        assert repository.latest(aggregate) == 1
        aggregate.epoch = 7
        repository.store(aggregate)   # uses aggregate.epoch
        assert repository.latest(aggregate) == 7
    finally:
        repository.close()


def test_gc_keeps_window_plus_periodic(tmp_path, state):
    """max_to_keep bounds the rolling window while keep_every pins every
    Nth epoch forever — the GC policy for long runs (ROADMAP robustness)."""
    with Checkpointer(tmp_path, async_save=False, max_to_keep=2,
                      keep_every=4) as ckpt:
        for epoch in range(10):
            ckpt.save('m', epoch, state)
        kept = ckpt.epochs('m')
    assert set(kept) >= {0, 4, 8}            # periodic pins survive
    assert set(kept) >= {8, 9}               # the rolling window survives
    assert 5 not in kept and 6 not in kept   # evicted between pins
