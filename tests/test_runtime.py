"""Runtime facade: single-process degenerate case and housekeeping."""

from __future__ import annotations

import pytest

from tpusystem import Runtime
from tpusystem.observe.events import Trained
from tpusystem.parallel.multihost import Loopback
from tpusystem.services.prodcon import Consumer


class Model:
    id = 'model-id'
    epoch = 0


class TestControlAddress:
    def test_env_var_wins(self, monkeypatch):
        from tpusystem.runtime import _control_address
        monkeypatch.setenv('TPUSYSTEM_CONTROL', '10.0.0.5:9000')
        assert _control_address('other:1234', None) == ('10.0.0.5', 9000)

    def test_coordinator_port_plus_one(self, monkeypatch):
        from tpusystem.runtime import _control_address
        monkeypatch.delenv('TPUSYSTEM_CONTROL', raising=False)
        assert _control_address('head:8476', None) == ('head', 8477)
        assert _control_address('head:8476', 7000) == ('head', 7000)
        assert _control_address('head', 7000) == ('head', 7000)

    def test_no_address_is_an_error_not_localhost(self, monkeypatch):
        from tpusystem.runtime import _control_address
        monkeypatch.delenv('TPUSYSTEM_CONTROL', raising=False)
        with pytest.raises(ValueError, match='control-plane address'):
            _control_address(None, None)
        with pytest.raises(ValueError, match='control-plane address'):
            _control_address('head-no-port', None)


def test_single_process_runtime_is_loopback():
    with Runtime() as runtime:
        assert runtime.world.process_count == 1
        assert runtime.is_primary
        assert isinstance(runtime.transport, Loopback)
        assert runtime.hub is None


def test_primary_only_consumers_run_on_rank0():
    with Runtime() as runtime:
        seen = []
        consumer = Consumer()
        consumer.register(Trained, seen.append)
        runtime.producer.register(consumer, primary_only=True)
        runtime.producer.dispatch(Trained(model=Model(), metrics={'loss': 0.1}))
        assert len(seen) == 1


def test_sync_and_stop_housekeeping():
    with Runtime(ledger=True) as runtime:
        runtime.producer.dispatch(Trained(model=Model(), metrics={}))
        runtime.sync()                       # drains + verifies ledger
        assert runtime.ledger.count == 1
        assert runtime.should_stop(False) is False
        assert runtime.should_stop(True) is True
        runtime.barrier()


def test_epoch_loop_pattern_with_early_stop():
    """The docstring's pod-ready loop, end to end on Loopback."""
    with Runtime() as runtime:
        stopped_at = None
        for epoch in range(10):
            wants_stop = epoch >= 3          # stand-in for a stop event
            runtime.sync()
            if runtime.should_stop(wants_stop):
                stopped_at = epoch
                break
        assert stopped_at == 3


def test_worker_loss_unwinds_through_sync():
    """Runtime + recovery wiring: a WorkerLost queued from the transport
    surfaces as WorkerLostError at the epoch-boundary sync(), on the host
    loop thread — the restart-resume entry point."""
    from tpusystem.parallel.multihost import WorkerLost
    from tpusystem.parallel.recovery import WorkerLostError, recovery_consumer

    runtime = Runtime()
    try:
        runtime.producer.register(recovery_consumer())
        runtime.producer._inbox.put(WorkerLost(rank=2, last_seen=12.5))
        with pytest.raises(WorkerLostError) as excinfo:
            runtime.sync()
        assert excinfo.value.rank == 2
    finally:
        runtime.close()
