"""Service contracts (reference parity: tests/test_service.py:12-21)."""

from unittest.mock import Mock

import pytest

from tpusystem.services import Service
from tpusystem.depends import Depends


def test_handler_registered_under_kebab_name_with_override():
    service = Service()

    def device():
        raise NotImplementedError

    @service.handler
    def train_model(model, device=Depends(device)):
        model.trained_on(device)
        return device

    service.dependency_overrides[device] = lambda: 'tpu:0'
    model = Mock()
    assert service.handle('train-model', model) == 'tpu:0'
    model.trained_on.assert_called_once_with('tpu:0')


def test_handler_remains_directly_callable():
    service = Service()

    @service.handler
    def validate(model):
        return ('validated', model)

    assert validate('m') == ('validated', 'm')
    assert service.handle('validate', 'm') == ('validated', 'm')


def test_unknown_action_raises_keyerror():
    service = Service()
    with pytest.raises(KeyError):
        service.handle('missing-action')


def test_custom_name_generator():
    service = Service(generator=str.upper)

    @service.handler
    def iterate():
        return 'ok'

    assert service.handle('ITERATE') == 'ok'
