"""Headline benchmark: GPT-2 125M training MFU on one chip.

Prints the ``tp_ffn_overlap_speedup_vs_gspmd`` row first (the
latency-hiding TP collectives A/B, ``benchmarks/tp_overlap.py headline``
in a subprocess — virtual-mesh smoke on CPU, real numbers on multi-chip
TPU; see BASELINE.md "tp_overlap protocol"), then the headline as the
LAST JSON line (the one the driver parses):
``{"metric": ..., "value": N, "spread": N, "unit": ..., "vs_baseline": N}``.

``value`` is the **median of TRIALS (>= 3) timed runs** after a shared
warmup/compile, and ``spread`` is the max-min range across those runs —
so a BENCH_r* delta can be told from the sweep's own run-to-run noise
(round 5 measured +-0.006 MFU between identical runs; a single sample
cannot distinguish a real 1% regression from that).

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
measured MFU against the north-star target of 0.50 MFU (BASELINE.json).
Model FLOPs use the standard 6*N*T approximation (fwd+bwd) plus exact
attention term 12*L*H*S^2*D_head*B.
"""

from __future__ import annotations

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

TRIALS = 3   # timed runs per report (median printed, max-min as spread)

# bf16 peak FLOP/s per chip by device kind substring
PEAKS = {
    'v5 lite': 197e12,  # v5e
    'v5e': 197e12,
    'v5p': 459e12,
    'v4': 275e12,
    'v6': 918e12,
}


def materialize(tree) -> None:
    """Force completion with a host read. On the tunneled platform
    ``jax.block_until_ready`` returns before the computation finishes
    (it reported 'impossible' microsecond steps); transferring a scalar
    to the host is the only reliable fence — every benchmark in this
    repo times with this."""
    leaf = jax.tree.leaves(tree)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))


def peak_flops(device) -> float | None:
    kind = device.device_kind.lower()
    for key, value in PEAKS.items():
        if key in kind:
            return value
    return None


def tp_overlap_row() -> None:
    """Print the latency-hiding TP collectives row (BASELINE.md
    "tp_overlap protocol"): ``benchmarks/tp_overlap.py headline`` in a
    subprocess (it picks the real mesh on multi-chip hardware and
    re-execs onto the virtual CPU mesh otherwise — smoke numbers there,
    real numbers on TPU). Printed BEFORE the MFU headline so the
    driver's parsed last-line metric stays ``gpt2_125m_train_mfu_1chip``.
    Never fails the headline run: probe errors print a null-value row."""
    import pathlib
    import subprocess
    import sys
    script = pathlib.Path(__file__).parent / 'benchmarks' / 'tp_overlap.py'
    try:
        probe = subprocess.run([sys.executable, str(script), 'headline'],
                               capture_output=True, text=True, timeout=1800)
        lines = [line for line in probe.stdout.strip().splitlines()
                 if line.startswith('{')]
        if probe.returncode == 0 and lines:
            print(lines[-1])
            return
        note = (probe.stderr.strip().splitlines() or ['no output'])[-1][:160]
    except (OSError, subprocess.TimeoutExpired) as error:
        note = str(error)[:160]
    print(json.dumps({'metric': 'tp_ffn_overlap_speedup_vs_gspmd',
                      'value': None, 'unit': 'x',
                      'note': f'probe failed: {note}'}))


def main() -> None:
    from tpusystem.models import GPT2
    from tpusystem.train import (ChunkedNextTokenLoss, AdamW, build_train_step,
                                 flax_apply, init_state)

    batch, seq = 16, 1024
    # Perf recipe (each measured on a v5e chip):
    # - vocab padded 50257 -> 50304 (x128): the unpadded table mis-tiles the
    #   MXU on the head matmul (~10% whole-step MFU);
    # - Pallas flash attention for the single-chip run (1024/1024 tiles);
    # - fused chunked LM loss (return_features): the [B*S, vocab] f32 logits
    #   tensor is never materialized (~5% MFU, and unlocks batch >= 32);
    # - 90 steps per jit call (lax.fori_loop): per-dispatch overhead through
    #   the tunneled-TPU relay is ~7 ms (~5% of a 135 ms step) and the final
    #   host sync costs another dispatch — amortized across the loop
    #   (measured r2: 10 steps 0.498, 30 0.515, 60 0.519; r3: 90 edges 60
    #   by ~0.3% and 120 is flat). Round 3 also keeps the flash kernels
    #   seedless at dropout=0 (the in-kernel dropout path wires its seed
    #   input only when active — a persistent SMEM arg cost ~0.5%).
    module = GPT2(dropout=0.0, attention='flash', vocab_size=50304,
                  return_features=True)
    optimizer = AdamW(lr=3e-4, grad_clip=1.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 50257, (batch, seq)),
        jnp.int32)
    state = init_state(module, optimizer, tokens[:1, :8])
    params_count = sum(leaf.size for leaf in jax.tree.leaves(state.params))
    step = build_train_step(flax_apply(module), ChunkedNextTokenLoss(chunks=8),
                            optimizer, jit=False)

    steps = 90

    @partial(jax.jit, donate_argnums=0)   # in-place param/slot updates in HBM
    def run(state, tokens):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, tokens, tokens)[0], state)

    # warmup / compile. NOTE: force completion by materializing a value —
    # jax.block_until_ready returns early through the tunneled-TPU relay.
    state = run(state, tokens)
    float(jax.tree.leaves(state.params)[0].sum())

    # median-of-TRIALS with the max-min range: BENCH_r* deltas smaller
    # than the printed spread are the sweep's own noise, not a change
    elapsed_trials = []
    for _ in range(TRIALS):
        start = time.perf_counter()
        state = run(state, tokens)
        float(jax.tree.leaves(state.params)[0].sum())
        elapsed_trials.append(time.perf_counter() - start)
    elapsed = sorted(elapsed_trials)[len(elapsed_trials) // 2]

    tokens_per_step = batch * seq
    head_dim = module.dim // module.heads
    # 12*L*H*S^2*Dh*B covers fwd (4*S^2*Dh per head: QK^T + AV at 2 FLOPs/MAC)
    # plus bwd at 2x fwd
    attention_flops = 12 * module.layers * module.heads * seq * seq * head_dim * batch
    step_flops = 6 * params_count * tokens_per_step + attention_flops
    achieved = step_flops * steps / elapsed

    device = jax.devices()[0]
    peak = peak_flops(device)
    if peak:
        to_mfu = lambda secs: step_flops * steps / secs / peak
        mfu = achieved / peak
        print(json.dumps({
            'metric': 'gpt2_125m_train_mfu_1chip',
            'value': round(mfu, 4),
            'spread': round(to_mfu(min(elapsed_trials))
                            - to_mfu(max(elapsed_trials)), 4),
            'unit': 'MFU',
            'vs_baseline': round(mfu / 0.5, 4),
        }))
    else:  # CPU fallback: report throughput
        to_sps = lambda secs: steps / secs
        print(json.dumps({
            'metric': 'gpt2_125m_train_steps_per_sec_cpu',
            'value': round(steps / elapsed, 4),
            'spread': round(to_sps(min(elapsed_trials))
                            - to_sps(max(elapsed_trials)), 4),
            'unit': 'steps/s',
            'vs_baseline': 1.0,
        }))


if __name__ == '__main__':
    tp_overlap_row()
    main()
