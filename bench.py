"""Headline benchmark: GPT-2 125M training MFU on one chip.

Prints the ``tp_ffn_overlap_speedup_vs_gspmd`` row first (the
latency-hiding TP collectives A/B, ``benchmarks/tp_overlap.py headline``
in a subprocess — virtual-mesh smoke on CPU, real numbers on multi-chip
TPU; see BASELINE.md "tp_overlap protocol"), then the
``fsdp_overlap_speedup_vs_gspmd`` row (the unified overlap scheduler's
FSDP param-prefetch/grad-scatter hiding A/B,
``benchmarks/fsdp_overlap.py headline``, same protocol), then the
``pp_overlap_speedup_vs_gspmd`` and ``moe_a2a_overlap_speedup`` rows
(the scheduler's two new arms: skewed GPipe sends and pipelined expert
all-to-all, ``benchmarks/pp_overlap.py`` / ``moe_a2a_overlap.py``,
BASELINE.md "pp/moe overlap protocol"), then the
``sentinel_overhead`` row (steps/s with the in-graph divergence guard on
vs off — the < 2% budget tracked in BENCH_*.json from day one), then the
``recovery_seconds`` row (hot in-memory restore vs disk restore wall
time on the tiny model — the per-recovery saving the Supervisor's
memstore tier buys), then the ``resize_seconds`` row (elastic
hot-reshard of a 4-host world onto a 2-host mesh vs the disk restore a
cold restart would pay, ``benchmarks/elastic_resize.py headline``),
then the ``decode_tok_s``/``decode_stream_bytes``
rows (serving-path greedy decode throughput at the BASELINE decode
config plus the per-step streamed weight bytes auto-vs-int8 — the
roofline lever, ``benchmarks/decode_roofline.py``), then the
``serve_tok_s`` row (continuous batching vs static padded batching
through the serving engine, ``benchmarks/serve_bench.py headline``),
then the ``serve_shared_prefix_speedup`` row (radix prefix sharing on
a shared-system-prompt workload vs no sharing,
``benchmarks/serve_bench.py shared``),
then the ``serve_sampled_tok_s`` row (seeded top-k/top-p sampling vs
greedy on the same compiled step, determinism asserted bitwise every
trial, ``benchmarks/serve_bench.py sampled``),
then the ``serve_recovery_seconds`` row (kill -> first replayed token
through the serving failover layer, hot journal replay vs cold
re-submit, ``benchmarks/serve_recovery.py headline``),
then the ``fleet_recovery_seconds`` row (kill one of three routed
replicas -> first rerouted token on a survivor, journal handoff vs
routing-table cold re-submit, ``benchmarks/serve_fleet.py headline``),
then the ``embedding_lookup_speedup`` row (the recommender workload's
fused Pallas lookup vs the ``jnp.take`` fallback,
``benchmarks/embedding_bench.py headline``),
then the headline as the LAST JSON line (the one the driver parses):
``{"metric": ..., "value": N, "spread": N, "unit": ..., "vs_baseline": N}``.

``value`` is the **median of TRIALS (>= 3) timed runs** after a shared
warmup/compile, and ``spread`` is the max-min range across those runs —
so a BENCH_r* delta can be told from the sweep's own run-to-run noise
(round 5 measured +-0.006 MFU between identical runs; a single sample
cannot distinguish a real 1% regression from that).

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
measured MFU against the north-star target of 0.50 MFU (BASELINE.json).
Model FLOPs use the standard 6*N*T approximation (fwd+bwd) plus exact
attention term 12*L*H*S^2*D_head*B.
"""

from __future__ import annotations

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

TRIALS = 3   # timed runs per report (median printed, max-min as spread)

_MANIFEST: dict | None = None


def run_manifest() -> dict:
    """The environment stamp every JSON row carries, so BENCH_r*.json
    trajectories are comparable across containers: a value moved because
    the code moved, or because jax/jaxlib/the backend did — the manifest
    says which."""
    global _MANIFEST
    if _MANIFEST is None:
        try:
            import jaxlib
            jaxlib_version = getattr(jaxlib, '__version__', None)
        except ImportError:
            jaxlib_version = None
        _MANIFEST = {
            'jax': jax.__version__,
            'jaxlib': jaxlib_version,
            'backend': jax.default_backend(),
            'device_count': jax.device_count(),
            'host_count': jax.process_count(),
        }
    return _MANIFEST


def emit(row: dict) -> None:
    """Print one benchmark row as a JSON line, stamped with the run
    manifest (every row, including the subprocess probe rows re-stamped
    in _overlap_probe_row)."""
    print(json.dumps({**row, 'manifest': run_manifest()}))


# bf16 peak FLOP/s per chip by device kind substring
PEAKS = {
    'v5 lite': 197e12,  # v5e
    'v5e': 197e12,
    'v5p': 459e12,
    'v4': 275e12,
    'v6': 918e12,
}


def materialize(tree) -> None:
    """Force completion with a host read. On the tunneled platform
    ``jax.block_until_ready`` returns before the computation finishes
    (it reported 'impossible' microsecond steps); transferring a scalar
    to the host is the only reliable fence — every benchmark in this
    repo times with this."""
    leaf = jax.tree.leaves(tree)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))


def peak_flops(device) -> float | None:
    kind = device.device_kind.lower()
    for key, value in PEAKS.items():
        if key in kind:
            return value
    return None


def _overlap_probe_row(script_name: str, metric: str,
                       arg: str = 'headline') -> None:
    """Print one latency-hiding A/B row: ``benchmarks/<script> headline``
    in a subprocess (each script picks the real mesh on multi-chip
    hardware and re-execs onto the virtual CPU mesh otherwise — smoke
    numbers there, real numbers on TPU). Printed BEFORE the MFU headline
    so the driver's parsed last-line metric stays
    ``gpt2_125m_train_mfu_1chip``. Never fails the headline run: probe
    errors print a null-value row."""
    import pathlib
    import subprocess
    import sys
    script = pathlib.Path(__file__).parent / 'benchmarks' / script_name
    try:
        probe = subprocess.run([sys.executable, str(script), arg],
                               capture_output=True, text=True, timeout=1800)
        lines = [line for line in probe.stdout.strip().splitlines()
                 if line.startswith('{')]
        if probe.returncode == 0 and lines:
            try:                     # re-stamp with THIS run's manifest
                emit(json.loads(lines[-1]))
            except ValueError:
                print(lines[-1])
            return
        note = (probe.stderr.strip().splitlines() or ['no output'])[-1][:160]
    except (OSError, subprocess.TimeoutExpired) as error:
        note = str(error)[:160]
    emit({'metric': metric, 'value': None, 'unit': 'x',
                      'note': f'probe failed: {note}'})


def tp_overlap_row() -> None:
    """The latency-hiding TP collectives row (BASELINE.md "tp_overlap
    protocol")."""
    _overlap_probe_row('tp_overlap.py', 'tp_ffn_overlap_speedup_vs_gspmd')


def fsdp_overlap_row() -> None:
    """The FSDP param-prefetch/grad-scatter hiding row (the unified
    overlap scheduler's second client, `parallel/schedule.py`; BASELINE.md
    "fsdp_overlap protocol")."""
    _overlap_probe_row('fsdp_overlap.py', 'fsdp_overlap_speedup_vs_gspmd')


def pp_overlap_row() -> None:
    """The pipeline p2p hiding row: skewed-overlap GPipe ticks (sends
    issued under the next microbatch's stage compute, the schedule's
    ``pp='overlap'`` arm) vs the classic post-compute sends
    (`benchmarks/pp_overlap.py headline`; BASELINE.md "pp/moe overlap
    protocol" — virtual-CPU numbers are smoke)."""
    _overlap_probe_row('pp_overlap.py', 'pp_overlap_speedup_vs_gspmd')


def moe_a2a_overlap_row() -> None:
    """The MoE expert all-to-all hiding row: pipelined dispatch (piece
    k+1's exchange under the expert matmuls of piece k, the schedule's
    ``moe='overlap'`` arm) vs the one-shot whole-batch exchange
    (`benchmarks/moe_a2a_overlap.py headline`; same protocol)."""
    _overlap_probe_row('moe_a2a_overlap.py', 'moe_a2a_overlap_speedup')


def resize_seconds_row() -> None:
    """The elastic-resize cost row: wall seconds to hot-reshard a 4-host
    world's state onto a 2-host mesh from in-memory pieces vs restoring
    the same step from disk onto the same mesh
    (`benchmarks/elastic_resize.py`; the reshard the elastic loop
    `tpusystem/parallel/elastic.py` performs instead of a cold
    full-world restart)."""
    _overlap_probe_row('elastic_resize.py', 'resize_seconds')


def embedding_row() -> None:
    """The recommender-workload lookup row: fused Pallas row-gather /
    grad scatter-add vs the ``jnp.take`` fallback at the headline
    table shape (`benchmarks/embedding_bench.py headline`; CPU numbers
    are interpreter-mode smoke — parity, not performance)."""
    _overlap_probe_row('embedding_bench.py', 'embedding_lookup_speedup')


def serve_row() -> None:
    """The serving-engine throughput row: continuous batching (paged KV
    + iteration-level scheduling, `tpusystem/serve/`) vs static padded
    batching on a mixed-length workload (`benchmarks/serve_bench.py`;
    BASELINE.md "serve protocol" — CPU numbers are smoke, the >= 2x
    speedup ratio is the architectural claim)."""
    _overlap_probe_row('serve_bench.py', 'serve_tok_s')


def serve_shared_prefix_row() -> None:
    """The radix prefix-sharing row: delivered tok/s on a shared-system-
    prompt workload with ``share_prefix=True`` vs without
    (`benchmarks/serve_bench.py shared`; BASELINE.md "shared-prefix
    serve protocol" — CPU numbers are smoke, the >= 1.5x speedup ratio
    is the architectural claim and every completion is asserted
    token-exact against standalone ``generate()``)."""
    _overlap_probe_row('serve_bench.py', 'serve_shared_prefix_speedup',
                       arg='shared')


def serve_sampled_row() -> None:
    """The seeded-sampling row: delivered tok/s with per-request seeded
    top-k/top-p ``SamplingParams`` vs greedy on the same mixed workload
    and the SAME compiled step (`benchmarks/serve_bench.py sampled`;
    the counter-based sampling of `tpusystem/serve/engine.py` — every
    timed trial is re-run with the same seeds and asserted bitwise-
    identical, the determinism every replay/reroute/hedge guarantee
    rides on)."""
    _overlap_probe_row('serve_bench.py', 'serve_sampled_tok_s',
                       arg='sampled')


def serve_recovery_row() -> None:
    """The serving-failover recovery row: wall seconds from a mid-decode
    kill to the first replayed token, hot journal replay vs cold
    re-submit (`benchmarks/serve_recovery.py headline`; the journal +
    token-prefix replay of `tpusystem/serve/failover.py` — both arms
    finish token-exact, the hot arm skips re-decoding already-delivered
    tokens)."""
    _overlap_probe_row('serve_recovery.py', 'serve_recovery_seconds')


def fleet_recovery_row() -> None:
    """The fleet-failover recovery row: wall seconds from killing one of
    three serving replicas mid-stream to the first token a rerouted
    request emits on a SURVIVOR, journal handoff (hot prefixes onto a
    different engine) vs routing-table cold re-submit
    (`benchmarks/serve_fleet.py headline`; the Router redistribution of
    `tpusystem/serve/fleet.py` — both arms drain token-exact vs an
    uninterrupted fleet)."""
    _overlap_probe_row('serve_fleet.py', 'fleet_recovery_seconds')


def router_failover_row() -> None:
    """The router-failover MTTR row: wall seconds from killing the
    ACTIVE Router mid-stream to the first completed token under the
    warm standby, hot journal replay vs cold health sweep
    (`benchmarks/serve_failover.py headline`; the crash-recoverable
    Router of `tpusystem/serve/fleet.py` — the lease fence and the
    recovery replay are both inside the timed window, and both arms
    drain token-exact vs an uninterrupted fleet)."""
    _overlap_probe_row('serve_failover.py', 'router_failover_seconds')


def arbitration_row() -> None:
    """The gang-orchestrator arbitration row: wall seconds from a
    serving burst's ``request_capacity`` to the shrunk trainer stepping
    again on its granted-down submesh — the two-phase journaled
    decision plus the exit-46 hot reshard
    (`benchmarks/arbitration.py headline`; the capacity arbitration of
    `tpusystem/orchestrator/gang.py` — decision-only and release/ebb
    arms ride alongside)."""
    _overlap_probe_row('arbitration.py', 'arbitration_seconds')


def serve_disagg_ttft_row() -> None:
    """The disaggregated-serving head-of-line row: p99 submit→first-token
    over the SHORT requests of a mixed long:short workload, prefill-role
    replica streaming KV strips over the blob plane to decode-role
    replicas vs the same replica count colocated
    (`benchmarks/serve_disagg.py headline`; the prefill/decode split of
    `tpusystem/serve/disagg.py` — both arms drain token-exact, the
    colocated tail eats the long prompts' prefill latency)."""
    _overlap_probe_row('serve_disagg.py', 'serve_disagg_ttft_p99')


def serve_ttft_row() -> None:
    """Print the serving TTFT percentile row: p50/p95/p99 submit→first-
    token over a staggered mixed-length workload on the tiny engine,
    measured through the mergeable log-bucketed histogram
    (``tpusystem.observe.metrics.Histogram`` — the same aggregation the
    fleet dashboard charts). Percentiles, not means: tail latency is the
    serving claim, and a mean TTFT hides exactly the overload the
    watermark/brownout machinery exists for. Printed BEFORE the MFU
    headline; never fails the run."""
    try:
        from tpusystem.models import gpt2_tiny
        from tpusystem.observe.metrics import Histogram
        from tpusystem.serve import Engine, Request, Scheduler

        module = gpt2_tiny(dtype='float32')
        rng = np.random.default_rng(3)
        lengths = (5, 9, 7, 4, 11, 6, 8, 5, 10, 7, 6, 9)
        budgets = (8, 6, 10, 5, 7, 9, 6, 10, 7, 8, 5, 6)
        prompts = [rng.integers(0, 256, (n,)).tolist() for n in lengths]
        params = module.init(jax.random.PRNGKey(0),
                             jnp.asarray([prompts[0]], jnp.int32))['params']
        engine = Engine(module, params, rows=4, block_size=8)
        pending = list(zip(prompts, budgets))

        def run_workload() -> Histogram:
            scheduler = Scheduler(engine)
            ttft = Histogram()
            index = 0
            for step in range(10_000):
                # staggered arrivals: a new burst every other tick, so
                # later requests genuinely queue behind seated rows
                if step % 2 == 0 and index < len(pending):
                    for prompt, budget in pending[index:index + 2]:
                        scheduler.submit(Request(f'r{index}', prompt,
                                                 budget))
                        index += 1
                tick = scheduler.step()
                for _request, _admission, seconds in tick.admitted:
                    ttft.add(seconds)
                if index >= len(pending) and scheduler.idle:
                    break
            return ttft

        run_workload()    # warm every prefill bucket + the decode step:
        # without this, p99 charts one-time XLA compiles, not queueing
        ttft = run_workload()
        summary = ttft.summary()
        emit({
            'metric': 'serve_ttft_p50_p99',
            'value': round(summary['p50'], 4),
            'unit': 's (tiny engine, staggered mixed workload, p50)',
            'p95': round(summary['p95'], 4),
            'p99': round(summary['p99'], 4),
            'count': summary['count'],
        })
    except Exception as error:  # never cost the headline its run
        emit({'metric': 'serve_ttft_p50_p99', 'value': None, 'unit': 's',
              'note': f'probe failed: {str(error)[:160]}'})


def trace_overhead_row() -> None:
    """Print the tracer's serving-path cost: scheduler steps/s with a
    live ``observe.Tracer`` attached vs the default ``tracer=None``, the
    ``sentinel_overhead`` protocol (median of TRIALS per arm). The
    acceptance budget is < 0.02 for the DISABLED tracer — which shares
    the off arm's code path exactly (one ``is not None`` test per hook),
    so the printed value bounds it from above: even tracing ENABLED must
    stay cheap, because spans record only at lifecycle edges, never per
    token. Printed BEFORE the MFU headline; never fails the run."""
    try:
        from tpusystem.models import gpt2_tiny
        from tpusystem.observe import Tracer
        from tpusystem.serve import Engine, Request, Scheduler

        module = gpt2_tiny(dtype='float32')
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, 256, (n,)).tolist() for n in (6, 8, 5, 7)]
        params = module.init(jax.random.PRNGKey(0),
                             jnp.asarray([prompts[0]], jnp.int32))['params']
        engine = Engine(module, params, rows=4, block_size=8)

        def run_once(tracer) -> float:
            scheduler = Scheduler(engine, tracer=tracer)
            for index, prompt in enumerate(prompts):
                scheduler.submit(Request(f'r{index}', prompt, 48))
            start = time.perf_counter()
            scheduler.run()
            return scheduler.steps / (time.perf_counter() - start)

        run_once(None)               # warm the decode/prefill compiles
        # interleave the arms (off, on, off, on, ...) so machine-load
        # drift lands on both equally; report the median paired rates
        pairs = [(run_once(None), run_once(Tracer('bench')))
                 for _ in range(max(TRIALS, 5))]
        ratios = sorted(on / off for off, on in pairs)
        middle = ratios[len(ratios) // 2]
        off = sorted(off for off, _ in pairs)[len(pairs) // 2]
        on = off * middle
        emit({
            'metric': 'trace_overhead',
            'value': round(1.0 - on / off, 4),
            'unit': 'fraction of serve steps/s (tracer on vs off)',
            'tracer_on_steps_per_sec': round(on, 2),
            'tracer_off_steps_per_sec': round(off, 2),
        })
    except Exception as error:  # never cost the headline its run
        emit({'metric': 'trace_overhead', 'value': None,
              'unit': 'fraction of serve steps/s',
              'note': f'probe failed: {str(error)[:160]}'})


BATCH, SEQ = 16, 1024


def bench_recipe():
    """The headline 125M recipe, shared by every row that measures it.

    Perf recipe (each measured on a v5e chip):
    - vocab padded 50257 -> 50304 (x128): the unpadded table mis-tiles the
      MXU on the head matmul (~10% whole-step MFU);
    - Pallas flash attention for the single-chip run (1024/1024 tiles);
    - fused chunked LM loss (return_features): the [B*S, vocab] f32 logits
      tensor is never materialized (~5% MFU, and unlocks batch >= 32);
    - many steps per jit call (lax.fori_loop): per-dispatch overhead through
      the tunneled-TPU relay is ~7 ms (~5% of a 135 ms step) and the final
      host sync costs another dispatch — amortized across the loop
      (measured r2: 10 steps 0.498, 30 0.515, 60 0.519; r3: 90 edges 60
      by ~0.3% and 120 is flat). Round 3 also keeps the flash kernels
      seedless at dropout=0 (the in-kernel dropout path wires its seed
      input only when active — a persistent SMEM arg cost ~0.5%).
    """
    from tpusystem.models import GPT2
    from tpusystem.train import AdamW

    module = GPT2(dropout=0.0, attention='flash', vocab_size=50304,
                  return_features=True)
    optimizer = AdamW(lr=3e-4, grad_clip=1.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 50257, (BATCH, SEQ)),
        jnp.int32)
    return module, optimizer, tokens


def looped_runner(step, steps: int):
    """``steps`` train steps per dispatch, state donated in place in HBM."""
    @partial(jax.jit, donate_argnums=0)
    def run(state, tokens):
        return jax.lax.fori_loop(
            0, steps, lambda i, st: step(st, tokens, tokens)[0], state)
    return run


def timed_trials(run, state, tokens):
    """Shared timing protocol: one warmup/compile dispatch, then TRIALS
    timed runs — completion forced by :func:`materialize` every time
    (``jax.block_until_ready`` returns early through the tunneled-TPU
    relay). Returns ``(state, elapsed_trials)``; report the median and the
    max-min spread so BENCH_r* deltas can be told from run-to-run noise."""
    state = run(state, tokens)
    materialize(state.params)
    elapsed_trials = []
    for _ in range(TRIALS):
        start = time.perf_counter()
        state = run(state, tokens)
        materialize(state.params)
        elapsed_trials.append(time.perf_counter() - start)
    return state, elapsed_trials


def sentinel_overhead_row() -> None:
    """Print the in-graph guard's cost: steps/s with ``guard=`` on vs off
    on the bench model (same 125M recipe and timing protocol as the
    headline, fewer steps per arm), as ``{"metric": "sentinel_overhead",
    "value": <fractional slowdown>}`` — the acceptance budget is < 0.02
    (2%). Printed BEFORE the MFU headline so the driver's parsed last-line
    metric is unchanged; never fails the run (probe errors print a
    null-value row)."""
    try:
        from tpusystem.train import (ChunkedNextTokenLoss, Guard,
                                     build_train_step, flax_apply, init_state)

        steps = 12
        module, optimizer, tokens = bench_recipe()
        guard = Guard()

        def arm_rate(guarded: bool) -> float:
            step = build_train_step(
                flax_apply(module), ChunkedNextTokenLoss(chunks=8), optimizer,
                jit=False, guard=guard if guarded else None)
            state = init_state(module, optimizer, tokens[:1, :8])
            if guarded:
                state = guard.arm(state)
            _, elapsed = timed_trials(looped_runner(step, steps), state,
                                      tokens)
            return steps / sorted(elapsed)[len(elapsed) // 2]

        off, on = arm_rate(False), arm_rate(True)
        emit({
            'metric': 'sentinel_overhead',
            'value': round(1.0 - on / off, 4),
            'unit': 'fraction of steps/s',
            'guard_on_steps_per_sec': round(on, 4),
            'guard_off_steps_per_sec': round(off, 4),
        })
    except Exception as error:  # never cost the headline its run
        emit({'metric': 'sentinel_overhead', 'value': None,
                          'unit': 'fraction of steps/s',
                          'note': f'probe failed: {str(error)[:160]}'})


def recovery_seconds_row() -> None:
    """Print the hot-vs-disk restore cost on the tiny model: wall seconds
    to materialize a resumable ``TrainState`` from the supervisor's
    in-memory store (``hot_resume`` via a local ``MemStore``) vs from the
    newest committed Orbax checkpoint — the per-recovery saving the
    Supervisor's memstore tier buys (``value`` is the hot time; both
    medians of TRIALS). Printed BEFORE the MFU headline; never fails the
    run (probe errors print a null-value row)."""
    import tempfile
    try:
        import jax.numpy as jnp

        from tpusystem.checkpoint import (Checkpointer, MemStore, hot_resume,
                                          serialize_state)
        from tpusystem.models import gpt2_tiny
        from tpusystem.train import (AdamW, NextTokenLoss, build_train_step,
                                     flax_apply, init_state)

        module = gpt2_tiny()
        optimizer = AdamW(lr=1e-3)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 256, (4, 32)), jnp.int32)
        state = init_state(module, optimizer, tokens[:1])
        step = build_train_step(flax_apply(module), NextTokenLoss(),
                                optimizer)
        state, _ = step(state, tokens, tokens)
        identity = 'bench-recovery'
        with tempfile.TemporaryDirectory() as root, \
                Checkpointer(root, async_save=False) as checkpointer:
            checkpointer.save(identity, 1, state, extras={'step': 1})
            store = MemStore()
            store.put(identity, 1, serialize_state(state),
                      extras={'step': 1})

            def timed(client):
                times = []
                for _ in range(TRIALS):
                    start = time.perf_counter()
                    restored, _, _, source = hot_resume(
                        checkpointer, identity, state, client)
                    materialize(restored.params)
                    times.append(time.perf_counter() - start)
                return source, sorted(times)[len(times) // 2]

            hot_source, hot = timed(store)
            disk_source, disk = timed(None)
        assert (hot_source, disk_source) == ('hot', 'disk')
        emit({
            'metric': 'recovery_seconds',
            'value': round(hot, 4),
            'unit': 's (hot restore, tiny model)',
            'disk_seconds': round(disk, 4),
            'hot_speedup_vs_disk': round(disk / hot, 2) if hot else None,
        })
    except Exception as error:  # never cost the headline its run
        emit({'metric': 'recovery_seconds', 'value': None,
                          'unit': 's',
                          'note': f'probe failed: {str(error)[:160]}'})


def decode_rows() -> None:
    """Print the serving-path decode rows: ``decode_tok_s`` (greedy
    generate at the BASELINE decode config — GPT-2 125M, batch 8,
    prefill 128, decode 128, ``stream_dtype='auto'``) and
    ``decode_stream_bytes`` (per-step streamed weight bytes of that
    tree, with the int8-quantized tree's bytes alongside — the
    roofline lever, ``benchmarks/decode_roofline.py``). Printed BEFORE
    the MFU headline so the driver's parsed last-line metric is
    unchanged; never fails the run (probe errors print null rows)."""
    try:
        from tpusystem.models import GPT2
        from tpusystem.train.generate import generate, streamed_bytes

        batch, prefill, decode = 8, 128, 128
        module = GPT2(dropout=0.0, vocab_size=50304, max_seq=512)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 50257, (batch, prefill)),
            jnp.int32)
        params = module.init(jax.random.PRNGKey(0),
                             prompt[:1, :8])['params']

        out = generate(module, params, prompt, steps=decode)   # warm/compile
        materialize(out)
        elapsed_trials = []
        for _ in range(TRIALS):
            start = time.perf_counter()
            out = generate(module, params, prompt, steps=decode)
            materialize(out)
            elapsed_trials.append(time.perf_counter() - start)
        elapsed = sorted(elapsed_trials)[len(elapsed_trials) // 2]
        to_tok = lambda secs: batch * decode / secs
        emit({
            'metric': 'decode_tok_s',
            'value': round(to_tok(elapsed)),
            'spread': round(to_tok(min(elapsed_trials))
                            - to_tok(max(elapsed_trials))),
            'unit': 'tok/s (125M, batch 8, prefill 128, decode 128)',
        })
        auto_bytes = streamed_bytes(module, params, 'auto')
        int8_bytes = streamed_bytes(module, params, 'int8')
        emit({
            'metric': 'decode_stream_bytes',
            'value': auto_bytes,
            'unit': 'bytes/step (streamed param tree, stream_dtype=auto)',
            'int8_bytes': int8_bytes,
            'int8_reduction': round(auto_bytes / int8_bytes, 2),
        })
    except Exception as error:  # never cost the headline its run
        for metric, unit in (('decode_tok_s', 'tok/s'),
                             ('decode_stream_bytes', 'bytes/step')):
            emit({'metric': metric, 'value': None, 'unit': unit,
                              'note': f'probe failed: {str(error)[:160]}'})


def main() -> None:
    from tpusystem.train import (ChunkedNextTokenLoss, build_train_step,
                                 flax_apply, init_state)

    batch, seq = BATCH, SEQ
    module, optimizer, tokens = bench_recipe()
    state = init_state(module, optimizer, tokens[:1, :8])
    params_count = sum(leaf.size for leaf in jax.tree.leaves(state.params))
    step = build_train_step(flax_apply(module), ChunkedNextTokenLoss(chunks=8),
                            optimizer, jit=False)

    steps = 90
    state, elapsed_trials = timed_trials(looped_runner(step, steps), state,
                                         tokens)
    elapsed = sorted(elapsed_trials)[len(elapsed_trials) // 2]

    tokens_per_step = batch * seq
    head_dim = module.dim // module.heads
    # 12*L*H*S^2*Dh*B covers fwd (4*S^2*Dh per head: QK^T + AV at 2 FLOPs/MAC)
    # plus bwd at 2x fwd
    attention_flops = 12 * module.layers * module.heads * seq * seq * head_dim * batch
    step_flops = 6 * params_count * tokens_per_step + attention_flops
    achieved = step_flops * steps / elapsed

    device = jax.devices()[0]
    peak = peak_flops(device)
    if peak:
        to_mfu = lambda secs: step_flops * steps / secs / peak
        mfu = achieved / peak
        emit({
            'metric': 'gpt2_125m_train_mfu_1chip',
            'value': round(mfu, 4),
            'spread': round(to_mfu(min(elapsed_trials))
                            - to_mfu(max(elapsed_trials)), 4),
            'unit': 'MFU',
            'vs_baseline': round(mfu / 0.5, 4),
        })
    else:  # CPU fallback: report throughput
        to_sps = lambda secs: steps / secs
        emit({
            'metric': 'gpt2_125m_train_steps_per_sec_cpu',
            'value': round(steps / elapsed, 4),
            'spread': round(to_sps(min(elapsed_trials))
                            - to_sps(max(elapsed_trials)), 4),
            'unit': 'steps/s',
            'vs_baseline': 1.0,
        })


if __name__ == '__main__':
    tp_overlap_row()
    fsdp_overlap_row()
    pp_overlap_row()
    moe_a2a_overlap_row()
    sentinel_overhead_row()
    recovery_seconds_row()
    resize_seconds_row()
    decode_rows()
    serve_row()
    serve_shared_prefix_row()
    serve_sampled_row()
    serve_recovery_row()
    fleet_recovery_row()
    router_failover_row()
    arbitration_row()
    serve_disagg_ttft_row()
    embedding_row()
    serve_ttft_row()
    trace_overhead_row()
    main()
