"""Headline benchmark: GPT-2 125M training MFU on one chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
measured MFU against the north-star target of 0.50 MFU (BASELINE.json).
Model FLOPs use the standard 6*N*T approximation (fwd+bwd) plus exact
attention term 12*L*H*S^2*D_head*B.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOP/s per chip by device kind substring
PEAKS = {
    'v5 lite': 197e12,  # v5e
    'v5e': 197e12,
    'v5p': 459e12,
    'v4': 275e12,
    'v6': 918e12,
}


def peak_flops(device) -> float | None:
    kind = device.device_kind.lower()
    for key, value in PEAKS.items():
        if key in kind:
            return value
    return None


def main() -> None:
    from tpusystem.models import GPT2
    from tpusystem.train import AdamW, NextTokenLoss, build_train_step, flax_apply, init_state

    batch, seq = 16, 1024
    module = GPT2(dropout=0.0, attention='flash')  # single chip: Pallas kernel
    optimizer = AdamW(lr=3e-4, grad_clip=1.0)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, module.vocab_size, (batch, seq)),
        jnp.int32)
    state = init_state(module, optimizer, tokens[:1, :8])
    params_count = sum(leaf.size for leaf in jax.tree.leaves(state.params))
    step = build_train_step(flax_apply(module), NextTokenLoss(), optimizer)

    # warmup / compile. NOTE: force completion by materializing the loss —
    # jax.block_until_ready returns early through the tunneled-TPU relay.
    for _ in range(3):
        state, (_, loss) = step(state, tokens, tokens)
    float(loss)

    steps = 10
    start = time.perf_counter()
    for _ in range(steps):
        state, (_, loss) = step(state, tokens, tokens)
    float(loss)
    elapsed = time.perf_counter() - start

    tokens_per_step = batch * seq
    head_dim = module.dim // module.heads
    # 12*L*H*S^2*Dh*B covers fwd (4*S^2*Dh per head: QK^T + AV at 2 FLOPs/MAC)
    # plus bwd at 2x fwd
    attention_flops = 12 * module.layers * module.heads * seq * seq * head_dim * batch
    step_flops = 6 * params_count * tokens_per_step + attention_flops
    achieved = step_flops * steps / elapsed

    device = jax.devices()[0]
    peak = peak_flops(device)
    if peak:
        mfu = achieved / peak
        print(json.dumps({
            'metric': 'gpt2_125m_train_mfu_1chip',
            'value': round(mfu, 4),
            'unit': 'MFU',
            'vs_baseline': round(mfu / 0.5, 4),
        }))
    else:  # CPU fallback: report throughput
        print(json.dumps({
            'metric': 'gpt2_125m_train_steps_per_sec_cpu',
            'value': round(steps / elapsed, 4),
            'unit': 'steps/s',
            'vs_baseline': 1.0,
        }))


if __name__ == '__main__':
    main()
