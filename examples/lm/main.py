"""Language-model pretraining system — the tinysys architecture at LM scale.

The same message-driven stack as ``examples/tinysys`` (compiler pipeline,
named service handlers, event consumers, resume-by-identity), applied to
the BASELINE.md ladder-4 workload: a GPT-2 aggregate trained with the
fused chunked LM loss under an FSDP sharding policy on the job's mesh.
Every piece is a DI seam: swap the mesh, the policy (e.g.
``TensorParallel(GPT2.partition_rules(), fsdp=True)``), the dataset
(``MemmapTokens('corpus.bin')`` for a real corpus), or the preset from
this composition root without touching the system.

Run: ``python main.py [epochs]``  (tiny preset; ``--full`` for 125M).
"""

from __future__ import annotations

import contextlib
import logging
import pathlib

import jax
import jax.numpy as jnp

from tpusystem import Aggregate, Compiler, Depends, Runtime
from tpusystem.checkpoint import Repository
from tpusystem.data import Loader, MemmapTokens, SyntheticTokens
from tpusystem.depends import Provider
from tpusystem.models import GPT2, gpt2_tiny
from tpusystem.observe import checkpoint_consumer, logging_consumer, tracking
from tpusystem.observe.events import Iterated, Trained, Validated
from tpusystem.observe.profile import StepTimer
from tpusystem.parallel import (FullyShardedDataParallel, MeshSpec,
                                batch_sharding)
from tpusystem.registry import gethash
from tpusystem.services import Producer, Service
from tpusystem.storage import (DocumentIterations, DocumentMetrics,
                               DocumentModels, DocumentModules, DocumentStore)
from tpusystem.train import (AdamW, ChunkedNextTokenLoss, Mean, Perplexity,
                             build_eval_step, build_multi_eval_step,
                             build_multi_step, build_train_step, flax_apply,
                             grouped_batches, init_state)

ROOT = pathlib.Path(__file__).parent / 'data'


# --------------------------------------------------------------------------
# aggregate

class LanguageModel(Aggregate):
    """Network + criterion + optimizer as one identity-bearing unit; the
    math is two jitted steps over an FSDP-sharded TrainState."""

    def __init__(self, network, criterion, optimizer, accumulate: int = 1):
        super().__init__()
        self.network = network
        self.criterion = criterion
        self.optimizer = optimizer
        self.state = None
        self.mesh = None
        self.epoch = 0
        self.accumulate = accumulate
        self._build_steps(network)

    def _build_steps(self, network) -> None:
        apply_fn = flax_apply(network)
        self._train_step = build_train_step(apply_fn, self.criterion,
                                            self.optimizer,
                                            accumulate=self.accumulate)
        self._eval_step = build_eval_step(apply_fn, self.criterion)
        # N steps per host dispatch: one lax.scan amortizes the per-dispatch
        # relay/Python cost the same way bench.py's compiled loop does
        self._train_many = build_multi_step(
            build_train_step(apply_fn, self.criterion, self.optimizer,
                             accumulate=self.accumulate, jit=False))
        self._eval_many = build_multi_eval_step(
            build_eval_step(apply_fn, self.criterion, jit=False))

    @property
    def id(self) -> str:
        return gethash(self.network)

    def modules(self):
        return {'nn': self.network, 'criterion': self.criterion,
                'optimizer': self.optimizer}

    def place(self, sample_tokens, mesh, policy) -> None:
        self.mesh = mesh
        if getattr(self.network, 'mesh', 'absent') is None:
            # bind the placement mesh into the network so mesh-aware
            # kernels (flash via shard_map, ring, MoE exchanges) compose
            # with the sharding policy; steps rebuild against the clone
            import dataclasses
            self.network = dataclasses.replace(self.network, mesh=mesh)
            self._build_steps(self.network)
        state = init_state(self.network, self.optimizer, sample_tokens)
        self.state = policy.place(state, mesh)

    def shard_batch(self, tokens):
        return jax.device_put(tokens, batch_sharding(self.mesh))

    def shard_batches(self, tokens_stack):
        """Place a [steps, batch, ...] stack: batch axis (dim 1) shards
        over (data, fsdp); the steps axis stays whole on every device."""
        from tpusystem.parallel import stacked_batch_sharding
        return jax.device_put(tokens_stack,
                              stacked_batch_sharding(self.mesh))

    def fit(self, tokens):
        self.state, (_, loss) = self._train_step(self.state, tokens, tokens)
        return loss

    def fit_many(self, tokens_stack):
        """Run ``tokens_stack.shape[0]`` train steps in one dispatch;
        returns the per-step loss vector (exact per-phase metrics)."""
        self.state, losses = self._train_many(self.state, tokens_stack,
                                              tokens_stack)
        return losses

    def evaluate_many(self, tokens_stack):
        return self._eval_many(self.state, tokens_stack, tokens_stack)

    def evaluate(self, tokens):
        _, loss = self._eval_step(self.state, tokens, tokens)
        return loss

    def onepoch(self) -> None:
        self.events.commit()


# --------------------------------------------------------------------------
# metrics

class LMMetrics:
    """Loss + perplexity, accumulated on device, one sync per phase."""

    def __init__(self):
        self.loss = Mean()
        self.perplexity = Perplexity()

    def update(self, loss) -> None:
        self.loss.update(loss)
        self.perplexity.update(loss)

    def compute(self) -> dict:
        return {'loss': self.loss.compute(),
                'perplexity': self.perplexity.compute()}

    def reset(self) -> None:
        self.loss.reset()
        self.perplexity.reset()


# --------------------------------------------------------------------------
# compilation pipeline

provider = Provider()
compiler = Compiler[LanguageModel](provider=provider)


def mesh():
    """FSDP over every chip in the job (a 1x1 mesh on one chip)."""
    return MeshSpec(fsdp=-1).build()


def policy():
    return FullyShardedDataParallel()


def sample_tokens():
    return jnp.zeros((1, 8), jnp.int32)


def accumulate() -> int:
    """Gradient-accumulation microsteps (override at the composition
    root when the target global batch does not fit)."""
    return 1


def steps_per_dispatch() -> int:
    """Train/validate steps per host dispatch (1 = a dispatch per batch;
    override at the composition root — e.g. 8 pays the ~7 ms relay cost
    once per 8 batches). Events/metrics keep phase cadence either way."""
    return 1


def models():
    raise NotImplementedError('override the models store dependency')


def repository():
    raise NotImplementedError('override the repository dependency')


def experiment() -> str:
    return 'lm'


@compiler.step
def build(network, criterion, optimizer,
          microsteps: int = Depends(accumulate)) -> LanguageModel:
    return LanguageModel(network, criterion, optimizer, accumulate=microsteps)


@compiler.step
def place_on_mesh(model: LanguageModel, device_mesh=Depends(mesh),
                  sharding=Depends(policy),
                  sample=Depends(sample_tokens)) -> LanguageModel:
    model.place(sample, device_mesh, sharding)
    return model


@compiler.step
def bring_epoch(model: LanguageModel, store=Depends(models),
                name: str = Depends(experiment)) -> LanguageModel:
    from tpusystem.storage import ports
    row = store.read(str(model.id), name)
    if row is None:
        store.create(ports.Model(hash=str(model.id), experiment=name, epoch=0))
        return model
    if row.epoch < model.epoch:
        raise ValueError(f'epoch regression: store at {row.epoch}')
    model.epoch = row.epoch
    return model


@compiler.step
def restore_weights(model: LanguageModel,
                    weights=Depends(repository)) -> LanguageModel:
    if model.epoch > 0:
        weights.restore(model)
    return model


# --------------------------------------------------------------------------
# training service

service = Service(provider=provider)
producer = Producer()


@service.handler
def iterate(model, loaders, metrics) -> None:
    train(model, loaders['train'], metrics)
    metrics.reset()
    validate(model, loaders['evaluation'], metrics)
    metrics.reset()
    try:
        model.epoch += 1
    finally:
        producer.dispatch(Iterated(model, loaders))


@service.handler
def train(model, loader, metrics,
          dispatch: int = Depends(steps_per_dispatch)) -> None:
    model.phase = 'train'
    timer = StepTimer(producer).start()
    for (stack,) in grouped_batches(loader, dispatch):
        metrics.update(model.fit_many(model.shard_batches(stack)))
    results = metrics.compute()
    timer.stop(model, 'train', steps=len(loader))
    producer.dispatch(Trained(model, results))


@service.handler
def validate(model, loader, metrics,
             dispatch: int = Depends(steps_per_dispatch)) -> None:
    model.phase = 'evaluation'
    timer = StepTimer(producer).start()
    for (stack,) in grouped_batches(loader, dispatch):
        metrics.update(model.evaluate_many(model.shard_batches(stack)))
    results = metrics.compute()
    timer.stop(model, 'evaluation', steps=len(loader))
    producer.dispatch(Validated(model, results))


# --------------------------------------------------------------------------
# composition root

def main(epochs: int = 3, full: bool = False, corpus: str | None = None,
         holdout_corpus: str | None = None, microsteps: int = 1,
         dispatch_steps: int = 8) -> None:
    global producer
    logging.basicConfig(level=logging.INFO, format='%(message)s', force=True)
    for noisy in ('orbax', 'absl', 'jax'):
        logging.getLogger(noisy).setLevel(logging.WARNING)
    runtime = Runtime()
    store = DocumentStore(ROOT / 'experiments.json')
    weights = Repository(ROOT / 'weights')

    tracker = tracking.tracking_consumer()
    tracker.dependency_overrides.update({
        tracking.metrics_store: lambda: DocumentMetrics(store),
        tracking.models_store: lambda: DocumentModels(store),
        tracking.modules_store: lambda: DocumentModules(store),
        tracking.iterations_store: lambda: DocumentIterations(store),
        tracking.repository: lambda: weights,
        tracking.experiment: experiment,
    })
    runtime.producer.register(tracker, primary_only=True)
    saver = checkpoint_consumer()
    saver.dependency_overrides[tracking.repository] = lambda: weights
    runtime.producer.register(saver)
    runtime.producer.register(logging_consumer())
    producer = runtime.producer

    provider.override(models, lambda: DocumentModels(store))
    provider.override(repository, lambda: weights)
    provider.override(accumulate, lambda: microsteps)
    provider.override(steps_per_dispatch, lambda: dispatch_steps)

    if full:
        # the headline recipe: flash attention (composed with the FSDP mesh
        # via shard_map at placement), fused chunked LM loss, padded vocab
        network = GPT2(vocab_size=50304, dropout=0.0, return_features=True,
                       attention='flash')
        sequence, batch = 1024, 16
    else:
        network = gpt2_tiny(return_features=True)
        sequence, batch = 128, 16
    model = compiler.compile(network, ChunkedNextTokenLoss(chunks=8),
                             AdamW(lr=3e-4, grad_clip=1.0))

    if corpus:
        # MemmapTokens windows are sequence_length + 1 (the loss shifts
        # inputs/targets out of one tensor): size them to the model's cap
        dataset = MemmapTokens(corpus, sequence_length=sequence - 1)
        # evaluate on a separate file, or reuse the training corpus when
        # none is given (then eval loss is training-distribution loss)
        holdout = (MemmapTokens(holdout_corpus, sequence_length=sequence - 1)
                   if holdout_corpus else dataset)
    else:
        dataset = SyntheticTokens(samples=64 * batch, sequence_length=sequence,
                                  vocab_size=min(network.vocab_size, 256))
        holdout = SyntheticTokens(samples=8 * batch, sequence_length=sequence,
                                  vocab_size=min(network.vocab_size, 256),
                                  train=False)  # same bigram table, unseen draws
    loaders = {'train': Loader(dataset, batch_size=batch, shuffle=True, seed=0),
               'evaluation': Loader(holdout, batch_size=batch)}
    metrics = LMMetrics()

    print(f'pretraining {model.id} from epoch {model.epoch}')
    try:
        for _ in range(model.epoch, epochs):
            wants_stop = False
            try:
                service.handle('iterate', model, loaders, metrics)
            except StopIteration:
                wants_stop = True
            runtime.sync()
            if runtime.should_stop(wants_stop):
                break
    finally:
        with contextlib.ExitStack() as cleanup:
            cleanup.callback(runtime.close)
            cleanup.callback(store.close)
            weights.close()


if __name__ == '__main__':
    import argparse
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('epochs', nargs='?', type=int, default=3)
    parser.add_argument('--full', action='store_true',
                        help='125M preset instead of tiny')
    parser.add_argument('--corpus', help='flat binary token file '
                        '(MemmapTokens layout) instead of synthetic data')
    parser.add_argument('--holdout', help='separate corpus file for eval')
    def positive(value: str) -> int:
        steps = int(value)
        if steps < 1:
            raise argparse.ArgumentTypeError('must be >= 1')
        return steps

    parser.add_argument('--accumulate', type=positive, default=1,
                        help='gradient-accumulation microsteps per batch')
    parser.add_argument('--dispatch', type=positive, default=8,
                        help='train/validate steps per host dispatch')
    args = parser.parse_args()
    main(args.epochs, full=args.full, corpus=args.corpus,
         holdout_corpus=args.holdout, microsteps=args.accumulate,
         dispatch_steps=args.dispatch)
