"""tinysys composition root.

Reference parity: ``examples/tinysys/main.py`` — register types, override
dependencies, wire producer->consumers, build parts, compile the aggregate,
drive epochs. The same file is pod-ready: :class:`tpusystem.Runtime` brings
up the control plane (a no-op Loopback when single-process), storage and
TensorBoard consumers register ``primary_only``, and the early-stop verdict
is collectively agreed each epoch.

Run: ``python main.py [epochs]`` from this directory.
"""

from __future__ import annotations

import contextlib
import logging
import pathlib
import sys

from tpusystem import Runtime
from tpusystem.checkpoint import Repository
from tpusystem.data import Loader, SyntheticDigits
from tpusystem.models import MLP
from tpusystem.observe import (checkpoint_consumer, logging_consumer,
                               tensorboard_consumer, tracking_consumer)
from tpusystem.parallel import MeshSpec
from tpusystem.observe import tensorboard as tb
from tpusystem.observe import tracking
from tpusystem.storage import (DocumentIterations, DocumentMetrics,
                               DocumentModels, DocumentModules, DocumentStore)
from tpusystem.train import Adam, CrossEntropyLoss

from tinysys.metrics import ClassifierMetrics
from tinysys.services import compilation, training

ROOT = pathlib.Path(__file__).parent / 'data'
BATCH = 64


def main(epochs: int = 10) -> None:
    logging.basicConfig(level=logging.INFO, format='%(message)s', force=True)
    for noisy in ('orbax', 'absl', 'jax'):
        logging.getLogger(noisy).setLevel(logging.WARNING)
    runtime = Runtime(ledger=True)

    # --- storage + observability wiring (primary host only) ---------------
    store = DocumentStore(ROOT / 'experiments.json')
    repository = Repository(ROOT / 'weights')
    overrides = {
        tracking.metrics_store: lambda: DocumentMetrics(store),
        tracking.models_store: lambda: DocumentModels(store),
        tracking.modules_store: lambda: DocumentModules(store),
        tracking.iterations_store: lambda: DocumentIterations(store),
        tracking.repository: lambda: repository,
        tb.writer: lambda: tb.SummaryWriter(ROOT / 'tensorboard'),
    }
    for consumer in (tracking_consumer(), tensorboard_consumer()):
        consumer.dependency_overrides.update(overrides)
        runtime.producer.register(consumer, primary_only=True)
    # Checkpoint saves are collective (each host writes its own shards), so
    # this consumer runs on EVERY host, unlike the metadata stores above.
    saver = checkpoint_consumer()
    saver.dependency_overrides[tracking.repository] = lambda: repository
    runtime.producer.register(saver)
    runtime.producer.register(logging_consumer())
    training.producer = runtime.producer   # handlers dispatch on the runtime bus
    # 8 jitted steps per host dispatch: the per-batch Python/relay cost is
    # paid once per 8 batches (events/metrics keep phase cadence)
    training.provider.override(training.steps_per_dispatch, lambda: 8)

    # --- compilation pipeline overrides -----------------------------------
    compilation.provider.override(compilation.models, lambda: DocumentModels(store))
    compilation.provider.override(compilation.repository, lambda: repository)
    # Data-parallel over every chip in the job (global mesh on a pod); the
    # default is a single-device mesh, which would be wrong everywhere else.
    compilation.provider.override(compilation.mesh, lambda: MeshSpec(data=-1).build())
    compilation.provider.override(compilation.batch_size, lambda: BATCH)

    # --- build + compile the aggregate ------------------------------------
    network = MLP(features=(256, 128), classes=10, dropout=0.1)
    classifier = compilation.compiler.compile(
        network, CrossEntropyLoss(), Adam(lr=1e-3))

    loaders = {
        'train': Loader(SyntheticDigits(samples=4096), batch_size=BATCH,
                        shuffle=True, seed=1),
        'evaluation': Loader(SyntheticDigits(samples=1024, train=False),
                             batch_size=BATCH),
    }
    metrics = ClassifierMetrics()

    # --- epoch loop, pod-correct early stop -------------------------------
    print(f'training {classifier.id} from epoch {classifier.epoch}')
    try:
        for _ in range(classifier.epoch, epochs):
            wants_stop = False
            try:
                training.service.handle('iterate', classifier, loaders, metrics)
            except StopIteration:
                wants_stop = True
            runtime.sync()
            if runtime.should_stop(wants_stop):
                print('early stop agreed across hosts')
                break
    finally:
        # LIFO stack: each close runs even if an earlier one (or the async
        # checkpoint wait) raises — a failed save must not leak the control
        # plane or the document store.
        with contextlib.ExitStack() as cleanup:
            cleanup.callback(runtime.close)
            cleanup.callback(store.close)
            repository.close()   # waits for pending async saves, then releases


if __name__ == '__main__':
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 10)
