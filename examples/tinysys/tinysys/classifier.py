"""The classifier aggregate.

Reference parity: ``examples/tinysys/tinysys/classifier.py`` — an aggregate
whose identity is the hash of its network and whose ``fit``/``evaluate``
are the per-step hot path. TPU-native split: the host side (this class)
carries identity, phase and epoch; the math is two jitted step functions
advancing an immutable :class:`~tpusystem.train.TrainState` that lives
sharded on the mesh. ``fit`` returns device values only — metrics
accumulate on device and the single host sync happens once per phase.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpusystem import Aggregate
from tpusystem.parallel import batch_sharding, replicated
from tpusystem.registry import gethash
from tpusystem.train import (build_eval_step, build_multi_eval_step,
                             build_multi_step, build_train_step, flax_apply,
                             init_state)


class Classifier(Aggregate):
    """Network + criterion + optimizer as one identity-bearing unit."""

    def __init__(self, network, criterion, optimizer):
        super().__init__()
        self.network = network
        self.criterion = criterion
        self.optimizer = optimizer
        self.state = None           # TrainState; created by place()
        self.mesh = None
        self.epoch = 0              # first assignment: no onepoch() fire
        apply_fn = flax_apply(network)
        self._train_step = build_train_step(apply_fn, criterion, optimizer)
        self._eval_step = build_eval_step(apply_fn, criterion)
        # N steps per host dispatch (one lax.scan, one compiled program):
        # predictions stack reduced to argmax so metrics stay exact
        predictions = lambda outputs: jnp.argmax(outputs, -1)
        self._train_many = build_multi_step(
            build_train_step(apply_fn, criterion, optimizer, jit=False),
            outputs_fn=predictions)
        self._eval_many = build_multi_eval_step(
            build_eval_step(apply_fn, criterion, jit=False),
            outputs_fn=predictions)

    @property
    def id(self) -> str:
        """Registry hash of the network — deterministic across hosts and
        restarts (``examples/tinysys/tinysys/classifier.py:18-20``)."""
        return gethash(self.network)

    def modules(self) -> dict[str, Any]:
        """Registered parts, for the experiment-tracking consumer."""
        return {'nn': self.network, 'criterion': self.criterion,
                'optimizer': self.optimizer}

    def place(self, sample_inputs, mesh) -> None:
        """Initialize device state on the mesh: parameters replicated (small
        model), batches sharded over the data axes."""
        self.mesh = mesh
        state = init_state(self.network, self.optimizer, sample_inputs)
        self.state = jax.device_put(state, replicated(mesh))

    def shard_batch(self, batch: tuple) -> tuple:
        return tuple(jax.device_put(part, batch_sharding(self.mesh))
                     for part in batch)

    def shard_batches(self, stacked: tuple) -> tuple:
        """Place [steps, batch, ...] stacks: the batch axis (dim 1)
        shards over (data, fsdp); the steps axis stays whole."""
        from tpusystem.parallel import stacked_batch_sharding
        return tuple(jax.device_put(part, stacked_batch_sharding(self.mesh))
                     for part in stacked)

    def fit(self, inputs, targets):
        """One optimization step; returns (predictions, loss) on device."""
        self.state, (outputs, loss) = self._train_step(self.state, inputs, targets)
        return jnp.argmax(outputs, -1), loss

    def evaluate(self, inputs, targets):
        """Deterministic forward; returns (predictions, loss) on device."""
        outputs, loss = self._eval_step(self.state, inputs, targets)
        return jnp.argmax(outputs, -1), loss

    def fit_many(self, inputs, targets):
        """N optimization steps in one dispatch over [N, batch, ...]
        stacks; returns (predictions [N, batch], losses [N])."""
        self.state, (predictions, losses) = self._train_many(
            self.state, inputs, targets)
        return predictions, losses

    def evaluate_many(self, inputs, targets):
        return self._eval_many(self.state, inputs, targets)

    def onepoch(self) -> None:
        """Commit domain events at every epoch edge — enqueued exceptions
        (early stop) unwind into the epoch loop here."""
        self.events.commit()
