"""Classification metric bundle.

Reference parity: ``examples/tinysys/tinysys/metrics.py`` (torcheval Mean +
MulticlassAccuracy, on device). Accumulation is on-device per batch; the
one ``jax.device_get`` per phase happens in :meth:`compute`.
"""

from __future__ import annotations

from tpusystem.train import Accuracy, Mean


class ClassifierMetrics:
    def __init__(self) -> None:
        self.loss = Mean()
        self.accuracy = Accuracy()

    def update(self, loss, predictions, targets) -> None:
        self.loss.update(loss)
        self.accuracy.update(predictions, targets)

    def compute(self) -> dict[str, float]:
        return {'loss': self.loss.compute(),
                'accuracy': self.accuracy.compute()}

    def reset(self) -> None:
        self.loss.reset()
        self.accuracy.reset()
