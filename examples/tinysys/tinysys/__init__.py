"""tinysys — the reference application, TPU-native.

The end-to-end training system the reference ships as its flagship example
(``/root/reference/examples/tinysys``): a classifier aggregate built by a
compiler pipeline, driven by a named service, observed by decoupled
consumers (logging, experiment tracking, TensorBoard), with identity-keyed
checkpoint/resume. Here the classifier trains on a TPU mesh through jitted,
donated step functions; everything else is the same architecture.
"""
