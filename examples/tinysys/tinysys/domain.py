"""Structural contracts between services and implementations.

Reference parity: ``examples/tinysys/tinysys/domain.py:10-48`` — services
depend on these protocols, never on concrete classes, so any aggregate
satisfying ``Model`` trains under the same service handlers.
"""

from __future__ import annotations

from typing import Any, Iterator, Protocol, runtime_checkable


@runtime_checkable
class Model(Protocol):
    """What the training service needs from an aggregate."""
    id: Any
    epoch: int
    phase: str

    def fit(self, inputs, targets) -> tuple[Any, Any]: ...
    def evaluate(self, inputs, targets) -> tuple[Any, Any]: ...


@runtime_checkable
class Loader(Protocol):
    def __iter__(self) -> Iterator[tuple]: ...
    def __len__(self) -> int: ...


@runtime_checkable
class Metrics(Protocol):
    def update(self, loss, predictions, targets) -> None: ...
    def compute(self) -> dict[str, float]: ...
    def reset(self) -> None: ...
