"""Training service: iterate / train / validate.

Reference parity: ``examples/tinysys/tinysys/services/training.py`` — the
epoch choreography as named, DI-injected handlers, with one event per phase
on the producer. TPU difference: the hot loop advances a jitted step and
touches no host values; throughput timing brackets the whole phase
(:class:`tpusystem.observe.StepTimer`), and batches land pre-sharded via
the aggregate's ``shard_batch``.
"""

from __future__ import annotations

from tpusystem.depends import Provider
from tpusystem.observe import StepTimer
from tpusystem.observe.events import Iterated, Trained, Validated
from tpusystem.services import Producer, Service

provider = Provider()
service = Service(provider=provider)
producer = Producer()


@service.handler
def iterate(model, loaders, metrics) -> None:
    """One epoch: train phase, validation phase, epoch edge + event."""
    train(model, loaders['train'], metrics)
    metrics.reset()
    validate(model, loaders['evaluation'], metrics)
    metrics.reset()
    try:
        model.epoch += 1                  # fires onepoch() -> events.commit()
    finally:
        # The epoch edge may unwind an early-stop exception; the Iterated
        # event must still go out or the stopping epoch — the one most worth
        # keeping — would never reach the store/checkpoint consumers.
        producer.dispatch(Iterated(model, loaders))


@service.handler
def train(model, loader, metrics) -> None:
    model.phase = 'train'
    timer = StepTimer(producer).start()
    loss = None
    for batch in loader:
        inputs, targets = model.shard_batch(batch)
        predictions, loss = model.fit(inputs, targets)
        metrics.update(loss, predictions, targets)
    results = metrics.compute()           # the one device->host sync
    timer.stop(model, 'train', steps=len(loader), result=loss)
    producer.dispatch(Trained(model, results))


@service.handler
def validate(model, loader, metrics) -> None:
    model.phase = 'evaluation'
    timer = StepTimer(producer).start()
    loss = None
    for batch in loader:
        inputs, targets = model.shard_batch(batch)
        predictions, loss = model.evaluate(inputs, targets)
        metrics.update(loss, predictions, targets)
    results = metrics.compute()
    timer.stop(model, 'evaluation', steps=len(loader), result=loss)
    producer.dispatch(Validated(model, results))
