"""Training service: iterate / train / validate.

Reference parity: ``examples/tinysys/tinysys/services/training.py`` — the
epoch choreography as named, DI-injected handlers, with one event per phase
on the producer. TPU difference: the hot loop advances a jitted step and
touches no host values; throughput timing brackets the whole phase
(:class:`tpusystem.observe.StepTimer`), and batches land pre-sharded via
the aggregate's ``shard_batch``.
"""

from __future__ import annotations

from tpusystem.depends import Depends, Provider
from tpusystem.observe import StepTimer
from tpusystem.observe.events import Iterated, Trained, Validated
from tpusystem.services import Producer, Service
from tpusystem.train import grouped_batches

provider = Provider()
service = Service(provider=provider)
producer = Producer()


def steps_per_dispatch() -> int:
    """Train/validate steps per host dispatch (override at the
    composition root; N > 1 amortizes the per-dispatch host cost over N
    batches via one compiled lax.scan — events/metrics keep phase
    cadence)."""
    return 1


@service.handler
def iterate(model, loaders, metrics) -> None:
    """One epoch: train phase, validation phase, epoch edge + event."""
    train(model, loaders['train'], metrics)
    metrics.reset()
    validate(model, loaders['evaluation'], metrics)
    metrics.reset()
    try:
        model.epoch += 1                  # fires onepoch() -> events.commit()
    finally:
        # The epoch edge may unwind an early-stop exception; the Iterated
        # event must still go out or the stopping epoch — the one most worth
        # keeping — would never reach the store/checkpoint consumers.
        producer.dispatch(Iterated(model, loaders))


@service.handler
def train(model, loader, metrics,
          dispatch: int = Depends(steps_per_dispatch)) -> None:
    model.phase = 'train'
    timer = StepTimer(producer).start()
    loss = None
    if dispatch == 1 or not hasattr(model, 'fit_many'):
        # per-batch path — the Model protocol's surface (fit/shard_batch);
        # models without the aggregate-level fit_many stay here
        for batch in loader:
            inputs, targets = model.shard_batch(batch)
            predictions, loss = model.fit(inputs, targets)
            metrics.update(loss, predictions, targets)
    else:
        # N steps per host dispatch (aggregate-level fit_many)
        for batch_stack in grouped_batches(loader, dispatch):
            inputs, targets = model.shard_batches(batch_stack)
            predictions, loss = model.fit_many(inputs, targets)
            metrics.update(loss, predictions, targets)
    results = metrics.compute()           # the one device->host sync
    timer.stop(model, 'train', steps=len(loader), result=loss)
    producer.dispatch(Trained(model, results))


@service.handler
def validate(model, loader, metrics,
             dispatch: int = Depends(steps_per_dispatch)) -> None:
    model.phase = 'evaluation'
    timer = StepTimer(producer).start()
    loss = None
    if dispatch == 1 or not hasattr(model, 'evaluate_many'):
        for batch in loader:
            inputs, targets = model.shard_batch(batch)
            predictions, loss = model.evaluate(inputs, targets)
            metrics.update(loss, predictions, targets)
    else:
        for batch_stack in grouped_batches(loader, dispatch):
            inputs, targets = model.shard_batches(batch_stack)
            predictions, loss = model.evaluate_many(inputs, targets)
            metrics.update(loss, predictions, targets)
    results = metrics.compute()
    timer.stop(model, 'evaluation', steps=len(loader), result=loss)
    producer.dispatch(Validated(model, results))
