"""Aggregate compilation pipeline.

Reference parity: ``examples/tinysys/tinysys/services/compilation.py`` —
build -> move to device -> compile -> bring epoch -> restore weights. The
TPU lowering of each stage: construction is pure host Python; "move to
device" places the state pytree on the injected *mesh* with its shardings;
"compile" warms the jitted steps (XLA lowering is cached, so first-batch
latency moves here); create-or-resume reads the experiment store by the
aggregate's identity hash and refuses epoch regressions; restore loads the
sharded checkpoint onto the current mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusystem.compiler import Compiler, Depends
from tpusystem.depends import Provider
from tpusystem.parallel import single_device_mesh
from tpusystem.storage import ports

from ..classifier import Classifier

provider = Provider()
compiler = Compiler[Classifier](provider=provider)


def mesh():
    """The device mesh (override at the composition root for pods)."""
    return single_device_mesh()


def sample_inputs():
    """A shape-defining sample batch for parameter initialization."""
    return jnp.zeros((1, 28, 28), jnp.float32)


def batch_size() -> int:
    """The production batch size — jit caches are keyed by shape and
    sharding, so warming with any other size compiles a trace that is never
    reused (override to match the loaders at the composition root)."""
    return 64


def models() -> ports.Models:
    raise NotImplementedError('override the models store dependency')


def experiment() -> str:
    return 'default'


def repository():
    raise NotImplementedError('override the repository dependency')


@compiler.step
def build_classifier(network, criterion, optimizer) -> Classifier:
    return Classifier(network, criterion, optimizer)


@compiler.step
def place_on_mesh(classifier: Classifier,
                  device_mesh=Depends(mesh),
                  sample=Depends(sample_inputs)) -> Classifier:
    classifier.place(sample, device_mesh)
    return classifier


@compiler.step
def warm_compile(classifier: Classifier,
                 sample=Depends(sample_inputs),
                 size: int = Depends(batch_size)) -> Classifier:
    """Trigger XLA lowering now: the analogue of the reference's
    ``torch.compile`` stage. Both steps are traced with production-shaped,
    production-sharded batches (jit caches key on shape *and* sharding);
    the train step runs on a copy of the state because it donates its
    buffers."""
    inputs = jnp.zeros((size, *sample.shape[1:]), sample.dtype)
    targets = jnp.zeros((size,), jnp.int32)
    inputs, targets = classifier.shard_batch((inputs, targets))
    classifier._eval_step(classifier.state, inputs, targets)
    throwaway = jax.tree_util.tree_map(jnp.copy, classifier.state)
    classifier._train_step(throwaway, inputs, targets)
    return classifier


@compiler.step
def bring_epoch(classifier: Classifier,
                store: ports.Models = Depends(models),
                name: str = Depends(experiment)) -> Classifier:
    """Create-or-resume by identity (``compilation.py:41-57``): an existing
    row resumes at its recorded epoch; a fresh aggregate gets a row at 0."""
    row = store.read(str(classifier.id), name)
    if row is None:
        store.create(ports.Model(hash=str(classifier.id), experiment=name, epoch=0))
        return classifier
    if row.epoch < classifier.epoch:
        raise ValueError(
            f'epoch regression: store has {row.epoch}, aggregate at {classifier.epoch}')
    classifier.epoch = row.epoch
    return classifier


@compiler.step
def restore_weights(classifier: Classifier,
                    weights=Depends(repository)) -> Classifier:
    if classifier.epoch > 0:
        weights.restore(classifier)
    return classifier
