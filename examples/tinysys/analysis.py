"""Post-hoc experiment analysis — the reference's ``notebook.ipynb`` as a
script (``/root/reference/examples/tinysys/notebook.ipynb`` queries TinyDB
and plots metric curves; here the document store is queried the same way).

Run after ``python main.py``:

    python analysis.py            # text report of every model's curves
    python analysis.py --plot     # also writes data/metrics.png (matplotlib)
"""

from __future__ import annotations

import pathlib
import sys
from collections import defaultdict

from tpusystem.storage import (DocumentMetrics, DocumentModels,
                               DocumentModules, DocumentStore)

ROOT = pathlib.Path(__file__).parent / 'data'


def curves(metrics_rows):
    """{(metric, phase): [(epoch, value), ...]} sorted by epoch."""
    series = defaultdict(list)
    for row in metrics_rows:
        series[(row.name, row.phase)].append((row.epoch, row.value))
    return {key: sorted(points) for key, points in series.items()}


def report(store: DocumentStore) -> list:
    models = DocumentModels(store).list('default')
    if not models:
        print('no experiments recorded — run main.py first')
        return []
    for model in models:
        print(f'model {model.hash}  (epoch {model.epoch})')
        for row in DocumentModules(store).list(model.hash):
            print(f'  {row.kind:10} {row.name} {row.arguments}')
        for (name, phase), points in sorted(curves(
                DocumentMetrics(store).list(model.hash)).items()):
            values = ' '.join(f'{value:.4f}' for _, value in points)
            print(f'  {name}/{phase:11} {values}')
    return models


def plot(store: DocumentStore, models, path: pathlib.Path) -> None:
    import matplotlib
    matplotlib.use('Agg')
    import matplotlib.pyplot as plt

    series = curves(DocumentMetrics(store).list(models[0].hash))
    names = sorted({name for name, _ in series})
    figure, axes = plt.subplots(1, len(names), figsize=(6 * len(names), 4))
    for axis, name in zip([axes] if len(names) == 1 else axes, names):
        for (metric, phase), points in sorted(series.items()):
            if metric == name:
                axis.plot(*zip(*points), marker='o', label=phase)
        axis.set_title(name)
        axis.set_xlabel('epoch')
        axis.legend()
    figure.tight_layout()
    figure.savefig(path)
    print(f'wrote {path}')


def main() -> None:
    store = DocumentStore(ROOT / 'experiments.json')
    try:
        models = report(store)
        if models and '--plot' in sys.argv:
            plot(store, models, ROOT / 'metrics.png')
    finally:
        store.close()


if __name__ == '__main__':
    main()
