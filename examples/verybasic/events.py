"""Domain events in isolation (reference: ``examples/verybasic/events.py``).

An aggregate enqueues events during an epoch; ``commit`` dispatches them.
Exceptions without handlers raise at commit — the early-stop mechanism.
"""

from tpusystem.domain import Events


class Overfitting(Exception):
    """Validation loss rose while training loss fell."""


def main() -> None:
    events = Events()

    events.handlers[Overfitting] = lambda: print('handled: reduce lr, continue')
    events.enqueue(Overfitting())
    events.commit()                     # handled -> no raise

    del events.handlers[Overfitting]
    events.enqueue(Overfitting('val loss diverged'))
    try:
        events.commit()                 # unhandled exception raises here
    except Overfitting as stop:
        print(f'training stopped: {stop}')


if __name__ == '__main__':
    main()
