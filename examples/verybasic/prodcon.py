"""Producer/Consumer bus in isolation (reference:
``examples/verybasic/prodcon.py``).

Events route by *type*; a handler annotated with a union consumes every
member; dependencies inject per call.
"""

from tpusystem.services import Consumer, Depends, Producer, event


@event
class ModelTrained:
    accuracy: float


@event
class ModelEvaluated:
    accuracy: float


def database() -> list:
    raise NotImplementedError('overridden at the composition root')


consumer = Consumer()
producer = Producer()
producer.register(consumer)


@consumer.handler
def on_metrics(message: ModelTrained | ModelEvaluated,
               db: list = Depends(database)) -> None:
    phase = 'train' if isinstance(message, ModelTrained) else 'eval'
    db.append((phase, message.accuracy))
    print(f'{phase}: accuracy={message.accuracy}')


def main() -> None:
    rows: list = []
    consumer.dependency_overrides[database] = lambda: rows
    producer.dispatch(ModelTrained(accuracy=0.91))
    producer.dispatch(ModelEvaluated(accuracy=0.88))
    print('stored rows:', rows)


if __name__ == '__main__':
    main()
