"""Publisher/Subscriber bus in isolation (reference:
``examples/verybasic/pubsub.py``).

Messages route by *topic*; a handler exception propagates to the publisher —
the designed early-stop signal path.
"""

from tpusystem.services import Publisher, Subscriber


def main() -> None:
    subscriber = Subscriber()
    publisher = Publisher()
    publisher.register(subscriber)

    @subscriber.subscribe('loss', 'accuracy')
    def chart(value: float) -> None:
        print(f'charting {value}')

    @subscriber.subscribe('loss')
    def watchdog(value: float) -> None:
        if value > 10.0:
            raise StopIteration('loss diverged')

    publisher.publish(0.37, 'loss')
    publisher.publish(0.91, 'accuracy')
    try:
        publisher.publish(99.0, 'loss')
    except StopIteration as stop:
        print(f'stopped: {stop}')


if __name__ == '__main__':
    main()
