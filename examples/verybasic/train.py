"""Minimal end-to-end training — the framework without the architecture.

One MLP, one jitted donated step, one loader; loss decreases on a single
chip. This is the smallest possible tpusystem program (the reference's
``examples/verybasic`` tier); ``examples/tinysys`` shows the full
message-driven system on top of the same pieces.
"""

import jax.numpy as jnp

from tpusystem.data import Loader, SyntheticDigits
from tpusystem.models import MLP
from tpusystem.train import (Adam, CrossEntropyLoss, Mean, build_train_step,
                             flax_apply, init_state)


def main() -> None:
    module = MLP(features=(128,), classes=10)
    optimizer = Adam(lr=1e-3)
    step = build_train_step(flax_apply(module), CrossEntropyLoss(), optimizer)
    state = init_state(module, optimizer, jnp.zeros((1, 28, 28)))

    loader = Loader(SyntheticDigits(samples=2048), batch_size=64, shuffle=True)
    for epoch in range(3):
        loss = Mean()
        for inputs, targets in loader:
            state, (_, batch_loss) = step(state, inputs, targets)
            loss.update(batch_loss)
        print(f'epoch {epoch}: loss={loss.compute():.4f}')


if __name__ == '__main__':
    main()
