"""Aggregate root for TPU training systems.

A DDD *aggregate* is the unit of consistency a training service operates on:
the neural network plus everything needed to train/evaluate it (optimizer
state, RNG streams, tokenizer, ...). The reference builds this on a mutable
``torch.nn.Module`` (``torchsystem/domain/aggregate.py:26``); the TPU-native
design splits the aggregate in two:

* **host side** (this class): identity, phase state machine, epoch hooks and
  the domain-event queue — plain Python, mutated freely between steps;
* **device side**: an immutable parameter/optimizer pytree (see
  :class:`tpusystem.train.state.TrainState`) advanced only by pure, jitted
  step functions. Subclasses hold the pytree as an attribute and replace it
  wholesale each step (``self.state = self._step(self.state, batch)``).

This keeps the reference's ergonomic API (``model.phase = 'train'``,
``model.epoch += 1`` firing hooks, ``model.events.enqueue(StopIteration)``)
while the math stays XLA-compilable: nothing on the host side is ever traced.

Behavioral parity contracts (``torchsystem/domain/aggregate.py:102-158``):
``id`` is abstract; ``phase`` maps the training flag to
``'train' | 'evaluation'``; setting ``phase`` flips the flag then calls
``onphase()``; assigning ``epoch`` calls ``onepoch()`` only when the
attribute already existed (so ``__init__`` assignment does not fire it).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Literal

from tpusystem.domain.events import Events

Phase = Literal['train', 'evaluation'] | str


class Aggregate(ABC):
    """Host-side aggregate root with phase/epoch hooks and domain events."""

    def __init__(self) -> None:
        self.events = Events()
        self._training = True

    @property
    @abstractmethod
    def id(self) -> Any:
        """Unique identity of the aggregate root within its boundary.

        Use :func:`tpusystem.registry.gethash` over the registered network
        definition for a deterministic, restart-stable id that keys
        experiment rows and checkpoint directories.
        """

    @property
    def phase(self) -> Phase:
        """``'train'`` while in training mode, ``'evaluation'`` otherwise.

        On TPU the phase decides which jitted step executes (the train step
        with dropout RNGs and optimizer update, or the eval step with
        deterministic forward) — the analogue of torch's
        ``train()/eval()`` mode flag.
        """
        return 'train' if self._training else 'evaluation'

    @phase.setter
    def phase(self, value: Phase) -> None:
        self.train() if value == 'train' else self.eval()
        self.onphase()

    def train(self) -> None:
        """Enter training mode. Subclasses may extend (e.g. swap step fns)."""
        self._training = True

    def eval(self) -> None:
        """Enter evaluation mode."""
        self._training = False

    def onphase(self) -> None:
        """Hook fired after every phase change. Override for custom behavior."""

    def onepoch(self) -> None:
        """Hook fired after every epoch assignment (post-``__init__``).

        Typical use: ``self.events.commit()`` so exceptions enqueued during
        the epoch (early stopping) unwind into the epoch loop here.
        """

    def __setattr__(self, name: str, value: Any) -> None:
        if name == 'epoch' and hasattr(self, 'epoch'):
            super().__setattr__(name, value)
            self.onepoch()
        else:
            super().__setattr__(name, value)
