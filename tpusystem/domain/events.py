"""Domain events with exceptions-as-control-flow.

An :class:`Events` buffer collects things that happened inside an aggregate
boundary and dispatches them on :meth:`Events.commit`. The contract mirrors
the reference (``torchsystem/domain/events.py:94-167``):

* both *instances* and *classes* may be enqueued, of plain events **and**
  exceptions;
* dispatch key is the event itself when it is a type, else its type;
* a handler taking zero parameters is called without the event, otherwise it
  receives the event;
* a handlers entry may be one callable or a sequence of callables;
* an exception with no registered handler is **raised** at commit time — this
  is the early-stopping mechanism (e.g. enqueue ``StopIteration`` and let it
  unwind the epoch loop);
* a plain event with no handler is silently dropped.

On a multi-host TPU pod the commit point must be reached consistently on all
workers; see :mod:`tpusystem.parallel.multihost` for the agreement primitive
that turns a local stop-exception into a collective stop decision.
"""

from __future__ import annotations

from collections import deque
from collections.abc import Callable, Iterable, Sequence
from inspect import signature
from typing import Optional


class Event:
    """Optional base class for self-documenting domain events."""


EVENT = Event | type[Event] | Exception | type[Exception]
HANDLERS = Callable | Sequence[Callable]


def _is_exception(event: EVENT) -> bool:
    return isinstance(event, Exception) or (
        isinstance(event, type) and issubclass(event, Exception))


class Events:
    """FIFO of domain events with commit-time dispatch.

    Attributes:
        queue: pending events (instances or classes).
        handlers: mapping of event type -> callable or sequence of callables.
    """

    def __init__(self) -> None:
        self.queue: deque[EVENT] = deque()
        self.handlers: dict[type, HANDLERS] = {}

    def enqueue(self, event: EVENT) -> None:
        """Add an event (or exception) to the pending queue."""
        self.queue.append(event)

    def dequeue(self) -> Optional[EVENT]:
        """Pop the oldest pending event, or ``None`` when empty."""
        return self.queue.popleft() if self.queue else None

    def handle(self, event: EVENT) -> None:
        """Dispatch one event to its handlers.

        Raises the event when it is an unhandled exception (class or
        instance); silently ignores unhandled plain events.
        """
        key = event if isinstance(event, type) else type(event)
        registered = self.handlers.get(key)
        if registered:
            callables = registered if isinstance(registered, Iterable) else [registered]
            for handler in callables:
                if len(signature(handler).parameters) == 0:
                    handler()
                else:
                    handler(event)
        elif _is_exception(event):
            raise event

    def commit(self) -> None:
        """Drain the queue, dispatching each event in FIFO order."""
        while (event := self.dequeue()) is not None:
            self.handle(event)
