from tpusystem.domain.aggregate import Aggregate, Phase
from tpusystem.domain.events import Event, Events

__all__ = ['Aggregate', 'Phase', 'Event', 'Events']
