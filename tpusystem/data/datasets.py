"""Built-in datasets.

The environment has no network egress, so the MNIST-shaped workloads the
reference trains on (``examples/tinysys/tinysys/datasets/mnist.py``) are
modeled by deterministic synthetic datasets with the same shapes and a
learnable signal — sufficient for end-to-end and convergence tests. A torch
``Dataset`` adapter covers users bringing their own torch data pipelines.
"""

from __future__ import annotations

import numpy as np

from tpusystem.data.loader import ArrayDataset
from tpusystem.registry import register


@register
class SyntheticDigits(ArrayDataset):
    """MNIST-shaped 28x28 classification set: each class is a Gaussian blob
    around a fixed random prototype, so a small MLP separates it quickly."""

    def __init__(self, samples: int = 4096, classes: int = 10, seed: int = 0,
                 noise: float = 0.35, train: bool = True):
        rng = np.random.default_rng(seed if train else seed + 1)
        prototype_rng = np.random.default_rng(seed)  # shared across splits
        prototypes = prototype_rng.normal(size=(classes, 28 * 28)).astype(np.float32)
        labels = rng.integers(0, classes, size=samples)
        images = prototypes[labels] + noise * rng.normal(size=(samples, 28 * 28)).astype(np.float32)
        super().__init__(images.reshape(samples, 28, 28).astype(np.float32),
                         labels.astype(np.int32))


@register
class SyntheticTokens(ArrayDataset):
    """Language-model token streams with learnable bigram structure.

    The sparse bigram transition table derives from ``seed`` alone and is
    shared across splits (like :class:`SyntheticDigits` prototypes), so a
    ``train=False`` holdout draws *different sequences from the same
    distribution* — held-out perplexity is meaningful."""

    def __init__(self, samples: int = 1024, sequence_length: int = 128,
                 vocab_size: int = 256, seed: int = 0, train: bool = True):
        table_rng = np.random.default_rng(seed)      # shared across splits
        table = table_rng.integers(0, vocab_size, size=(vocab_size, 4))
        # train continues the table stream (a fresh default_rng(seed) would
        # replay the table draw bit-for-bit into tokens[:, 0]); the holdout
        # seeds off-stream for independent draws from the same table
        rng = table_rng if train else np.random.default_rng(seed + 1)
        tokens = np.empty((samples, sequence_length), dtype=np.int32)
        tokens[:, 0] = rng.integers(0, vocab_size, size=samples)
        choices = rng.integers(0, 4, size=(samples, sequence_length))
        for position in range(1, sequence_length):
            tokens[:, position] = table[tokens[:, position - 1], choices[:, position]]
        super().__init__(tokens)


@register
class MemmapTokens:
    """Pretraining corpus as a flat binary file of token ids.

    The standard LM data layout (one contiguous ``dtype`` array on disk, as
    produced by GPT-2/nanoGPT-style tokenizer scripts): the file is
    memory-mapped, and sample *i* is the ``sequence_length + 1`` window at
    ``i * stride`` (``+1`` so the loss can shift inputs/targets from one
    tensor). Batches gather directly from the page cache via vectorized
    window indexing — no materialized copy of the corpus in RAM.

    Args:
        path: binary file of token ids.
        sequence_length: tokens per sample (the model's ``max_seq``).
        dtype: on-disk integer dtype (``uint16`` fits 64k vocabs and is the
            common choice; tokens come back as int32).
        stride: window step; defaults to ``sequence_length`` (disjoint
            windows — set smaller for overlapping augmentation).
    """

    def __init__(self, path, sequence_length: int = 1024,
                 dtype: str = 'uint16', stride: int | None = None):
        self.path = str(path)
        self.sequence_length = sequence_length
        self.dtype = dtype
        self.stride = stride or sequence_length
        self._tokens = np.memmap(self.path, dtype=np.dtype(dtype), mode='r')
        window = sequence_length + 1
        if len(self._tokens) < window:
            raise ValueError(
                f'{self.path}: {len(self._tokens)} tokens < one window ({window})')
        self._count = (len(self._tokens) - window) // self.stride + 1

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, index) -> tuple:
        window = self.sequence_length + 1
        if isinstance(index, np.ndarray):
            # batched window gather: native per-window memcpy straight from
            # the page cache (multithreaded, GIL released) when the
            # toolchain built batcher.cpp, numpy fancy indexing otherwise —
            # bit-identical either way
            from tpusystem.data import native
            starts = index.astype(np.int64) * self.stride
            rows = native.gather_windows(self._tokens, starts, window)
            return (rows.astype(np.int32),)
        start = int(index) * self.stride
        return (self._tokens[start:start + window].astype(np.int32),)


@register
class SyntheticClicks:
    """Synthetic click log for the recommender workload — the heavy-input
    -pipeline stress the LM corpora never apply.

    Every example carries a **pytree** of features plus a label:
    ``({'dense': [d] float32, 'ids': [features, hot] int32}, label)`` —
    multi-hot sparse ids padded with ``-1`` (per-row hotness is drawn
    uniformly in ``[1, hot]``, so the padding pattern is genuinely
    ragged), ids drawn from a **truncated Zipf** distribution per feature
    (exponent ``alpha``; rank-1 ids dominate, the tail is long — the
    duplicate-id regime embedding dedup and grad scatter-add exist for).
    Labels come from a planted logistic model over per-id weights and
    the dense slice (weights shared across splits like
    :class:`SyntheticDigits` prototypes), so AUC is learnable and a
    ``train=False`` holdout is meaningful.
    """

    def __init__(self, samples: int = 4096, vocabs: tuple = (64, 32),
                 hot: int = 4, dense: int = 4, seed: int = 0,
                 alpha: float = 1.3, train: bool = True):
        planted_rng = np.random.default_rng(seed)     # shared across splits
        rng = np.random.default_rng(seed + (0 if train else 1))
        features = len(vocabs)
        # planted logistic model: per-id weights + dense weights
        id_weights = [planted_rng.normal(size=vocab).astype(np.float32)
                      / np.sqrt(hot * features)
                      for vocab in vocabs]
        dense_weights = (planted_rng.normal(size=dense).astype(np.float32)
                         / np.sqrt(dense))
        # truncated Zipf pmf per feature (exact, vocab-bounded)
        ids = np.empty((samples, features, hot), np.int32)
        for feature, vocab in enumerate(vocabs):
            pmf = 1.0 / np.arange(1, vocab + 1) ** alpha
            pmf /= pmf.sum()
            ids[:, feature] = rng.choice(vocab, size=(samples, hot), p=pmf)
        hotness = rng.integers(1, hot + 1, size=(samples, features))
        ids[np.arange(hot)[None, None, :] >= hotness[..., None]] = -1
        dense_slice = rng.normal(size=(samples, dense)).astype(np.float32)
        logits = dense_slice @ dense_weights
        for feature in range(features):
            weights = id_weights[feature]
            hot_ids = ids[:, feature]
            logits = logits + np.where(hot_ids >= 0,
                                       weights[np.maximum(hot_ids, 0)],
                                       0.0).sum(axis=-1)
        labels = (rng.uniform(size=samples)
                  < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
        self._dense = dense_slice
        self._ids = ids
        self._labels = labels

    def __len__(self) -> int:
        return len(self._labels)

    def __getitem__(self, index) -> tuple:
        """Pytree batch: ``({'dense': ..., 'ids': ...}, labels)`` — the
        shape :class:`tpusystem.models.DLRM` consumes and the
        :class:`~tpusystem.data.Loader` prefetch thread device-places
        leaf by leaf."""
        return ({'dense': self._dense[index], 'ids': self._ids[index]},
                self._labels[index])


class TorchDataset(ArrayDataset):
    """Adapter: materialize a (map-style) torch dataset into arrays once,
    so batches feed the TPU without per-batch torch->numpy conversion."""

    def __init__(self, dataset):
        first = dataset[0]
        columns = len(first) if isinstance(first, (tuple, list)) else 1
        stacked = [[] for _ in range(columns)]
        for item in dataset:
            parts = item if isinstance(item, (tuple, list)) else (item,)
            for column, part in enumerate(parts):
                stacked[column].append(np.asarray(part))
        super().__init__(*[np.stack(column) for column in stacked])
