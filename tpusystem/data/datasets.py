"""Built-in datasets.

The environment has no network egress, so the MNIST-shaped workloads the
reference trains on (``examples/tinysys/tinysys/datasets/mnist.py``) are
modeled by deterministic synthetic datasets with the same shapes and a
learnable signal — sufficient for end-to-end and convergence tests. A torch
``Dataset`` adapter covers users bringing their own torch data pipelines.
"""

from __future__ import annotations

import numpy as np

from tpusystem.data.loader import ArrayDataset
from tpusystem.registry import register


@register
class SyntheticDigits(ArrayDataset):
    """MNIST-shaped 28x28 classification set: each class is a Gaussian blob
    around a fixed random prototype, so a small MLP separates it quickly."""

    def __init__(self, samples: int = 4096, classes: int = 10, seed: int = 0,
                 noise: float = 0.35, train: bool = True):
        rng = np.random.default_rng(seed if train else seed + 1)
        prototype_rng = np.random.default_rng(seed)  # shared across splits
        prototypes = prototype_rng.normal(size=(classes, 28 * 28)).astype(np.float32)
        labels = rng.integers(0, classes, size=samples)
        images = prototypes[labels] + noise * rng.normal(size=(samples, 28 * 28)).astype(np.float32)
        super().__init__(images.reshape(samples, 28, 28).astype(np.float32),
                         labels.astype(np.int32))


@register
class SyntheticTokens(ArrayDataset):
    """Language-model token streams with learnable bigram structure.

    The sparse bigram transition table derives from ``seed`` alone and is
    shared across splits (like :class:`SyntheticDigits` prototypes), so a
    ``train=False`` holdout draws *different sequences from the same
    distribution* — held-out perplexity is meaningful."""

    def __init__(self, samples: int = 1024, sequence_length: int = 128,
                 vocab_size: int = 256, seed: int = 0, train: bool = True):
        table_rng = np.random.default_rng(seed)      # shared across splits
        table = table_rng.integers(0, vocab_size, size=(vocab_size, 4))
        # train continues the table stream (a fresh default_rng(seed) would
        # replay the table draw bit-for-bit into tokens[:, 0]); the holdout
        # seeds off-stream for independent draws from the same table
        rng = table_rng if train else np.random.default_rng(seed + 1)
        tokens = np.empty((samples, sequence_length), dtype=np.int32)
        tokens[:, 0] = rng.integers(0, vocab_size, size=samples)
        choices = rng.integers(0, 4, size=(samples, sequence_length))
        for position in range(1, sequence_length):
            tokens[:, position] = table[tokens[:, position - 1], choices[:, position]]
        super().__init__(tokens)


@register
class MemmapTokens:
    """Pretraining corpus as a flat binary file of token ids.

    The standard LM data layout (one contiguous ``dtype`` array on disk, as
    produced by GPT-2/nanoGPT-style tokenizer scripts): the file is
    memory-mapped, and sample *i* is the ``sequence_length + 1`` window at
    ``i * stride`` (``+1`` so the loss can shift inputs/targets from one
    tensor). Batches gather directly from the page cache via vectorized
    window indexing — no materialized copy of the corpus in RAM.

    Args:
        path: binary file of token ids.
        sequence_length: tokens per sample (the model's ``max_seq``).
        dtype: on-disk integer dtype (``uint16`` fits 64k vocabs and is the
            common choice; tokens come back as int32).
        stride: window step; defaults to ``sequence_length`` (disjoint
            windows — set smaller for overlapping augmentation).
    """

    def __init__(self, path, sequence_length: int = 1024,
                 dtype: str = 'uint16', stride: int | None = None):
        self.path = str(path)
        self.sequence_length = sequence_length
        self.dtype = dtype
        self.stride = stride or sequence_length
        self._tokens = np.memmap(self.path, dtype=np.dtype(dtype), mode='r')
        window = sequence_length + 1
        if len(self._tokens) < window:
            raise ValueError(
                f'{self.path}: {len(self._tokens)} tokens < one window ({window})')
        self._count = (len(self._tokens) - window) // self.stride + 1

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, index) -> tuple:
        window = self.sequence_length + 1
        if isinstance(index, np.ndarray):
            # batched window gather: native per-window memcpy straight from
            # the page cache (multithreaded, GIL released) when the
            # toolchain built batcher.cpp, numpy fancy indexing otherwise —
            # bit-identical either way
            from tpusystem.data import native
            starts = index.astype(np.int64) * self.stride
            rows = native.gather_windows(self._tokens, starts, window)
            return (rows.astype(np.int32),)
        start = int(index) * self.stride
        return (self._tokens[start:start + window].astype(np.int32),)


class TorchDataset(ArrayDataset):
    """Adapter: materialize a (map-style) torch dataset into arrays once,
    so batches feed the TPU without per-batch torch->numpy conversion."""

    def __init__(self, dataset):
        first = dataset[0]
        columns = len(first) if isinstance(first, (tuple, list)) else 1
        stacked = [[] for _ in range(columns)]
        for item in dataset:
            parts = item if isinstance(item, (tuple, list)) else (item,)
            for column, part in enumerate(parts):
                stacked[column].append(np.asarray(part))
        super().__init__(*[np.stack(column) for column in stacked])
