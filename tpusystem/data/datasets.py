"""Built-in datasets.

The environment has no network egress, so the MNIST-shaped workloads the
reference trains on (``examples/tinysys/tinysys/datasets/mnist.py``) are
modeled by deterministic synthetic datasets with the same shapes and a
learnable signal — sufficient for end-to-end and convergence tests. A torch
``Dataset`` adapter covers users bringing their own torch data pipelines.
"""

from __future__ import annotations

import numpy as np

from tpusystem.data.loader import ArrayDataset
from tpusystem.registry import register


@register
class SyntheticDigits(ArrayDataset):
    """MNIST-shaped 28x28 classification set: each class is a Gaussian blob
    around a fixed random prototype, so a small MLP separates it quickly."""

    def __init__(self, samples: int = 4096, classes: int = 10, seed: int = 0,
                 noise: float = 0.35, train: bool = True):
        rng = np.random.default_rng(seed if train else seed + 1)
        prototype_rng = np.random.default_rng(seed)  # shared across splits
        prototypes = prototype_rng.normal(size=(classes, 28 * 28)).astype(np.float32)
        labels = rng.integers(0, classes, size=samples)
        images = prototypes[labels] + noise * rng.normal(size=(samples, 28 * 28)).astype(np.float32)
        super().__init__(images.reshape(samples, 28, 28).astype(np.float32),
                         labels.astype(np.int32))


@register
class SyntheticTokens(ArrayDataset):
    """Language-model token streams with learnable bigram structure."""

    def __init__(self, samples: int = 1024, sequence_length: int = 128,
                 vocab_size: int = 256, seed: int = 0):
        rng = np.random.default_rng(seed)
        # fixed sparse bigram transition table -> sequences are predictable
        table = rng.integers(0, vocab_size, size=(vocab_size, 4))
        tokens = np.empty((samples, sequence_length), dtype=np.int32)
        tokens[:, 0] = rng.integers(0, vocab_size, size=samples)
        choices = rng.integers(0, 4, size=(samples, sequence_length))
        for position in range(1, sequence_length):
            tokens[:, position] = table[tokens[:, position - 1], choices[:, position]]
        super().__init__(tokens)


class TorchDataset(ArrayDataset):
    """Adapter: materialize a (map-style) torch dataset into arrays once,
    so batches feed the TPU without per-batch torch->numpy conversion."""

    def __init__(self, dataset):
        first = dataset[0]
        columns = len(first) if isinstance(first, (tuple, list)) else 1
        stacked = [[] for _ in range(columns)]
        for item in dataset:
            parts = item if isinstance(item, (tuple, list)) else (item,)
            for column, part in enumerate(parts):
                stacked[column].append(np.asarray(part))
        super().__init__(*[np.stack(column) for column in stacked])
