"""ctypes bindings for the native batch-assembly core.

Builds ``batcher.cpp`` with the system C++ toolchain on first use (cached
under ``~/.cache/tpusystem`` keyed by a source digest) and degrades to pure
numpy when no toolchain is available — the framework never *requires* the
native path, it is a bandwidth upgrade (multithreaded row gather with the
GIL released) for host-side batch assembly.

Use :func:`gather` directly, or let :class:`tpusystem.data.ArrayDataset`
pick it up transparently. Results are bit-identical to numpy fancy
indexing; batch *order* never depends on availability (shuffle stays in
numpy).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import tempfile

import numpy as np

_SOURCE = pathlib.Path(__file__).with_name('batcher.cpp')
_ABI = 2
_lib: ctypes.CDLL | None | bool = False   # False = not tried yet


def _cache_dir() -> pathlib.Path:
    root = os.environ.get('TPUSYSTEM_CACHE')
    if root:
        return pathlib.Path(root)
    home = os.environ.get('XDG_CACHE_HOME') or pathlib.Path.home() / '.cache'
    return pathlib.Path(home) / 'tpusystem'


def _build() -> ctypes.CDLL | None:
    source = _SOURCE.read_bytes()
    digest = hashlib.md5(source).hexdigest()[:16]
    target = _cache_dir() / f'batcher-{digest}.so'
    if not target.exists():
        try:
            target.parent.mkdir(parents=True, exist_ok=True)
            # mkstemp: each concurrent builder gets its own fd-backed scratch
            # path (mktemp could hand two builders the same name and publish
            # a torn .so)
            fd, scratch = tempfile.mkstemp(dir=target.parent, suffix='.so')
            os.close(fd)
            # mkstemp's 0600 would survive os.replace and lock other users
            # of a shared cache dir out of the published .so
            os.chmod(scratch, 0o644)
            subprocess.run(
                ['g++', '-O3', '-shared', '-fPIC', '-std=c++17', '-pthread',
                 str(_SOURCE), '-o', scratch],
                check=True, capture_output=True, timeout=120)
            os.replace(scratch, target)   # atomic under concurrent builders
        except (OSError, subprocess.SubprocessError):
            return None
    try:
        lib = ctypes.CDLL(str(target))
        lib.ts_abi_version.restype = ctypes.c_int
        if lib.ts_abi_version() != _ABI:
            return None
        lib.ts_gather_rows.restype = None
        lib.ts_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
        lib.ts_gather_windows.restype = None
        lib.ts_gather_windows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
        return lib
    except OSError:
        return None


def library() -> ctypes.CDLL | None:
    """The loaded native library, building it on first call; None when the
    toolchain is unavailable (callers fall back to numpy)."""
    global _lib
    if _lib is False:
        _lib = None if os.environ.get('TPUSYSTEM_NO_NATIVE') else _build()
    return _lib


def available() -> bool:
    return library() is not None


def gather(array: np.ndarray, indices: np.ndarray,
           out: np.ndarray | None = None, threads: int = 0) -> np.ndarray:
    """``array[indices]`` via the native multithreaded row gather.

    Falls back to numpy fancy indexing when the native library is missing
    or the array layout is not row-gatherable (non-contiguous rows).
    Bit-identical to ``array[indices]`` either way.
    """
    lib = library()
    indices = np.asarray(indices)
    # The C side is a raw memcpy over int64 row numbers. Everything with
    # different semantics — boolean masks, float indices, negative or
    # out-of-range values, multi-dim index arrays, non-row-contiguous or
    # object arrays — keeps exact numpy behavior via numpy itself.
    native_ok = (
        lib is not None and array.ndim >= 1 and indices.ndim == 1
        and indices.dtype.kind in 'iu'
        and array.flags.c_contiguous and not array.dtype.hasobject
        and (len(indices) == 0
             or (int(indices.min()) >= 0 and int(indices.max()) < len(array))))
    expected_shape = (len(indices),) + array.shape[1:] if indices.ndim == 1 else None
    if native_ok and out is not None:
        # a caller-supplied buffer is written as raw bytes: only accept it
        # when that is exactly equivalent to numpy's element-wise copy —
        # including not aliasing the source (the raw memcpy reads rows the
        # previous row's write may already have clobbered)
        native_ok = (out.shape == expected_shape and out.dtype == array.dtype
                     and out.flags.c_contiguous
                     and not np.shares_memory(out, array))
    if not native_ok:
        fallback = array[indices]
        if out is None:
            return fallback
        np.copyto(out, fallback)
        return out
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    if out is None:
        out = np.empty(expected_shape, array.dtype)
    row_bytes = array.dtype.itemsize * int(np.prod(array.shape[1:], dtype=np.int64))
    lib.ts_gather_rows(
        array.ctypes.data_as(ctypes.c_void_p),
        indices.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        len(indices), row_bytes, threads)
    return out


def gather_windows(array: np.ndarray, starts: np.ndarray, window: int,
                   threads: int = 0) -> np.ndarray:
    """Gather ``[len(starts), window]`` element windows from a flat array.

    The LM-corpus hot path: ``array`` is typically a read-only memmap of
    token ids and ``starts`` the (possibly overlapping) sample offsets —
    each window is one contiguous memcpy straight from the page cache,
    multithreaded with the GIL released, instead of numpy's per-element
    fancy indexing over a ``[batch, window]`` position matrix. Falls back
    to equivalent numpy indexing when the native library is missing or the
    inputs are not window-gatherable. Bit-identical either way.
    """
    lib = library()
    starts = np.asarray(starts)
    native_ok = (
        lib is not None and array.ndim == 1 and window > 0
        and starts.ndim == 1 and starts.dtype.kind in 'iu'
        and array.flags.c_contiguous and not array.dtype.hasobject
        and (len(starts) == 0
             or (int(starts.min()) >= 0
                 and int(starts.max()) + window <= len(array))))
    if not native_ok:
        positions = (np.asarray(starts, np.int64)[:, None]
                     + np.arange(window)[None, :])
        return array[positions]
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    byte_starts = starts * array.dtype.itemsize
    out = np.empty((len(starts), window), array.dtype)
    lib.ts_gather_windows(
        array.ctypes.data_as(ctypes.c_void_p),
        byte_starts.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        len(starts), window * array.dtype.itemsize, threads)
    return out
