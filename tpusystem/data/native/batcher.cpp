// Native batch-assembly core for tpusystem.data.
//
// The reference's data path is torch DataLoader collation (pure Python in
// the repo; SURVEY.md §2.3 notes the reference itself ships no native code
// and delegates to torch). Here the host-side hot operation — gathering
// sample rows into a contiguous batch buffer the device transfer DMA-reads
// from — is a multithreaded memcpy in C++, called from Python via ctypes
// (ctypes foreign calls release the GIL, so gathers overlap the host loop).
//
// Deliberately minimal ABI: plain C, fixed-width types, no ownership — the
// caller (numpy) owns every buffer. Shuffle-order generation stays in
// Python so batch order is identical with or without this library.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Copy dst[i] = src[indices[i]] for i in [begin, end).
void gather_span(const char* src, const int64_t* indices, char* dst,
                 int64_t begin, int64_t end, int64_t row_bytes) {
  for (int64_t i = begin; i < end; ++i) {
    std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

// Copy dst[i] = src[byte_starts[i] .. +window_bytes) for i in [begin, end).
// Windows may overlap in the source (stride < window is augmentation).
void window_span(const char* src, const int64_t* byte_starts, char* dst,
                 int64_t begin, int64_t end, int64_t window_bytes) {
  for (int64_t i = begin; i < end; ++i) {
    std::memcpy(dst + i * window_bytes, src + byte_starts[i],
                static_cast<size_t>(window_bytes));
  }
}

// Shared fan-out: run `span(src, offsets, dst, begin, end, bytes)` over
// [0, rows) across up to `threads` workers (auto when <= 0), staying
// single-threaded while the total copy is under ~1 MiB per worker.
template <typename Span>
void parallel_spans(Span span, const char* src, const int64_t* offsets,
                    char* dst, int64_t rows, int64_t row_bytes,
                    int32_t threads) {
  if (rows <= 0 || row_bytes <= 0) return;
  int64_t want = threads > 0 ? threads : std::thread::hardware_concurrency();
  const int64_t min_bytes_per_worker = 1 << 20;
  int64_t useful = (rows * row_bytes + min_bytes_per_worker - 1) /
                   min_bytes_per_worker;
  int64_t n = std::max<int64_t>(1, std::min({want, useful, rows}));
  if (n == 1) {
    span(src, offsets, dst, 0, rows, row_bytes);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(static_cast<size_t>(n));
  int64_t chunk = (rows + n - 1) / n;
  for (int64_t w = 0; w < n; ++w) {
    int64_t begin = w * chunk;
    int64_t end = std::min(rows, begin + chunk);
    if (begin >= end) break;
    workers.emplace_back(span, src, offsets, dst, begin, end, row_bytes);
  }
  for (auto& worker : workers) worker.join();
}

}  // namespace

extern "C" {

// ABI version probe — bump when the signatures below change.
int ts_abi_version() { return 2; }

// Gather `rows` rows of `row_bytes` bytes each from `src` into `dst`
// following `indices`. `threads` <= 0 means auto (hardware concurrency,
// capped so tiny batches stay single-threaded).
void ts_gather_rows(const char* src, const int64_t* indices, char* dst,
                    int64_t rows, int64_t row_bytes, int32_t threads) {
  parallel_spans(gather_span, src, indices, dst, rows, row_bytes, threads);
}

// Gather `windows` windows of `window_bytes` bytes each from `src` into
// `dst`; window i starts at byte offset `byte_starts[i]`. The LM-corpus
// hot path (MemmapTokens): overlapping sequence windows memcpy'd straight
// from the page cache instead of numpy's per-element fancy indexing.
void ts_gather_windows(const char* src, const int64_t* byte_starts, char* dst,
                       int64_t windows, int64_t window_bytes,
                       int32_t threads) {
  parallel_spans(window_span, src, byte_starts, dst, windows, window_bytes,
                 threads);
}

}  // extern "C"
