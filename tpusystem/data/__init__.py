from tpusystem.data.loader import ArrayDataset, Loader
from tpusystem.data.datasets import (MemmapTokens, SyntheticDigits,
                                     SyntheticTokens, TorchDataset)

__all__ = ['ArrayDataset', 'Loader', 'MemmapTokens', 'SyntheticDigits',
           'SyntheticTokens', 'TorchDataset']
