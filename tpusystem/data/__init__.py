from tpusystem.data.loader import ArrayDataset, Loader
from tpusystem.data.datasets import (MemmapTokens, SyntheticClicks,
                                     SyntheticDigits, SyntheticTokens,
                                     TorchDataset)

__all__ = ['ArrayDataset', 'Loader', 'MemmapTokens', 'SyntheticClicks',
           'SyntheticDigits', 'SyntheticTokens', 'TorchDataset']
