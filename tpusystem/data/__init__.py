from tpusystem.data.loader import ArrayDataset, Loader
from tpusystem.data.datasets import SyntheticDigits, SyntheticTokens, TorchDataset

__all__ = ['ArrayDataset', 'Loader', 'SyntheticDigits', 'SyntheticTokens', 'TorchDataset']
