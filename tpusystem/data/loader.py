"""Device-feeding data pipeline.

The reference moves every batch host->device inside the hot loop
(``examples/tinysys/tinysys/services/training.py:33`` — ``.to(device)`` per
batch). On TPU that transfer must overlap compute: the :class:`Loader`
prepares batches on a background prefetch thread — the ``dataset[span]``
gather AND the (asynchronous) ``jax.device_put`` both run off the training
thread, keeping up to ``prefetch`` batches in flight — so batch *N+1*'s
host prep and PCIe/ICI transfer overlap batch *N*'s device compute, and
places each batch with an optional ``NamedSharding`` so a global batch
lands pre-sharded across the mesh data axis.

``Loader`` is registry-friendly: its hyperparameters (batch size, shuffle
seed) capture into the identity hash of the experiment, with the dataset
argument excluded — mirroring ``register(DataLoader, excluded_args=[0])``
in the reference composition root (``examples/tinysys/main.py:31``).
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterator
from typing import Any

import jax
import numpy as np

from tpusystem.registry import register


class ArrayDataset:
    """In-memory dataset over parallel arrays (inputs, targets, ...).

    Batch gathers go through the native multithreaded core
    (:mod:`tpusystem.data.native`) when it is available; results are
    bit-identical to numpy fancy indexing either way.
    """

    def __init__(self, *arrays: np.ndarray):
        lengths = {len(array) for array in arrays}
        assert len(lengths) == 1, 'all arrays must share the leading dimension'
        self.arrays = arrays

    def __len__(self) -> int:
        return len(self.arrays[0])

    def __getitem__(self, index) -> tuple:
        if isinstance(index, np.ndarray):
            from tpusystem.data import native
            return tuple(native.gather(array, index) for array in self.arrays)
        return tuple(array[index] for array in self.arrays)


class _PrefetchError:
    """Carries a prefetch-thread exception across the queue so it
    re-raises on the consuming thread."""

    def __init__(self, error: BaseException):
        self.error = error


class Loader:
    """Batched, shuffled, prefetching iterator over an array dataset.

    Args:
        dataset: :class:`ArrayDataset` or any object with ``__len__`` and
            numpy fancy-indexing ``__getitem__``. Batches may be any
            **pytree** of arrays sharing the leading batch dimension
            (tuples, dict-of-arrays with ragged/multi-hot sparse fields,
            nested mixes) — the prefetch thread and the ``state()``/
            ``seek()`` cursors are structure-agnostic: the cursor names
            batch *positions*, never batch contents.
        batch_size: per-iteration **global** batch size.
        shuffle: reshuffle each epoch with a per-epoch derived seed.
        seed: base shuffle seed (captured in identity).
        drop_remainder: drop the trailing partial batch (required under jit —
            static shapes keep XLA from recompiling).
        sharding: optional ``jax.sharding.NamedSharding`` (or device) each
            batch is placed with; ``None`` leaves placement to jit.
        prefetch: number of batches kept in flight ahead of consumption.
    """

    def __init__(self, dataset, batch_size: int, shuffle: bool = False,
                 seed: int = 0, drop_remainder: bool = True,
                 sharding: Any | None = None, prefetch: int = 2):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.sharding = sharding
        self.prefetch = prefetch
        self._epoch = 0
        self._skip = 0
        self._position = {'epoch': 0, 'batch': 0}

    def __len__(self) -> int:
        n, b = len(self.dataset), self.batch_size
        return n // b if self.drop_remainder else (n + b - 1) // b

    def state(self) -> dict:
        """Resume cursor: the position of the **next batch to be yielded**.

        ``{'epoch': e, 'batch': b}`` means batch ``b`` of epoch ``e`` has not
        been consumed yet. The cursor advances as batches are *yielded* (not
        as the prefetch thread produces them), so a checkpoint taken after
        step N records exactly the data step N+1 should start from. The
        cursor is JSON-able on purpose — it rides a checkpoint's host-side
        ``extras`` (:meth:`tpusystem.checkpoint.Checkpointer.save`)."""
        return dict(self._position)

    def seek(self, cursor: dict) -> 'Loader':
        """Position the next ``__iter__`` at ``cursor`` (from :meth:`state`).

        The batch order of an epoch is a pure function of ``(seed, epoch)``,
        so a fresh process seeking a saved cursor regenerates the *identical*
        permutation and skips the already-consumed batches instead of
        replaying the epoch — the step-granular half of preemption resume.
        A cursor at or past the epoch end normalizes to the next epoch."""
        epoch, batch = int(cursor['epoch']), int(cursor['batch'])
        if batch < 0:
            raise ValueError(f'cursor batch must be >= 0, got {batch}')
        batches = len(self)
        if batches and batch >= batches:
            epoch, batch = epoch + batch // batches, batch % batches
        self._epoch = epoch
        self._skip = batch
        self._position = {'epoch': epoch, 'batch': batch}
        return self

    def _order(self, epoch: int | None = None) -> np.ndarray:
        """Epoch's batch order — a pure function of ``(seed, epoch)``, which
        is what makes a :meth:`seek`-ed resume replay-identical."""
        epoch = self._epoch if epoch is None else epoch
        indices = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(indices)
        return indices

    def _place(self, batch):
        """Device-place a batch **pytree** leaf by leaf.

        Batches are whatever the dataset's ``__getitem__`` returns —
        parallel-array tuples (:class:`ArrayDataset`), or arbitrary
        pytrees like the dict-of-arrays click batches with ragged
        (``-1``-padded) multi-hot sparse fields
        (:class:`~tpusystem.data.datasets.SyntheticClicks`). The
        ``sharding`` applies to every leaf: a batch-dim
        ``PartitionSpec`` (rank <= the leaf's) shards dim 0 of dense
        ``[B, d]``, sparse ``[B, F, K]`` and label ``[B]`` leaves alike,
        so a heterogeneous global batch lands pre-sharded.
        ``jax.device_put`` is natively pytree-aware (one batched
        transfer, the sharding broadcast to every leaf)."""
        if self.sharding is not None:
            return jax.device_put(batch, self.sharding)
        return jax.device_put(batch)

    def __iter__(self) -> Iterator:
        """Yield device-placed batch pytrees, prepared by a background
        thread.

        Host-side batch prep — the ``dataset[span]`` gather plus the
        (asynchronous) ``device_put`` — runs in a prefetch thread, so
        step ``N+1``'s indexing/copy overlaps step ``N``'s device
        compute instead of serializing into the training loop. The
        thread keeps at most ``prefetch`` batches queued ahead of
        consumption (the depth semantics of the old double-buffer), and
        shuts down cleanly when the generator is closed early: every
        queue operation polls a stop flag, so an abandoned iterator
        never leaves a blocked producer behind.
        """
        epoch = self._epoch
        skip = self._skip
        self._skip = 0
        self._epoch += 1
        order = self._order(epoch)
        spans = [order[start:start + self.batch_size]
                 for start in range(0, len(order), self.batch_size)]
        if self.drop_remainder and spans and len(spans[-1]) < self.batch_size:
            spans.pop()
        self._position = {'epoch': epoch, 'batch': skip}
        spans = spans[skip:]          # seek(): already-consumed batches
        if not spans:
            self._position = {'epoch': epoch + 1, 'batch': 0}
            return
        buffer: queue.Queue = queue.Queue(maxsize=max(self.prefetch, 1))
        stop = threading.Event()
        done = object()          # sentinel: producer finished cleanly

        def offer(item) -> bool:
            while not stop.is_set():
                try:
                    buffer.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def produce() -> None:
            try:
                for span in spans:
                    if stop.is_set():
                        return
                    if not offer(self._place(self.dataset[span])):
                        return
                offer(done)
            except BaseException as error:    # re-raised on the consumer
                offer(_PrefetchError(error))

        thread = threading.Thread(target=produce, daemon=True,
                                  name='loader-prefetch')
        thread.start()
        try:
            consumed = skip
            while True:
                item = buffer.get()
                if item is done:
                    self._position = {'epoch': epoch + 1, 'batch': 0}
                    break
                if isinstance(item, _PrefetchError):
                    raise item.error
                # advance BEFORE yielding: the consumer checkpoints from
                # inside the loop body (the generator is suspended here), so
                # state() must already name the batch AFTER this one
                consumed += 1
                self._position = {'epoch': epoch, 'batch': consumed}
                yield item
        finally:
            stop.set()
            # drain so a producer blocked on a full queue sees the flag
            while thread.is_alive():
                try:
                    buffer.get_nowait()
                except queue.Empty:
                    pass
                thread.join(timeout=0.05)


register(Loader, excluded_args=[0], excluded_kwargs={'dataset'})
