"""On-device metric accumulators.

The reference accumulates metrics per batch with torcheval on the CUDA
device and materializes them once per phase
(``examples/tinysys/tinysys/metrics.py:8-27``) — the cadence that keeps the
event bus off the hot path. These accumulators do the same on TPU: ``update``
runs a tiny jitted program against device values (no host sync, no
data-dependent Python), ``compute`` performs the single ``jax.device_get``
per phase.
"""

from __future__ import annotations

from functools import partial
from typing import Protocol

import jax
import jax.numpy as jnp


class Metric(Protocol):
    def update(self, *args, **kwargs) -> None: ...
    def compute(self) -> float: ...
    def reset(self) -> None: ...


@jax.jit
def _mean_update(total, count, values, weight):
    return total + jnp.sum(values) * weight, count + values.size * weight


@jax.jit
def _accuracy_update(correct, count, predictions, targets):
    return correct + jnp.sum(predictions == targets), count + targets.size


class Mean:
    """Weighted running mean of scalar or array values (loss, grad-norm...)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._total = jnp.zeros((), jnp.float32)
        self._count = jnp.zeros((), jnp.float32)

    def update(self, values, weight: float = 1.0) -> None:
        self._total, self._count = _mean_update(
            self._total, self._count, jnp.asarray(values, jnp.float32), weight)

    def compute(self) -> float:
        total, count = jax.device_get((self._total, self._count))
        return float(total / count) if count else 0.0


class Accuracy:
    """Multiclass accuracy from integer predictions vs targets."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._correct = jnp.zeros((), jnp.int32)
        self._count = jnp.zeros((), jnp.int32)

    def update(self, predictions, targets) -> None:
        self._correct, self._count = _accuracy_update(
            self._correct, self._count, predictions, targets)

    def compute(self) -> float:
        correct, count = jax.device_get((self._correct, self._count))
        return float(correct / count) if count else 0.0


@partial(jax.jit, static_argnames='k')
def _topk_update(hits, count, logits, targets, k):
    top = jax.lax.top_k(logits, k)[1]
    match = jnp.any(top == targets[..., None], axis=-1)
    return hits + jnp.sum(match), count + targets.size


class TopKAccuracy:
    """Top-k accuracy from logits vs integer targets."""

    def __init__(self, k: int = 5) -> None:
        self.k = k
        self.reset()

    def reset(self) -> None:
        self._hits = jnp.zeros((), jnp.int32)
        self._count = jnp.zeros((), jnp.int32)

    def update(self, logits, targets) -> None:
        self._hits, self._count = _topk_update(self._hits, self._count, logits, targets, self.k)

    def compute(self) -> float:
        hits, count = jax.device_get((self._hits, self._count))
        return float(hits / count) if count else 0.0


class Perplexity:
    """exp(mean token cross-entropy) for language models."""

    def __init__(self) -> None:
        self._mean = Mean()

    def reset(self) -> None:
        self._mean.reset()

    def update(self, token_losses, weight: float = 1.0) -> None:
        self._mean.update(token_losses, weight)

    def compute(self) -> float:
        import math
        return math.exp(min(self._mean.compute(), 80.0))
