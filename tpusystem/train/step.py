"""Jitted step builders — the TPU hot path.

The reference's per-step work is eager autograd driven from a Python batch
loop (``examples/tinysys/tinysys/classifier.py:29-35``:
zero_grad -> forward -> loss -> backward -> step). Here the whole step is a
single pure function lowered once through ``jax.jit``:

* forward + loss via ``jax.value_and_grad`` (autograd seam),
* optimizer update fused into the same XLA program,
* the :class:`~tpusystem.train.state.TrainState` argument is **donated**, so
  parameters and optimizer slots update in place in HBM (no copy),
* gradient all-reduce over the mesh data axis is inserted by GSPMD when the
  batch is sharded — the step body is identical on 1 chip and on a pod.

Metrics consumed by the event bus must read only the returned loss/outputs
*after* the phase completes (one device->host sync per phase, never per
batch) — the cadence the reference models with ``metrics.compute()``
(``examples/tinysys/tinysys/metrics.py:19-23``).
"""

from __future__ import annotations

from collections.abc import Callable
from inspect import signature
from typing import Any

import jax
import optax

from tpusystem.train.state import TrainState

# apply_fn contract: (params, inputs, rng, train) -> outputs
ApplyFn = Callable[[Any, Any, jax.Array | None, bool], Any]
# criterion contract: (outputs, targets) -> scalar loss
Criterion = Callable[[Any, Any], jax.Array]


def flax_apply(module) -> ApplyFn:
    """Adapt a flax linen module to the step-builder apply contract.

    Passes ``train=`` and dropout RNGs only when the module's ``__call__``
    accepts them, so simple modules stay simple.
    """
    parameters = signature(module.__call__).parameters
    accepts_train = 'train' in parameters

    def apply(params, inputs, rng=None, train=False):
        kwargs = {'train': train} if accepts_train else {}
        rngs = {'dropout': rng} if rng is not None else None
        return module.apply({'params': params}, inputs, rngs=rngs, **kwargs)

    return apply


def build_train_step(apply_fn: ApplyFn, criterion: Criterion, optimizer,
                     *, jit: bool = True):
    """Build ``step(state, inputs, targets) -> (state, (outputs, loss))``.

    ``optimizer`` is a :class:`tpusystem.train.optim.Optimizer` or a raw
    ``optax.GradientTransformation``. The returned step donates ``state``:
    callers must treat the passed-in state as consumed.

    For activation rematerialisation use per-layer checkpointing at the
    model level (e.g. ``GPT2(remat=True)``) — whole-forward checkpointing
    here would double FLOPs without reducing backward peak memory.
    """
    transform = optimizer.transform() if hasattr(optimizer, 'transform') else optimizer

    def step(state: TrainState, inputs, targets):
        state, dropout_rng = state.next_rng()

        def objective(params):
            outputs = apply_fn(params, inputs, dropout_rng, True)
            return criterion(outputs, targets), outputs

        (loss, outputs), grads = jax.value_and_grad(objective, has_aux=True)(state.params)
        updates, opt_state = transform.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        state = state.replace(params=params, opt_state=opt_state, step=state.step + 1)
        return state, (outputs, loss)

    return jax.jit(step, donate_argnums=0) if jit else step


def build_eval_step(apply_fn: ApplyFn, criterion: Criterion, *, jit: bool = True):
    """Build ``step(state, inputs, targets) -> (outputs, loss)`` (no grads,
    deterministic forward) — the ``inference_mode`` analogue."""

    def step(state: TrainState, inputs, targets):
        outputs = apply_fn(state.params, inputs, None, False)
        return outputs, criterion(outputs, targets)

    return jax.jit(step) if jit else step


def init_state(module, optimizer, sample_inputs, *, rng: int | jax.Array = 0,
               param_dtype=None) -> TrainState:
    """Initialize a :class:`TrainState` for a flax module.

    Runs ``module.init`` on the sample batch shape, initializes optimizer
    slots, and seeds the carried RNG stream.
    """
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    init_rng, carry_rng = jax.random.split(rng)
    parameters = signature(module.__call__).parameters
    kwargs = {'train': False} if 'train' in parameters else {}
    variables = module.init(init_rng, sample_inputs, **kwargs)
    params = variables['params']
    if param_dtype is not None:
        params = jax.tree.map(lambda leaf: leaf.astype(param_dtype), params)
    transform = optimizer.transform() if hasattr(optimizer, 'transform') else optimizer
    return TrainState.create(params, transform.init(params), carry_rng)
